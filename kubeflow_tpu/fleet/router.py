"""Fleet router: the HTTP front door over N serving replicas.

Routes `POST /v1/models/{name}:generate` by consistent-hash prefix
affinity — the routing key is the request's first `kv_block_size`
tokens (the first block is what the replicas' radix prefix cache
indexes), so repeated prompts land on the replica that already holds
the cached KV and prefill only computes the suffix. When the affinity
target is unavailable (draining/dead) or overloaded, the request falls
back to the least-loaded replica; proxy failures retry on the next
candidate with exponential backoff; a request still unanswered after
`hedge_after_s` is duplicated to a second replica and the first
response wins (tail-latency insurance — the loser is cancelled).

The router is deliberately jax-free: it boots in milliseconds, knows
nothing about models beyond their names, and observes replicas purely
through the registration/heartbeat handshake
(`serving.server.enable_fleet_registration`) plus its own proxy
outcomes. Decisions are observable: `fleet_route_total{reason}`,
`fleet_hedge_wins_total`, `fleet_replicas{state}` (render-time
collector), a route-latency histogram, and spans whose
`replica_trace` attribute carries the replica's `X-Trace-Id` — one
trace id per hop, joined in the router's span attrs.

    from kubeflow_tpu.fleet.router import create_router_app
    web.run_app(create_router_app(block_size=64), port=9000)
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
import secrets
import time

import aiohttp
from aiohttp import web

from kubeflow_tpu import obs as obs_lib
from kubeflow_tpu.fleet import autoscale
from kubeflow_tpu.fleet import control as control_mod
from kubeflow_tpu.fleet import rollout as rollout_mod
from kubeflow_tpu.fleet.registry import (
    DECODE,
    DEGRADED,
    POOLS,
    PREFILL,
    READY,
    STATES,
    ReplicaRegistry,
)
from kubeflow_tpu.obs import endpoints as obs_endpoints
from kubeflow_tpu.tenancy import TenancyConfig, TenantLedger, Throttled

log = logging.getLogger(__name__)

FLEET_KEY: web.AppKey = web.AppKey("fleet_state", object)

ROUTE_REASONS = ("affinity", "fallback", "hedge", "retry")

# Mirrors serving.server's byte tokenizer constants (BOS=1, bytes at
# +3): the router must hash "text" bodies to the SAME first block the
# replica will tokenize, without importing the jax-loaded server
# module. Drift is pinned by tests/test_fleet.py.
_BOS, _BYTE_OFFSET = 1, 3


def affinity_tokens(body: dict, block_size: int) -> list[int] | None:
    """The first `block_size` prompt tokens the routing key and the
    prefix-heat hash are both built from; None for malformed bodies."""
    toks = None
    if isinstance(body, dict):
        t = body.get("tokens")
        if (isinstance(t, list) and t and isinstance(t[0], list)
                and all(isinstance(x, int) and not isinstance(x, bool)
                        for x in t[0])):
            toks = t[0]
        elif isinstance(body.get("text"), str):
            toks = [_BOS] + [b + _BYTE_OFFSET
                             for b in body["text"].encode("utf-8")]
    return toks[:block_size] if toks else None


def affinity_key(body: dict, block_size: int) -> bytes:
    """Routing key: the first `block_size`-aligned token block of the
    prompt. Requests sharing it co-locate on one replica (where the
    radix cache can serve it); malformed bodies key to b"" (no
    affinity — the replica will 400 them, but through a live one)."""
    toks = affinity_tokens(body, block_size)
    if not toks:
        return b""
    return " ".join(str(x) for x in toks).encode()


def _byte_decode_fleet(ids) -> str:
    """Best-effort byte-tokenizer decode for SPLICED text-mode
    responses (mirrors the serving byte tokenizer: bytes at +3,
    specials below). Only used when the router itself rebuilds the
    text of a failed-over generation; replicas with a real tokenizer
    should use token-mode bodies through the fleet door."""
    return bytes(t - _BYTE_OFFSET for t in ids
                 if t >= _BYTE_OFFSET).decode("utf-8", errors="replace")


def _resume_from_checkpoint(body: dict, ck: dict,
                            sent: list) -> tuple[bytes | None, int]:
    """Failover re-dispatch body from a heartbeat checkpoint: replay
    prompt = checkpoint prompt (embeds any registered-prefix
    expansion, so 'prefix' is dropped) + every token the client
    already holds; budget = what remains. Returns (raw, remaining) —
    remaining <= 0 means the generation already completed."""
    toks = [int(t) for t in ck.get("tokens", [])]
    n_out = len(ck.get("out", []))
    prompt = toks[: len(toks) - n_out]
    remaining = int(ck.get("max_new", 0)) - len(sent)
    if remaining <= 0 or not prompt:
        return None, remaining
    nb = {k: v for k, v in body.items()
          if k not in ("text", "tokens", "prefix", "max_new")}
    nb["tokens"] = [prompt + [int(t) for t in sent]]
    nb["max_new"] = remaining
    return json.dumps(nb).encode(), remaining


def _resume_from_body(body: dict, sent: list) -> bytes | None:
    """Checkpoint-less failover for token-mode bodies with an explicit
    max_new: splice the delivered tokens onto the client's own prompt.
    (The 'prefix' field stays — the replica re-expands it exactly as
    the dead one did.) Returns None when the body is not resumable
    this way — the caller re-sends the original and skips."""
    t = body.get("tokens")
    if (not isinstance(t, list) or len(t) != 1
            or not isinstance(t[0], list)
            or not isinstance(body.get("max_new"), int)):
        return None
    remaining = body["max_new"] - len(sent)
    if remaining <= 0:
        return None
    nb = {k: v for k, v in body.items() if k not in ("tokens", "max_new")}
    nb["tokens"] = [list(t[0]) + [int(x) for x in sent]]
    nb["max_new"] = remaining
    return json.dumps(nb).encode()


def _splice_oneshot(payload: bytes, prepend: list,
                    text_mode: bool) -> bytes:
    """Merge a resumed one-shot response with the tokens the dead
    replica already produced: the client must see ONE complete row, as
    if nothing failed. Unparseable payloads pass through untouched."""
    try:
        pj = json.loads(payload)
        rows = pj["tokens"]
        rows[0] = [int(t) for t in prepend] + rows[0]
    except (KeyError, IndexError, TypeError, ValueError):
        return payload
    if text_mode:
        pj["text"] = _byte_decode_fleet(rows[0])
    return json.dumps(pj).encode()


def _parse_sse_event(raw: bytes) -> dict | None:
    """One `data: {...}` SSE frame -> dict, or None for anything the
    serving replicas don't emit (comments, malformed JSON)."""
    line = raw.strip()
    if not line.startswith(b"data:"):
        return None
    try:
        ev = json.loads(line[5:].strip())
    except (ValueError, UnicodeDecodeError):
        return None
    return ev if isinstance(ev, dict) else None


class FleetObs:
    """Router observability bundle (the serving `ServingObs` pattern):
    metric registry + tracer + the fleet_* instruments."""

    def __init__(self, reg: ReplicaRegistry, registry=None, tracer=None):
        from kubeflow_tpu.controlplane.metrics import (
            Counter,
            Gauge,
            Registry,
        )

        self.registry = registry if registry is not None else Registry()
        self.tracer = tracer if tracer is not None else obs_lib.Tracer()
        self.route_total = Counter(
            "fleet_route_total",
            "Routing decisions by reason — affinity (rendezvous "
            "target), fallback (least-loaded), retry (previous replica "
            "failed), hedge (duplicate dispatch after the latency "
            "deadline) — and by the chosen replica's disaggregation "
            "pool (prefill/decode/mixed)",
            self.registry)
        # Disaggregated handoff instruments (ISSUE 12): one handoff =
        # one prefill-pool dispatch whose KV blocks landed on a decode
        # replica. Bytes are the wire payload (base64 K+V) actually
        # pushed over /v1/migrate/in.
        self.handoff_seconds = obs_lib.get_or_create_histogram(
            self.registry, "fleet_handoff_seconds",
            "Prefill->decode handoff latency (prefill dispatch + KV "
            "push to the decode peer), by model and outcome")
        self.handoff_bytes = Counter(
            "fleet_handoff_bytes_total",
            "KV bytes shipped prefill->decode over /v1/migrate/in "
            "(base64 wire size), by model", self.registry)
        # pool labels enumerate code, not traffic: closed guard
        self.pool_guard = obs_lib.LabelGuard(seed=POOLS, closed=True)
        self.hedge_wins = Counter(
            "fleet_hedge_wins_total",
            "Hedged duplicates that answered before the primary",
            self.registry)
        # Counterfactual fleet prefix hits (ISSUE 13): requests whose
        # chosen replica's heat digest lacked the routing prefix while
        # some OTHER replica's digest had it hot — each one is a
        # prefill a cross-replica cache tier would have saved. The gap
        # between (hits + remote_hits) / lookups and the measured
        # affinity hit rate is that tier's business case, as a number.
        self.remote_hits = Counter(
            "fleet_prefix_remote_hits_total",
            "Routed generates whose prefix was cold on the chosen "
            "replica but hot in a peer's heat digest — misses a "
            "cross-replica KV cache tier would have served",
            self.registry)
        self.failover = Counter(
            "fleet_failover_total",
            "In-flight generations re-dispatched to a healthy replica "
            "after their replica failed mid-request (checkpoint resume "
            "or seamless stream splice)", self.registry)
        self.route_latency = obs_lib.get_or_create_histogram(
            self.registry, "fleet_route_duration_seconds",
            "Routed request latency through the router, by model and "
            "final routing reason")
        replicas_g = Gauge(
            "fleet_replicas",
            "Registered replicas by health state "
            "(ready/degraded/draining/dead) and disaggregation pool "
            "(prefill/decode/mixed)", self.registry)
        # Per-tenant routing accounting (X-Tenant header). With a
        # tenancy config, names resolve through it (bounded by
        # configuration); without one, raw header values pass the
        # cardinality guard so scanners can't mint unbounded series.
        self.tenant_requests = Counter(
            "fleet_tenant_requests_total",
            "Routed generate requests by tenant (X-Tenant header)",
            self.registry)
        self.tenant_throttled = Counter(
            "fleet_tenant_throttled_total",
            "Requests 429'd at the router door by the tenant's "
            "request bucket, before any replica dispatch",
            self.registry)
        self.tenant_guard = obs_lib.LabelGuard()
        # Federation: bounds the `replica` label on /fleet/metrics so a
        # churning fleet can't grow the merged exposition unboundedly.
        self.replica_guard = obs_lib.LabelGuard()
        # Router-side SLOs: end-to-end routed latency (what the CLIENT
        # experiences through the door, retries and hedges included)
        # and availability (5xx / no-replica-at-all are budget spends).
        self.slo = obs_lib.SloEngine([
            obs_lib.Slo("fleet_route_latency", 0.95, threshold_s=2.5,
                        description="95% of routed generates under "
                        "2.5 s end to end"),
            obs_lib.Slo("fleet_availability", 0.99,
                        description="99% of routed generates answered "
                        "by some replica without a 5xx"),
        ])
        try:
            self.registry.register(self.slo)
        except ValueError:
            pass  # shared registry already carries a burn-rate gauge
        else:
            obs_lib.register_budget_gauge(self.registry, self.slo)
        # Decision-plane counters (ISSUE 16): the controller's ledger
        # hooks feed these; series are zero-seeded per configured
        # policy by `bind_control` once the policy set is known.
        self.control_decisions = Counter(
            "fleet_control_decisions_total",
            "Controller policy evaluations by outcome — every "
            "evaluation lands in exactly one of fired / "
            "suppressed_hysteresis / suppressed_cooldown / "
            "below_threshold / actuator_failed (ledger conservation)",
            self.registry)
        self.control_actions = Counter(
            "fleet_control_actions_total",
            "Actuations the controller actually fired, by policy and "
            "action (scale_out / drain_replica / evict_worker / "
            "disable_draft)", self.registry)
        # policy/outcome/action labels enumerate code + configuration,
        # never traffic: closed guards (a misconfigured policy name
        # collapses to the overflow bucket instead of minting series)
        self.control_policy_guard = obs_lib.LabelGuard(closed=True)
        self.control_outcome_guard = obs_lib.LabelGuard(
            seed=obs_lib.DECISION_OUTCOMES, closed=True)
        self.control_action_guard = obs_lib.LabelGuard(
            seed=control_mod.ACTIONS, closed=True)
        # Rollout plane (ISSUE 18): the RolloutLedger's hooks feed
        # these; the full closed phase/outcome grids are zero-seeded
        # below so every series exists on the first scrape.
        self.rollout_published = Counter(
            "fleet_rollout_published_total",
            "Model versions published to the registry by the trainer "
            "(POST /fleet/versions; idempotent re-publishes excluded)",
            self.registry)
        self.rollout_transitions = Counter(
            "fleet_rollout_transitions_total",
            "Rollout phase transitions — every one lands in exactly "
            "one of published / canarying / baking / promoting / "
            "rolled_back / completed (ledger conservation)",
            self.registry)
        self.rollout_reloads = Counter(
            "fleet_rollout_reloads_total",
            "Replica weight reloads dispatched by the RolloutManager "
            "(canary, promote wave, and rollback restores), by outcome",
            self.registry)
        self.rollout_active_g = Gauge(
            "fleet_rollout_active",
            "Rollouts currently in a non-terminal phase (0 or 1: the "
            "manager runs one rollout at a time)", self.registry)
        self.rollout_phase_guard = obs_lib.LabelGuard(
            seed=rollout_mod.PHASES, closed=True)
        self.rollout_outcome_guard = obs_lib.LabelGuard(
            seed=rollout_mod.RELOAD_OUTCOMES, closed=True)
        # Version label values come from TRAFFIC (the trainer mints
        # one per committed checkpoint), so the guard stays open but
        # capped — the parallel version-labelled fleet_replicas series
        # cannot outgrow it.
        self.version_guard = obs_lib.LabelGuard()
        # bound by bind_rollout; collect() reads it for the gauge
        self.rollout_ledger = None
        circuit_g = Gauge(
            "fleet_circuit_open",
            "1 while the replica's circuit breaker is open (skipped by "
            "fresh routing picks until the half-open probe)",
            self.registry)
        # zero-seed so the series exist (at 0) before any traffic —
        # the full closed reason x pool grid
        for reason in ROUTE_REASONS:
            for _pool in POOLS:
                self.route_total.inc(0, reason=reason, pool=_pool)
        self.hedge_wins.inc(0)
        self.failover.inc(0)
        self.handoff_bytes.inc(0)
        self.remote_hits.inc(0)
        self.rollout_published.inc(0)
        for _ph in rollout_mod.PHASES:
            self.rollout_transitions.inc(0, phase=_ph)
        for _oc in rollout_mod.RELOAD_OUTCOMES:
            self.rollout_reloads.inc(0, outcome=_oc)
        self.rollout_active_g.set(0)
        for _oc in ("ok", "skipped", "failed"):
            self.handoff_seconds.seed(outcome=_oc)

        def collect():
            reg.sweep()
            for _pool, states in reg.pool_counts().items():
                for state, nn in states.items():
                    replicas_g.set(nn, state=state,
                                   pool=self.pool_guard.admit(_pool))
            # Parallel version-labelled series in the SAME family
            # (ISSUE 18, the PR 13 tenant pattern): the unlabeled
            # {state, pool} totals above are untouched; {state,
            # version} series ride beside them, guard-capped. Every
            # known (state, version) cell is written each scrape so a
            # version that left the fleet drops to 0 instead of
            # freezing at its last count.
            by_ver: dict[tuple, int] = {}
            for rep in reg.replicas():
                ver = self.version_guard.admit(rep.version or "none")
                by_ver[(rep.state, ver)] = \
                    by_ver.get((rep.state, ver), 0) + 1
            for ver in self.version_guard.known():
                for state in STATES:
                    replicas_g.set(by_ver.get((state, ver), 0),
                                   state=state, version=ver)
            for rep in reg.replicas():
                circuit_g.set(int(reg.circuit_open(rep.id)),
                              replica=self.replica_guard.admit(rep.id))
            if self.rollout_ledger is not None:
                self.rollout_active_g.set(self.rollout_ledger.active)

        self.registry.register_collector(collect)

    def note_route(self, reason: str, pool: str) -> None:
        """One routing decision into the reason x pool counter (pool
        values outside the closed set collapse to the guard's
        overflow bucket — they cannot happen via the registry, which
        validates roles at the heartbeat door)."""
        self.route_total.inc(reason=reason,
                             pool=self.pool_guard.admit(pool))

    def bind_control(self, policy_names, ledger) -> None:
        """Wire one DecisionLedger into the decision-plane counters:
        zero-seed the full policy x outcome and policy x action grids
        (every series exists on the first scrape) and bind the
        ledger's hooks. The policy guard is rebuilt CLOSED over the
        configured names — a policy minted at runtime cannot grow the
        label set past the overflow bucket."""
        names = list(policy_names)
        self.control_policy_guard = obs_lib.LabelGuard(
            seed=names, closed=True)
        for p in names:
            for oc in obs_lib.DECISION_OUTCOMES:
                self.control_decisions.inc(0, policy=p, outcome=oc)
            for act in control_mod.ACTIONS:
                self.control_actions.inc(0, policy=p, action=act)
        ledger.on_decision = lambda p, oc: self.control_decisions.inc(
            policy=self.control_policy_guard.admit(p),
            outcome=self.control_outcome_guard.admit(oc))
        ledger.on_action = lambda p, act: self.control_actions.inc(
            policy=self.control_policy_guard.admit(p),
            action=self.control_action_guard.admit(act))

    def bind_rollout(self, versions, ledger) -> None:
        """Wire the rollout plane into the `fleet_rollout_*` counters:
        the VersionRegistry's publish hook and the RolloutLedger's
        phase hook feed the (already zero-seeded) series, and collect()
        starts reading the ledger for the active-rollout gauge. Version
        names pass the open-but-capped version guard before becoming
        label values."""
        versions.on_publish = lambda entry: (
            self.rollout_published.inc(),
            self.version_guard.admit(entry.get("version", "") or "none"),
        )
        ledger.on_phase = lambda v, ph: self.rollout_transitions.inc(
            phase=self.rollout_phase_guard.admit(ph))
        self.rollout_ledger = ledger

    def note_reload(self, outcome: str) -> None:
        """One RolloutManager-dispatched replica reload by outcome."""
        self.rollout_reloads.inc(
            outcome=self.rollout_outcome_guard.admit(outcome))


class _FleetState:
    # bounds on the heartbeat-fed checkpoint store: entries older than
    # the TTL describe requests that finished or already failed over
    CHECKPOINT_TTL_S = 60.0
    CHECKPOINT_CAP = 4096

    def __init__(self, registry: ReplicaRegistry, obs: FleetObs, *,
                 block_size: int, policy: str, hedge_after_s: float,
                 retries: int, backoff_s: float, timeout_s: float,
                 tenancy: TenancyConfig | None = None,
                 max_attempts: int | None = None, chaos=None,
                 peer_hints: bool = True):
        self.registry = registry
        self.obs = obs
        self.block_size = block_size
        self.policy = policy
        # X-KV-Peer heat hints (ISSUE 19): off = the control arm of
        # the cache-tier A/B (replicas never peer-fetch)
        self.peer_hints = peer_hints
        self.hedge_after_s = hedge_after_s
        self.retries = retries
        self.backoff_s = backoff_s
        self.timeout_s = timeout_s
        # retry BUDGET: total upstream dispatches one client request
        # may cost (primaries + retries + hedges together) — a slow
        # fleet must not amplify every request into an unbounded fan
        self.max_attempts = (max_attempts if max_attempts is not None
                             else retries + 2)
        self.session: aiohttp.ClientSession | None = None
        # round-robin cursor + membership snapshot (policy="roundrobin"
        # A/B arm): the sorted id tuple is rebuilt only when fleet
        # membership changes, and the cursor walks IT — not whatever
        # subset this request's exclusions left — so per-request
        # exclusions cannot bias the rotation (ISSUE 12 satellite)
        self.rr = 0
        self.rr_ids: tuple[str, ...] = ()
        # fleet.chaos.ChaosInjector (loadtest --mode chaos): seeded
        # fault hooks on the router->replica path. None in production.
        self.chaos = chaos
        # request_id -> {"ck": checkpoint, "replica": id, "t": stamp}
        # fed by heartbeats; read by the failover paths when the
        # owning replica dies mid-request
        self.checkpoints: dict[str, dict] = {}
        # Router-side tenant rate limiting: the same TenancyConfig the
        # replicas run, enforced at the fleet door so a flooding tenant
        # is shed ONCE here instead of N times downstream. The replicas
        # keep their own ledgers (per-replica limits still apply).
        self.tenancy = tenancy
        self.ledger = TenantLedger(tenancy) if tenancy is not None \
            else None
        # Closed-loop control (ISSUE 16): the controller and its
        # background task, plus the scale_out actuator's desired-
        # replica floor (absolute count, TTL'd) that /fleet/autoscale
        # folds into its recommendation.
        self.controller = None
        self.control_task: asyncio.Task | None = None
        self.control_floor = 0
        self.control_floor_until = float("-inf")
        # shift_pool_split actuator (ISSUE 19): TTL'd lean of the
        # prefill/decode recommendation toward decode, in replicas
        self.pool_shift = 0
        self.pool_shift_until = float("-inf")
        # Rollout plane (ISSUE 18): version registry, conservation-
        # checked phase ledger, manager + its background task. Always
        # constructed by create_router_app (like the controller) so
        # /fleet/versions and /fleet/rollouts answer even when the
        # background loop is off.
        self.versions: rollout_mod.VersionRegistry | None = None
        self.rollout_ledger: rollout_mod.RolloutLedger | None = None
        self.rollout: rollout_mod.RolloutManager | None = None
        self.rollout_task: asyncio.Task | None = None

    def ingest_checkpoints(self, replica_id: str, cks) -> None:
        """Fold one heartbeat's sequence checkpoints into the store
        (bounded: stale entries pruned, oldest dropped over the cap)."""
        now = time.monotonic()
        if isinstance(cks, list):
            for ck in cks[:512]:
                if not isinstance(ck, dict):
                    continue
                rid = str(ck.get("request_id", ""))
                if rid:
                    self.checkpoints[rid] = {
                        "ck": ck, "replica": replica_id, "t": now}
        stale = now - self.CHECKPOINT_TTL_S
        for rid in [r for r, e in self.checkpoints.items()
                    if e["t"] < stale]:
            del self.checkpoints[rid]
        while len(self.checkpoints) > self.CHECKPOINT_CAP:
            oldest = min(self.checkpoints, key=lambda r:
                         self.checkpoints[r]["t"])
            del self.checkpoints[oldest]

    def checkpoint_for(self, request_id: str) -> dict | None:
        entry = self.checkpoints.get(request_id)
        if entry is None or (time.monotonic() - entry["t"]
                             > self.CHECKPOINT_TTL_S):
            return None
        return entry["ck"]


class _UpstreamError(RuntimeError):
    """Replica-side failure (connect error, timeout, 5xx) — retryable
    on another replica, unlike a 4xx which is the client's problem."""


@web.middleware
async def _router_obs_middleware(request: web.Request, handler):
    st: _FleetState = request.app[FLEET_KEY]
    resource = getattr(request.match_info.route, "resource", None)
    route = getattr(resource, "canonical", None) or "unmatched"
    with st.obs.tracer.span("fleet.request", method=request.method,
                            route=route) as span:
        try:
            resp = await handler(request)
            span.attrs["status"] = resp.status
            if not resp.prepared:
                resp.headers.setdefault("X-Trace-Id", span.trace_id)
            return resp
        except web.HTTPException as exc:
            span.attrs["status"] = exc.status
            exc.headers.setdefault("X-Trace-Id", span.trace_id)
            raise


def _choose(st: _FleetState, key: bytes, exclude: set,
            pool: str | None = None):
    """One routing decision under the configured policy. `pool`
    narrows candidates to one disaggregation role (registry.pick
    relaxes to the whole fleet when the pool is empty). The
    "roundrobin" policy exists for the affinity-vs-random A/B
    (loadtest --fleet-policy roundrobin), labels as fallback, and is
    pool-blind — the A/B control arm measures the symmetric fleet."""
    if st.policy == "roundrobin":
        cands = st.registry.routable(exclude)
        if not cands:
            st.registry.sweep()
            cands = st.registry.routable(exclude)
        if not cands:
            return None, "fallback"
        # O(1) round-robin over a STABLE membership snapshot: re-sort
        # only when the routable id set actually changed, then advance
        # one persistent cursor over the snapshot, skipping this
        # request's exclusions — `cursor % len(subset)` over a
        # per-request subset would both re-sort every request and bias
        # the rotation whenever exclusions shrink the list.
        by_id = {r.id: r for r in cands}
        full = {r.id for r in st.registry.routable(frozenset())} or \
            set(by_id)
        if full != set(st.rr_ids):
            st.rr_ids = tuple(sorted(full))
            st.rr %= len(st.rr_ids)
        for _ in range(len(st.rr_ids)):
            rid = st.rr_ids[st.rr % len(st.rr_ids)]
            st.rr += 1
            rep = by_id.get(rid)
            if rep is not None:
                return rep, "fallback"
        # snapshot exhausted without a routable hit (all excluded):
        # fall back to the first candidate rather than 503
        return cands[0], "fallback"
    return st.registry.pick(key, exclude, pool=pool)


def _inject_trace_context(st: _FleetState, headers: dict) -> dict:
    """Propagate the CURRENT span's context into an upstream dispatch:
    the replica's middleware adopts `X-Trace-Id`/`X-Parent-Span` via
    `Tracer.span_from_remote`, so its segment commits under the
    router's trace id. Copied per dispatch — retries and hedges each
    carry the live span ids."""
    span = st.obs.tracer.current_span()
    if span is None:
        return headers
    return {**headers, "X-Trace-Id": span.trace_id,
            "X-Parent-Span": span.span_id}


async def _chaos_shadow(st: _FleetState, url: str, raw: bytes,
                        headers: dict) -> None:
    """Fire-and-forget duplicate dispatch (chaos 'duplicate' fault):
    exercises at-least-once delivery — the replica must tolerate the
    same request body arriving twice. The shadow's outcome is
    discarded."""
    try:
        async with st.session.post(
                url, data=raw, headers=headers,
                timeout=aiohttp.ClientTimeout(total=st.timeout_s)) as r:
            await r.read()
    except Exception:  # noqa: BLE001 — shadow outcomes never surface
        pass


async def _chaos_gate(st: _FleetState, rep, name: str, raw: bytes,
                      headers: dict) -> None:
    """Apply the injector's dispatch faults for one router->replica
    call: may sleep (delay), spawn a duplicate shadow dispatch, or
    raise `_UpstreamError` (drop)."""
    if st.chaos is None:
        return
    action = await st.chaos.before_dispatch(rep.id)
    if action == "duplicate":
        asyncio.ensure_future(_chaos_shadow(
            st, f"{rep.url}/v1/models/{name}:generate", raw, headers))
    elif action == "drop":
        raise _UpstreamError(f"chaos: dropped dispatch to {rep.id}")


async def _call_replica(st: _FleetState, rep, name: str, raw: bytes,
                        tried: set, headers: dict, body=None):
    """One proxied generate against one replica. Success returns
    (status, payload, replica, upstream_trace_id); replica-side
    failures mark the replica, add it to `tried`, and raise
    `_UpstreamError` so the caller moves on. `body` (the parsed
    request, when the caller has it) enables the per-TARGET
    `X-KV-Peer` heat hint — it must be computed here, against the
    replica actually dialed, because a hedge dispatch goes to a
    different replica whose digest changes the answer."""
    headers = _with_peer_hint(st, body, rep, headers)
    st.registry.note_dispatch(rep.id)
    try:
        await _chaos_gate(st, rep, name, raw, headers)
        async with st.session.post(
                f"{rep.url}/v1/models/{name}:generate", data=raw,
                headers=_inject_trace_context(st, headers),
                timeout=aiohttp.ClientTimeout(total=st.timeout_s)) as r:
            payload = await r.read()
            if r.status >= 500:
                raise _UpstreamError(
                    f"replica {rep.id} answered {r.status}")
            st.registry.note_success(rep.id)
            return r.status, payload, rep, r.headers.get("X-Trace-Id", "")
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
            _UpstreamError) as e:
        st.registry.note_failure(rep.id)
        tried.add(rep.id)
        raise _UpstreamError(str(e)) from e
    finally:
        st.registry.note_done(rep.id)


async def _race_hedged(st: _FleetState, primary, name: str, raw: bytes,
                       key: bytes, tried: set, model: str,
                       headers: dict, budget: list,
                       pool: str | None = None, body=None):
    """Dispatch to `primary`; past the hedge deadline, duplicate to a
    second replica (from the same disaggregation `pool`, if any) and
    take whichever answers first. Every dispatch (primary and hedge
    alike) spends one unit of the request's attempt `budget` — a hedge
    is skipped once the budget is gone. Returns
    (status, payload, replica, hedge_won, upstream_trace) or None when
    every dispatched replica failed (all are in `tried` by then)."""
    budget[0] -= 1
    tasks = {asyncio.create_task(_call_replica(st, primary, name, raw,
                                               tried, headers,
                                               body=body))}
    hedged_id = None
    if st.hedge_after_s > 0:
        done, _pending = await asyncio.wait(tasks,
                                            timeout=st.hedge_after_s)
        if not done and budget[0] > 0:
            hedge_rep, _ = _choose(st, key, tried | {primary.id}, pool)
            if hedge_rep is not None:
                budget[0] -= 1
                hedged_id = hedge_rep.id
                st.obs.note_route("hedge", hedge_rep.pool)
                tasks.add(asyncio.create_task(_call_replica(
                    st, hedge_rep, name, raw, tried, headers,
                    body=body)))
    winner = None
    pending = tasks
    while pending:
        done, pending = await asyncio.wait(
            pending, return_when=asyncio.FIRST_COMPLETED)
        for t in done:
            if not t.cancelled() and t.exception() is None:
                winner = t
                break
        if winner is not None:
            break
    for t in pending:
        t.cancel()
    if pending:
        await asyncio.gather(*pending, return_exceptions=True)
    if winner is None:
        return None
    status, payload, rep, trace = winner.result()
    hedge_won = hedged_id is not None and rep.id == hedged_id
    if hedge_won:
        st.obs.hedge_wins.inc()
    return status, payload, rep, hedge_won, trace


def _tenant_gate(st: _FleetState, request: web.Request):
    """Tenant admission at the fleet door. Returns (forward_headers,
    None) when admitted, or (None, 429 response) when the tenant's
    request bucket is empty. Always forwards X-Tenant so the replica's
    own ledger/scheduler sees the same identity the router billed."""
    headers = {"Content-Type": "application/json"}
    tenant_hdr = request.headers.get("X-Tenant", "")
    if tenant_hdr:
        headers["X-Tenant"] = tenant_hdr
    if st.ledger is not None:
        tname = st.tenancy.resolve(tenant_hdr).name
        try:
            st.ledger.check_request(tname)
        except Throttled as e:
            st.obs.tenant_throttled.inc(tenant=tname)
            return None, web.json_response(
                {"error": str(e)}, status=429,
                headers={"Retry-After": str(max(1, min(
                    60, math.ceil(e.retry_after))))})
        st.obs.tenant_requests.inc(tenant=tname)
    elif tenant_hdr:
        # tenant-blind router still counts per tenant, behind the
        # cardinality guard (the header is raw client input here)
        st.obs.tenant_requests.inc(
            tenant=st.obs.tenant_guard.admit(tenant_hdr))
    return headers, None


def _handoff_body(body, peer: str) -> bytes | None:
    """Build the `:prefill` dispatch body: the prompt plus the decode
    peer the prefill replica ships its KV blocks to. Returns None for
    shapes the handoff endpoint cannot serve (batched prompts,
    registered-prefix expansion, no prompt at all) — the caller then
    skips the handoff and lets the decode pool prefill for itself."""
    if not isinstance(body, dict):
        return None
    if body.get("prefix"):
        return None
    nb: dict = {"peer": peer}
    toks = body.get("tokens")
    text = body.get("text")
    if isinstance(toks, list) and toks:
        if isinstance(toks[0], list):
            if len(toks) != 1 or not toks[0]:
                return None
            nb["tokens"] = toks
        else:
            nb["tokens"] = [toks]
    elif isinstance(text, str) and text:
        nb["text"] = text
    else:
        return None
    return json.dumps(nb).encode()


def _prompt_tokens(body) -> int:
    """Prompt length in tokens (byte count for text bodies — the
    router's byte-tokenizer mirror, same as affinity_key). 0 for
    shapes the handoff cannot serve."""
    if not isinstance(body, dict):
        return 0
    toks = body.get("tokens")
    if isinstance(toks, list) and toks:
        if isinstance(toks[0], list):
            return len(toks[0]) if len(toks) == 1 else 0
        return len(toks)
    text = body.get("text")
    if isinstance(text, str):
        return len(text.encode())
    return 0


async def _disagg_handoff(st: _FleetState, name: str, body,
                          key: bytes, rid: str, headers: dict):
    """Prefill->decode handoff for one request on a disaggregated
    fleet. Picks the least-loaded decode replica as the KV
    destination, then asks a prefill replica (affinity-picked, so
    shared prompt prefixes keep landing on the same prefill replica's
    radix cache) to prefill the prompt and push its paged KV blocks to
    that peer over `/v1/migrate/in`. Returns the decode replica to pin
    the generate dispatch to — or None when the fleet has no decode
    target, in which case the caller routes as a symmetric fleet.

    Best-effort BY DESIGN: a failed or skipped handoff only costs the
    decode replica a redundant prefill (the generate path prefills
    whatever the radix cache does not already hold), so a prefill
    replica dying mid-handoff is retried once and then abandoned
    without ever surfacing to the client."""
    decode_rep, _ = st.registry.pick(b"", frozenset(), pool=DECODE)
    if decode_rep is None or decode_rep.pool != DECODE:
        return None
    if _prompt_tokens(body) < st.block_size:
        # shorter than one KV block: nothing full-block to ship, the
        # decode replica's own prefill is strictly cheaper than a
        # handoff round-trip — pin to the decode pool and move on
        return decode_rep
    raw = _handoff_body(body, decode_rep.url)
    if raw is None:
        st.obs.handoff_seconds.observe(0.0, outcome="skipped")
        return decode_rep
    t0 = time.perf_counter()
    outcome = "failed"
    tried: set[str] = set()
    hdrs = _inject_trace_context(st, {**headers, "X-Request-Id": rid})
    for _ in range(2):  # one in-pool retry: covers a prefill SIGKILL
        pre, reason = st.registry.pick(key, tried, pool=PREFILL)
        if pre is None or pre.pool != PREFILL or pre.id in tried:
            break
        st.obs.note_route(reason, pre.pool)
        st.registry.note_dispatch(pre.id)
        try:
            async with st.session.post(
                    f"{pre.url}/v1/models/{name}:prefill", data=raw,
                    headers=hdrs,
                    timeout=aiohttp.ClientTimeout(
                        total=st.timeout_s)) as r:
                if r.status >= 500:
                    raise _UpstreamError(f"prefill {r.status}")
                pj = await r.json(content_type=None)
                st.registry.note_success(pre.id)
                if (r.status == 200 and isinstance(pj, dict)
                        and pj.get("handoff")):
                    outcome = "ok"
                    nbytes = int(pj.get("bytes", 0) or 0)
                    if nbytes > 0:
                        st.obs.handoff_bytes.inc(nbytes)
                else:
                    # prefill ran but the KV push did not land (peer
                    # draining, prompt shorter than a block, ...):
                    # not a replica fault, don't retry
                    outcome = "skipped"
                break
        except (_UpstreamError, aiohttp.ClientError,
                asyncio.TimeoutError, OSError):
            st.registry.note_failure(pre.id)
            tried.add(pre.id)
        finally:
            st.registry.note_done(pre.id)
    st.obs.handoff_seconds.observe(time.perf_counter() - t0,
                                   outcome=outcome)
    return decode_rep


def _pick_target(st: _FleetState, key: bytes, exclude: set,
                 pool: str | None, pinned):
    """One generate-dispatch choice, honoring a handoff pin: the
    decode replica now holding this request's prefilled KV blocks is
    preferred (its radix cache turns the generate's prefill into a
    lookup) until it fails once, then routing falls back to the
    normal pool-aware policy."""
    if pinned is not None and pinned.id not in exclude:
        rep = st.registry.get(pinned.id)
        if rep is not None and rep.state in (READY, DEGRADED):
            return rep, "affinity"
    return _choose(st, key, exclude, pool)


def _note_counterfactual(st: "_FleetState", body, rep) -> None:
    """Counterfactual fleet prefix hit (ISSUE 13): the request landed
    on `rep` whose heartbeat heat digest does NOT show its routing
    prefix (so the replica almost certainly prefilled it cold), while
    some OTHER replica's digest shows it hot — a cross-replica cache
    tier would have served this prefix remotely. Hashes join because
    replica digests and this check both run `prefix_hash` over the
    same first-KV-block token slice (namespaced tenant entries are
    salted and simply never match — conservative undercount)."""
    toks = affinity_tokens(body, st.block_size)
    if not toks:
        return
    h = obs_lib.prefix_hash(toks)
    if any(e.get("prefix") == h for e in rep.cache_digest):
        return
    for other in st.registry.replicas():
        if other.id != rep.id and any(
                e.get("prefix") == h for e in other.cache_digest):
            st.obs.remote_hits.inc()
            return


def _with_peer_hint(st: "_FleetState", body, rep,
                    headers: dict) -> dict:
    """Attach the `X-KV-Peer` heat hint for one dispatch target: when
    `rep`'s heartbeat digest does NOT show this request's routing
    prefix but a live peer's digest does — exactly the condition
    `fleet_prefix_remote_hits_total` counts as a missed remote hit —
    the hint names the hottest carrier so the replica can pull the
    prefix's KV blocks instead of prefilling cold. Returns `headers`
    untouched (same object) when no hint applies; the hint rides a
    COPY, because the caller reuses its dict across retries/hedges
    to different targets."""
    if not getattr(st, "peer_hints", True):
        return headers                      # A/B control arm
    if not isinstance(body, dict) or body.get("prefix"):
        # registered-prefix expansion happens replica-side; the
        # router cannot name the expanded first block
        return headers
    toks = affinity_tokens(body, st.block_size)
    if not toks or len(toks) < st.block_size:
        # shorter than one full block: nothing a peer could export
        return headers
    h = obs_lib.prefix_hash(toks)
    if any(e.get("prefix") == h for e in rep.cache_digest):
        return headers                      # target already hot
    carriers = st.registry.digest_carriers(h, exclude=rep.id)
    if not carriers:
        return headers
    out = dict(headers)
    out["X-KV-Peer"] = carriers[0].url
    return out


async def _routed_generate(request: web.Request):
    st: _FleetState = request.app[FLEET_KEY]
    name = request.match_info["name"]
    raw = await request.read()
    try:
        body = json.loads(raw)
    except Exception:
        return web.json_response({"error": "invalid JSON"}, status=400)
    fwd_headers, throttled = _tenant_gate(st, request)
    if throttled is not None:
        return throttled
    # Router-minted request id: forwarded to every dispatch (the
    # replica keys its token timeline and sequence checkpoints by it),
    # so a failover resume finds the dead replica's checkpoint and the
    # timeline survives the hop.
    rid = request.headers.get("X-Request-Id") or secrets.token_hex(8)
    fwd_headers["X-Request-Id"] = rid
    key = affinity_key(body, st.block_size)
    # Disaggregated fleet: prefill the prompt on the prefill pool and
    # ship its KV blocks to a decode replica BEFORE dispatching the
    # generate, then pin the generate to that decode replica — its
    # radix cache turns the shipped prefix into a cache hit. The
    # handoff is best-effort; on any failure the generate simply goes
    # to the decode pool, which prefills for itself.
    pool: str | None = None
    pinned = None
    if st.registry.disaggregated():
        pool = DECODE
        pinned = await _disagg_handoff(st, name, body, key, rid,
                                       fwd_headers)
        if pinned is None:
            pool = None
    if isinstance(body, dict) and body.get("stream"):
        return await _routed_stream(request, st, name, raw, body,
                                    fwd_headers, rid, pool=pool,
                                    pinned=pinned)
    t0 = time.perf_counter()
    tried: set[str] = set()
    budget = [st.max_attempts]
    with st.obs.tracer.span("fleet.route", model=name) as span:
        for attempt in range(st.retries + 1):
            if budget[0] <= 0:
                break
            replica, reason = _pick_target(st, key, tried, pool, pinned)
            if replica is None and tried:
                # every routable replica failed once this request:
                # transient faults (a chaos drop, a connection blip)
                # deserve a fresh sweep while attempt budget remains —
                # persistent corpses are held off by their circuit
                # breakers, not by this per-request memory
                tried.clear()
                replica, reason = _pick_target(st, key, tried, pool,
                                               pinned)
            if replica is None:
                # fleet-wide blip: every replica dead or draining for a
                # beat (a lone survivor can trip its breaker to DEAD
                # with the heartbeat that would resurrect it still in
                # flight). Burn a retry waiting — the sleep yields the
                # event loop so that heartbeat can land — instead of
                # 503ing with attempt budget left.
                await asyncio.sleep(
                    min(st.backoff_s * (2 ** attempt), 1.0))
                continue
            if attempt:
                reason = "retry"
                await asyncio.sleep(
                    min(st.backoff_s * (2 ** (attempt - 1)), 1.0))
            # crash failover: a retry whose dead replica checkpointed
            # partial output resumes from it (re-prefill, decode only
            # the remainder) instead of regenerating from scratch
            dispatch_raw, prepend = raw, []
            ck = st.checkpoint_for(rid) if attempt else None
            if (ck is not None and ck.get("out")
                    and isinstance(body, dict)
                    and not body.get("logprobs")):
                rb, remaining = _resume_from_checkpoint(
                    body, ck, list(ck["out"]))
                if rb is not None and remaining > 0:
                    dispatch_raw, prepend = rb, list(ck["out"])
            result = await _race_hedged(st, replica, name,
                                        dispatch_raw, key, tried,
                                        name, fwd_headers, budget,
                                        pool=pool, body=body)
            if result is None:
                continue  # dispatched replicas failed; retry others
            status, payload, rep, hedge_won, trace = result
            if prepend and status == 200:
                payload = _splice_oneshot(
                    payload, prepend,
                    isinstance(body, dict) and "text" in body)
                st.obs.failover.inc()
            dt = time.perf_counter() - t0
            st.obs.note_route(reason, rep.pool)
            _note_counterfactual(st, body, rep)
            st.obs.route_latency.observe(dt, model=name, reason=reason)
            st.obs.slo.observe("fleet_route_latency", dt)
            st.obs.slo.record("fleet_availability", status < 500)
            if st.rollout is not None:
                # passive canary feed: latency/status attributed to the
                # answering replica's version (never throws)
                st.rollout.observe_request(rep.version, dt,
                                           status < 500)
            span.attrs.update(replica=rep.id, reason=reason,
                              hedge_won=hedge_won, status=status)
            if trace:
                span.attrs["replica_trace"] = trace
            headers = {"X-Fleet-Replica": rep.id,
                       "X-Fleet-Route-Reason": reason,
                       "X-Request-Id": rid}
            if trace:
                headers["X-Fleet-Replica-Trace"] = trace
            return web.Response(body=payload, status=status,
                                content_type="application/json",
                                headers=headers)
        span.attrs["status"] = 503
    st.obs.slo.record("fleet_availability", False)
    return web.json_response(
        {"error": "no serving replica available"}, status=503,
        headers={"Retry-After": "1"})


async def _routed_stream(request: web.Request, st: _FleetState,
                         name: str, raw: bytes, body: dict,
                         fwd_headers: dict, rid: str,
                         pool: str | None = None, pinned=None):
    """SSE with mid-stream failover. The router PARSES the upstream
    event stream instead of blind passthrough: token events are
    re-emitted to the client as they arrive, and when the replica dies
    mid-stream (connection cut, 5xx, or a terminal error event) the
    router picks another replica, resumes from the heartbeat
    checkpoint — or re-issues the request and swallows the tokens the
    client already has — and splices the two halves into ONE stream
    with no duplicate or missing tokens. Retries before the first
    byte behave as before. No hedging: duplicating a stream would
    decode the prompt twice for one winner on every long request."""
    key = affinity_key(body, st.block_size)
    tried: set[str] = set()
    sent: list[int] = []   # token ids already forwarded to the client
    resp: web.StreamResponse | None = None
    text_mode = isinstance(body, dict) and "text" in body
    budget = st.max_attempts
    failed_over = False
    final_evt: dict | None = None
    for attempt in range(st.retries + 1):
        if budget <= 0 or final_evt is not None:
            break
        replica, reason = _pick_target(st, key, tried, pool, pinned)
        if replica is None and tried:
            # same fresh sweep as the one-shot path: a transient fault
            # on the last untried replica must not strand the stream
            # while attempt budget remains
            tried.clear()
            replica, reason = _pick_target(st, key, tried, pool, pinned)
        if replica is None:
            # same fleet-wide-blip wait as the one-shot path: hold the
            # stream open through a beat where nobody is routable
            # rather than abandoning it with budget left
            await asyncio.sleep(min(st.backoff_s * (2 ** attempt), 1.0))
            continue
        if attempt:
            reason = "retry"
            await asyncio.sleep(
                min(st.backoff_s * (2 ** (attempt - 1)), 1.0))
        dispatch_raw, skip = raw, 0
        if sent:
            # mid-stream failover: prefer the checkpoint (re-prefill
            # only), else splice onto the client's own token prompt,
            # else replay in full and swallow what was already sent
            ck = st.checkpoint_for(rid)
            if ck is not None and isinstance(ck.get("out"), list):
                rb, remaining = _resume_from_checkpoint(body, ck, sent)
                if remaining <= 0:
                    final_evt = {"done": True, "total": len(sent)}
                    break
                if rb is not None:
                    dispatch_raw = rb
            else:
                rb = _resume_from_body(body, sent)
                if rb is not None:
                    dispatch_raw = rb
                else:
                    dispatch_raw, skip = raw, len(sent)
            if not failed_over:
                failed_over = True
                st.obs.failover.inc()
        # per-target heat hint: recomputed every attempt because the
        # failover replica's digest (and the live peer set) differ
        hdrs = _with_peer_hint(st, body, replica, fwd_headers)
        st.registry.note_dispatch(replica.id)
        budget -= 1
        try:
            await _chaos_gate(st, replica, name, dispatch_raw,
                              hdrs)
            async with st.session.post(
                    f"{replica.url}/v1/models/{name}:generate",
                    data=dispatch_raw,
                    headers=_inject_trace_context(st, hdrs),
                    timeout=aiohttp.ClientTimeout(
                        total=st.timeout_s)) as up:
                if up.status >= 500:
                    st.registry.note_failure(replica.id)
                    tried.add(replica.id)
                    continue
                if up.content_type != "text/event-stream":
                    payload = await up.read()
                    if resp is None:
                        # replica rejected pre-stream (4xx): passthrough
                        st.obs.note_route(reason, replica.pool)
                        return web.Response(
                            body=payload, status=up.status,
                            content_type="application/json",
                            headers={"X-Fleet-Replica": replica.id,
                                     "X-Request-Id": rid})
                    # resume rejected (e.g. peer started draining):
                    # retryable, the client stream is still open
                    tried.add(replica.id)
                    continue
                st.obs.note_route(reason, replica.pool)
                _note_counterfactual(st, body, replica)
                if resp is None:
                    headers = {
                        "Content-Type": "text/event-stream",
                        "Cache-Control": "no-cache",
                        "X-Fleet-Replica": replica.id,
                        "X-Request-Id": rid,
                    }
                    up_trace = up.headers.get("X-Trace-Id", "")
                    if up_trace:
                        headers["X-Fleet-Replica-Trace"] = up_trace
                    resp = web.StreamResponse(headers=headers)
                    await resp.prepare(request)
                buf = b""
                to_skip = skip
                upstream_error = False
                async for chunk in up.content.iter_any():
                    buf += chunk
                    while b"\n\n" in buf:
                        frame, buf = buf.split(b"\n\n", 1)
                        ev = _parse_sse_event(frame)
                        if ev is None:
                            continue
                        if "error" in ev:
                            # terminal error event: NOT forwarded —
                            # the router absorbs it and fails over
                            upstream_error = True
                            break
                        if ev.get("done"):
                            final_evt = ev
                            break
                        toks = ev.get("tokens")
                        if (not isinstance(toks, list) or not toks
                                or not isinstance(toks[0], list)
                                or not toks[0]):
                            continue
                        for tok in toks[0]:
                            if to_skip > 0:
                                to_skip -= 1
                                continue
                            sent.append(int(tok))
                            await resp.write(
                                b"data: " + json.dumps(
                                    {"tokens": [[int(tok)]]}).encode()
                                + b"\n\n")
                    if upstream_error or final_evt is not None:
                        break
                if upstream_error or final_evt is None:
                    # error event or connection ended with no terminal
                    # frame: the replica is gone mid-stream
                    st.registry.note_failure(replica.id)
                    tried.add(replica.id)
                    continue
                st.registry.note_success(replica.id)
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                _UpstreamError):
            # _UpstreamError covers a chaos-gate drop BEFORE the
            # dispatch: same failover path as a replica dying mid-frame
            st.registry.note_failure(replica.id)
            tried.add(replica.id)
        finally:
            st.registry.note_done(replica.id)
    if resp is None:
        return web.json_response(
            {"error": "no serving replica available"}, status=503,
            headers={"Retry-After": "1"})
    if final_evt is None:
        final = {"error": "no serving replica available",
                 "total": len(sent)}
    else:
        final = dict(final_evt)
        final["total"] = len(sent)
        if failed_over and final.get("done") and text_mode:
            # the resumed replica only saw the tail; rebuild the text
            # over the FULL spliced output (byte tokenizer mirror)
            final["text"] = _byte_decode_fleet(sent)
    await resp.write(b"data: " + json.dumps(final).encode() + b"\n\n")
    await resp.write_eof()
    return resp


# -- fleet control-plane endpoints ---------------------------------------


async def _register(request: web.Request):
    st: _FleetState = request.app[FLEET_KEY]
    try:
        body = await request.json()
    except Exception:
        return web.json_response({"error": "invalid JSON"}, status=400)
    url = body.get("url")
    if not isinstance(url, str) or not url.startswith("http"):
        return web.json_response(
            {"error": "body needs an http 'url'"}, status=400)
    models = body.get("models", [])
    if not isinstance(models, list):
        models = []
    rep = st.registry.register(
        url.rstrip("/"), replica_id=str(body.get("id", "")),
        models=[m for m in models if isinstance(m, str)],
        **{k: v for k, v in body.items()
           if k in ("queue_depth", "active_slots", "max_slots",
                    "kv_blocks_free", "kv_blocks_total",
                    "pool", "phase_seconds", "cache_digest",
                    "version")})
    st.ingest_checkpoints(rep.id, body.get("checkpoints"))
    log.info("fleet: registered replica %s at %s", rep.id, rep.url)
    return web.json_response({"id": rep.id, "state": rep.state})


async def _heartbeat(request: web.Request):
    st: _FleetState = request.app[FLEET_KEY]
    try:
        body = await request.json()
    except Exception:
        return web.json_response({"error": "invalid JSON"}, status=400)
    rid = str(body.get("id", ""))
    if st.chaos is not None and st.chaos.heartbeat_blackholed(rid):
        # chaos blackhole: swallow the beat (the replica believes it
        # landed; the sweeper sees staleness) — the crash-detection
        # path without killing anything
        return web.json_response({"ok": True})
    # sequence checkpoints ride the heartbeat raw payload (they are
    # NOT registry stats): fold them into the failover store first
    st.ingest_checkpoints(rid, body.get("checkpoints"))
    ok = st.registry.heartbeat(rid, **{
        k: v for k, v in body.items()
        if k in ("queue_depth", "active_slots", "max_slots",
                 "kv_blocks_free", "kv_blocks_total", "draining",
                 "pool", "phase_seconds", "cache_digest",
                 "version")})
    if not ok:
        # unknown id: the router restarted and lost its table — 404
        # tells the replica to re-register (server.py's beat loop does)
        return web.json_response(
            {"error": f"unknown replica {rid!r}"}, status=404)
    return web.json_response({"ok": True})


async def _deregister(request: web.Request):
    st: _FleetState = request.app[FLEET_KEY]
    try:
        body = await request.json()
    except Exception:
        return web.json_response({"error": "invalid JSON"}, status=400)
    rid = str(body.get("id", ""))
    removed = st.registry.deregister(rid)
    if removed:
        log.info("fleet: deregistered replica %s", rid)
    return web.json_response({"removed": removed})


async def _drain(request: web.Request):
    """Mark a replica draining in the table AND forward the drain to
    the replica itself — the scale-down path the ModelServer
    controller models. INSTANT drain: when healthy peers exist, the
    forwarded drain carries `{"migrate": true, "peers": [...]}` so the
    replica pushes every in-flight sequence (KV blocks included) to
    them and can exit in seconds instead of waiting out its longest
    generation. A lone replica falls back to the legacy wait-out
    drain — there is nowhere to migrate to."""
    st: _FleetState = request.app[FLEET_KEY]
    try:
        body = await request.json()
    except Exception:
        return web.json_response({"error": "invalid JSON"}, status=400)
    rid = str(body.get("id", ""))
    if st.registry.get(rid) is None:
        return web.json_response(
            {"error": f"unknown replica {rid!r}"}, status=404)
    out = await drain_and_migrate(st, rid,
                                  migrate=body.get("migrate", True))
    return web.json_response(out)


async def drain_and_migrate(st: _FleetState, rid: str, *,
                            migrate: bool = True) -> dict:
    """Drain one replica: mark it draining in the table and forward
    the drain (with migrate peers when any exist). Shared by the
    `/fleet/drain` handler and the controller's `drain_replica`
    actuator — the closed loop fires the exact code path an operator
    would."""
    rep = st.registry.get(rid)
    if rep is None:
        raise KeyError(f"unknown replica {rid!r}")
    st.registry.drain(rid)
    # migrated KV describes the SOURCE replica's weights — mid-rollout,
    # landing it on a peer serving a different version would finish the
    # generation with the wrong model. Only same-version peers qualify;
    # with none (the last replica of a version to roll), the reload's
    # admission-stopped grace wait finishes in-flight work in place.
    peers = sorted((r for r in st.registry.routable({rid})
                    if r.version == rep.version),
                   key=lambda r: (r.load(), r.id))
    migrate = bool(peers) and migrate
    payload = ({"migrate": True, "peers": [r.url for r in peers]}
               if migrate else None)
    forwarded: dict = {}
    try:
        async with st.session.post(
                f"{rep.url}/drain", json=payload,
                timeout=aiohttp.ClientTimeout(
                    total=30 if migrate else 5)) as r:
            if r.content_type == "application/json":
                forwarded = await r.json()
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
        pass  # marking it draining here already stops routing
    return {"id": rid, "state": "draining", "replica": forwarded}


async def _placements(request: web.Request):
    """GET /fleet/placements?exclude=a,b — advisory migration targets:
    healthy peers (least-loaded first) a draining replica should push
    its sequences to. `/fleet/drain` computes the same list itself;
    this endpoint serves operators and the chaos harness."""
    st: _FleetState = request.app[FLEET_KEY]
    st.registry.sweep()
    excl = {e for e in
            request.rel_url.query.get("exclude", "").split(",") if e}
    peers = sorted(st.registry.routable(excl),
                   key=lambda r: (r.load(), r.id))
    return web.json_response({"peers": [r.url for r in peers],
                              "ids": [r.id for r in peers]})


async def _replicas(request: web.Request):
    st: _FleetState = request.app[FLEET_KEY]
    st.registry.sweep()
    now = st.registry.clock()
    out = []
    for rep in st.registry.replicas():
        snap = rep.snapshot()
        snap["last_heartbeat_age_s"] = round(now - rep.last_heartbeat, 3)
        out.append(snap)
    return web.json_response({
        "replicas": out,
        "counts": st.registry.counts(),
        "pools": st.registry.pool_counts(),
        "disaggregated": st.registry.disaggregated(),
    })


async def _autoscale(request: web.Request):
    """GET /fleet/autoscale[?pools=1] — replica-count recommendation.
    With `pools=1` the response adds the prefill/decode split driven
    by the fleet's phase-seconds shares (autoscale.recommend_pools);
    the min defaults to 2 there so both pools can hold a replica.
    When the controller's scale_out actuator has raised a desired
    floor (and its TTL has not lapsed), `desired` is lifted to it —
    the infra layer polling this endpoint is the dumb half of the
    closed loop."""
    st: _FleetState = request.app[FLEET_KEY]
    st.registry.sweep()
    q = request.rel_url.query
    pools = q.get("pools", "") not in ("", "0", "false")
    floor = (st.control_floor
             if st.registry.clock() < st.control_floor_until else 0)
    try:
        lo = int(q.get("min", 2 if pools else 1))
        hi = int(q.get("max", 8))
        if pools:
            prec = autoscale.recommend_pools(
                st.registry.replicas(), min_replicas=lo,
                max_replicas=hi)
            # controller lean (shift_pool_split, TTL'd): move whole
            # replicas of the recommendation from prefill to decode,
            # never below one prefill replica
            shift = (st.pool_shift
                     if st.registry.clock() < st.pool_shift_until
                     else 0)
            prefill, decode = prec.prefill, prec.decode
            if shift:
                total = prefill + decode
                decode = min(total - 1, decode + shift)
                prefill = total - decode
            return web.json_response({
                "desired": max(prec.desired, min(hi, floor)),
                "pools": {"prefill": prefill,
                          "decode": decode},
                "reason": prec.reason,
                "signals": prec.signals,
                "controller_floor": floor,
                "pool_shift": shift})
        rec = autoscale.recommend_replicas(
            st.registry.replicas(), min_replicas=lo, max_replicas=hi)
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=400)
    return web.json_response({"desired": max(rec.desired,
                                             min(hi, floor)),
                              "reason": rec.reason,
                              "signals": rec.signals,
                              "controller_floor": floor})


async def _stats(request: web.Request):
    """Machine-readable routing counters (the loadtest's evidence feed
    — same numbers as /metrics, without a Prometheus parse)."""
    st: _FleetState = request.app[FLEET_KEY]
    # route_total carries (reason, pool) keys; the per-reason view
    # sums over the closed pool set (Counter.value is exact-key)
    return web.json_response({
        "route_total": {
            reason: sum(st.obs.route_total.value(reason=reason, pool=p)
                        for p in POOLS)
            for reason in ROUTE_REASONS},
        "route_by_pool": {
            p: sum(st.obs.route_total.value(reason=r, pool=p)
                   for r in ROUTE_REASONS)
            for p in POOLS},
        "handoff": {
            oc: st.obs.handoff_seconds.count(outcome=oc)
            for oc in ("ok", "skipped", "failed")},
        "handoff_bytes": st.obs.handoff_bytes.value(),
        "hedge_wins": st.obs.hedge_wins.value(),
        "failover": st.obs.failover.value(),
        "checkpoints": len(st.checkpoints),
        # fault-injection ledger (None outside chaos runs): the chaos
        # loadtest's proof that faults actually fired
        "chaos": dict(st.chaos.injected) if st.chaos else None,
    })


async def _scrape_replicas(st: _FleetState, path: str, *,
                           params: dict | None = None,
                           as_json: bool, timeout_s: float = 10.0):
    """GET `path` from every routable replica concurrently. Returns
    [(replica_id, body-or-None), ...] — None marks an unreachable or
    non-200 replica; the caller decides what a hole means."""
    st.registry.sweep()
    reps = sorted(st.registry.routable(set()), key=lambda r: r.id)

    async def fetch(rep):
        try:
            async with st.session.get(
                    f"{rep.url}{path}", params=params,
                    timeout=aiohttp.ClientTimeout(total=timeout_s)) as r:
                if r.status != 200:
                    return rep.id, None
                return rep.id, (await r.json() if as_json
                                else await r.text())
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                json.JSONDecodeError):
            return rep.id, None

    return await asyncio.gather(*(fetch(rep) for rep in reps))


async def _fleet_cache(request: web.Request):
    """GET /fleet/cache — the fleet-wide prefix heat map: every
    replica's heartbeat heat digest, plus the merged view (scores
    summed per 16-hex prefix, carriers listed), plus the cumulative
    counterfactual remote-hit count. No replica round-trips: this
    reads the registry table the heartbeats already fed, so it is
    cheap enough for a loadtest to poll."""
    st: _FleetState = request.app[FLEET_KEY]
    st.registry.sweep()
    per_replica = {}
    merged: dict[str, dict] = {}
    for rep in sorted(st.registry.replicas(), key=lambda r: r.id):
        digest = [dict(e) for e in rep.cache_digest]
        per_replica[rep.id] = {"state": rep.state, "pool": rep.pool,
                               "digest": digest}
        for e in digest:
            m = merged.setdefault(
                e["prefix"], {"prefix": e["prefix"], "score": 0.0,
                              "replicas": []})
            m["score"] = round(m["score"] + e["score"], 4)
            m["replicas"].append(rep.id)
    heat = sorted(merged.values(), key=lambda m: m["score"],
                  reverse=True)
    return web.json_response({
        "replicas": per_replica,
        "heat": heat,
        # prefixes hot on >1 replica: each is duplicated prefill work
        # a cross-replica cache tier would de-duplicate
        "shared_prefixes": sum(1 for m in heat
                               if len(m["replicas"]) > 1),
        "remote_hits_total": st.obs.remote_hits.value(),
    })


async def _fleet_metrics(request: web.Request):
    """GET /fleet/metrics — one exposition for the whole fleet: every
    routable replica's /metrics scraped, strictly parsed, and merged
    (counters/gauges summed, histogram buckets merged on the union
    grid) with a `fleet_federation_up{replica}` coverage gauge. The
    router's OWN metrics stay at /metrics; federating them in would
    double-count once an external Prometheus scrapes both."""
    st: _FleetState = request.app[FLEET_KEY]
    scrapes = await _scrape_replicas(st, "/metrics", as_json=False)
    versions = {rep.id: rep.version
                for rep in st.registry.replicas() if rep.version}
    text = obs_lib.federate(dict(scrapes), guard=st.obs.replica_guard,
                            versions=versions,
                            version_guard=st.obs.version_guard)
    return web.Response(text=text, content_type="text/plain")


async def _merged_traces(request: web.Request):
    """GET /debug/traces with cross-process merge: `?trace_id=` (the id
    from any X-Trace-Id header) additionally fetches each replica's
    segment of that trace and merges all Chrome events into one
    document, router and replicas as separate process tracks. Without
    `trace_id` (or with `format=summary`) this is the plain local
    endpoint every other app mounts."""
    st: _FleetState = request.app[FLEET_KEY]
    q = request.rel_url.query
    try:
        local = obs_lib.traces_response_payload(st.obs.tracer, q)
    except ValueError as e:
        raise web.HTTPBadRequest(text=str(e)) from None
    trace_id = q.get("trace_id") or None
    if trace_id is None or q.get("format") == "summary":
        return web.json_response(local)
    segments = [("router", local)]
    for rid, payload in await _scrape_replicas(
            st, "/debug/traces", params={"trace_id": trace_id},
            as_json=True):
        if isinstance(payload, dict) and payload.get("traceEvents"):
            segments.append((rid, payload))
    return web.json_response(obs_lib.merge_chrome_traces(segments))


async def _decisions(request: web.Request):
    """GET /fleet/decisions[?limit=N] — the control plane's audit
    book: the conservation-checked ledger snapshot (every evaluation
    booked to exactly one outcome), the bounded audit trail of
    decision records (evidence in, action taken, verdict out), and
    the live policy state (latched flags, cooldown remainders)."""
    st: _FleetState = request.app[FLEET_KEY]
    ctl = st.controller
    if ctl is None:
        return web.json_response(
            {"error": "router has no controller"}, status=404)
    q = request.rel_url.query
    try:
        limit = int(q.get("limit", 0)) or None
    except ValueError:
        return web.json_response({"error": "bad limit"}, status=400)
    return web.json_response({
        **ctl.ledger.snapshot(),
        "records": ctl.ledger.records(limit),
        "controller": ctl.describe(),
    })


async def _publish_version(request: web.Request):
    """POST /fleet/versions — the trainer's publish door (ISSUE 18):
    the elastic chief announces each COMMITTED checkpoint here
    (`{"version": "step-12", "model": ..., "step": 12, "source":
    {"checkpoint": dir, "step": 12}}`). Idempotent by version name —
    a chief re-announcing after a coordinator blip must not restart a
    finished rollout. The RolloutManager picks the newest pending
    entry up on its next tick."""
    st: _FleetState = request.app[FLEET_KEY]
    try:
        body = await request.json()
    except Exception:
        return web.json_response({"error": "invalid JSON"}, status=400)
    if not isinstance(body, dict):
        return web.json_response({"error": "body must be an object"},
                                 status=400)
    version = body.get("version", "")
    if not rollout_mod.valid_version(version):
        return web.json_response(
            {"error": "version must be 1..64 chars of [A-Za-z0-9._-]"},
            status=400)
    source = body.get("source")
    if source is not None and not isinstance(source, dict):
        return web.json_response({"error": "source must be an object"},
                                 status=400)
    step = body.get("step")
    entry, created = st.versions.publish(
        version, model=str(body.get("model", "") or ""),
        source=source,
        step=step if isinstance(step, int)
        and not isinstance(step, bool) else None)
    if created:
        log.info("fleet: version %s published (model=%s step=%s)",
                 version, entry["model"], entry["step"])
    return web.json_response({"published": created, "entry": entry,
                              "current": st.versions.current})


async def _versions(request: web.Request):
    """GET /fleet/versions — the version registry: every published
    entry with its lifecycle status, plus the fleet-wide current."""
    st: _FleetState = request.app[FLEET_KEY]
    return web.json_response(st.versions.snapshot())


async def _rollouts(request: web.Request):
    """GET /fleet/rollouts[?limit=N] — the rollout plane's audit book:
    the conservation-checked phase ledger (every transition booked to
    exactly one phase; every started rollout active or terminal), the
    bounded transition records, and the manager's live state (active
    rollout, burn rates, knobs)."""
    st: _FleetState = request.app[FLEET_KEY]
    q = request.rel_url.query
    try:
        limit = int(q.get("limit", 0)) or None
    except ValueError:
        return web.json_response({"error": "bad limit"}, status=400)
    return web.json_response({
        **st.rollout_ledger.snapshot(),
        "records": st.rollout_ledger.records(limit),
        "manager": st.rollout.describe(),
    })


async def _rollout_control(request: web.Request):
    """POST /fleet/rollouts — the operator's manual knobs:
    `{"pin": true}` freezes new rollouts (an active one finishes its
    course), `{"pin": false}` unfreezes, `{"rollback": true, "reason":
    "..."}` aborts the active rollout on the manager's next tick."""
    st: _FleetState = request.app[FLEET_KEY]
    try:
        body = await request.json()
    except Exception:
        return web.json_response({"error": "invalid JSON"}, status=400)
    if not isinstance(body, dict):
        return web.json_response({"error": "body must be an object"},
                                 status=400)
    out = {}
    if "pin" in body:
        if not isinstance(body["pin"], bool):
            return web.json_response({"error": "pin must be boolean"},
                                     status=400)
        st.rollout.pin(body["pin"])
        out["pinned"] = st.rollout.pinned
    if body.get("rollback"):
        out["rollback_requested"] = st.rollout.request_rollback(
            str(body.get("reason", "manual")))
    if not out:
        return web.json_response(
            {"error": "body needs 'pin' and/or 'rollback'"},
            status=400)
    return web.json_response(out)


async def _healthz(request: web.Request):
    st: _FleetState = request.app[FLEET_KEY]
    st.registry.sweep()
    counts = st.registry.counts()
    return web.json_response({
        "status": "ok",
        "routable": counts["ready"] + counts["degraded"],
        "replicas": counts,
    })


async def _proxied_models(request: web.Request):
    """GET /v1/models via the least-loaded routable replica — clients
    written against a single server work unchanged through the door."""
    st: _FleetState = request.app[FLEET_KEY]
    st.registry.sweep()
    tried: set[str] = set()
    for _ in range(st.retries + 1):
        pool = st.registry.routable(tried)
        if not pool:
            break
        rep = min(pool, key=lambda r: (r.load(), r.id))
        try:
            async with st.session.get(
                    f"{rep.url}/v1/models",
                    timeout=aiohttp.ClientTimeout(total=10)) as r:
                payload = await r.read()
                if r.status >= 500:
                    raise _UpstreamError(str(r.status))
                return web.Response(
                    body=payload, status=r.status,
                    content_type="application/json",
                    headers={"X-Fleet-Replica": rep.id})
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                _UpstreamError):
            st.registry.note_failure(rep.id)
            tried.add(rep.id)
    return web.json_response(
        {"error": "no serving replica available"}, status=503)


def create_router_app(registry: ReplicaRegistry | None = None, *,
                      block_size: int = 64, policy: str = "affinity",
                      hedge_after_s: float = 2.0, retries: int = 3,
                      backoff_s: float = 0.05,
                      request_timeout_s: float = 300.0,
                      metrics_registry=None, tracer=None,
                      tenancy: TenancyConfig | None = None,
                      max_attempts: int | None = None,
                      chaos=None,
                      policies=None,
                      control_interval_s: float = 2.0,
                      elastic_url: str | None = None,
                      rollout_interval_s: float = 1.0,
                      rollout_bake_s: float = 30.0,
                      rollout_min_probes: int = 4,
                      rollout_burn_threshold: float = 2.0,
                      rollout_ttft_slo_s: float = 1.5,
                      rollout_confirm_timeout_s: float = 60.0,
                      peer_hints: bool = True,
                      ) -> web.Application:
    """Build the router app. `block_size` must match the replicas'
    `kv_block_size` (the affinity key is the first block — a mismatch
    only costs cache hits, never correctness). `policy` is "affinity"
    or "roundrobin" (the A/B control arm). `hedge_after_s <= 0`
    disables hedging. `metrics_registry`/`tracer` share external obs
    instances; by default the app owns fresh ones at `/metrics` and
    `/debug/traces`. `tenancy` enables router-side tenant rate
    limiting (`tenancy.TenancyConfig`, normally the same file the
    replicas load): a tenant over its requests/s bucket is 429'd at
    the fleet door before any replica dispatch. With or without it,
    the X-Tenant header is forwarded to replicas verbatim.
    `max_attempts` caps TOTAL upstream dispatches per request —
    primaries, retries and hedges together (default `retries + 2`).
    `chaos` is a `fleet.chaos.ChaosInjector` for the fault-injection
    loadtest; leave None in production. `policies` is a list of
    `fleet.control.Policy` rules: when given, a closed-loop
    `Controller` evaluates them every `control_interval_s` seconds
    against the federated metrics view and fires the built-in
    actuators (see `control.router_actuators`; `elastic_url` points
    `evict_worker` at an elastic coordinator). With or without
    policies, `/fleet/decisions` serves the decision ledger.
    The rollout plane (ISSUE 18) is always mounted: the trainer
    publishes versions at `POST /fleet/versions` and a `RolloutManager`
    canaries each one on a single replica, bakes it for
    `rollout_bake_s` seconds (at least `rollout_min_probes` judged
    events), and rolls or rolls back on its SLO burn vs
    `rollout_burn_threshold`; `rollout_ttft_slo_s` is the canary TTFT
    threshold and `rollout_confirm_timeout_s` bounds how long a
    reloaded replica may take to re-register with the new version
    label. `rollout_interval_s <= 0` disables the background loop
    (tests and `ci/obs_check rollout` drive `step()` by hand);
    `/fleet/rollouts` serves the phase ledger either way.
    `peer_hints=False` disables the `X-KV-Peer` heat hints (the
    cache-tier A/B's control arm — replicas never peer-fetch)."""
    if policy not in ("affinity", "roundrobin"):
        raise ValueError(f"unknown policy {policy!r}")
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    reg = registry if registry is not None else ReplicaRegistry()
    obs = FleetObs(reg, registry=metrics_registry, tracer=tracer)
    if tenancy is not None:
        # zero-seed the per-tenant series for every configured name
        for _t in tenancy.names():
            obs.tenant_guard.admit(_t)
            obs.tenant_requests.inc(0, tenant=_t)
            obs.tenant_throttled.inc(0, tenant=_t)
    st = _FleetState(reg, obs, block_size=block_size, policy=policy,
                     hedge_after_s=hedge_after_s, retries=retries,
                     backoff_s=backoff_s, timeout_s=request_timeout_s,
                     tenancy=tenancy, max_attempts=max_attempts,
                     chaos=chaos, peer_hints=peer_hints)
    # Closed-loop controller: constructed with or without policies so
    # /fleet/decisions always answers; the background loop only runs
    # when there are policies to evaluate.
    pols = list(policies) if policies else []
    decision_ledger = obs_lib.DecisionLedger()
    obs.bind_control([p.name for p in pols], decision_ledger)
    st.controller = control_mod.Controller(
        pols, ledger=decision_ledger,
        reader=control_mod.FederatedSignalReader(st, clock=reg.clock),
        actuators=control_mod.router_actuators(
            st, elastic_url=elastic_url, clock=reg.clock),
        interval_s=control_interval_s, clock=reg.clock,
        tracer=obs.tracer)
    # Rollout plane (ISSUE 18): registry + ledger + manager, always
    # constructed (like the controller) so the /fleet/versions and
    # /fleet/rollouts doors answer even with the loop disabled. The
    # three injected callables are the ONLY I/O the manager does.
    st.versions = rollout_mod.VersionRegistry()
    st.rollout_ledger = rollout_mod.RolloutLedger()
    obs.bind_rollout(st.versions, st.rollout_ledger)

    async def _rollout_drain(rid: str) -> None:
        # same path the operator's POST /fleet/drain and the
        # controller's drain_replica actuator fire: mark draining +
        # migrate in-flight KV to peers, so the reload never aborts a
        # client's generation
        await drain_and_migrate(st, rid)

    async def _rollout_reload(rep, entry) -> bool:
        payload: dict = {"version": entry["version"],
                         "source": dict(entry["source"])}
        if entry.get("model"):
            payload["model"] = entry["model"]
        # the chaos harness publishes deliberately-bad versions by
        # tucking a defect into the source; it rides to the replica as
        # the /v1/reload defect field (reload resets any previous one)
        if isinstance(entry["source"].get("defect"), dict):
            payload["defect"] = entry["source"]["defect"]
        try:
            async with st.session.post(
                    f"{rep.url}/v1/reload", json=payload,
                    timeout=aiohttp.ClientTimeout(total=120)) as r:
                return r.status == 200
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            return False

    async def _rollout_probe(rep):
        # active canary judge: one tiny direct generate against the
        # canary. Direct on purpose — the router's retry/hedge shell
        # would mask a failing canary by answering from a healthy
        # replica, which is exactly the blind spot a canary exists to
        # not have.
        models = rep.models or ["llama-tiny"]
        t0 = time.perf_counter()
        try:
            async with st.session.post(
                    f"{rep.url}/v1/models/{models[0]}:generate",
                    json={"tokens": [[1]], "max_new": 1},
                    timeout=aiohttp.ClientTimeout(
                        total=max(5.0, 4 * rollout_ttft_slo_s))) as r:
                await r.read()
                return time.perf_counter() - t0, r.status < 500
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            return time.perf_counter() - t0, False

    st.rollout = rollout_mod.RolloutManager(
        reg, st.versions, st.rollout_ledger,
        drain_fn=_rollout_drain, reload_fn=_rollout_reload,
        probe_fn=_rollout_probe,
        bake_window_s=rollout_bake_s,
        bake_min_probes=rollout_min_probes,
        burn_threshold=rollout_burn_threshold,
        ttft_threshold_s=rollout_ttft_slo_s,
        confirm_timeout_s=rollout_confirm_timeout_s,
        interval_s=rollout_interval_s, clock=reg.clock,
        tracer=obs.tracer, on_reload=obs.note_reload)
    app = web.Application(middlewares=[_router_obs_middleware])
    app[FLEET_KEY] = st

    async def _start(app_):
        st.session = aiohttp.ClientSession()
        if pols and control_interval_s > 0:
            st.control_task = asyncio.create_task(st.controller.run())
        if rollout_interval_s > 0:
            st.rollout_task = asyncio.create_task(st.rollout.run())

    async def _stop(app_):
        for task_attr in ("control_task", "rollout_task"):
            task = getattr(st, task_attr)
            if task is not None:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
                setattr(st, task_attr, None)
        if st.session is not None:
            await st.session.close()

    app.on_startup.append(_start)
    app.on_cleanup.append(_stop)

    app.router.add_get("/healthz", _healthz)
    # /metrics via the shared helper; /debug/traces is the router's own
    # handler because it grows the cross-process ?trace_id= merge.
    app.router.add_get("/metrics",
                       obs_endpoints.metrics_handler(obs.registry))
    app.router.add_get("/debug/traces", _merged_traces)
    app.router.add_get("/fleet/metrics", _fleet_metrics)
    app.router.add_post("/fleet/register", _register)
    app.router.add_post("/fleet/heartbeat", _heartbeat)
    app.router.add_post("/fleet/deregister", _deregister)
    app.router.add_post("/fleet/drain", _drain)
    app.router.add_get("/fleet/placements", _placements)
    app.router.add_get("/fleet/replicas", _replicas)
    app.router.add_get("/fleet/autoscale", _autoscale)
    app.router.add_get("/fleet/decisions", _decisions)
    app.router.add_get("/fleet/versions", _versions)
    app.router.add_post("/fleet/versions", _publish_version)
    app.router.add_get("/fleet/rollouts", _rollouts)
    app.router.add_post("/fleet/rollouts", _rollout_control)
    app.router.add_get("/fleet/stats", _stats)
    app.router.add_get("/fleet/cache", _fleet_cache)
    app.router.add_get("/v1/models", _proxied_models)
    app.router.add_post("/v1/models/{name}:generate", _routed_generate)
    return app
