"""Serving-fleet layer: replica registry, prefix-affinity router,
autoscale signals.

The missing subsystem between `ModelServerController` (which turns a CR
into pods) and `serving/server.py` (one well-instrumented replica): a
thin HTTP front door that (1) tracks replica health through a
registration + heartbeat handshake (`registry.py`), (2) routes
generate traffic by consistent-hash prefix affinity so repeated
prompts land on the replica already holding the radix-cache entry,
with least-queue-depth fallback, retry/backoff and hedged requests
(`router.py`), and (3) aggregates queue-depth + KV-pool-pressure into
a desired-replica recommendation the ModelServer controller consumes
(`autoscale.py`).

The closed loop (`control.py`, ISSUE 16) rides on top of the router:
declarative `Policy` rules over the federated metrics view fire the
existing actuators (autoscale floor bumps, drain/migrate, elastic
eviction, draft disable), with every evaluation booked into the
conservation-checked decision ledger served at `/fleet/decisions`.

The rollout plane (`rollout.py`, ISSUE 18) closes the train→serve
loop: the elastic chief publishes each COMMITTED checkpoint to the
`VersionRegistry` (`POST /fleet/versions`), and the `RolloutManager`
canaries it on one drained replica, bakes it against version-labelled
TTFT/error SLOs, then rolls the fleet replica-by-replica — migrating
in-flight KV first, rolling back automatically on burn — with every
phase transition booked in the conservation-checked `RolloutLedger`
served at `/fleet/rollouts`.

Import discipline: `registry`, `autoscale` and `control`'s math half
are pure Python (the control plane imports `autoscale` and must stay
jax-free; `control` only imports aiohttp lazily inside the router
actuators); `router` adds aiohttp + obs, still no jax — the router
process boots in milliseconds while replicas compile.
"""

from kubeflow_tpu.fleet.registry import (
    DEAD,
    DEGRADED,
    DRAINING,
    READY,
    Replica,
    ReplicaRegistry,
    rendezvous,
)
from kubeflow_tpu.fleet.autoscale import Recommendation, recommend_replicas
from kubeflow_tpu.fleet.control import (
    ACTIONS,
    Controller,
    Policy,
    Signal,
    default_policies,
)
from kubeflow_tpu.fleet.rollout import (
    PHASES,
    RolloutLedger,
    RolloutManager,
    VersionRegistry,
    valid_version,
)

__all__ = [
    "ACTIONS",
    "Controller",
    "DEAD",
    "DEGRADED",
    "DRAINING",
    "PHASES",
    "Policy",
    "READY",
    "Recommendation",
    "Replica",
    "ReplicaRegistry",
    "RolloutLedger",
    "RolloutManager",
    "Signal",
    "VersionRegistry",
    "default_policies",
    "recommend_replicas",
    "rendezvous",
    "valid_version",
]
