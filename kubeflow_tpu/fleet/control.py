"""Closed-loop fleet control: SLO-burn-driven actuation.

PRs 6-14 made every layer observable — burn-rate gauges, cache and
goodput ledgers, straggler forensics — but nothing *acted* on those
signals. This module closes the loop: a `Controller` evaluates
declarative `Policy` objects (a signal query over the router's
federated metrics view, a threshold, a hysteresis band, a cooldown)
and fires the EXISTING actuators — desired-replica bumps surfaced
through `/fleet/autoscale`, replica drain/migrate, elastic worker
eviction via the coordinator's generation bump, draft-model disable on
speculative-acceptance burn.

Autopilot-lineage systems are only trustworthy when every decision is
itself a first-class observable, so the controller's one hard rule is:
every evaluation is booked into exactly one outcome in the
`obs.decisions.DecisionLedger` (conservation: evaluations == sum of
outcomes), every fired action carries its evidence snapshot, and after
the policy's verify window the controller re-reads the signal and
books a recovered / not_recovered verdict. The book is served at
`GET /fleet/decisions`, counted in zero-seeded
`fleet_control_decisions_total{policy,outcome}` /
`fleet_control_actions_total{policy,action}`, and each fired action is
a `control.action` span in `/debug/traces`.

The controller is sans-jax and pure-asyncio; the clock, signal reader
and actuator table are all injectable, so the hysteresis/cooldown math
is testable on a fake clock with stub actuators.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable

from kubeflow_tpu.fleet.registry import DEGRADED, READY
from kubeflow_tpu.obs.decisions import DecisionLedger
from kubeflow_tpu.obs.exposition import ExpositionError, parse_exposition
from kubeflow_tpu.obs.federation import merge_families

log = logging.getLogger(__name__)

# Closed set of things the controller can do. These become the `action`
# label on `fleet_control_actions_total`, so the set is CLOSED by
# design (cardinality bounded by code, not configuration):
#   scale_out     — raise the desired-replica floor surfaced at
#                   /fleet/autoscale (the infra layer watching that
#                   endpoint boots the replica; the controller decides)
#   drain_replica — drain + migrate the most-loaded replica (sheds a
#                   hot spot; its sequences move to healthy peers)
#   evict_worker  — ask the elastic coordinator to evict its straggler
#                   (generation bump; survivors resume at the new size)
#   disable_draft — turn speculative decoding off fleet-wide when the
#                   draft model stops earning its keep
#   shift_pool_split — lean the disaggregated prefill/decode split one
#                   replica toward decode (TTL'd, like the scale_out
#                   floor): pressure evictions mean decode KV demand
#                   outgrew its pool share, and /fleet/autoscale folds
#                   the shift into its recommendation
ACTIONS = ("scale_out", "drain_replica", "evict_worker", "disable_draft",
           "shift_pool_split")

_SIGNAL_MODES = ("value", "rate")
_SIGNAL_REDUCES = ("max", "sum", "avg")
_SIGNAL_SOURCES = ("federated", "local")
_DIRECTIONS = ("above", "below")


@dataclass(frozen=True)
class Signal:
    """One metric query: which family, which label subset, and how to
    collapse the matching series into one number.

    `source` is "local" (the router's own registry: fleet_* families,
    router-side burn rates) or "federated" (every routable replica's
    /metrics, merged — serving_* and train_* families live there).
    `mode` is "value" (gauges) or "rate" (counters: per-second delta
    against the previous read; first read and counter resets report
    0.0). `reduce` collapses multiple matching series (max for burn
    rates — the hottest replica is the breach; sum for event rates)."""

    family: str
    labels: dict = field(default_factory=dict)
    mode: str = "value"
    reduce: str = "max"
    source: str = "federated"

    def __post_init__(self):
        if not self.family:
            raise ValueError("signal needs a metric family name")
        if self.mode not in _SIGNAL_MODES:
            raise ValueError(f"unknown signal mode {self.mode!r}")
        if self.reduce not in _SIGNAL_REDUCES:
            raise ValueError(f"unknown signal reduce {self.reduce!r}")
        if self.source not in _SIGNAL_SOURCES:
            raise ValueError(f"unknown signal source {self.source!r}")

    def describe(self) -> dict:
        return {"family": self.family, "labels": dict(self.labels),
                "mode": self.mode, "reduce": self.reduce,
                "source": self.source}


@dataclass
class Policy:
    """One declarative control rule.

    Fires `action` when the signal breaches `threshold` (strictly
    above for direction="above"). `clear` is the hysteresis level the
    signal must drop back to/below before the policy can fire again
    (defaults to the threshold — no band); `cooldown_s` is the minimum
    time between fires regardless of the signal; `verify_window_s` is
    how long after a fire the controller waits before re-reading the
    signal and booking the recovered / not_recovered verdict."""

    name: str
    signal: Signal
    threshold: float
    action: str
    clear: float | None = None
    cooldown_s: float = 30.0
    verify_window_s: float = 30.0
    direction: str = "above"

    def __post_init__(self):
        if not self.name:
            raise ValueError("policy needs a name")
        if self.action not in ACTIONS:
            raise ValueError(f"policy {self.name!r}: unknown action "
                             f"{self.action!r} (not in {ACTIONS})")
        if self.direction not in _DIRECTIONS:
            raise ValueError(f"policy {self.name!r}: direction must be "
                             f"one of {_DIRECTIONS}")
        if self.clear is None:
            self.clear = self.threshold
        ok = (self.clear <= self.threshold if self.direction == "above"
              else self.clear >= self.threshold)
        if not ok:
            raise ValueError(
                f"policy {self.name!r}: clear level must sit on the "
                "healthy side of the threshold")
        if self.cooldown_s < 0 or self.verify_window_s <= 0:
            raise ValueError(
                f"policy {self.name!r}: cooldown must be >= 0 and "
                "verify window > 0")

    def breached(self, value: float) -> bool:
        return (value > self.threshold if self.direction == "above"
                else value < self.threshold)

    def still_hot(self, value: float) -> bool:
        """Inside the hysteresis band: back under the threshold but not
        yet past the clear level — a latched policy stays latched."""
        return (value > self.clear if self.direction == "above"
                else value < self.clear)

    def describe(self) -> dict:
        return {"name": self.name, "signal": self.signal.describe(),
                "threshold": self.threshold, "clear": self.clear,
                "direction": self.direction,
                "cooldown_s": self.cooldown_s,
                "verify_window_s": self.verify_window_s,
                "action": self.action}


def signal_value(families: dict, sig: Signal) -> float | None:
    """Extract one number from a `parse_exposition`-shaped dict: every
    sample of `sig.family` whose labels are a superset of `sig.labels`
    (extra labels — replica, window — are ignored), collapsed by
    `sig.reduce`. None when no series matches (an absent family is
    "can't tell", never 0 — zero-seeding is what makes healthy zeros
    distinguishable from holes)."""
    fam = families.get(sig.family)
    if fam is None:
        return None
    want = sig.labels.items()
    vals = [v for (sname, labels), v in fam["samples"].items()
            if sname == sig.family
            and all(dict(labels).get(k) == lv for k, lv in want)]
    if not vals:
        return None
    if sig.reduce == "max":
        return max(vals)
    if sig.reduce == "sum":
        return sum(vals)
    return sum(vals) / len(vals)


class FederatedSignalReader:
    """Default signal source: the router's own registry ("local") or
    every routable replica's /metrics merged ("federated") — the same
    strict parse + merge `/fleet/metrics` serves. Keeps per-policy
    baselines for rate-mode signals. Any scrape/parse trouble reads as
    None (signal unavailable), never an exception — the control loop
    must not die because one replica served garbage."""

    def __init__(self, st, *, clock: Callable[[], float] | None = None):
        self._st = st
        self._clock = clock or time.monotonic
        # policy name -> (t, cumulative value) for rate signals
        self._last: dict[str, tuple[float, float]] = {}

    async def __call__(self, policy: Policy) -> float | None:
        sig = policy.signal
        try:
            if sig.source == "local":
                texts = [self._st.obs.registry.render()]
            else:
                from kubeflow_tpu.fleet import router as router_mod

                scrapes = await router_mod._scrape_replicas(
                    self._st, "/metrics", as_json=False)
                texts = [t for _, t in scrapes if t]
            parsed = []
            for t in texts:
                try:
                    parsed.append(parse_exposition(t))
                except ExpositionError:
                    continue
            merged = merge_families(parsed)
        except Exception:  # noqa: BLE001 — unavailable, not fatal
            return None
        value = signal_value(merged, sig)
        if value is None or sig.mode == "value":
            return value
        now = self._clock()
        prev = self._last.get(policy.name)
        self._last[policy.name] = (now, value)
        if prev is None:
            return 0.0
        dt = now - prev[0]
        delta = value - prev[1]
        if dt <= 0 or delta < 0:
            # counter reset or a replica left the merge: re-baseline
            return 0.0
        return delta / dt


class _PolicyState:
    __slots__ = ("latched", "cooldown_until")

    def __init__(self):
        self.latched = False
        self.cooldown_until = float("-inf")


class Controller:
    """Evaluates every policy once per tick and books each evaluation
    into exactly one `DecisionLedger` outcome.

    Per-policy per-tick state machine (the math `tests/test_control.py`
    pins on a fake clock):

        breached, in cooldown          -> suppressed_cooldown
        breached, latched              -> suppressed_hysteresis
        breached, unlatched, cooled    -> fire (latch + start cooldown)
        actuator raised                -> actuator_failed (NOT latched:
                                          retried next tick)
        healthy but still above clear  -> suppressed_hysteresis
        healthy, below clear           -> below_threshold (unlatch)

    An unreadable signal (no replicas yet, scrape failed) evaluates as
    healthy-below-clear: the controller never actuates on evidence it
    does not have. Fired decisions are re-read after
    `policy.verify_window_s` and their verdict booked.
    """

    def __init__(self, policies, *,
                 ledger: DecisionLedger | None = None,
                 reader: Callable[[Policy],
                                  Awaitable[float | None]] | None = None,
                 actuators: dict[str, Callable] | None = None,
                 interval_s: float = 2.0,
                 clock: Callable[[], float] | None = None,
                 tracer=None):
        policies = list(policies)
        if len({p.name for p in policies}) != len(policies):
            raise ValueError("duplicate policy names")
        self.policies = policies
        self.ledger = ledger if ledger is not None else DecisionLedger()
        self.reader = reader
        self.actuators = dict(actuators or {})
        self.interval_s = interval_s
        self.clock = clock or time.monotonic
        self.tracer = tracer
        self._state = {p.name: _PolicyState() for p in policies}
        # fired decisions awaiting their verdict: {id, policy, due}
        self._pending: list[dict] = []

    # -- one tick ----------------------------------------------------------

    async def evaluate_once(self) -> list[dict]:
        """One control tick: resolve due verdicts, then evaluate every
        policy. Returns the tick's ledger records (tests inspect
        them); the ledger and metrics are the durable book."""
        now = self.clock()
        await self.resolve_due(now)
        records = []
        for p in self.policies:
            records.append(await self._evaluate_policy(p, now))
        return records

    async def _evaluate_policy(self, p: Policy, now: float) -> dict:
        ps = self._state[p.name]
        value = await self._read(p)
        evidence = {"signal": value, "family": p.signal.family,
                    "threshold": p.threshold, "clear": p.clear}
        breached = value is not None and p.breached(value)
        if not breached:
            if ps.latched and value is not None and p.still_hot(value):
                return self.ledger.note(p.name, "suppressed_hysteresis",
                                        evidence=evidence)
            ps.latched = False
            return self.ledger.note(p.name, "below_threshold",
                                    evidence=evidence)
        if now < ps.cooldown_until:
            evidence["cooldown_remaining_s"] = round(
                ps.cooldown_until - now, 3)
            return self.ledger.note(p.name, "suppressed_cooldown",
                                    evidence=evidence)
        if ps.latched:
            return self.ledger.note(p.name, "suppressed_hysteresis",
                                    evidence=evidence)
        return await self._fire(p, ps, now, evidence)

    async def _fire(self, p: Policy, ps: _PolicyState, now: float,
                    evidence: dict) -> dict:
        span_cm = (self.tracer.span("control.action", policy=p.name,
                                    action=p.action)
                   if self.tracer is not None
                   else contextlib.nullcontext())
        with span_cm as span:
            try:
                actuator = self.actuators.get(p.action)
                if actuator is None:
                    raise RuntimeError(
                        f"no actuator bound for {p.action!r}")
                detail = await actuator(p, dict(evidence))
            except Exception as e:  # noqa: BLE001 — booked, not raised
                evidence["error"] = str(e) or type(e).__name__
                if span is not None:
                    span.attrs["outcome"] = "actuator_failed"
                log.warning("control: policy %s actuator %s failed: %s",
                            p.name, p.action, e)
                return self.ledger.note(p.name, "actuator_failed",
                                        action=p.action,
                                        evidence=evidence)
            if span is not None:
                span.attrs["outcome"] = "fired"
        ps.latched = True
        ps.cooldown_until = now + p.cooldown_s
        if isinstance(detail, dict):
            evidence["result"] = detail
        log.info("control: policy %s fired %s (signal=%s threshold=%s)",
                 p.name, p.action, evidence.get("signal"), p.threshold)
        rec = self.ledger.note(p.name, "fired", action=p.action,
                               evidence=evidence)
        self._pending.append({"id": rec["id"], "policy": p,
                              "due": now + p.verify_window_s})
        return rec

    async def resolve_due(self, now: float | None = None) -> None:
        """Book verdicts for fired decisions whose verify window has
        elapsed: re-read the signal; recovered iff no longer breached."""
        now = self.clock() if now is None else now
        due = [e for e in self._pending if e["due"] <= now]
        if not due:
            return
        self._pending = [e for e in self._pending if e["due"] > now]
        for ent in due:
            p = ent["policy"]
            value = await self._read(p)
            recovered = value is not None and not p.breached(value)
            self.ledger.resolve(
                ent["id"],
                "recovered" if recovered else "not_recovered",
                evidence={"signal": value, "threshold": p.threshold})

    async def _read(self, p: Policy) -> float | None:
        if self.reader is None:
            return None
        try:
            return await self.reader(p)
        except Exception:  # noqa: BLE001 — unreadable, not fatal
            return None

    # -- background loop ---------------------------------------------------

    async def run(self) -> None:
        """Tick forever (the router runs this as a background task)."""
        while True:
            try:
                await self.evaluate_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("control: evaluation tick failed")
            await asyncio.sleep(self.interval_s)

    def describe(self) -> dict:
        """Controller state for `GET /fleet/decisions`."""
        now = self.clock()
        return {
            "interval_s": self.interval_s,
            "policies": [
                {**p.describe(),
                 "latched": self._state[p.name].latched,
                 "cooldown_remaining_s": max(
                     0.0, round(self._state[p.name].cooldown_until - now,
                                3))}
                for p in self.policies],
            "pending_verdicts": len(self._pending),
        }


# -- the router's actuator table ------------------------------------------


def router_actuators(st, *, elastic_url: str | None = None,
                     clock: Callable[[], float] | None = None,
                     floor_ttl_s: float = 120.0) -> dict:
    """Bind the closed ACTIONS set to one router's `_FleetState`.

    Every actuator returns a jsonable evidence dict (folded into the
    ledger record) or raises — the controller books the raise as
    `actuator_failed`. `elastic_url` points at the elastic training
    coordinator for `evict_worker`; without one that actuator fails
    loudly rather than silently no-oping."""
    clk = clock or time.monotonic

    async def scale_out(policy: Policy, evidence: dict) -> dict:
        st.registry.sweep()
        counts = st.registry.counts()
        routable = counts[READY] + counts[DEGRADED]
        floor = max(routable + 1, getattr(st, "control_floor", 0))
        st.control_floor = floor
        st.control_floor_until = clk() + floor_ttl_s
        return {"desired_floor": floor, "routable": routable,
                "floor_ttl_s": floor_ttl_s}

    async def drain_replica(policy: Policy, evidence: dict) -> dict:
        from kubeflow_tpu.fleet import router as router_mod

        st.registry.sweep()
        cands = st.registry.routable(set())
        if len(cands) < 2:
            raise RuntimeError(
                "need >= 2 routable replicas to drain one")
        victim = max(cands, key=lambda r: (r.load(), r.id))
        out = await router_mod.drain_and_migrate(st, victim.id)
        return {"replica": victim.id, "drain": out}

    async def evict_worker(policy: Policy, evidence: dict) -> dict:
        if elastic_url is None:
            raise RuntimeError("no elastic coordinator configured")
        async with st.session.post(
                f"{elastic_url.rstrip('/')}/elastic/evict", json={},
                timeout=aiohttp_timeout(10.0)) as r:
            body = await r.json(content_type=None)
            if r.status != 200:
                raise RuntimeError(
                    f"coordinator refused eviction: {body}")
            return body if isinstance(body, dict) else {"world": body}

    async def disable_draft(policy: Policy, evidence: dict) -> dict:
        st.registry.sweep()
        reps = st.registry.routable(set())
        if not reps:
            raise RuntimeError("no routable replicas")
        results: dict[str, int] = {}
        for rep in reps:
            try:
                async with st.session.post(
                        f"{rep.url}/v1/spec", json={"enabled": False},
                        timeout=aiohttp_timeout(10.0)) as r:
                    results[rep.id] = r.status
            except Exception:  # noqa: BLE001 — per-replica best effort
                results[rep.id] = 0
        if not any(s == 200 for s in results.values()):
            raise RuntimeError(
                f"no replica accepted the draft disable: {results}")
        return {"replicas": results, "enabled": False}

    async def shift_pool_split(policy: Policy, evidence: dict) -> dict:
        # one replica of lean per fire, TTL'd like the scale_out
        # floor: when the burn stops, the shift quietly expires and
        # the phase-seconds recommendation takes back over
        shift = min(int(getattr(st, "pool_shift", 0)) + 1, 8)
        st.pool_shift = shift
        st.pool_shift_until = clk() + floor_ttl_s
        return {"pool_shift": shift, "shift_ttl_s": floor_ttl_s,
                "disaggregated": st.registry.disaggregated()}

    return {"scale_out": scale_out, "drain_replica": drain_replica,
            "evict_worker": evict_worker, "disable_draft": disable_draft,
            "shift_pool_split": shift_pool_split}


def aiohttp_timeout(total: float):
    """Lazy aiohttp import so this module stays importable without it
    (the math half — Policy/Controller/ledger — has no HTTP needs)."""
    import aiohttp

    return aiohttp.ClientTimeout(total=total)


def default_policies(*, burn_threshold: float = 1.0,
                     burn_clear: float = 0.5,
                     cooldown_s: float = 20.0,
                     verify_window_s: float = 30.0,
                     kv_pressure_rate: float = 5.0,
                     kv_shift_rate: float | None = None,
                     straggler_ratio: float = 0.25) -> list[Policy]:
    """The canonical policy set the closed-loop chaos arm and the docs
    describe — one policy per actuator, driven by the signals the
    observability PRs built:

    - router availability burn (short window) -> scale out
    - fleet-wide pressure-eviction rate       -> drain the hot replica
    - train straggler ratio                   -> evict the straggler
    - speculative-acceptance burn             -> disable the draft
    - pressure-eviction rate (half the drain
      threshold: the gentler lever fires first) -> shift pool split
    """
    if kv_shift_rate is None:
        kv_shift_rate = kv_pressure_rate / 2
    return [
        Policy(name="availability_burn_scale_out",
               signal=Signal("slo_burn_rate",
                             {"slo": "fleet_availability",
                              "window": "short"},
                             source="local", reduce="max"),
               threshold=burn_threshold, clear=burn_clear,
               cooldown_s=cooldown_s, verify_window_s=verify_window_s,
               action="scale_out"),
        Policy(name="kv_pressure_drain",
               signal=Signal("serving_kv_evictions_total",
                             {"cause": "pressure"},
                             mode="rate", reduce="sum"),
               threshold=kv_pressure_rate,
               clear=kv_pressure_rate / 2,
               cooldown_s=cooldown_s, verify_window_s=verify_window_s,
               action="drain_replica"),
        Policy(name="straggler_evict",
               signal=Signal("train_straggler_ratio", {},
                             reduce="max"),
               threshold=straggler_ratio,
               clear=straggler_ratio / 2,
               cooldown_s=cooldown_s, verify_window_s=verify_window_s,
               action="evict_worker"),
        Policy(name="kv_pressure_shift_split",
               signal=Signal("serving_kv_evictions_total",
                             {"cause": "pressure"},
                             mode="rate", reduce="sum"),
               threshold=kv_shift_rate,
               clear=kv_shift_rate / 2,
               cooldown_s=cooldown_s, verify_window_s=verify_window_s,
               action="shift_pool_split"),
        Policy(name="spec_acceptance_burn_draft_off",
               signal=Signal("slo_burn_rate",
                             {"slo": "serving_spec_acceptance",
                              "window": "short"},
                             reduce="max"),
               threshold=burn_threshold, clear=burn_clear,
               cooldown_s=cooldown_s, verify_window_s=verify_window_s,
               action="disable_draft"),
    ]
