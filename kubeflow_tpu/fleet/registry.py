"""Replica registry: heartbeat-driven health states + rendezvous hash.

One registry instance lives inside the router process and is fed by the
serving replicas' registration handshake (`serving.server.
enable_fleet_registration`): register once at startup, heartbeat every
couple of seconds with routing/autoscale stats, deregister at shutdown.

Health state machine (per replica):

    register ──> ready ──(no heartbeat > degraded_after_s)──> degraded
                   ^            │
                   │            └──(no heartbeat > dead_after_s)──> dead
                   └──(heartbeat)── degraded / dead        (recovery)
    drain() / heartbeat{draining: true} ──> draining  (terminal until
                                            deregister: admission
                                            stopped, in-flight finishes)

Router-observed failures are a second, faster signal than heartbeat
staleness: `note_failure` (connection refused / 5xx) degrades a replica
immediately and kills it after `dead_failures` consecutive errors —
a crashed replica stops receiving traffic on the FIRST failed proxy,
not a heartbeat window later.

Routing targets come from `pick()`: rendezvous (highest-random-weight)
hashing of the request's first KV-block of tokens over the ready set.
Rendezvous rather than a hash ring because stability under replica
add/remove is the whole point — removing a replica remaps ONLY the
keys that lived on it, adding one steals only the keys it now wins
(pinned by tests/test_fleet.py).
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field
from typing import Callable, Iterable

READY = "ready"
DEGRADED = "degraded"
DRAINING = "draining"
DEAD = "dead"
STATES = (READY, DEGRADED, DRAINING, DEAD)

# Disaggregated serving roles. "mixed" (the default) is the symmetric
# replica that both prefills and decodes; "prefill"/"decode" replicas
# specialize and hand sequences off over /v1/migrate/in. The set is
# CLOSED — it bounds the `pool` metric label and the router's routing
# table, so an unknown role in a heartbeat falls back to "mixed".
PREFILL = "prefill"
DECODE = "decode"
MIXED = "mixed"
POOLS = (PREFILL, DECODE, MIXED)


def rendezvous(key: bytes, ids: Iterable[str]) -> str | None:
    """Highest-random-weight winner for `key` among `ids` (stable:
    independent per-(key, id) scores, so membership changes move only
    the keys whose winner joined/left)."""
    best, best_score = None, b""
    for rid in ids:
        score = hashlib.sha256(rid.encode() + b"\x00" + key).digest()
        if best is None or score > best_score:
            best, best_score = rid, score
    return best


@dataclass
class Replica:
    """One serving replica as the router sees it."""

    id: str
    url: str
    models: list[str] = field(default_factory=list)
    state: str = READY
    registered_at: float = 0.0
    last_heartbeat: float = 0.0
    # disaggregation role ("prefill" / "decode" / "mixed"): which pool
    # the router files this replica under when picking targets
    pool: str = MIXED
    # model version the replica advertises in heartbeats ("" until it
    # adopts one): the rollout plane's confirmation signal, and a
    # LabelGuard-capped label on fleet_replicas / federated metrics
    version: str = ""
    # heartbeat-reported routing/autoscale signals
    queue_depth: int = 0
    active_slots: int = 0
    max_slots: int = 0
    kv_blocks_free: int = 0
    kv_blocks_total: int = 0
    # cumulative step-phase seconds from the replica's PhaseProfiler
    # ({"prefill": s, "decode": s}): the pool autoscaler's only signal
    phase_seconds: dict = field(default_factory=dict)
    # heartbeat-reported prefix-heat digest: top-K
    # [{"prefix": 16-hex, "score": float}] — feeds /fleet/cache and
    # the counterfactual remote-hit counter
    cache_digest: list = field(default_factory=list)
    # router-side accounting
    inflight: int = 0            # proxied requests currently open
    failures: int = 0            # consecutive router-observed failures
    # circuit breaker: while clock() < circuit_open_until the replica
    # is skipped by fresh routing picks. After the cooldown the next
    # pick IS the half-open probe: one more failure re-opens the
    # circuit (failures is still at/over the trip line), one success
    # closes it (note_success zeroes both).
    circuit_open_until: float = 0.0

    def load(self) -> int:
        """Least-queue-depth ordering key: heartbeat-reported queue plus
        the router's own open requests (fresher than any heartbeat)."""
        return self.queue_depth + self.inflight

    def snapshot(self) -> dict:
        return {
            "id": self.id, "url": self.url, "models": list(self.models),
            "state": self.state, "pool": self.pool,
            "version": self.version,
            "phase_seconds": dict(self.phase_seconds),
            "queue_depth": self.queue_depth,
            "active_slots": self.active_slots,
            "max_slots": self.max_slots,
            "kv_blocks_free": self.kv_blocks_free,
            "kv_blocks_total": self.kv_blocks_total,
            "cache_digest": [dict(d) for d in self.cache_digest],
            "inflight": self.inflight, "failures": self.failures,
            "circuit_open_until": self.circuit_open_until,
            "last_heartbeat_age_s": None,
        }


class ReplicaRegistry:
    """Single-threaded (event-loop) replica table. `clock` is injectable
    so tests drive the staleness transitions deterministically."""

    def __init__(self, *, degraded_after_s: float = 6.0,
                 dead_after_s: float = 20.0, dead_failures: int = 3,
                 circuit_failures: int = 2,
                 circuit_cooldown_s: float = 2.0,
                 overload_depth: int = 64,
                 clock: Callable[[], float] = time.monotonic):
        if not degraded_after_s < dead_after_s:
            raise ValueError(
                f"degraded_after_s ({degraded_after_s}) must be < "
                f"dead_after_s ({dead_after_s})")
        self.degraded_after_s = degraded_after_s
        self.dead_after_s = dead_after_s
        self.dead_failures = dead_failures
        # consecutive failures that open the per-replica circuit (must
        # stay below dead_failures to matter — DEAD already unroutes)
        # and how long the circuit stays open before a half-open probe
        self.circuit_failures = circuit_failures
        self.circuit_cooldown_s = circuit_cooldown_s
        # affinity target past this load routes by least-depth instead:
        # a hot prefix must not pile the whole fleet's traffic onto one
        # replica once the cache win is smaller than the queue loss
        self.overload_depth = overload_depth
        self.clock = clock
        self._replicas: dict[str, Replica] = {}

    # -- membership -------------------------------------------------------

    def register(self, url: str, *, replica_id: str = "",
                 models: Iterable[str] = (), **stats) -> Replica:
        """Idempotent: re-registration (replica restart, router restart
        losing state) refreshes the record and returns it ready."""
        rid = replica_id or url
        now = self.clock()
        rep = self._replicas.get(rid)
        if rep is None:
            rep = Replica(id=rid, url=url, registered_at=now)
            self._replicas[rid] = rep
        rep.url = url
        rep.models = sorted(models)
        rep.state = READY
        rep.failures = 0
        rep.last_heartbeat = now
        self._apply_stats(rep, stats)
        return rep

    def deregister(self, replica_id: str) -> bool:
        return self._replicas.pop(replica_id, None) is not None

    def get(self, replica_id: str) -> Replica | None:
        return self._replicas.get(replica_id)

    def replicas(self) -> list[Replica]:
        return list(self._replicas.values())

    # -- health signals ---------------------------------------------------

    def heartbeat(self, replica_id: str, **stats) -> bool:
        """Refresh liveness + stats. Returns False for an unknown id —
        the replica should re-register (router restarted)."""
        rep = self._replicas.get(replica_id)
        if rep is None:
            return False
        rep.last_heartbeat = self.clock()
        self._apply_stats(rep, stats)
        if stats.get("draining"):
            rep.state = DRAINING
        elif rep.state in (DEGRADED, DEAD):
            rep.state = READY      # recovery
            rep.failures = 0
            rep.circuit_open_until = 0.0  # live heartbeat = probe passed
        return True

    @staticmethod
    def _apply_stats(rep: Replica, stats: dict) -> None:
        for k in ("queue_depth", "active_slots", "max_slots",
                  "kv_blocks_free", "kv_blocks_total"):
            v = stats.get(k)
            if isinstance(v, int) and not isinstance(v, bool) and v >= 0:
                setattr(rep, k, v)
        # pool role is a string from a CLOSED set (it becomes a metric
        # label); anything else quietly stays at the current role
        p = stats.get("pool")
        if isinstance(p, str) and p in POOLS:
            rep.pool = p
        # version label: same charset/length contract as
        # fleet.rollout.valid_version (not imported — rollout imports
        # this module). Malformed values stay at the current version;
        # "" is legal (a replica that never adopted one).
        ver = stats.get("version")
        if isinstance(ver, str) and len(ver) <= 64 and all(
                ("a" <= c <= "z") or ("A" <= c <= "Z")
                or ("0" <= c <= "9") or c in "._-" for c in ver):
            rep.version = ver
        # cumulative phase seconds: keep only finite non-negative
        # numbers under string keys (fed straight to the pool
        # autoscaler, so garbage must die at the door)
        ph = stats.get("phase_seconds")
        if isinstance(ph, dict):
            clean = {k: float(v) for k, v in ph.items()
                     if isinstance(k, str)
                     and isinstance(v, (int, float))
                     and not isinstance(v, bool) and v >= 0.0}
            if clean or not ph:
                rep.phase_seconds = clean
        # prefix-heat digest: keep only well-formed entries — 16-hex
        # prefix names (the hashed-LabelGuard format, so a replica
        # can never smuggle raw tokens or unbounded strings into the
        # fleet heat map) with finite non-negative scores — and cap
        # the list length defensively
        dg = stats.get("cache_digest")
        if isinstance(dg, list):
            clean_dg = []
            for e in dg[:64]:
                if not isinstance(e, dict):
                    continue
                p, s = e.get("prefix"), e.get("score")
                if (isinstance(p, str) and len(p) == 16
                        and all(c in "0123456789abcdef" for c in p)
                        and isinstance(s, (int, float))
                        and not isinstance(s, bool) and s >= 0.0):
                    clean_dg.append({"prefix": p, "score": float(s)})
            rep.cache_digest = clean_dg

    def drain(self, replica_id: str) -> bool:
        rep = self._replicas.get(replica_id)
        if rep is None:
            return False
        rep.state = DRAINING
        return True

    def note_dispatch(self, replica_id: str) -> None:
        rep = self._replicas.get(replica_id)
        if rep is not None:
            rep.inflight += 1

    def note_done(self, replica_id: str) -> None:
        rep = self._replicas.get(replica_id)
        if rep is not None and rep.inflight > 0:
            rep.inflight -= 1

    def note_failure(self, replica_id: str) -> None:
        """Router-observed proxy failure (connect error / 5xx): degrade
        NOW, kill after `dead_failures` in a row — faster than waiting
        out a heartbeat window when the process is already gone."""
        rep = self._replicas.get(replica_id)
        if rep is None:
            return
        rep.failures += 1
        if rep.failures >= self.circuit_failures:
            rep.circuit_open_until = self.clock() + self.circuit_cooldown_s
        if rep.failures >= self.dead_failures:
            rep.state = DEAD
        elif rep.state == READY:
            rep.state = DEGRADED

    def note_success(self, replica_id: str) -> None:
        rep = self._replicas.get(replica_id)
        if rep is not None:
            rep.failures = 0
            rep.circuit_open_until = 0.0

    def circuit_open(self, replica_id: str) -> bool:
        """Is this replica's circuit currently open? (the
        `fleet_circuit_open{replica}` gauge reads this)"""
        rep = self._replicas.get(replica_id)
        return (rep is not None
                and self.clock() < rep.circuit_open_until)

    def sweep(self) -> None:
        """Apply heartbeat-staleness transitions. Call before routing
        decisions and gauge renders; draining/dead states are sticky
        (only a fresh heartbeat resurrects dead, nothing unsticks
        draining but deregister)."""
        now = self.clock()
        for rep in self._replicas.values():
            if rep.state in (DRAINING, DEAD):
                continue
            age = now - rep.last_heartbeat
            if age > self.dead_after_s:
                rep.state = DEAD
            elif age > self.degraded_after_s:
                rep.state = DEGRADED

    def counts(self) -> dict[str, int]:
        """State -> replica count, zero-filled (the `fleet_replicas`
        gauge must carry all four series from the first render)."""
        out = {s: 0 for s in STATES}
        for rep in self._replicas.values():
            out[rep.state] += 1
        return out

    def pool_counts(self) -> dict[str, dict[str, int]]:
        """Pool -> state -> replica count, zero-filled over the full
        POOLS x STATES grid (the `fleet_replicas{state,pool}` gauge
        renders every cell from the first scrape)."""
        out = {p: {s: 0 for s in STATES} for p in POOLS}
        for rep in self._replicas.values():
            out[rep.pool][rep.state] += 1
        return out

    def digest_carriers(self, prefix: str,
                        exclude: str = "") -> list[Replica]:
        """Live (ready/degraded) replicas whose heartbeat heat digest
        advertises `prefix` (a 16-hex `prefix_hash`), hottest first.
        These are the candidates a cold replica can pull the prefix's
        KV blocks from — the router's `X-KV-Peer` hint and the
        counterfactual remote-hit check both read this. Draining and
        dead replicas are skipped: a block pull must not pin work on
        a replica that is leaving."""
        scored: list[tuple[float, Replica]] = []
        for rep in self._replicas.values():
            if rep.id == exclude or rep.state not in (READY, DEGRADED):
                continue
            for e in rep.cache_digest:
                if e.get("prefix") == prefix:
                    scored.append((float(e.get("score", 0.0)), rep))
                    break
        scored.sort(key=lambda t: (-t[0], t[1].id))
        return [rep for _, rep in scored]

    def disaggregated(self) -> bool:
        """True when the fleet actually runs split pools: at least one
        live (ready/degraded) prefill replica AND one live decode
        replica. The router only engages the handoff path then — a
        fleet of mixed replicas keeps the symmetric behavior."""
        live = {PREFILL: 0, DECODE: 0, MIXED: 0}
        for rep in self._replicas.values():
            if rep.state in (READY, DEGRADED):
                live[rep.pool] += 1
        return live[PREFILL] > 0 and live[DECODE] > 0

    # -- routing ----------------------------------------------------------

    def routable(self, exclude: frozenset | set = frozenset(), *,
                 pool: str | None = None) -> list[Replica]:
        """Candidates in preference order: the ready set, else (every
        ready replica excluded/absent) the degraded set — a degraded
        replica may still answer, and retrying it beats a client 503.
        `pool` narrows to one disaggregation role (mixed replicas
        qualify for EITHER role — they can do both phases); when the
        requested pool has no candidates at all the filter relaxes to
        the whole fleet, because any replica beats a 503."""
        now = self.clock()

        def _closed(cands: list[Replica]) -> list[Replica]:
            # skip open circuits — but when EVERY candidate's circuit
            # is open, route anyway: a long-shot retry beats a certain
            # client 503, and the attempt doubles as the probe
            ok = [r for r in cands if now >= r.circuit_open_until]
            return ok or cands

        def _in_pool(r: Replica) -> bool:
            return pool is None or r.pool == pool or r.pool == MIXED

        def _select(want_pool: bool) -> list[Replica]:
            ready = [r for r in self._replicas.values()
                     if r.state == READY and r.id not in exclude
                     and (not want_pool or _in_pool(r))]
            if ready:
                return _closed(ready)
            deg = [r for r in self._replicas.values()
                   if r.state == DEGRADED and r.id not in exclude
                   and (not want_pool or _in_pool(r))]
            return _closed(deg)

        got = _select(True)
        if got or pool is None:
            return got
        return _select(False)

    def pick(self, key: bytes, exclude: frozenset | set = frozenset(),
             *, pool: str | None = None) -> tuple[Replica | None, str]:
        """Route one request: rendezvous affinity target for `key` if it
        is routable and not overloaded, else least-loaded fallback.
        `pool` narrows candidates to one disaggregation role (prefix
        affinity then operates INSIDE that pool, so a disaggregated
        fleet keeps its radix-cache hit rate among the prefill
        replicas). Returns (replica, "affinity" | "fallback")
        or (None, _)."""
        self.sweep()
        cands = self.routable(exclude, pool=pool)
        if not cands:
            return None, "fallback"
        if key:
            winner = rendezvous(key, [r.id for r in cands])
            target = self._replicas[winner]
            if target.load() < self.overload_depth:
                return target, "affinity"
        return min(cands, key=lambda r: (r.load(), r.id)), "fallback"
