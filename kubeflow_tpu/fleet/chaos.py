"""Deterministic fleet fault injection.

One `ChaosInjector` rides inside the router (`create_router_app(
chaos=...)`) and perturbs the router->replica path with a SEEDED fault
plan, so a chaos run is reproducible bit-for-bit: same seed, same
faults, same order. Faults at this layer:

- **drop** — the dispatch never reaches the replica (raised as a
  retryable upstream error; the router's retry/hedge machinery must
  absorb it with zero client-visible failures);
- **delay** — the dispatch is held for `delay_s` before it proceeds
  (inflates tails; the chaos loadtest asserts the inflation stays
  bounded);
- **duplicate** — the same request body is dispatched twice (the
  shadow's outcome is discarded; replicas must tolerate at-least-once
  delivery);
- **heartbeat blackhole** — a replica's heartbeats are swallowed for a
  window (the router's sweeper sees staleness and walks the
  degraded/dead path with the process still alive).

Process-level faults (SIGKILL a replica, wedge a migration
mid-transfer) don't belong here — they are driven by the chaos
loadtest (`loadtest.serving_loadtest --mode chaos`), which owns the
replica processes. This module is pure host Python with no jax or
aiohttp imports; the injector is event-loop-friendly (its only await
is `asyncio.sleep`).

The plan is decided per-call from a dedicated `random.Random(seed)`:
injecting a fault never consumes entropy from anything else, and two
routers built with the same seed and fed the same call sequence make
identical decisions.
"""

from __future__ import annotations

import asyncio
import random

__all__ = ["ChaosInjector"]


class ChaosInjector:
    def __init__(self, seed: int, *, drop_rate: float = 0.0,
                 delay_rate: float = 0.0, delay_s: float = 0.05,
                 duplicate_rate: float = 0.0,
                 heartbeat_blackhole: dict[str, int] | None = None):
        """`*_rate` are per-dispatch probabilities in [0, 1] (drawn in
        a fixed order, so the fault sequence is a pure function of the
        seed and the call count). `heartbeat_blackhole` maps replica id
        -> number of consecutive heartbeats to swallow, armed by
        `blackhole()` at any point mid-run."""
        for nm, rate in (("drop_rate", drop_rate),
                         ("delay_rate", delay_rate),
                         ("duplicate_rate", duplicate_rate)):
            if not 0.0 <= rate <= 1.0:
                raise ValueError(f"{nm} must be in [0, 1], got {rate}")
        self.seed = int(seed)
        self.drop_rate = drop_rate
        self.delay_rate = delay_rate
        self.delay_s = delay_s
        self.duplicate_rate = duplicate_rate
        self._rng = random.Random(self.seed)
        self._blackhole: dict[str, int] = dict(heartbeat_blackhole or {})
        # ledger of every injected fault, for the loadtest's evidence
        # line: {"drop": N, "delay": N, "duplicate": N, "blackhole": N}
        self.injected: dict[str, int] = {
            "drop": 0, "delay": 0, "duplicate": 0, "blackhole": 0}

    async def before_dispatch(self, replica_id: str) -> str | None:
        """Called by the router once per upstream dispatch. Returns
        "drop" / "duplicate" / None; a delay fault sleeps here before
        returning. Draw order is fixed (drop, delay, duplicate) so the
        fault sequence replays exactly under one seed."""
        r_drop = self._rng.random()
        r_delay = self._rng.random()
        r_dup = self._rng.random()
        if r_drop < self.drop_rate:
            self.injected["drop"] += 1
            return "drop"
        if r_delay < self.delay_rate:
            self.injected["delay"] += 1
            await asyncio.sleep(self.delay_s)
        if r_dup < self.duplicate_rate:
            self.injected["duplicate"] += 1
            return "duplicate"
        return None

    def blackhole(self, replica_id: str, beats: int) -> None:
        """Arm a heartbeat blackhole: swallow the next `beats`
        heartbeats from `replica_id`."""
        self._blackhole[replica_id] = max(
            int(beats), self._blackhole.get(replica_id, 0))

    def heartbeat_blackholed(self, replica_id: str) -> bool:
        left = self._blackhole.get(replica_id, 0)
        if left <= 0:
            return False
        self._blackhole[replica_id] = left - 1
        self.injected["blackhole"] += 1
        return True
