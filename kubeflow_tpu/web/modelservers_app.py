"""Model-servers web app: ModelServer CR CRUD.

The serving sibling of the tensorboards app (ref
crud-web-apps/tensorboards/backend pattern): list/create/delete model
servers in a namespace, with readiness and the routed URL surfaced for
the dashboard. Model/quant validation happens in the CONTROLLER (it
emits warning events the UI can mine), so this layer stays a thin,
authz-gated door like its siblings.
"""

from __future__ import annotations

from aiohttp import web

from kubeflow_tpu.api.crds import ModelServer
from kubeflow_tpu.controlplane.store import Store
from kubeflow_tpu.web.common import (
    STORE_KEY,
    base_app,
    ensure_authorized,
    json_success,
)


def create_modelservers_app(store: Store, *,
                            cluster_admins: set[str] | None = None,
                            csrf: bool = True) -> web.Application:
    app = base_app(store, csrf=csrf, cluster_admins=cluster_admins)
    app.router.add_get("/api/namespaces/{ns}/modelservers", list_ms)
    app.router.add_post("/api/namespaces/{ns}/modelservers", post_ms)
    app.router.add_delete("/api/namespaces/{ns}/modelservers/{name}",
                          delete_ms)
    return app


async def list_ms(request: web.Request):
    ns = request.match_info["ns"]
    ensure_authorized(request, "list", "ModelServer", ns)
    store: Store = request.app[STORE_KEY]

    def warning(m) -> str:
        # the controller explains config rejects as warning events
        # (InvalidModel/InvalidTopology/...); surface the NEWEST BY
        # TIMESTAMP — store.list orders by name (random uuid suffix),
        # so [-1] would pick an arbitrary event and an operator could
        # be sent to fix an already-fixed field (same discipline as
        # jupyter_app's error-event mining, ref status.py:79-95)
        evs = [e for e in store.events_for(
            "ModelServer", ns, m.metadata.name) if e.type == "Warning"]
        if not evs:
            return ""
        return max(evs, key=lambda e: e.last_timestamp).message

    return json_success({
        "modelservers": [
            {
                "name": m.metadata.name,
                "model": m.spec.model,
                "checkpoint": m.spec.checkpoint,
                "quant": m.spec.quant,
                "topology": m.spec.tpu.topology,
                "ready": m.status.ready,
                "url": m.status.url,
                "warning": warning(m),
            }
            for m in store.list("ModelServer", ns)
        ]
    })


async def post_ms(request: web.Request):
    ns = request.match_info["ns"]
    ensure_authorized(request, "create", "ModelServer", ns)
    body = await request.json()
    if not body.get("name") or not body.get("model"):
        raise ValueError("name and model are required")
    ms = ModelServer()
    ms.metadata.name = body["name"]
    ms.metadata.namespace = ns
    ms.spec.model = body["model"]
    ms.spec.checkpoint = body.get("checkpoint", "")
    if "quant" in body:
        ms.spec.quant = body["quant"]
    if "max_len" in body:
        ms.spec.max_len = int(body["max_len"])
    if "topology" in body:
        ms.spec.tpu.topology = body["topology"]
    request.app[STORE_KEY].create(ms)
    return json_success({"name": ms.metadata.name}, status=201)


async def delete_ms(request: web.Request):
    ns, name = request.match_info["ns"], request.match_info["name"]
    ensure_authorized(request, "delete", "ModelServer", ns)
    request.app[STORE_KEY].delete("ModelServer", ns, name)
    return json_success({"deleted": name})
