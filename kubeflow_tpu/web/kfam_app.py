"""KFAM REST service (ref access-management kfam/routers.go:30-101).

KfamError → HTTP status conversion happens in the shared
`common.error_middleware`; handlers raise and stay flat.
"""

from __future__ import annotations

from aiohttp import web

from kubeflow_tpu.controlplane.kfam import Binding, Kfam
from kubeflow_tpu.controlplane.store import Store
from kubeflow_tpu.web.common import (
    KFAM_KEY,
    base_app,
    json_success,
)


def create_kfam_app(store: Store, *, cluster_admins: set[str] | None = None,
                    csrf: bool = False) -> web.Application:
    # The reference KFAM sits behind the mesh and uses no CSRF (it is a
    # service API, not a browser app) — kept configurable.
    app = base_app(store, csrf=csrf, cluster_admins=cluster_admins)
    app[KFAM_KEY] = Kfam(store, cluster_admins)

    app.router.add_get("/v1/bindings", get_bindings)
    app.router.add_post("/v1/bindings", post_binding)
    app.router.add_delete("/v1/bindings", delete_binding)
    app.router.add_post("/v1/profiles", post_profile)
    app.router.add_delete("/v1/profiles/{name}", delete_profile)
    app.router.add_get("/v1/role/clusteradmin", get_clusteradmin)
    return app


def _binding_from(body: dict) -> Binding:
    # accept both flat and reference-style nested payloads
    if "roleRef" in body:   # reference Binding shape (bindings.go)
        user = body.get("user", {}).get("name", "")
        ns = body.get("referredNamespace", "")
        role = body.get("roleRef", {}).get("name", "")
    else:
        user, ns, role = body.get("user", ""), body.get("namespace", ""), body.get("role", "")
    return Binding(user=user, namespace=ns, role=role)


async def get_bindings(request: web.Request):
    kfam: Kfam = request.app[KFAM_KEY]
    bindings = kfam.list_bindings(
        request["user"],
        namespace=request.query.get("namespace") or None,
        user=request.query.get("user") or None,
    )
    return json_success({
        "bindings": [
            {"user": b.user, "namespace": b.namespace, "role": b.role}
            for b in bindings
        ]
    })


async def post_binding(request: web.Request):
    kfam: Kfam = request.app[KFAM_KEY]
    kfam.create_binding(request["user"], _binding_from(await request.json()))
    return json_success(status=201)


async def delete_binding(request: web.Request):
    kfam: Kfam = request.app[KFAM_KEY]
    kfam.delete_binding(request["user"], _binding_from(await request.json()))
    return json_success()


async def post_profile(request: web.Request):
    kfam: Kfam = request.app[KFAM_KEY]
    body = await request.json()
    kfam.create_profile(
        request["user"], body["name"], owner=body.get("owner", ""),
        quota=body.get("quota"),
    )
    return json_success(status=201)


async def delete_profile(request: web.Request):
    kfam: Kfam = request.app[KFAM_KEY]
    kfam.delete_profile(request["user"], request.match_info["name"])
    return json_success()


async def get_clusteradmin(request: web.Request):
    kfam: Kfam = request.app[KFAM_KEY]
    from kubeflow_tpu.controlplane.auth import User

    user = request.query.get("user") or request["user"].name
    return json_success({"isClusterAdmin": kfam.is_cluster_admin(User(user))})
