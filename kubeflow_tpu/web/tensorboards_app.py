"""Tensorboards web app (TWA): Tensorboard CR CRUD
(ref crud-web-apps/tensorboards/backend)."""

from __future__ import annotations

from aiohttp import web

from kubeflow_tpu.api.crds import Tensorboard
from kubeflow_tpu.controlplane.store import Store
from kubeflow_tpu.web.common import (
    STORE_KEY,
    base_app,
    ensure_authorized,
    json_success,
)


def create_tensorboards_app(store: Store, *,
                            cluster_admins: set[str] | None = None,
                            csrf: bool = True) -> web.Application:
    app = base_app(store, csrf=csrf, cluster_admins=cluster_admins)
    app.router.add_get("/api/namespaces/{ns}/tensorboards", list_tbs)
    app.router.add_post("/api/namespaces/{ns}/tensorboards", post_tb)
    app.router.add_delete("/api/namespaces/{ns}/tensorboards/{name}", delete_tb)
    return app


async def list_tbs(request: web.Request):
    ns = request.match_info["ns"]
    ensure_authorized(request, "list", "Tensorboard", ns)
    store: Store = request.app[STORE_KEY]
    return json_success({
        "tensorboards": [
            {
                "name": t.metadata.name,
                "logspath": t.spec.logspath,
                "ready": t.status.ready,
                "url": f"/tensorboard/{ns}/{t.metadata.name}/",
            }
            for t in store.list("Tensorboard", ns)
        ]
    })


async def post_tb(request: web.Request):
    ns = request.match_info["ns"]
    ensure_authorized(request, "create", "Tensorboard", ns)
    body = await request.json()
    if not body.get("name") or not body.get("logspath"):
        raise ValueError("name and logspath are required")
    tb = Tensorboard()
    tb.metadata.name = body["name"]
    tb.metadata.namespace = ns
    tb.spec.logspath = body["logspath"]
    request.app[STORE_KEY].create(tb)
    return json_success({"name": tb.metadata.name}, status=201)


async def delete_tb(request: web.Request):
    ns, name = request.match_info["ns"], request.match_info["name"]
    ensure_authorized(request, "delete", "Tensorboard", ns)
    request.app[STORE_KEY].delete("Tensorboard", ns, name)
    return json_success()
