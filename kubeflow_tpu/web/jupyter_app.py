"""Jupyter web app backend (JWA): notebook spawner/manager REST API.

Re-design of crud-web-apps/jupyter/backend:
- POST creates workspace/data PVCs then the Notebook CR, validating the
  CR with a dry-run create FIRST so users get errors before any PVC is
  made (ref post.py:48-54);
- GET list summarizes status from CR + warning events (status.py);
- PATCH stopped:true/false toggles the stop annotation (the culler
  restart path);
- config endpoint serves the admin spawner config (utils.py:22-53),
  TPU slice picker included;
- poddefaults endpoint lists selectable TpuPodDefaults (ref JWA lists
  PodDefaults for the configurations picker).
"""

from __future__ import annotations

from aiohttp import web

from kubeflow_tpu.api.core import PersistentVolumeClaim
from kubeflow_tpu.api.crds import Notebook, STOP_ANNOTATION
from kubeflow_tpu.controlplane.store import AlreadyExists, Store
from kubeflow_tpu.web import form as form_lib
from kubeflow_tpu.web.common import (
    SPAWNER_CONFIG_KEY,
    STORE_KEY,
    base_app,
    ensure_authorized,
    json_success,
)


def create_jupyter_app(store: Store, *, spawner_config=None,
                       cluster_admins: set[str] | None = None,
                       csrf: bool = True) -> web.Application:
    """`spawner_config` is a dict OR a hot-reloading source (anything
    with .get() -> dict, e.g. platform.SpawnerConfigSource wrapping the
    mounted ConfigMap file)."""
    app = base_app(store, csrf=csrf, cluster_admins=cluster_admins)
    app[SPAWNER_CONFIG_KEY] = spawner_config or form_lib.DEFAULT_SPAWNER_CONFIG

    app.router.add_get("/api/config", get_config)
    app.router.add_get("/api/namespaces/{ns}/notebooks", list_notebooks)
    app.router.add_post("/api/namespaces/{ns}/notebooks", post_notebook)
    app.router.add_get("/api/namespaces/{ns}/notebooks/{name}", get_notebook)
    app.router.add_delete("/api/namespaces/{ns}/notebooks/{name}", delete_notebook)
    app.router.add_patch("/api/namespaces/{ns}/notebooks/{name}", patch_notebook)
    app.router.add_get("/api/namespaces/{ns}/poddefaults", list_poddefaults)
    return app


def _spawner_config(request: web.Request) -> dict:
    cfg = request.app[SPAWNER_CONFIG_KEY]
    return cfg.get() if hasattr(cfg, "get") and not isinstance(
        cfg, dict) else cfg


async def get_config(request: web.Request):
    # tpuTopologies rides along so the SPA form can validate the mesh
    # product against the picked slice's chip count CLIENT-side (the
    # backend stays the authority — form.parse_form re-checks).
    from kubeflow_tpu.parallel.mesh import SLICE_TOPOLOGIES

    return json_success({
        "config": _spawner_config(request),
        "tpuTopologies": {name: t.chips
                          for name, t in SLICE_TOPOLOGIES.items()},
    })


def _summarize(store: Store, nb: Notebook) -> dict:
    events = store.events_for(
        "Notebook", nb.metadata.namespace, nb.metadata.name
    )
    status = form_lib.notebook_status(nb, events)
    return {
        "name": nb.metadata.name,
        "namespace": nb.metadata.namespace,
        "image": (nb.spec.template.spec.containers[0].image
                  if nb.spec.template.spec.containers else ""),
        "tpu": {"topology": nb.spec.tpu.topology, "mesh": nb.spec.tpu.mesh},
        "status": status,
        "readyReplicas": nb.status.ready_replicas,
        "serverUrl": f"/notebook/{nb.metadata.namespace}/{nb.metadata.name}/",
    }


async def list_notebooks(request: web.Request):
    ns = request.match_info["ns"]
    ensure_authorized(request, "list", "Notebook", ns)
    store: Store = request.app[STORE_KEY]
    return json_success({
        "notebooks": [_summarize(store, nb) for nb in store.list("Notebook", ns)]
    })


async def get_notebook(request: web.Request):
    """Detail payload: the list summary plus the explain-my-notebook
    data the reference's JWA details page shows (events via
    find_error_event/status.py, the pod list via the notebook-name
    label) — here with the gang structure first-class (per-pod
    TPU_WORKER_ID)."""
    ns, name = request.match_info["ns"], request.match_info["name"]
    ensure_authorized(request, "get", "Notebook", ns)
    store: Store = request.app[STORE_KEY]
    nb = store.get("Notebook", ns, name)
    out = _summarize(store, nb)
    out["events"] = [
        {"type": e.type, "reason": e.reason, "message": e.message,
         "count": e.count, "lastTimestamp": e.last_timestamp}
        for e in sorted(
            store.events_for("Notebook", ns, name),
            key=lambda e: e.last_timestamp, reverse=True)
    ]
    pods = store.list("Pod", ns, label_selector={"notebook-name": name})
    out["pods"] = [
        {"name": p.metadata.name, "phase": p.phase,
         "workerId": next(
             (e.value for c in p.spec.containers for e in c.env
              if e.name == "TPU_WORKER_ID"), "")}
        for p in pods
    ]
    return json_success({"notebook": out})


async def post_notebook(request: web.Request):
    ns = request.match_info["ns"]
    ensure_authorized(request, "create", "Notebook", ns)
    store: Store = request.app[STORE_KEY]
    body = await request.json()
    body["namespace"] = ns
    config = _spawner_config(request)
    form = form_lib.parse_form(body, config)
    nb = form_lib.build_notebook(form, config)

    # Selected configurations: adopt each TpuPodDefault's selector labels
    # on the pod template so the admission webhook matches it (the JWA
    # copies PodDefault matchLabels the same way).
    for conf in form.configurations:
        pd = store.get("TpuPodDefault", ns, conf)
        nb.spec.template.metadata.labels.update(pd.spec.selector)

    # dry-run validate the CR before creating PVCs (ref post.py:48-54)
    store.create(nb, dry_run=True)

    for vol in nb.spec.template.spec.volumes:
        if not vol.pvc_name:
            continue
        if store.try_get("PersistentVolumeClaim", ns, vol.pvc_name) is None:
            pvc = PersistentVolumeClaim()
            pvc.metadata.name = vol.pvc_name
            pvc.metadata.namespace = ns
            if form.workspace and vol.pvc_name == form.workspace["name"]:
                pvc.storage = form.workspace.get("size", "5Gi")
            try:
                store.create(pvc)
            except AlreadyExists:
                pass
    store.create(nb)
    return json_success({"name": form.name}, status=201)


async def delete_notebook(request: web.Request):
    ns, name = request.match_info["ns"], request.match_info["name"]
    ensure_authorized(request, "delete", "Notebook", ns)
    request.app[STORE_KEY].delete("Notebook", ns, name)
    return json_success()


async def patch_notebook(request: web.Request):
    ns, name = request.match_info["ns"], request.match_info["name"]
    ensure_authorized(request, "update", "Notebook", ns)
    store: Store = request.app[STORE_KEY]
    body = await request.json()
    nb = store.get("Notebook", ns, name)
    if "stopped" in body:
        if body["stopped"]:
            import datetime

            nb.metadata.annotations[STOP_ANNOTATION] = (
                datetime.datetime.now(datetime.timezone.utc).isoformat()
            )
        else:
            nb.metadata.annotations.pop(STOP_ANNOTATION, None)
    store.update(nb)
    return json_success()


async def list_poddefaults(request: web.Request):
    ns = request.match_info["ns"]
    ensure_authorized(request, "list", "TpuPodDefault", ns)
    store: Store = request.app[STORE_KEY]
    return json_success({
        "poddefaults": [
            {"name": pd.metadata.name, "desc": pd.spec.desc,
             "selector": pd.spec.selector}
            for pd in store.list("TpuPodDefault", ns)
        ]
    })
