"""Central dashboard BFF (ref components/centraldashboard).

Aggregation endpoints the Polymer SPA calls (app/api.ts:29-102,
api_workgroup.ts:255-391), re-done over the in-process store + Kfam:
- /api/workgroup/env-info   — identity, namespaces, clusterAdmin flag,
  platform metadata (getProfileAwareEnv :134-158);
- /api/workgroup/exists     — has the user a profile? (registration flow)
- /api/workgroup/create     — self-serve profile creation
- /api/namespaces, /api/activities/{ns} (events), /api/dashboard-links,
  /api/metrics/{type} (TPU utilization summary replaces Stackdriver).
"""

from __future__ import annotations

import asyncio

from aiohttp import web

from kubeflow_tpu.controlplane import auth
from kubeflow_tpu.controlplane.kfam import Kfam
from kubeflow_tpu.controlplane.metrics import MetricsHistory, scan_usage
from kubeflow_tpu.controlplane.store import Store
from kubeflow_tpu.web.common import (
    CLUSTER_ADMINS_KEY,
    KFAM_KEY,
    LINKS_KEY,
    STORE_KEY,
    base_app,
    json_error,
    json_success,
)

HISTORY_KEY: web.AppKey = web.AppKey("metrics_history", MetricsHistory)

DEFAULT_LINKS = {
    "menuLinks": [
        {"link": "/jupyter/", "text": "Notebooks"},
        {"link": "/tensorboards/", "text": "TensorBoards"},
        {"link": "/volumes/", "text": "Volumes"},
    ],
    "externalLinks": [],
    "quickLinks": [
        {"desc": "Create a new Notebook server", "link": "/jupyter/new"},
        {"desc": "View TPU slice usage", "link": "/metrics"},
    ],
    "documentationItems": [],
}


def create_dashboard_app(store: Store, *, cluster_admins: set[str] | None = None,
                         links: dict | None = None,
                         csrf: bool = True,
                         history_cadence_s: float = 30.0) -> web.Application:
    app = base_app(store, csrf=csrf, cluster_admins=cluster_admins)
    app[KFAM_KEY] = Kfam(store, cluster_admins)
    app[LINKS_KEY] = links or DEFAULT_LINKS
    app[HISTORY_KEY] = MetricsHistory(store, cadence_s=history_cadence_s)

    # Background sampler: ALL ring history comes from this task (the
    # reference gets collection for free from Stackdriver). metrics()
    # never stores — it appends a per-request live point to the
    # RESPONSE only — so if this task dies the chart degrades to a
    # single live point, which is why the loop logs failures instead
    # of dying.
    async def _sampler(app_: web.Application):
        import logging

        async def loop_():
            while True:
                try:
                    app_[HISTORY_KEY].sample()
                except Exception:  # noqa: BLE001 — sampling must not die
                    # ...but a chart silently flatlining with no trail
                    # is its own failure mode: leave a diagnostic.
                    logging.getLogger(__name__).warning(
                        "metrics history sample failed", exc_info=True)
                await asyncio.sleep(app_[HISTORY_KEY].cadence_s)

        task = asyncio.create_task(loop_())
        yield
        task.cancel()
        try:
            await task
        except (asyncio.CancelledError, Exception):  # noqa: BLE001
            pass

    app.cleanup_ctx.append(_sampler)

    app.router.add_get("/api/workgroup/env-info", env_info)
    app.router.add_get("/api/workgroup/exists", workgroup_exists)
    app.router.add_post("/api/workgroup/create", workgroup_create)
    app.router.add_get("/api/namespaces", list_namespaces)
    app.router.add_get("/api/activities/{ns}", activities)
    app.router.add_get("/api/dashboard-links", dashboard_links)
    app.router.add_get("/api/metrics/{type}", metrics)
    return app


async def env_info(request: web.Request):
    store: Store = request.app[STORE_KEY]
    kfam: Kfam = request.app[KFAM_KEY]
    user: auth.User = request["user"]
    namespaces = auth.namespaces_for(store, user, request.app[CLUSTER_ADMINS_KEY])
    profiles = [p.metadata.name for p in store.list("Profile")
                if p.spec.owner == user.name]
    return json_success({
        "user": user.name,
        "platform": {
            "kind": "kubeflow-tpu",
            "provider": "tpu",
            "namespaces": len(store.list("Namespace")),
        },
        "namespaces": namespaces,
        "ownedNamespaces": profiles,
        "isClusterAdmin": kfam.is_cluster_admin(user),
    })


async def workgroup_exists(request: web.Request):
    store: Store = request.app[STORE_KEY]
    user: auth.User = request["user"]
    owned = [p for p in store.list("Profile") if p.spec.owner == user.name]
    return json_success({"hasWorkgroup": bool(owned),
                         "user": user.name})


async def workgroup_create(request: web.Request):
    kfam: Kfam = request.app[KFAM_KEY]
    user: auth.User = request["user"]
    body = await request.json() if request.can_read_body else {}
    name = body.get("namespace") or user.name.split("@")[0]
    kfam.create_profile(user, name)
    return json_success({"namespace": name}, status=201)


async def list_namespaces(request: web.Request):
    store: Store = request.app[STORE_KEY]
    user: auth.User = request["user"]
    return json_success({
        "namespaces": auth.namespaces_for(
            store, user, request.app[CLUSTER_ADMINS_KEY])
    })


async def activities(request: web.Request):
    ns = request.match_info["ns"]
    from kubeflow_tpu.web.common import ensure_authorized

    ensure_authorized(request, "list", "Event", ns)
    store: Store = request.app[STORE_KEY]
    events = sorted(store.list("Event", ns), key=lambda e: -e.timestamp)[:50]
    return json_success({
        "activities": [
            {"kind": e.involved_kind, "name": e.involved_name,
             "type": e.type, "reason": e.reason, "message": e.message,
             "time": e.timestamp}
            for e in events
        ]
    })


async def dashboard_links(request: web.Request):
    return json_success({"links": request.app[LINKS_KEY]})


async def metrics(request: web.Request):
    """TPU-native replacement for the Stackdriver charts
    (stackdriver_metrics_service.ts): summarize slice allocation from
    live pods. Scoped to the namespaces the caller can see — cluster
    admins get the cluster-wide view, everyone else their own tenants
    (the sibling endpoints all gate per-namespace; metrics must not be
    the one cross-tenant leak)."""
    store: Store = request.app[STORE_KEY]
    user: auth.User = request["user"]
    admins = request.app[CLUSTER_ADMINS_KEY]
    if auth.is_cluster_admin(store, user, admins):
        visible = None  # all namespaces
    else:
        visible = set(auth.namespaces_for(store, user, admins))

    # ONE store walk feeds both the summary tiles and (as the series'
    # live point) the chart — metrics.scan_usage is the single
    # definition of "TPU host in use".
    pods, nbs_by_ns = scan_usage(store)
    by_topo: dict[str, int] = {}
    tpu_by_ns: dict[str, int] = {}
    for ns, topo in pods:
        tpu_by_ns[ns] = tpu_by_ns.get(ns, 0) + 1
        if visible is None or ns in visible:
            by_topo[topo] = by_topo.get(topo, 0) + 1
    notebooks = sum(n for ns, n in nbs_by_ns.items()
                    if visible is None or ns in visible)
    body = {
        "type": request.match_info["type"],
        "tpuHostsInUse": by_topo,
        "notebooks": notebooks,
    }

    # ?window=<minutes> adds the time series the SPA charts (ref
    # metrics_service.ts:2-8 interval enum; same 5/15/30/60/180 set).
    window = request.rel_url.query.get("window")
    if window is not None:
        history = request.app[HISTORY_KEY]
        try:
            minutes = int(window)
            # the live now-point reuses the scan above (never stored —
            # polling cannot evict ring history)
            points = history.series(minutes, visible,
                                    live=(tpu_by_ns, nbs_by_ns))
        except ValueError:
            return json_error(
                f"window must be one of "
                f"{list(MetricsHistory.WINDOWS_MIN)} (minutes), "
                f"got {window!r}", 400)
        body["window"] = minutes
        body["points"] = points
    return json_success(body)
