"""Versioned raw-resource API: the apiserver-style door for CRs.

The reference's L0 serves each CRD at every version in the CRD's
`versions` list with conversion in between (Notebook
v1alpha1/v1beta1/v1, conversion in notebook_conversion.go); clients —
kubectl, operators, old SDKs — speak whichever version they were built
against. This app is that surface for our store:

    GET/POST   /apis/kubeflow-tpu.dev/{version}/namespaces/{ns}/notebooks
    GET/DELETE /apis/kubeflow-tpu.dev/{version}/namespaces/{ns}/notebooks/{name}

Bodies and responses are serialized at {version}; the store keeps only
the storage version (api/versioning.py converts at the boundary, which
is exactly where k8s conversion webhooks sit). SAR-style authz per
call, like every other backend (crud_backend authz.py semantics).
"""

from __future__ import annotations

from aiohttp import web

from kubeflow_tpu.api import versioning
from kubeflow_tpu.controlplane.store import Store
from kubeflow_tpu.web.common import (
    CLUSTER_ADMINS_KEY,
    STORE_KEY,
    base_app,
    ensure_authorized,
)

# kind <-> URL plural segment for the kinds this API serves. CRs plus
# the owned workload kinds an operator inspects with kubectl (the
# reference's L0 serves these natively; e2e and conformance read them
# through this door instead of reaching into the store).
PLURALS = {
    "notebooks": "Notebook",
    "tensorboards": "Tensorboard",
    "modelservers": "ModelServer",
    "experiments": "Experiment",
    "trials": "Trial",
    "pods": "Pod",
    "statefulsets": "StatefulSet",
    "deployments": "Deployment",
    "services": "Service",
    "events": "Event",
    "persistentvolumeclaims": "PersistentVolumeClaim",
}
# Controller-owned kinds are served READ-ONLY: their lifecycle belongs
# to reconcilers (ownership + cascade), and authz checks verbs, not
# kinds — without this gate any namespace editor could delete a live
# gang pod or a workspace PVC out from under its controller.
READONLY_KINDS = frozenset(
    {"Pod", "StatefulSet", "Deployment", "Service", "Event",
     "PersistentVolumeClaim"})


def _require_mutable(kind: str) -> None:
    if kind in READONLY_KINDS:
        raise web.HTTPMethodNotAllowed(
            "POST/DELETE", ["GET"],
            text=f"{kind} is read-only through /apis/ — it is owned by a "
                 "controller; mutate the owning custom resource instead")

# Mutations require this custom header. Browsers will not attach custom
# headers to cross-site requests without a CORS preflight (which we
# never approve), so this is the CSRF defense for an API whose clients
# are programmatic (no cookie/CSRF dance like the SPA's double-submit):
# a kubectl-style client just always sends it.
API_CLIENT_HEADER = "X-KFTPU-API-CLIENT"


def _require_api_client(request: web.Request) -> None:
    if API_CLIENT_HEADER not in request.headers:
        raise web.HTTPForbidden(
            text=f"mutations on /apis/ require the {API_CLIENT_HEADER} "
                 "header (cross-site request forgery defense; set it to "
                 "any value from your API client)")


def _version(request: web.Request, kind: str) -> str:
    version = request.match_info["version"]
    served = versioning.SERVED_VERSIONS.get(
        kind, (versioning.STORAGE_VERSION,))
    if version not in served:
        raise web.HTTPNotFound(
            text=f"{kind} is not served at {version} "
                 f"(served: {list(served)})")
    return version


def _kind(request: web.Request) -> str:
    plural = request.match_info["plural"]
    kind = PLURALS.get(plural)
    if kind is None:
        raise web.HTTPNotFound(text=f"unknown resource {plural!r}")
    return kind


async def list_resources(request: web.Request) -> web.Response:
    store: Store = request.app[STORE_KEY]
    kind = _kind(request)
    version = _version(request, kind)
    ns = request.match_info["ns"]
    ensure_authorized(request, "list", kind, ns)
    items = [
        versioning.to_versioned_dict(obj, version)
        for obj in store.list(kind, ns)
    ]
    return web.json_response({
        "apiVersion": f"{versioning.GROUP}/{version}",
        "kind": f"{kind}List",
        "items": items,
    })


async def get_resource(request: web.Request) -> web.Response:
    store: Store = request.app[STORE_KEY]
    kind = _kind(request)
    version = _version(request, kind)
    ns, name = request.match_info["ns"], request.match_info["name"]
    ensure_authorized(request, "get", kind, ns)
    obj = store.get(kind, ns, name)
    return web.json_response(versioning.to_versioned_dict(obj, version))


async def create_resource(request: web.Request) -> web.Response:
    store: Store = request.app[STORE_KEY]
    kind = _kind(request)
    _require_mutable(kind)
    version = _version(request, kind)
    ns = request.match_info["ns"]
    _require_api_client(request)
    ensure_authorized(request, "create", kind, ns)
    body = await request.json()
    body.setdefault("kind", kind)
    body.setdefault("apiVersion", f"{versioning.GROUP}/{version}")
    if versioning.parse_api_version(body["apiVersion"]) != version:
        raise ValueError(
            f"body apiVersion {body['apiVersion']!r} does not match "
            f"request path version {version!r}")
    obj = versioning.resource_from_versioned_dict(body)
    if obj.kind != kind:
        raise ValueError(f"body kind {obj.kind!r} != path kind {kind!r}")
    obj.metadata.namespace = ns
    created = store.create(obj)
    return web.json_response(
        versioning.to_versioned_dict(created, version), status=201)


async def update_resource(request: web.Request) -> web.Response:
    """PUT: full replace with optimistic concurrency — the body must
    carry the resourceVersion being replaced (kubectl edit/replace
    semantics; the store raises Conflict on a stale version)."""
    store: Store = request.app[STORE_KEY]
    kind = _kind(request)
    _require_mutable(kind)
    version = _version(request, kind)
    ns, name = request.match_info["ns"], request.match_info["name"]
    _require_api_client(request)
    ensure_authorized(request, "update", kind, ns)
    body = await request.json()
    body.setdefault("kind", kind)
    body.setdefault("apiVersion", f"{versioning.GROUP}/{version}")
    if versioning.parse_api_version(body["apiVersion"]) != version:
        raise ValueError(
            f"body apiVersion {body['apiVersion']!r} does not match "
            f"request path version {version!r}")
    obj = versioning.resource_from_versioned_dict(body)
    if obj.kind != kind:
        raise ValueError(f"body kind {obj.kind!r} != path kind {kind!r}")
    if obj.metadata.name and obj.metadata.name != name:
        raise ValueError(
            f"body name {obj.metadata.name!r} != path name {name!r}")
    # A client PUT replaces spec + user metadata only (subresource
    # semantics); the client's resourceVersion is the concurrency token.
    cur = store.get(kind, ns, name)
    _pin_controller_fields(obj, cur, keep_client_rv=True)
    updated = store.update(obj)
    return web.json_response(versioning.to_versioned_dict(updated, version))


# Mutable-by-clients parts of a resource under JSON merge patch:
# spec plus the user-owned metadata maps. status/ownerRefs/finalizers
# stay controller-owned (the reference's apiserver guards these with
# subresources; refusing them here is the equivalent).
_PATCHABLE_TOP = {"spec"}
_PATCHABLE_META = {"labels", "annotations"}


def _merge_patch(target, patch):
    """RFC 7386 JSON merge patch: null deletes, objects merge, anything
    else replaces."""
    if not isinstance(patch, dict):
        return patch
    if not isinstance(target, dict):
        target = {}
    out = dict(target)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = _merge_patch(out.get(k), v)
    return out


def _pin_controller_fields(obj, cur, *, keep_client_rv: bool) -> None:
    """Identity and controller-owned fields are never client-writable
    through the /apis door: status, ownership, finalizers, and the
    deletion mark (a PUT that cleared deletion_timestamp would
    resurrect a terminating object mid-finalization — k8s forbids that
    transition). resourceVersion stays the CLIENT's on PUT (it is the
    optimistic-concurrency token) and the STORE's on PATCH (the
    merge-retry loop re-reads)."""
    obj.metadata.name = cur.metadata.name
    obj.metadata.namespace = cur.metadata.namespace
    obj.metadata.owner_references = cur.metadata.owner_references
    obj.metadata.finalizers = cur.metadata.finalizers
    obj.metadata.deletion_timestamp = cur.metadata.deletion_timestamp
    obj.status = cur.status
    if not keep_client_rv:
        obj.metadata.resource_version = cur.metadata.resource_version


def _validate_patch_body(patch) -> None:
    if not isinstance(patch, dict):
        raise ValueError("merge patch body must be a JSON object")
    bad_top = set(patch) - _PATCHABLE_TOP - {"metadata"}
    bad_meta = set(patch.get("metadata", {}) or {}) - _PATCHABLE_META
    if bad_top or bad_meta:
        raise ValueError(
            f"merge patch may touch spec/metadata.labels/annotations "
            f"only (got {sorted(bad_top) + sorted(bad_meta)})")


async def _merge_patch_with_retry(store, kind, ns, name, version, patch,
                                  check=None) -> web.Response:
    """The shared kubectl-style PATCH loop: serialize at the request
    version, merge, convert through the hub, pin controller fields,
    retry Conflicts from a fresh read. `check(cur, obj)` hooks per-kind
    authorization/invariants."""
    from kubeflow_tpu.controlplane.store import Conflict

    for _ in range(5):
        cur = store.get(kind, ns, name)
        wire = versioning.to_versioned_dict(cur, version)
        merged = _merge_patch(wire, patch)
        obj = versioning.resource_from_versioned_dict(merged)
        _pin_controller_fields(obj, cur, keep_client_rv=False)
        if check is not None:
            check(cur, obj)
        try:
            updated = store.update(obj)
            return web.json_response(
                versioning.to_versioned_dict(updated, version))
        except Conflict:
            continue
    raise web.HTTPConflict(text=f"{kind} {ns}/{name}: persistent "
                                "write contention")


async def patch_resource(request: web.Request) -> web.Response:
    """PATCH: RFC 7386 merge patch against the resource serialized at
    the REQUEST version (patches written by old clients patch the shape
    they know), then converted through the hub for storage."""
    store: Store = request.app[STORE_KEY]
    kind = _kind(request)
    _require_mutable(kind)
    version = _version(request, kind)
    ns, name = request.match_info["ns"], request.match_info["name"]
    _require_api_client(request)
    ensure_authorized(request, "update", kind, ns)
    patch = await request.json()
    _validate_patch_body(patch)
    return await _merge_patch_with_retry(store, kind, ns, name, version,
                                         patch)


async def delete_resource(request: web.Request) -> web.Response:
    store: Store = request.app[STORE_KEY]
    kind = _kind(request)
    _require_mutable(kind)
    _version(request, kind)
    ns, name = request.match_info["ns"], request.match_info["name"]
    _require_api_client(request)
    ensure_authorized(request, "delete", kind, ns)
    store.delete(kind, ns, name)
    return web.json_response({"status": "deleted"})


# -- cluster-scoped resources (Profile) -------------------------------------
# The reference's L0 serves Profile at BOTH v1beta1 and v1 (storage v1,
# profile-controller/api/v1/profile_types.go:59, conversion files beside
# it); old clients built against either version keep working. Authz
# follows KFAM's owner-or-admin rule (kfam/api_default.go:293-310):
# admins see/mutate everything, owners see their own profile.


def _cluster_admin_and_user(request: web.Request):
    from kubeflow_tpu.controlplane import auth

    user: auth.User = request["user"]
    store: Store = request.app[STORE_KEY]
    admins = request.app.get(CLUSTER_ADMINS_KEY) or set()
    return auth.is_cluster_admin(store, user, admins), user


async def list_profiles(request: web.Request) -> web.Response:
    store: Store = request.app[STORE_KEY]
    version = _version(request, "Profile")
    is_admin, user = _cluster_admin_and_user(request)
    items = [
        versioning.to_versioned_dict(p, version)
        for p in store.list("Profile")
        if is_admin or p.spec.owner == user.name
    ]
    return web.json_response({
        "apiVersion": f"{versioning.GROUP}/{version}",
        "kind": "ProfileList",
        "items": items,
    })


async def get_profile(request: web.Request) -> web.Response:
    store: Store = request.app[STORE_KEY]
    version = _version(request, "Profile")
    name = request.match_info["name"]
    is_admin, user = _cluster_admin_and_user(request)
    obj = store.get("Profile", "", name)
    if not is_admin and obj.spec.owner != user.name:
        raise web.HTTPForbidden(
            text=f"{user.name} is not owner/admin of profile {name}")
    return web.json_response(versioning.to_versioned_dict(obj, version))


async def create_profile(request: web.Request) -> web.Response:
    store: Store = request.app[STORE_KEY]
    version = _version(request, "Profile")
    _require_api_client(request)
    is_admin, user = _cluster_admin_and_user(request)
    body = await request.json()
    body.setdefault("kind", "Profile")
    body.setdefault("apiVersion", f"{versioning.GROUP}/{version}")
    if versioning.parse_api_version(body["apiVersion"]) != version:
        raise ValueError(
            f"body apiVersion {body['apiVersion']!r} does not match "
            f"request path version {version!r}")
    obj = versioning.resource_from_versioned_dict(body)
    if obj.kind != "Profile":
        raise ValueError(f"body kind {obj.kind!r} != Profile")
    # Cluster-scoped: a namespace in the body would store the object
    # under a key no GET/DELETE/reconcile ever reads (phantom profile).
    obj.metadata.namespace = ""
    # Same guards as KFAM's create door (kfam.create_profile): the name
    # becomes a namespace, so it must be a valid non-reserved label.
    from kubeflow_tpu.controlplane.auth import is_reserved_namespace
    from kubeflow_tpu.controlplane.kfam import PROFILE_NAME_RE

    name = obj.metadata.name
    if not PROFILE_NAME_RE.match(name):
        raise ValueError(f"invalid profile name {name!r}")
    if is_reserved_namespace(name):
        raise web.HTTPForbidden(
            text=f"namespace name {name!r} is reserved")
    # Self-service registration creates a profile owned by the caller;
    # creating FOR someone else needs admin (kfam.create_profile rule).
    obj.spec.owner = obj.spec.owner or user.name
    if obj.spec.owner != user.name and not is_admin:
        raise web.HTTPForbidden(
            text=f"{user.name} cannot create a profile owned by "
                 f"{obj.spec.owner}")
    created = store.create(obj)
    return web.json_response(
        versioning.to_versioned_dict(created, version), status=201)


async def patch_profile(request: web.Request) -> web.Response:
    """Merge-patch a Profile (quota edits etc.): admin, or the owner —
    but owners cannot reassign ownership to someone else."""
    store: Store = request.app[STORE_KEY]
    version = _version(request, "Profile")
    name = request.match_info["name"]
    _require_api_client(request)
    is_admin, user = _cluster_admin_and_user(request)
    patch = await request.json()
    _validate_patch_body(patch)

    def check(cur, obj):
        if not is_admin and cur.spec.owner != user.name:
            raise web.HTTPForbidden(
                text=f"{user.name} is not owner/admin of profile {name}")
        if obj.spec.owner != cur.spec.owner and not is_admin:
            raise web.HTTPForbidden(
                text="only cluster admins reassign profile ownership")

    return await _merge_patch_with_retry(store, "Profile", "", name,
                                         version, patch, check=check)


async def delete_profile(request: web.Request) -> web.Response:
    store: Store = request.app[STORE_KEY]
    _version(request, "Profile")
    name = request.match_info["name"]
    _require_api_client(request)
    is_admin, user = _cluster_admin_and_user(request)
    obj = store.get("Profile", "", name)
    if not is_admin and obj.spec.owner != user.name:
        raise web.HTTPForbidden(
            text=f"{user.name} is not owner/admin of profile {name}")
    store.delete("Profile", "", name)
    return web.json_response({"status": "deleted"})


def create_apis_app(store: Store, *, cluster_admins=None,
                    csrf: bool = True) -> web.Application:
    app = base_app(store, csrf=csrf, cluster_admins=cluster_admins)
    base = f"/{versioning.GROUP}/{{version}}/namespaces/{{ns}}/{{plural}}"
    app.router.add_get(base, list_resources)
    app.router.add_post(base, create_resource)
    app.router.add_get(base + "/{name}", get_resource)
    app.router.add_put(base + "/{name}", update_resource)
    app.router.add_patch(base + "/{name}", patch_resource)
    app.router.add_delete(base + "/{name}", delete_resource)
    cluster = f"/{versioning.GROUP}/{{version}}/profiles"
    app.router.add_get(cluster, list_profiles)
    app.router.add_post(cluster, create_profile)
    app.router.add_get(cluster + "/{name}", get_profile)
    app.router.add_patch(cluster + "/{name}", patch_profile)
    app.router.add_delete(cluster + "/{name}", delete_profile)
    return app
