"""Platform app: all web services mounted under one aiohttp server.

The reference deploys the dashboard + three CRUD apps + KFAM as separate
pods behind Istio path routing (VirtualServices at /jupyter, /volumes,
/tensorboards, /kfam, /). One process serving the same paths preserves
the URL contract while staying hermetic; each subapp can also be served
alone (their create_* factories are independent).
"""

from __future__ import annotations

import os

from aiohttp import web

from kubeflow_tpu.controlplane.store import Store
from kubeflow_tpu.web.common import (
    CSRF_EXEMPT_KEY,
    DEV_USER_KEY,
    PLATFORM_METRICS_KEY,
    TRACER_KEY,
    tracing_middleware,
)
from kubeflow_tpu.web.apis_app import create_apis_app
from kubeflow_tpu.web.dashboard_app import create_dashboard_app
from kubeflow_tpu.web.jupyter_app import create_jupyter_app
from kubeflow_tpu.web.kfam_app import create_kfam_app
from kubeflow_tpu.web.modelservers_app import create_modelservers_app
from kubeflow_tpu.web.tensorboards_app import create_tensorboards_app
from kubeflow_tpu.web.volumes_app import create_volumes_app


def create_platform_app(
    store: Store,
    *,
    cluster_admins: set[str] | None = None,
    spawner_config=None,
    csrf: bool = True,
    metrics=None,
    tracer=None,
    dev_user: str | None = None,
) -> web.Application:
    root = create_dashboard_app(store, cluster_admins=cluster_admins, csrf=csrf)
    if dev_user:
        root[DEV_USER_KEY] = dev_user
    # Request tracing + /debug/traces next to /metrics. A fresh Tracer
    # per app unless the caller shares one (Cluster.create_web_app
    # passes the control plane's, so reconcile spans land here too).
    from kubeflow_tpu import obs

    root[TRACER_KEY] = tracer if tracer is not None else obs.Tracer()
    root.middlewares.insert(0, tracing_middleware)

    async def debug_traces(request):
        return web.json_response(obs.traces_response_payload(
            request.app[TRACER_KEY], request.rel_url.query))

    root.router.add_get("/debug/traces", debug_traces)
    if metrics is not None:
        # /metrics + request counters (ref kfam routers.go:82-86 exposes
        # prometheus on the same mux as the API). Outermost middleware so
        # it also counts authn/CSRF rejections and handler crashes.
        root[PLATFORM_METRICS_KEY] = metrics
        root.middlewares.insert(0, _request_counter_middleware)

        async def render_metrics(_request):
            return web.Response(text=metrics.registry.render(),
                                content_type="text/plain")

        root.router.add_get("/metrics", render_metrics)
    root.add_subapp("/jupyter/", create_jupyter_app(
        store, spawner_config=spawner_config, cluster_admins=cluster_admins,
        csrf=csrf))
    root.add_subapp("/volumes/", create_volumes_app(
        store, cluster_admins=cluster_admins, csrf=csrf))
    root.add_subapp("/tensorboards/", create_tensorboards_app(
        store, cluster_admins=cluster_admins, csrf=csrf))
    root.add_subapp("/modelservers/", create_modelservers_app(
        store, cluster_admins=cluster_admins, csrf=csrf))
    root.add_subapp("/kfam/", create_kfam_app(
        store, cluster_admins=cluster_admins, csrf=False))
    # apiserver-style versioned raw-resource door (multi-version CRDs
    # with conversion, ref notebook_conversion.go); programmatic
    # clients, not browsers — exempt from the SPA's cookie CSRF dance,
    # with its own custom-header CSRF defense on mutations
    # (apis_app.API_CLIENT_HEADER).
    root[CSRF_EXEMPT_KEY] = ("/kfam/", "/apis/")
    root.add_subapp("/apis/", create_apis_app(
        store, cluster_admins=cluster_admins, csrf=False))
    add_frontend(root)
    return root


FRONTEND_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "frontend")


def add_frontend(app: web.Application) -> None:
    """Serve the SPA (ref centraldashboard/public): index.html at /,
    hashed-routed so every view lives under the one document; modules
    and styles under /static/. Assets are committed files, no build
    step — the frameworkless answer to the reference's Polymer/Angular
    bundles."""

    async def index(_request: web.Request):
        return web.FileResponse(os.path.join(FRONTEND_DIR, "index.html"))

    app.router.add_get("/", index)
    app.router.add_static("/static/", FRONTEND_DIR)


# Bounded label set: unknown first segments (scanners, typos) bucket to
# "other" so request_total cardinality can't grow without limit.
_KNOWN_SERVICES = frozenset(
    {"api", "jupyter", "volumes", "tensorboards", "modelservers", "kfam",
     "metrics", "healthz", "readyz", "dashboard"})


@web.middleware
async def _request_counter_middleware(request: web.Request, handler):
    import time

    metrics = request.config_dict.get(PLATFORM_METRICS_KEY)
    segment = request.path.split("/")[1] or "dashboard"
    service = segment if segment in _KNOWN_SERVICES else "other"
    t0 = time.perf_counter()
    try:
        resp = await handler(request)
    except web.HTTPException as exc:
        if metrics is not None:
            metrics.record_request(service, request.method, exc.status,
                                   seconds=time.perf_counter() - t0)
        raise
    except Exception:
        if metrics is not None:
            metrics.record_request(service, request.method, 500,
                                   seconds=time.perf_counter() - t0)
        raise
    if metrics is not None:
        metrics.record_request(service, request.method, resp.status,
                               seconds=time.perf_counter() - t0)
    return resp


def load_spawner_config(path: str) -> dict | None:
    """Admin spawner config from a mounted file (the ConfigMap in
    deploy/overlays mounts at /etc/config/spawner_ui_config.yaml); None
    (built-in defaults) when unset or absent, like the reference's
    fallback to the in-repo dev copy (jupyter utils.py:22-53)."""
    if not path or not os.path.exists(path):
        return None
    import yaml

    with open(path) as f:
        config = yaml.safe_load(f)
    if not isinstance(config, dict):
        raise ValueError(f"spawner config {path} must be a mapping")
    return config


class SpawnerConfigSource:
    """Hot-reloading spawner config: the reference's JWA re-reads the
    mounted spawner_ui_config.yaml on every request (utils.py:22-53),
    so an admin edits the ConfigMap and the form changes WITHOUT a
    restart. Same behavior here, mtime-cached so the hot path is one
    stat. A broken edit keeps serving the last good config (an admin
    typo must not take the spawner down) and logs once per bad mtime;
    kubelet ConfigMap updates swap a symlink, which changes the mtime."""

    def __init__(self, path: str):
        self.path = path
        self._mtime: float | None = None
        self._config: dict | None = None
        self._warned_mtime: float | None = None
        # Fail FAST on a config that is broken at startup (the pre-hot-
        # reload behavior): "keep the last good config" needs a good
        # config to keep — otherwise a broken rollout + pod restart
        # would silently serve the permissive built-in defaults,
        # lifting admin restrictions (image allowlist, readOnly pins).
        # The parse result SEEDS the last-good state, so even an edit
        # that breaks before the first request keeps the startup config.
        # A MISSING file stays the documented defaults-fallback.
        if os.path.exists(path):
            self._config = load_spawner_config(path)  # raises if broken
            self._mtime = os.stat(path).st_mtime

    def get(self) -> dict:
        from kubeflow_tpu.web import form as form_lib

        try:
            mtime = os.stat(self.path).st_mtime
        except OSError:
            mtime = None
        if mtime is not None and mtime != self._mtime:
            try:
                self._config = load_spawner_config(self.path)
                self._mtime = mtime
            except Exception as e:  # noqa: BLE001 — keep the last good
                if self._warned_mtime != mtime:
                    import logging
                    logging.getLogger(__name__).error(
                        "spawner config %s unreadable (%s); keeping the "
                        "previous config", self.path, e)
                    self._warned_mtime = mtime
        return self._config or form_lib.DEFAULT_SPAWNER_CONFIG


def cluster_config_from_env(**overrides):
    """ClusterConfig honoring the reference's culler env knobs
    (culler.go:26-28: ENABLE_CULLING / CULL_IDLE_TIME minutes /
    IDLENESS_CHECK_PERIOD minutes) — the SAME env the deploy manifests
    set on the platform Deployment (deploy/generate.py platform()).
    Before this existed the gke overlay claimed culling and the booted
    process silently ignored it."""
    from kubeflow_tpu.controlplane.cluster import ClusterConfig
    from kubeflow_tpu.controlplane.controllers.culler import (
        HTTPActivityProbe,
    )

    enable = os.environ.get("ENABLE_CULLING", "false").lower() == "true"
    cfg = dict(
        enable_culling=enable,
        cull_idle_time=float(os.environ.get("CULL_IDLE_TIME",
                                            "1440")) * 60.0,
        cull_check_period=float(os.environ.get("IDLENESS_CHECK_PERIOD",
                                               "1")) * 60.0,
    )
    if enable:
        cfg["activity_probe"] = HTTPActivityProbe(
            cluster_domain=os.environ.get("CLUSTER_DOMAIN",
                                          "cluster.local"))
    cfg.update(overrides)
    return ClusterConfig(**cfg)


def main() -> None:  # pragma: no cover - manual entry point
    import argparse

    from kubeflow_tpu.controlplane.cluster import Cluster

    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, default=8082)
    p.add_argument("--tpu-slices", default="v5e-16=1,v5e-1=4")
    p.add_argument("--spawner-config", default="",
                   help="path to a spawner_ui_config.yaml (the deploy "
                        "manifests mount the spawner-config ConfigMap "
                        "here); empty/missing = built-in defaults, "
                        "matching the reference's dev fallback "
                        "(jupyter utils.py:22-53)")
    p.add_argument("--dev-user", default="",
                   help="identity to assume when no auth header is present "
                        "(local development without an auth proxy)")
    args = p.parse_args()

    spawner_config = (SpawnerConfigSource(args.spawner_config)
                      if args.spawner_config else None)
    slices = {}
    for part in args.tpu_slices.split(","):
        k, _, v = part.partition("=")
        if k:
            slices[k] = int(v or 1)
    cluster = Cluster(cluster_config_from_env(
        tpu_slices=slices,
        cluster_admins={args.dev_user} if args.dev_user else set(),
    )).start()
    app = cluster.create_web_app(dev_user=args.dev_user or None,
                                 spawner_config=spawner_config)
    web.run_app(app, port=args.port)


if __name__ == "__main__":  # pragma: no cover
    main()
