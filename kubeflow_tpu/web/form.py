"""Spawner form engine: admin config with value/readOnly semantics.

Re-design of the reference JWA's form layer
(jupyter/backend/apps/common/form.py:16-60 + spawner_ui_config.yaml):
- every form section has {value, readOnly}: readOnly pins the admin
  value; otherwise the user's value wins, falling back to the default;
- the GPU vendor picker (utils.py:56-85) becomes a TPU slice picker:
  the config lists allowed slice topologies (validated against the
  topology table) and a default parallelism mesh per topology;
- notebook construction fills a template Notebook CR the way
  post.py:27-36 calls form.set_notebook_* setters.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from kubeflow_tpu.api.core import (
    Container,
    PodTemplateSpec,
    Toleration,
    Volume,
    VolumeMount,
)
from kubeflow_tpu.api.crds import Notebook
from kubeflow_tpu.parallel.mesh import SLICE_TOPOLOGIES


class FormError(ValueError):
    pass


def parse_cpu(quantity: str) -> float:
    """k8s CPU quantity → cores: '500m' → 0.5, '2' → 2.0."""
    q = str(quantity).strip()
    try:
        if q.endswith("m"):
            return float(q[:-1]) / 1000.0
        return float(q)
    except ValueError:
        raise FormError(f"invalid CPU quantity {quantity!r}") from None


def _fmt_quantity_number(x: float) -> str:
    """Plain decimal (never scientific notation — k8s quantities forbid
    exponents combined with binary suffixes)."""
    if x == int(x):
        return str(int(x))
    out = f"{x:.3f}".rstrip("0").rstrip(".")
    if float(out or 0) == 0.0 and x != 0.0:
        # Don't collapse a tiny nonzero quantity to "0": widen precision
        # until the magnitude survives.
        out = f"{x:.12f}".rstrip("0").rstrip(".")
    return out


def format_cpu(cores: float) -> str:
    if cores < 1:
        return f"{int(round(cores * 1000))}m"
    return _fmt_quantity_number(cores)


def scale_memory(quantity: str, factor: float) -> str:
    """Scale a k8s memory quantity's numeric part, keeping its unit."""
    q = str(quantity).strip()
    i = len(q)
    while i > 0 and not (q[i - 1].isdigit() or q[i - 1] == "."):
        i -= 1
    num, unit = q[:i], q[i:]
    try:
        return f"{_fmt_quantity_number(float(num) * factor)}{unit}"
    except ValueError:
        raise FormError(f"invalid memory quantity {quantity!r}") from None


DEFAULT_SPAWNER_CONFIG: dict[str, Any] = {
    "image": {
        "value": "kubeflow-tpu/jupyter-jax:latest",
        "options": [
            # the images/ matrix (images/README.md) — every option is a
            # target `make -C images all` builds (tests/test_ci.py pins
            # this list to the Makefile)
            "kubeflow-tpu/jupyter-jax:latest",
            "kubeflow-tpu/jupyter-jax-tpu:latest",
            "kubeflow-tpu/jupyter-jax-full:latest",
            "kubeflow-tpu/jupyter-scipy:latest",
            "kubeflow-tpu/codeserver-jax:latest",
            "kubeflow-tpu/rstudio:latest",
            "kubeflow-tpu/rstudio-tidyverse:latest",
        ],
        "readOnly": False,
    },
    "cpu": {"value": "0.5", "limitFactor": 1.2, "readOnly": False},
    "memory": {"value": "1.0Gi", "limitFactor": 1.2, "readOnly": False},
    # TPU slice picker (replaces the reference's `gpus` vendor block)
    "tpu": {
        "value": {"topology": "", "mesh": ""},
        "options": ["", "v5e-1", "v5e-8", "v5e-16", "v5e-32"],
        "readOnly": False,
    },
    "workspaceVolume": {
        "value": {"name": "{notebook-name}-workspace", "size": "5Gi",
                  "mountPath": "/home/jovyan"},
        "readOnly": False,
    },
    "dataVolumes": {"value": [], "readOnly": False},
    "tolerations": {"value": [], "readOnly": False},
    "shm": {"value": True, "readOnly": False},
    "configurations": {"value": [], "readOnly": False},  # TpuPodDefault names
}


def get_form_value(body: dict, config: dict, field_name: str,
                   body_field: str | None = None) -> Any:
    """ref form.py:16-60: readOnly pins config; else user value or default."""
    section = config.get(field_name, {})
    if section.get("readOnly"):
        return section.get("value")
    return body.get(body_field or field_name, section.get("value"))


@dataclass
class NotebookForm:
    name: str
    namespace: str
    image: str
    cpu: str
    memory: str
    tpu_topology: str
    tpu_mesh: str
    workspace: dict | None
    data_volumes: list[dict] = field(default_factory=list)
    tolerations: list[dict] = field(default_factory=list)
    shm: bool = True
    configurations: list[str] = field(default_factory=list)


def parse_form(body: dict, config: dict[str, Any] | None = None) -> NotebookForm:
    config = config or DEFAULT_SPAWNER_CONFIG
    name = body.get("name", "")
    namespace = body.get("namespace", "")
    if not name or not namespace:
        raise FormError("name and namespace are required")

    image = get_form_value(body, config, "image")
    options = config.get("image", {}).get("options", [])
    # readOnly pins the admin value (trusted by construction); otherwise the
    # value is user-supplied and MUST be on the allowlist.
    if options and image not in options and not config["image"].get("readOnly"):
        raise FormError(f"image {image!r} not in allowed options")

    tpu = get_form_value(body, config, "tpu") or {}
    topo = tpu.get("topology", "")
    if topo and topo not in SLICE_TOPOLOGIES:
        raise FormError(
            f"unknown TPU topology {topo!r}; allowed: "
            f"{config.get('tpu', {}).get('options')}"
        )
    allowed = config.get("tpu", {}).get("options")
    if topo and allowed and topo not in allowed:
        raise FormError(f"TPU topology {topo!r} not allowed by admin config")

    ws = get_form_value(body, config, "workspaceVolume", "workspace")
    if ws:
        ws = dict(ws)
        ws["name"] = ws.get("name", "").replace("{notebook-name}", name) or (
            f"{name}-workspace"
        )

    return NotebookForm(
        name=name,
        namespace=namespace,
        image=image,
        cpu=str(get_form_value(body, config, "cpu")),
        memory=str(get_form_value(body, config, "memory")),
        tpu_topology=topo,
        tpu_mesh=tpu.get("mesh", ""),
        workspace=ws,
        data_volumes=get_form_value(body, config, "dataVolumes", "datavols") or [],
        tolerations=get_form_value(body, config, "tolerations") or [],
        shm=bool(get_form_value(body, config, "shm")),
        configurations=get_form_value(body, config, "configurations") or [],
    )


def build_notebook(form: NotebookForm, config: dict[str, Any] | None = None) -> Notebook:
    """Template → Notebook CR (ref notebook_template.yaml + setters)."""
    config = config or DEFAULT_SPAWNER_CONFIG
    nb = Notebook()
    nb.metadata.name = form.name
    nb.metadata.namespace = form.namespace
    nb.spec.tpu.topology = form.tpu_topology
    nb.spec.tpu.mesh = form.tpu_mesh

    cpu_factor = float(config.get("cpu", {}).get("limitFactor", 1.2))
    mem_factor = float(config.get("memory", {}).get("limitFactor", 1.2))
    container = Container(name=form.name, image=form.image)
    container.resources.requests = {"cpu": form.cpu, "memory": form.memory}
    container.resources.limits = {
        "cpu": format_cpu(parse_cpu(form.cpu) * cpu_factor),
        "memory": scale_memory(form.memory, mem_factor),
    }

    tmpl = PodTemplateSpec()
    tmpl.spec.containers.append(container)

    if form.workspace:
        tmpl.spec.volumes.append(
            Volume(name=form.workspace["name"],
                   pvc_name=form.workspace["name"])
        )
        container.volume_mounts.append(VolumeMount(
            name=form.workspace["name"],
            mount_path=form.workspace.get("mountPath", "/home/jovyan"),
        ))
    for dv in form.data_volumes:
        vol_name = dv.get("name") or dv.get("pvc")
        tmpl.spec.volumes.append(Volume(name=vol_name, pvc_name=vol_name))
        container.volume_mounts.append(VolumeMount(
            name=vol_name, mount_path=dv.get("mountPath", f"/data/{vol_name}"),
        ))
    if form.shm:
        tmpl.spec.volumes.append(Volume(name="dshm", empty_dir=True,
                                        size_limit="2Gi"))
        container.volume_mounts.append(
            VolumeMount(name="dshm", mount_path="/dev/shm"))
    for t in form.tolerations:
        tmpl.spec.tolerations.append(Toleration(
            key=t.get("key", ""), value=t.get("value", ""),
            effect=t.get("effect", ""),
        ))
    nb.spec.template = tmpl
    return nb


# -- status derivation (ref apps/common/status.py:9-99) ---------------------


def notebook_status(nb: Notebook, events: list) -> dict[str, str]:
    from kubeflow_tpu.api.crds import STOP_ANNOTATION

    if STOP_ANNOTATION in nb.metadata.annotations:
        if nb.status.ready_replicas == 0:
            return {"phase": "stopped", "message": "Notebook is stopped."}
        return {"phase": "terminating", "message": "Stopping the notebook."}
    if nb.status.ready_replicas > 0 and nb.status.container_state == "running":
        return {"phase": "ready", "message": "Running."}
    # ref find_error_event :79-95 — newest warning explains the wait
    warnings = sorted(
        (e for e in events if e.type == "Warning"),
        key=lambda e: e.timestamp, reverse=True,
    )
    if warnings:
        return {"phase": "warning", "message": warnings[0].message}
    return {"phase": "waiting", "message": "Starting the notebook."}
