"""Spawner form engine: admin config with value/readOnly semantics.

Re-design of the reference JWA's form layer
(jupyter/backend/apps/common/form.py:16-60 + spawner_ui_config.yaml):
- every form section has {value, readOnly}: readOnly pins the admin
  value; otherwise the user's value wins, falling back to the default;
- the GPU vendor picker (utils.py:56-85) becomes a TPU slice picker:
  the config lists allowed slice topologies (validated against the
  topology table) and a default parallelism mesh per topology;
- notebook construction fills a template Notebook CR the way
  post.py:27-36 calls form.set_notebook_* setters.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from kubeflow_tpu.api.core import (
    Container,
    PodTemplateSpec,
    Toleration,
    Volume,
    VolumeMount,
)
from kubeflow_tpu.api.crds import Notebook
from kubeflow_tpu.parallel.mesh import SLICE_TOPOLOGIES


class FormError(ValueError):
    pass


def parse_cpu(quantity: str) -> float:
    """k8s CPU quantity → cores: '500m' → 0.5, '2' → 2.0."""
    q = str(quantity).strip()
    try:
        if q.endswith("m"):
            return float(q[:-1]) / 1000.0
        return float(q)
    except ValueError:
        raise FormError(f"invalid CPU quantity {quantity!r}") from None


def _fmt_quantity_number(x: float) -> str:
    """Plain decimal (never scientific notation — k8s quantities forbid
    exponents combined with binary suffixes)."""
    if x == int(x):
        return str(int(x))
    out = f"{x:.3f}".rstrip("0").rstrip(".")
    if float(out or 0) == 0.0 and x != 0.0:
        # Don't collapse a tiny nonzero quantity to "0": widen precision
        # until the magnitude survives.
        out = f"{x:.12f}".rstrip("0").rstrip(".")
    return out


def format_cpu(cores: float) -> str:
    if cores < 1:
        return f"{int(round(cores * 1000))}m"
    return _fmt_quantity_number(cores)


def scale_memory(quantity: str, factor: float) -> str:
    """Scale a k8s memory quantity's numeric part, keeping its unit."""
    q = str(quantity).strip()
    i = len(q)
    while i > 0 and not (q[i - 1].isdigit() or q[i - 1] == "."):
        i -= 1
    num, unit = q[:i], q[i:]
    try:
        return f"{_fmt_quantity_number(float(num) * factor)}{unit}"
    except ValueError:
        raise FormError(f"invalid memory quantity {quantity!r}") from None


DEFAULT_SPAWNER_CONFIG: dict[str, Any] = {
    "image": {
        "value": "kubeflow-tpu/jupyter-jax:latest",
        "options": [
            # the images/ matrix (images/README.md) — every option is a
            # target `make -C images all` builds (tests/test_ci.py pins
            # this list to the Makefile)
            "kubeflow-tpu/jupyter-jax:latest",
            "kubeflow-tpu/jupyter-jax-tpu:latest",
            "kubeflow-tpu/jupyter-jax-full:latest",
            "kubeflow-tpu/jupyter-scipy:latest",
            "kubeflow-tpu/codeserver-jax:latest",
            "kubeflow-tpu/rstudio:latest",
            "kubeflow-tpu/rstudio-tidyverse:latest",
        ],
        "readOnly": False,
        # ref form.py:75-86 customImage: a body {"customImage": "..."}
        # bypasses the options list — but only when the admin opted in
        # (the reference trusts custom images unconditionally; an image
        # allowlist that any user can skip is not an allowlist).
        "allowCustom": False,
    },
    # ref form.py:88-93 set_notebook_image_pull_policy
    "imagePullPolicy": {
        "value": "IfNotPresent",
        "options": ["Always", "IfNotPresent", "Never"],
        "readOnly": False,
    },
    "cpu": {"value": "0.5", "limitFactor": 1.2, "readOnly": False},
    "memory": {"value": "1.0Gi", "limitFactor": 1.2, "readOnly": False},
    # Admin-defined placement groups (ref form.py:178-223
    # set_notebook_affinity/set_notebook_tolerations): the user picks a
    # KEY; the pod gets the admin's full affinity/toleration payload.
    # The worked example is the TPU story: pin notebooks to a TPU node
    # pool and tolerate its taint (generalizes the reference's only
    # placement-aware code, tensorboard RWO co-scheduling, SURVEY §5).
    "affinityConfig": {
        "value": "none",
        "options": [
            {"configKey": "tpu-v5e-pool",
             "desc": "Schedule onto the v5e TPU node pool",
             "affinity": [
                 {"key": "cloud.google.com/gke-tpu-accelerator",
                  "values": ["tpu-v5-lite-podslice"]},
             ]},
        ],
        "readOnly": False,
    },
    "tolerationGroup": {
        "value": "none",
        "options": [
            {"groupKey": "tpu-reserved",
             "desc": "Tolerate the reserved TPU pool taint",
             "tolerations": [
                 {"key": "google.com/tpu", "value": "present",
                  "effect": "NoSchedule"},
             ]},
        ],
        "readOnly": False,
    },
    # TPU slice picker (replaces the reference's `gpus` vendor block)
    "tpu": {
        "value": {"topology": "", "mesh": ""},
        "options": ["", "v5e-1", "v5e-8", "v5e-16", "v5e-32"],
        "readOnly": False,
    },
    "workspaceVolume": {
        "value": {"name": "{notebook-name}-workspace", "size": "5Gi",
                  "mountPath": "/home/jovyan"},
        "readOnly": False,
    },
    "dataVolumes": {"value": [], "readOnly": False},
    "tolerations": {"value": [], "readOnly": False},
    "shm": {"value": True, "readOnly": False},
    "configurations": {"value": [], "readOnly": False},  # TpuPodDefault names
}


def get_form_value(body: dict, config: dict, field_name: str,
                   body_field: str | None = None) -> Any:
    """ref form.py:16-60: readOnly pins config; else user value or default."""
    section = config.get(field_name, {})
    if section.get("readOnly"):
        return section.get("value")
    return body.get(body_field or field_name, section.get("value"))


@dataclass
class NotebookForm:
    name: str
    namespace: str
    image: str
    cpu: str
    memory: str
    tpu_topology: str
    tpu_mesh: str
    workspace: dict | None
    data_volumes: list[dict] = field(default_factory=list)
    tolerations: list[dict] = field(default_factory=list)
    shm: bool = True
    configurations: list[str] = field(default_factory=list)
    image_pull_policy: str = ""
    affinity_config: str = "none"     # configKey into admin options
    toleration_group: str = "none"    # groupKey into admin options


def parse_form(body: dict, config: dict[str, Any] | None = None) -> NotebookForm:
    config = config or DEFAULT_SPAWNER_CONFIG
    name = body.get("name", "")
    namespace = body.get("namespace", "")
    if not name or not namespace:
        raise FormError("name and namespace are required")

    image_cfg = config.get("image", {})
    custom_image = body.get("customImage", "")
    if custom_image and not image_cfg.get("readOnly"):
        # ref form.py:75-86: customImage bypasses the picker — gated on
        # admin opt-in here (readOnly still pins the admin image).
        if not image_cfg.get("allowCustom"):
            raise FormError("custom images are not allowed by the "
                            "admin config (image.allowCustom)")
        image = str(custom_image)
    else:
        image = get_form_value(body, config, "image")
        options = image_cfg.get("options", [])
        # readOnly pins the admin value (trusted by construction);
        # otherwise the value is user-supplied and MUST be allowlisted.
        if options and image not in options and not image_cfg.get("readOnly"):
            raise FormError(f"image {image!r} not in allowed options")

    pull_policy = str(get_form_value(body, config, "imagePullPolicy")
                      or "")
    pp_cfg = config.get("imagePullPolicy", {})
    pp_options = pp_cfg.get("options", [])
    # readOnly values are the admin's own (trusted by construction, same
    # rule as the image allowlist above) — only user input is checked.
    if (pull_policy and pp_options and pull_policy not in pp_options
            and not pp_cfg.get("readOnly")):
        raise FormError(
            f"imagePullPolicy {pull_policy!r} not in {pp_options}")

    tpu = get_form_value(body, config, "tpu") or {}
    topo = tpu.get("topology", "")
    if topo and topo not in SLICE_TOPOLOGIES:
        raise FormError(
            f"unknown TPU topology {topo!r}; allowed: "
            f"{config.get('tpu', {}).get('options')}"
        )
    allowed = config.get("tpu", {}).get("options")
    if topo and allowed and topo not in allowed:
        raise FormError(f"TPU topology {topo!r} not allowed by admin config")

    ws = get_form_value(body, config, "workspaceVolume", "workspace")
    if ws:
        ws = dict(ws)
        ws["name"] = ws.get("name", "").replace("{notebook-name}", name) or (
            f"{name}-workspace"
        )

    # Group-key pickers (ref form.py:178-223): resolved against the
    # admin options at BUILD time; validate the keys here so a typo is
    # a 400, not a silently unplaced pod (the reference only logs).
    aff_cfg = config.get("affinityConfig", {})
    aff_key = str(get_form_value(body, config, "affinityConfig")
                  or "none")
    aff_keys = {o.get("configKey") for o in aff_cfg.get("options", [])}
    if (aff_key != "none" and aff_key not in aff_keys
            and not aff_cfg.get("readOnly")):
        raise FormError(f"unknown affinityConfig key {aff_key!r}; "
                        f"allowed: {sorted(aff_keys) + ['none']}")
    tol_cfg = config.get("tolerationGroup", {})
    tol_key = str(get_form_value(body, config, "tolerationGroup")
                  or "none")
    tol_keys = {o.get("groupKey") for o in tol_cfg.get("options", [])}
    if (tol_key != "none" and tol_key not in tol_keys
            and not tol_cfg.get("readOnly")):
        raise FormError(f"unknown tolerationGroup key {tol_key!r}; "
                        f"allowed: {sorted(tol_keys) + ['none']}")

    return NotebookForm(
        name=name,
        namespace=namespace,
        image=image,
        cpu=str(get_form_value(body, config, "cpu")),
        memory=str(get_form_value(body, config, "memory")),
        tpu_topology=topo,
        tpu_mesh=tpu.get("mesh", ""),
        workspace=ws,
        data_volumes=get_form_value(body, config, "dataVolumes", "datavols") or [],
        tolerations=get_form_value(body, config, "tolerations") or [],
        shm=bool(get_form_value(body, config, "shm")),
        configurations=get_form_value(body, config, "configurations") or [],
        image_pull_policy=pull_policy,
        affinity_config=aff_key,
        toleration_group=tol_key,
    )


def build_notebook(form: NotebookForm, config: dict[str, Any] | None = None) -> Notebook:
    """Template → Notebook CR (ref notebook_template.yaml + setters)."""
    config = config or DEFAULT_SPAWNER_CONFIG
    nb = Notebook()
    nb.metadata.name = form.name
    nb.metadata.namespace = form.namespace
    nb.spec.tpu.topology = form.tpu_topology
    nb.spec.tpu.mesh = form.tpu_mesh

    cpu_factor = float(config.get("cpu", {}).get("limitFactor", 1.2))
    mem_factor = float(config.get("memory", {}).get("limitFactor", 1.2))
    container = Container(name=form.name, image=form.image,
                          image_pull_policy=form.image_pull_policy)
    container.resources.requests = {"cpu": form.cpu, "memory": form.memory}
    container.resources.limits = {
        "cpu": format_cpu(parse_cpu(form.cpu) * cpu_factor),
        "memory": scale_memory(form.memory, mem_factor),
    }

    tmpl = PodTemplateSpec()
    tmpl.spec.containers.append(container)

    if form.workspace:
        tmpl.spec.volumes.append(
            Volume(name=form.workspace["name"],
                   pvc_name=form.workspace["name"])
        )
        container.volume_mounts.append(VolumeMount(
            name=form.workspace["name"],
            mount_path=form.workspace.get("mountPath", "/home/jovyan"),
        ))
    for dv in form.data_volumes:
        vol_name = dv.get("name") or dv.get("pvc")
        tmpl.spec.volumes.append(Volume(name=vol_name, pvc_name=vol_name))
        container.volume_mounts.append(VolumeMount(
            name=vol_name, mount_path=dv.get("mountPath", f"/data/{vol_name}"),
        ))
    if form.shm:
        tmpl.spec.volumes.append(Volume(name="dshm", empty_dir=True,
                                        size_limit="2Gi"))
        container.volume_mounts.append(
            VolumeMount(name="dshm", mount_path="/dev/shm"))
    for t in form.tolerations:
        tmpl.spec.tolerations.append(Toleration(
            key=t.get("key", ""), value=t.get("value", ""),
            effect=t.get("effect", ""),
        ))

    # Admin placement groups (ref form.py:178-223): the key the user
    # picked expands to the admin's full payload on the pod template.
    if form.affinity_config != "none":
        for opt in config.get("affinityConfig", {}).get("options", []):
            if opt.get("configKey") == form.affinity_config:
                from kubeflow_tpu.api.core import NodeSelectorTerm
                tmpl.spec.affinity_terms.extend(
                    NodeSelectorTerm(key=a.get("key", ""),
                                     values=list(a.get("values", [])))
                    for a in opt.get("affinity", []))
                break
    if form.toleration_group != "none":
        for opt in config.get("tolerationGroup", {}).get("options", []):
            if opt.get("groupKey") == form.toleration_group:
                tmpl.spec.tolerations.extend(
                    Toleration(key=t.get("key", ""),
                               value=t.get("value", ""),
                               effect=t.get("effect", ""))
                    for t in opt.get("tolerations", []))
                break
    nb.spec.template = tmpl
    return nb


# -- status derivation (ref apps/common/status.py:9-99) ---------------------


def notebook_status(nb: Notebook, events: list) -> dict[str, str]:
    from kubeflow_tpu.api.crds import STOP_ANNOTATION

    if STOP_ANNOTATION in nb.metadata.annotations:
        if nb.status.ready_replicas == 0:
            return {"phase": "stopped", "message": "Notebook is stopped."}
        return {"phase": "terminating", "message": "Stopping the notebook."}
    if nb.status.ready_replicas > 0 and nb.status.container_state == "running":
        return {"phase": "ready", "message": "Running."}
    # ref find_error_event :79-95 — newest warning explains the wait
    warnings = sorted(
        (e for e in events if e.type == "Warning"),
        key=lambda e: e.timestamp, reverse=True,
    )
    if warnings:
        return {"phase": "warning", "message": warnings[0].message}
    return {"phase": "waiting", "message": "Starting the notebook."}
