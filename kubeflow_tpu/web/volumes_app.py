"""Volumes web app (VWA): PVC CRUD (ref crud-web-apps/volumes/backend)."""

from __future__ import annotations

from aiohttp import web

from kubeflow_tpu.api.core import PersistentVolumeClaim
from kubeflow_tpu.controlplane.store import Store
from kubeflow_tpu.web.common import (
    STORE_KEY,
    base_app,
    ensure_authorized,
    json_success,
)


def create_volumes_app(store: Store, *, cluster_admins: set[str] | None = None,
                       csrf: bool = True) -> web.Application:
    app = base_app(store, csrf=csrf, cluster_admins=cluster_admins)
    app.router.add_get("/api/namespaces/{ns}/pvcs", list_pvcs)
    app.router.add_post("/api/namespaces/{ns}/pvcs", post_pvc)
    app.router.add_delete("/api/namespaces/{ns}/pvcs/{name}", delete_pvc)
    return app


def _used_by(store: Store, ns: str, pvc_name: str) -> list[str]:
    """Workloads mounting this PVC (VWA shows 'used by' to block deletes):
    Notebooks via pod-template volumes, Tensorboards via pvc:// logspath."""
    out = []
    for nb in store.list("Notebook", ns):
        if any(v.pvc_name == pvc_name for v in nb.spec.template.spec.volumes):
            out.append(nb.metadata.name)
    for tb in store.list("Tensorboard", ns):
        logspath = tb.spec.logspath
        if logspath.startswith("pvc://"):
            mounted = logspath[len("pvc://"):].partition("/")[0]
            if mounted == pvc_name:
                out.append(f"tensorboard/{tb.metadata.name}")
    return out


async def list_pvcs(request: web.Request):
    ns = request.match_info["ns"]
    ensure_authorized(request, "list", "PersistentVolumeClaim", ns)
    store: Store = request.app[STORE_KEY]
    return json_success({
        "pvcs": [
            {
                "name": p.metadata.name,
                "size": p.storage,
                "accessModes": p.access_modes,
                "storageClass": p.storage_class,
                "phase": p.phase,
                "usedBy": _used_by(store, ns, p.metadata.name),
            }
            for p in store.list("PersistentVolumeClaim", ns)
        ]
    })


async def post_pvc(request: web.Request):
    ns = request.match_info["ns"]
    ensure_authorized(request, "create", "PersistentVolumeClaim", ns)
    body = await request.json()
    pvc = PersistentVolumeClaim()
    pvc.metadata.name = body["name"]
    pvc.metadata.namespace = ns
    pvc.storage = body.get("size", "5Gi")
    if body.get("mode"):
        pvc.access_modes = [body["mode"]]
    if body.get("class"):
        pvc.storage_class = body["class"]
    request.app[STORE_KEY].create(pvc)
    return json_success({"name": pvc.metadata.name}, status=201)


async def delete_pvc(request: web.Request):
    ns, name = request.match_info["ns"], request.match_info["name"]
    ensure_authorized(request, "delete", "PersistentVolumeClaim", ns)
    store: Store = request.app[STORE_KEY]
    users = _used_by(store, ns, name)
    if users:
        from kubeflow_tpu.web.common import json_error

        return json_error(
            f"PVC {name} is in use by: {', '.join(users)}", 409
        )
    store.delete("PersistentVolumeClaim", ns, name)
    return json_success()
