"""Shared web layer: authn middleware, authz helpers, CSRF, errors.

The asyncio re-design of the reference's crud_backend package
(crud-web-apps/common/backend/kubeflow/kubeflow/crud_backend/):
- header authn middleware (authn.py:12-67);
- per-request SAR-style authz via controlplane.auth (authz.py:113-132
  decorator → here a helper called by handlers);
- CSRF double-submit cookie for non-GET (csrf.py:57-111);
- uniform JSON errors + success envelopes, healthz/readyz probes
  (probes.py).
"""

from __future__ import annotations

import json
import logging
from typing import Any

from aiohttp import web

from kubeflow_tpu.controlplane import auth
from kubeflow_tpu.controlplane.kfam import Kfam, KfamError
from kubeflow_tpu.controlplane.store import (
    AdmissionDenied,
    AlreadyExists,
    Conflict,
    NotFound,
    Store,
)

log = logging.getLogger(__name__)

# Typed application-config keys (aiohttp AppKey). String keys still
# work but warn (NotAppKeyWarning) and lose type information; these are
# the platform's shared app-state slots, importable by every subapp.
STORE_KEY: web.AppKey = web.AppKey("store", Store)
CLUSTER_ADMINS_KEY: web.AppKey = web.AppKey("cluster_admins", set)
KFAM_KEY: web.AppKey = web.AppKey("kfam", Kfam)
# dict OR a hot-reloading source with .get() -> dict
# (platform.SpawnerConfigSource); read through
# jupyter_app._spawner_config, not directly.
SPAWNER_CONFIG_KEY: web.AppKey = web.AppKey("spawner_config", object)
LINKS_KEY: web.AppKey = web.AppKey("links", object)
PLATFORM_METRICS_KEY: web.AppKey = web.AppKey("platform_metrics", object)
# obs.Tracer serving request spans + /debug/traces (set by platform.py).
TRACER_KEY: web.AppKey = web.AppKey("tracer", object)
DEV_USER_KEY: web.AppKey = web.AppKey("dev_user", str)
CSRF_EXEMPT_KEY: web.AppKey = web.AppKey("csrf_exempt_prefixes", tuple)

AUTH_EXEMPT = {"/healthz", "/readyz", "/metrics", "/debug/traces", "/"}
# The SPA shell and its assets load before identity is known — the auth
# proxy injects the userid header on API calls; the shell itself is
# public (same as the reference serving the dashboard bundle).
AUTH_EXEMPT_PREFIXES = ("/static/",)


def json_success(payload: dict[str, Any] | None = None, status: int = 200):
    body = {"success": True, "status": status}
    if payload:
        body.update(payload)
    return web.json_response(body, status=status)


def json_error(message: str, status: int):
    return web.json_response(
        {"success": False, "status": status, "log": message}, status=status
    )


@web.middleware
async def error_middleware(request: web.Request, handler):
    try:
        return await handler(request)
    except web.HTTPException:
        raise
    except auth.Unauthenticated as e:
        return json_error(str(e), 401)
    except auth.Forbidden as e:
        return json_error(str(e), 403)
    except KfamError as e:
        return json_error(str(e), e.status)
    except NotFound as e:
        return json_error(str(e), 404)
    except (AlreadyExists, Conflict) as e:
        return json_error(str(e), 409)
    except AdmissionDenied as e:
        return json_error(str(e), 422)
    except (KeyError, ValueError, json.JSONDecodeError) as e:
        return json_error(f"bad request: {e}", 400)
    except Exception:
        log.exception("unhandled error for %s", request.path)
        return json_error("internal error", 500)


@web.middleware
async def tracing_middleware(request: web.Request, handler):
    """Root span per request + `X-Trace-Id` on the response, so a slow
    call's server-side trace is one header copy-paste away. Outermost
    (platform.py inserts it first): authn/CSRF rejections and handler
    crashes are spans too."""
    tracer = request.config_dict.get(TRACER_KEY)
    if tracer is None:
        return await handler(request)
    with tracer.span("http.request", method=request.method,
                     path=request.path) as span:
        try:
            resp = await handler(request)
        except web.HTTPException as exc:
            span.attrs["status"] = exc.status
            exc.headers.setdefault("X-Trace-Id", span.trace_id)
            raise
        span.attrs["status"] = resp.status
        if not resp.prepared:  # streamed responses set it pre-prepare
            resp.headers.setdefault("X-Trace-Id", span.trace_id)
        return resp


@web.middleware
async def authn_middleware(request: web.Request, handler):
    if request.path in AUTH_EXEMPT or request.path.startswith(
        AUTH_EXEMPT_PREFIXES
    ):
        return await handler(request)
    try:
        request["user"] = auth.authenticate(request.headers)
    except auth.Unauthenticated:
        # DEV fallback (ref getBasicEnvironment, api_workgroup.ts:147-158:
        # no identity headers ⇒ a fixed local identity). Only active when
        # the operator opts in (create_platform_app(dev_user=...)) —
        # production deployments sit behind an auth proxy that always
        # injects the header.
        dev = request.config_dict.get(DEV_USER_KEY)
        if not dev:
            raise
        request["user"] = auth.User(dev)
    return await handler(request)


@web.middleware
async def csrf_middleware(request: web.Request, handler):
    # Parent-app middlewares wrap subapp requests too; service APIs
    # (mesh-internal, no browser) opt out by prefix.
    for prefix in request.app.get(CSRF_EXEMPT_KEY, ()):
        if request.path.startswith(prefix):
            return await handler(request)
    if request.method in ("GET", "HEAD", "OPTIONS"):
        resp = await handler(request)
        # hand the SPA a token to echo back (double-submit)
        if auth.CSRF_COOKIE not in request.cookies:
            try:
                resp.set_cookie(auth.CSRF_COOKIE, auth.new_csrf_token(),
                                httponly=False, samesite="Strict")
            except AttributeError:
                pass
        return resp
    if request.path in AUTH_EXEMPT:
        return await handler(request)
    cookie = request.cookies.get(auth.CSRF_COOKIE)
    header = request.headers.get(auth.CSRF_HEADER)
    if not auth.check_csrf(cookie, header):
        return json_error("CSRF token missing or mismatched", 403)
    return await handler(request)


def add_probes(app: web.Application) -> None:
    async def ok(_request):
        return web.json_response({"status": "ok"})

    app.router.add_get("/healthz", ok)
    app.router.add_get("/readyz", ok)


def base_app(store: Store, *, csrf: bool = True,
             cluster_admins: set[str] | None = None) -> web.Application:
    middlewares = [error_middleware, authn_middleware]
    if csrf:
        middlewares.append(csrf_middleware)
    app = web.Application(middlewares=middlewares)
    app[STORE_KEY] = store
    app[CLUSTER_ADMINS_KEY] = cluster_admins or set()
    add_probes(app)
    return app


def ensure_authorized(request: web.Request, verb: str, kind: str,
                      namespace: str) -> auth.User:
    user: auth.User = request["user"]
    store: Store = request.app[STORE_KEY]
    admins = request.app.get(CLUSTER_ADMINS_KEY) or set()
    auth.ensure_authorized(store, user, verb, kind, namespace,
                           cluster_admins=admins)
    return user
