"""Web/API surface (reference L5): spawner, volumes, tensorboards CRUD
apps + central dashboard BFF + KFAM REST — aiohttp apps sharing one
authn/authz/CSRF middleware stack (the reference's crud_backend common
layer re-done for asyncio)."""

from kubeflow_tpu.web.platform import create_platform_app
