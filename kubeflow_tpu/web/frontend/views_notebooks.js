// Notebooks list + spawner form (ref crud-web-apps/jupyter/frontend
// pages/index and pages/form). The form is driven ENTIRELY by the
// admin spawner config from GET /jupyter/api/config: readOnly sections
// render pinned (disabled) controls, options populate pickers — the
// same value/readOnly contract the backend enforces (web/form.py).

import { api, routes } from '/static/api.js';
import { h, state, toast, reportError, render } from '/static/app.js';

const PHASE_DOT = {
  ready: 'ready',
  waiting: 'waiting',
  warning: 'warning',
  stopped: 'stopped',
  terminating: 'terminating',
};

export async function notebooksView() {
  const ns = state.namespace;
  if (!ns) return h('div', { class: 'card empty' }, 'No namespace selected.');
  const data = await api.get(routes.notebooks(ns));

  const rows = (data.notebooks || []).map((nb) => {
    const stopped = nb.status.phase === 'stopped';
    const stopBtn = h(
      'button',
      {
        class: 'small',
        onclick: async () => {
          try {
            await api.patch(routes.notebook(ns, nb.name), { stopped: !stopped });
            toast(stopped ? `Starting ${nb.name}` : `Stopping ${nb.name}`);
            render();
          } catch (err) {
            reportError(err);
          }
        },
      },
      stopped ? 'Start' : 'Stop',
    );
    const delBtn = h(
      'button',
      {
        class: 'small danger',
        onclick: async () => {
          if (!confirm(`Delete notebook ${nb.name}? Its workspace PVC is kept.`)) return;
          try {
            await api.del(routes.notebook(ns, nb.name));
            toast(`Deleted ${nb.name}`);
            render();
          } catch (err) {
            reportError(err);
          }
        },
      },
      'Delete',
    );
    return h(
      'tr',
      {},
      h(
        'td',
        {},
        h(
          'span',
          { class: 'status', title: nb.status.message },
          h('span', { class: `dot ${PHASE_DOT[nb.status.phase] || 'waiting'}` }),
          nb.status.phase,
        ),
      ),
      h('td', {},
        h('a', { href: `#/jupyter/detail/${encodeURIComponent(nb.name)}` }, nb.name),
        nb.status.phase === 'ready'
          ? h('span', {}, ' ', h('a', { href: nb.serverUrl, target: '_blank', rel: 'noopener', class: 'small' }, 'open ↗'))
          : null),
      h('td', {}, nb.image.split('/').pop()),
      h('td', {}, nb.tpu.topology || '—'),
      h('td', {}, String(nb.readyReplicas)),
      h('td', { title: nb.status.message }, nb.status.message),
      h('td', {}, stopBtn, ' ', delBtn),
    );
  });

  return h(
    'div',
    { class: 'card' },
    h(
      'div',
      { class: 'toolbar' },
      h('h2', {}, `Notebooks in ${ns}`),
      h('button', { class: 'primary', onclick: () => (location.hash = '#/jupyter/new') }, '+ New Notebook'),
    ),
    rows.length
      ? h(
          'table',
          { class: 'grid' },
          h(
            'thead',
            {},
            h('tr', {}, h('th', {}, 'Status'), h('th', {}, 'Name'), h('th', {}, 'Image'), h('th', {}, 'TPU'), h('th', {}, 'Ready'), h('th', {}, 'Info'), h('th', {}, '')),
          ),
          h('tbody', {}, rows),
        )
      : h('div', { class: 'empty' }, 'No notebooks yet — spawn one with “New Notebook”.'),
  );
}

// -- notebook detail (ref JWA details page: status + events + pods) --

export async function notebookDetailView(name) {
  const ns = state.namespace;
  if (!ns) return h('div', { class: 'card empty' }, 'No namespace selected.');
  const { notebook: nb } = await api.get(routes.notebook(ns, name));

  const eventRows = (nb.events || []).map((e) =>
    h(
      'tr',
      {},
      h('td', {}, h('span', { class: `dot ${e.type === 'Warning' ? 'warning' : 'ready'}` }), e.type),
      h('td', {}, e.reason),
      h('td', {}, e.message),
      h('td', {}, String(e.count)),
    ),
  );
  const podRows = (nb.pods || []).map((p) =>
    h(
      'tr',
      {},
      h('td', {}, p.name),
      h('td', {}, p.phase || 'Pending'),
      h('td', {}, p.workerId === '' ? '—' : p.workerId),
    ),
  );

  return h(
    'div',
    { class: 'card' },
    h(
      'div',
      { class: 'toolbar' },
      h('h2', {}, `Notebook ${name}`),
      h('button', { onclick: () => (location.hash = '#/jupyter') }, '← Back'),
    ),
    h(
      'div',
      { class: 'form-grid' },
      h('label', {}, 'Status'),
      h('span', { class: 'status' },
        h('span', { class: `dot ${PHASE_DOT[nb.status.phase] || 'waiting'}` }),
        `${nb.status.phase} — ${nb.status.message}`),
      h('label', {}, 'Image'),
      h('span', {}, nb.image),
      h('label', {}, 'TPU slice'),
      h('span', {}, nb.tpu.topology ? `${nb.tpu.topology}${nb.tpu.mesh ? ` (${nb.tpu.mesh})` : ''}` : 'none (CPU only)'),
      h('label', {}, 'Ready replicas'),
      h('span', {}, String(nb.readyReplicas)),
    ),
    h('h3', {}, `Gang pods (${podRows.length})`),
    podRows.length
      ? h(
          'table',
          { class: 'grid', id: 'detail-pods' },
          h('thead', {}, h('tr', {}, h('th', {}, 'Pod'), h('th', {}, 'Phase'), h('th', {}, 'TPU_WORKER_ID'))),
          h('tbody', {}, podRows),
        )
      : h('div', { class: 'empty' }, 'No pods (stopped or pending scheduling).'),
    h('h3', {}, `Events (${eventRows.length})`),
    eventRows.length
      ? h(
          'table',
          { class: 'grid', id: 'detail-events' },
          h('thead', {}, h('tr', {}, h('th', {}, 'Type'), h('th', {}, 'Reason'), h('th', {}, 'Message'), h('th', {}, 'Count'))),
          h('tbody', {}, eventRows),
        )
      : h('div', { class: 'empty' }, 'No events recorded.'),
  );
}

// -- spawner form ---------------------------------------------------

function section(config, key) {
  return config[key] || { value: '', readOnly: false };
}

function pinned(sec) {
  return sec.readOnly ? { disabled: '' } : {};
}

function roPill(sec) {
  return sec.readOnly ? h('span', { class: 'readonly-pill' }, 'admin-pinned') : null;
}

// Live validators (ref: the Angular spawner's per-field validation,
// crud-web-apps/jupyter/frontend form). These mirror the BACKEND's
// laws (web/form.py parse_form / parse_cpu / scale_memory + the
// notebook controller's mesh check) so a user learns about a bad
// value at the field, not from a 400 — the backend stays the
// authority either way.
export const validators = {
  name(v) {
    if (!v) return 'a name is required';
    if (v.length > 63) return 'at most 63 characters';
    if (!/^[a-z0-9]([-a-z0-9]*[a-z0-9])?$/.test(v)) {
      return 'lowercase letters, digits and dashes; must start and end alphanumeric';
    }
    return '';
  },
  cpu(v) {
    if (!v) return 'required';
    // mirror web/form.py parse_cpu exactly: float millicores allowed
    if (/^\d+(\.\d+)?m$/.test(v)) return '';
    return /^\d+(\.\d+)?$/.test(v) ? '' : "cores ('0.5') or millicores ('500m')";
  },
  memory(v) {
    if (!v) return 'required';
    // mirror web/form.py scale_memory's unit set (incl. Pi/Ei)
    return /^\d+(\.\d+)?(Ki|Mi|Gi|Ti|Pi|Ei|K|M|G|T|P|E)?$/.test(v)
      ? '' : "a quantity like '1Gi' or '512Mi'";
  },
  mesh(v, chips) {
    if (!v) return ''; // empty = pure FSDP
    let product = 1;
    const seen = new Set();
    for (const part of v.split(',')) {
      const m = /^\s*(data|fsdp|tensor)\s*=\s*(\d+)\s*$/.exec(part);
      if (!m) return "entries like 'data=1,fsdp=16,tensor=1'";
      // the backend keeps the LAST value per axis (dict overwrite), so
      // a duplicate whose product happens to match would green-light a
      // mesh that fails at runtime
      if (seen.has(m[1])) return `axis '${m[1]}' given twice`;
      seen.add(m[1]);
      product *= Number(m[2]);
    }
    if (chips && product !== chips) {
      return `axes multiply to ${product}, but the slice has ${chips} chips`;
    }
    return '';
  },
  size(v) {
    return /^\d+(\.\d+)?(Ki|Mi|Gi|Ti)$/.test(v) ? '' : "a size like '5Gi'";
  },
};

export async function notebookFormView() {
  const ns = state.namespace;
  if (!ns) return h('div', { class: 'card empty' }, 'No namespace selected.');
  const [cfgResp, pdResp] = await Promise.all([
    api.get(routes.spawnerConfig),
    api.get(routes.poddefaults(ns)),
  ]);
  const { config } = cfgResp;
  const tpuTopologies = cfgResp.tpuTopologies || {};
  const poddefaults = pdResp.poddefaults || [];

  const img = section(config, 'image');
  const cpu = section(config, 'cpu');
  const mem = section(config, 'memory');
  const tpu = section(config, 'tpu');
  const ws = section(config, 'workspaceVolume');
  const shm = section(config, 'shm');
  const confs = section(config, 'configurations');

  const nameInput = h('input', { placeholder: 'my-notebook', 'aria-label': 'Name' });
  const imageSelect = h(
    'select',
    { 'aria-label': 'Image', ...pinned(img) },
    (img.options || [img.value]).map((o) =>
      h('option', { value: o, ...(o === img.value ? { selected: '' } : {}) }, o),
    ),
  );
  const cpuInput = h('input', { value: cpu.value, ...(cpu.readOnly ? { readonly: '' } : {}) });
  const memInput = h('input', { value: mem.value, ...(mem.readOnly ? { readonly: '' } : {}) });

  const topoSelect = h(
    'select',
    { 'aria-label': 'TPU slice', ...pinned(tpu) },
    (tpu.options || ['']).map((o) =>
      h('option', { value: o, ...(o === (tpu.value || {}).topology ? { selected: '' } : {}) }, o === '' ? 'none (CPU only)' : o),
    ),
  );
  const meshInput = h('input', {
    placeholder: 'data=1,fsdp=16,tensor=1 (optional)',
    value: (tpu.value || {}).mesh || '',
    ...(tpu.readOnly ? { readonly: '' } : {}),
  });

  const aff = section(config, 'affinityConfig');
  const tol = section(config, 'tolerationGroup');
  const groupSelect = (sec, keyField, label) =>
    h(
      'select',
      { 'aria-label': label, ...pinned(sec) },
      [h('option', { value: 'none', ...(sec.value === 'none' ? { selected: '' } : {}) }, 'none')].concat(
        (sec.options || []).map((o) =>
          h(
            'option',
            { value: o[keyField], ...(o[keyField] === sec.value ? { selected: '' } : {}) },
            `${o[keyField]} — ${o.desc || ''}`,
          ),
        ),
      ),
    );
  const affSelect = groupSelect(aff, 'configKey', 'Affinity group');
  const tolSelect = groupSelect(tol, 'groupKey', 'Toleration group');

  const wsName = h('input', {
    value: (ws.value || {}).name || '{notebook-name}-workspace',
    ...(ws.readOnly ? { readonly: '' } : {}),
  });
  const wsSize = h('input', {
    value: (ws.value || {}).size || '5Gi',
    ...(ws.readOnly ? { readonly: '' } : {}),
  });
  const shmCheck = h('input', {
    type: 'checkbox',
    ...(shm.value ? { checked: '' } : {}),
    ...pinned(shm),
  });

  const pdChecks = poddefaults.map((pd) => {
    const selected = (confs.value || []).includes(pd.name);
    const cb = h('input', {
      type: 'checkbox',
      value: pd.name,
      ...(selected ? { checked: '' } : {}),
      ...pinned(confs),
    });
    return h('label', { class: 'check-row' }, cb, `${pd.name} — ${pd.desc || 'no description'}`);
  });

  const submit = h('button', { class: 'primary' }, 'Launch');

  // live validation: each field gets an inline error line, updated on
  // input; Launch disables while anything is invalid
  const errEls = {};
  const fieldErr = (key) => {
    errEls[key] = h('div', { class: 'field-err', 'data-for': key });
    return errEls[key];
  };
  const checks = {
    name: () => validators.name(nameInput.value.trim()),
    cpu: () => (cpu.readOnly ? '' : validators.cpu(cpuInput.value.trim())),
    memory: () => (mem.readOnly ? '' : validators.memory(memInput.value.trim())),
    mesh: () => (tpu.readOnly ? '' : validators.mesh(
      meshInput.value.trim(), tpuTopologies[topoSelect.value] || 0)),
    size: () => (ws.readOnly ? '' : validators.size(wsSize.value.trim())),
  };
  const revalidate = () => {
    let bad = false;
    for (const [key, check] of Object.entries(checks)) {
      const msg = check();
      if (errEls[key]) errEls[key].textContent = msg;
      bad = bad || !!msg;
    }
    submit.disabled = bad;
    return !bad;
  };
  for (const el of [nameInput, cpuInput, memInput, meshInput, wsSize]) {
    el.addEventListener('input', revalidate);
  }
  topoSelect.addEventListener('change', revalidate);

  submit.addEventListener('click', async () => {
    if (!revalidate()) return;
    submit.disabled = true;
    try {
      const body = {
        name: nameInput.value.trim(),
        image: imageSelect.value,
        cpu: cpuInput.value,
        memory: memInput.value,
        tpu: { topology: topoSelect.value, mesh: meshInput.value.trim() },
        affinityConfig: affSelect.value,
        tolerationGroup: tolSelect.value,
        workspace: { name: wsName.value, size: wsSize.value },
        shm: shmCheck.checked,
        configurations: pdChecks
          .map((row) => row.querySelector('input'))
          .filter((cb) => cb.checked)
          .map((cb) => cb.value),
      };
      await api.post(routes.notebooks(ns), body);
      toast(`Notebook ${body.name} created`);
      location.hash = '#/jupyter';
    } catch (err) {
      reportError(err);
      submit.disabled = false;
    }
  });

  return h(
    'div',
    { class: 'card' },
    h('h2', {}, 'New Notebook'),
    h('p', { class: 'sub' }, `Namespace ${ns} — fields the admin pinned are read-only.`),
    h(
      'div',
      { class: 'form-grid' },
      h('label', {}, 'Name'),
      h('div', {}, nameInput, fieldErr('name')),
      h('label', {}, 'Image', roPill(img)),
      imageSelect,
      h('label', {}, 'CPU'),
      h('div', {}, cpuInput, fieldErr('cpu')),
      h('label', {}, 'Memory'),
      h('div', {}, memInput, fieldErr('memory')),
      h('label', {}, 'TPU slice', roPill(tpu)),
      topoSelect,
      h('label', {}, 'Device mesh'),
      h('div', {}, meshInput, fieldErr('mesh')),
      h('div', { class: 'field-note' }, 'Mesh axes (data/fsdp/tensor) must multiply to the slice chip count; leave empty for pure FSDP.'),
      h('label', {}, 'Affinity group', roPill(aff)),
      affSelect,
      h('label', {}, 'Toleration group', roPill(tol)),
      tolSelect,
      h('label', {}, 'Workspace volume', roPill(ws)),
      h('div', {}, wsName, h('div', { class: 'field-note' }, '{notebook-name} expands to the server name.')),
      h('label', {}, 'Workspace size'),
      h('div', {}, wsSize, fieldErr('size')),
      h('label', {}, 'Shared memory'),
      h('label', { class: 'check-row' }, shmCheck, 'mount /dev/shm'),
      h('label', { class: 'span2' }, 'Configurations (TpuPodDefaults)'),
      pdChecks.length ? h('div', { class: 'span2' }, pdChecks) : h('div', { class: 'field-note span2' }, 'None available in this namespace.'),
      h('div', { class: 'span2' }, submit, ' ', h('button', { onclick: () => (location.hash = '#/jupyter') }, 'Cancel')),
    ),
  );
}
