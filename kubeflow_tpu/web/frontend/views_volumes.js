// Volumes view (ref crud-web-apps/volumes frontend): PVC list with
// used-by protection surfaced, inline create form.

import { api, routes } from '/static/api.js';
import { h, state, toast, reportError, render } from '/static/app.js';

export async function volumesView() {
  const ns = state.namespace;
  if (!ns) return h('div', { class: 'card empty' }, 'No namespace selected.');
  const data = await api.get(routes.pvcs(ns));

  const rows = (data.pvcs || []).map((p) => {
    const used = (p.usedBy || []).length > 0;
    const delBtn = h(
      'button',
      {
        class: 'small danger',
        ...(used ? { disabled: '', title: `in use by ${p.usedBy.join(', ')}` } : {}),
        onclick: async () => {
          if (!confirm(`Delete volume ${p.name}?`)) return;
          try {
            await api.del(routes.pvc(ns, p.name));
            toast(`Deleted ${p.name}`);
            render();
          } catch (err) {
            reportError(err);
          }
        },
      },
      'Delete',
    );
    return h(
      'tr',
      {},
      h('td', {}, p.name),
      h('td', {}, p.size),
      h('td', {}, (p.accessModes || []).join(', ')),
      h('td', {}, p.phase),
      h('td', {}, used ? p.usedBy.join(', ') : '—'),
      h('td', {}, delBtn),
    );
  });

  const nameInput = h('input', { placeholder: 'my-volume' });
  const sizeInput = h('input', { value: '5Gi' });
  const createBtn = h('button', { class: 'primary' }, 'Create');
  createBtn.addEventListener('click', async () => {
    createBtn.disabled = true;
    try {
      await api.post(routes.pvcs(ns), { name: nameInput.value.trim(), size: sizeInput.value });
      toast(`Volume ${nameInput.value.trim()} created`);
      render();
    } catch (err) {
      reportError(err);
      createBtn.disabled = false;
    }
  });

  return h(
    'div',
    {},
    h(
      'div',
      { class: 'card' },
      h('div', { class: 'toolbar' }, h('h2', {}, `Volumes in ${ns}`)),
      rows.length
        ? h(
            'table',
            { class: 'grid' },
            h('thead', {}, h('tr', {}, h('th', {}, 'Name'), h('th', {}, 'Size'), h('th', {}, 'Access'), h('th', {}, 'Phase'), h('th', {}, 'Used by'), h('th', {}, ''))),
            h('tbody', {}, rows),
          )
        : h('div', { class: 'empty' }, 'No volumes.'),
    ),
    h(
      'div',
      { class: 'card' },
      h('h3', {}, 'New volume'),
      h(
        'div',
        { class: 'form-grid' },
        h('label', {}, 'Name'),
        nameInput,
        h('label', {}, 'Size'),
        sizeInput,
        h('div', { class: 'span2' }, createBtn),
      ),
    ),
  );
}
