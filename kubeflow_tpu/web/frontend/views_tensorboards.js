// TensorBoards view (ref crud-web-apps/tensorboards frontend): list +
// create with logspath (pvc:// or gs://) + delete.

import { api, routes } from '/static/api.js';
import { h, state, toast, reportError, render } from '/static/app.js';

export async function tensorboardsView() {
  const ns = state.namespace;
  if (!ns) return h('div', { class: 'card empty' }, 'No namespace selected.');
  const data = await api.get(routes.tensorboards(ns));

  const rows = (data.tensorboards || []).map((t) =>
    h(
      'tr',
      {},
      h(
        'td',
        {},
        h(
          'span',
          { class: 'status' },
          h('span', { class: `dot ${t.ready ? 'ready' : 'waiting'}` }),
          t.ready ? 'ready' : 'starting',
        ),
      ),
      h('td', {}, t.ready ? h('a', { href: t.url, target: '_blank', rel: 'noopener' }, t.name) : t.name),
      h('td', {}, t.logspath),
      h(
        'td',
        {},
        h(
          'button',
          {
            class: 'small danger',
            onclick: async () => {
              if (!confirm(`Delete tensorboard ${t.name}?`)) return;
              try {
                await api.del(routes.tensorboard(ns, t.name));
                toast(`Deleted ${t.name}`);
                render();
              } catch (err) {
                reportError(err);
              }
            },
          },
          'Delete',
        ),
      ),
    ),
  );

  const nameInput = h('input', { placeholder: 'my-tensorboard' });
  const logsInput = h('input', { placeholder: 'pvc://my-volume/logs or gs://bucket/runs' });
  const createBtn = h('button', { class: 'primary' }, 'Create');
  createBtn.addEventListener('click', async () => {
    createBtn.disabled = true;
    try {
      await api.post(routes.tensorboards(ns), {
        name: nameInput.value.trim(),
        logspath: logsInput.value.trim(),
      });
      toast(`TensorBoard ${nameInput.value.trim()} created`);
      render();
    } catch (err) {
      reportError(err);
      createBtn.disabled = false;
    }
  });

  return h(
    'div',
    {},
    h(
      'div',
      { class: 'card' },
      h('div', { class: 'toolbar' }, h('h2', {}, `TensorBoards in ${ns}`)),
      rows.length
        ? h(
            'table',
            { class: 'grid' },
            h('thead', {}, h('tr', {}, h('th', {}, 'Status'), h('th', {}, 'Name'), h('th', {}, 'Logs path'), h('th', {}, ''))),
            h('tbody', {}, rows),
          )
        : h('div', { class: 'empty' }, 'No tensorboards.'),
    ),
    h(
      'div',
      { class: 'card' },
      h('h3', {}, 'New TensorBoard'),
      h(
        'div',
        { class: 'form-grid' },
        h('label', {}, 'Name'),
        nameInput,
        h('label', {}, 'Logs path'),
        logsInput,
        h('div', { class: 'field-note' }, 'pvc://volume/subpath mounts a volume; gs:// reads straight from object storage.'),
        h('div', { class: 'span2' }, createBtn),
      ),
    ),
  );
}
