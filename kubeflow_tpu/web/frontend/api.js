// API client: thin fetch wrapper speaking the backends' JSON envelope
// ({success, status, ...} — web/common.py json_success/json_error) with
// CSRF double-submit echo (cookie XSRF-TOKEN → header X-XSRF-TOKEN,
// ref crud_backend/csrf.py semantics).

const CSRF_COOKIE = 'XSRF-TOKEN';
const CSRF_HEADER = 'X-XSRF-TOKEN';

function csrfToken() {
  for (const part of document.cookie.split(';')) {
    const [k, ...v] = part.trim().split('=');
    if (k === CSRF_COOKIE) return decodeURIComponent(v.join('='));
  }
  return '';
}

export class ApiError extends Error {
  constructor(message, status) {
    super(message);
    this.status = status;
  }
}

async function call(method, path, body) {
  const headers = { Accept: 'application/json' };
  if (method !== 'GET') headers[CSRF_HEADER] = csrfToken();
  if (body !== undefined) headers['Content-Type'] = 'application/json';
  const resp = await fetch(path, {
    method,
    headers,
    body: body === undefined ? undefined : JSON.stringify(body),
    credentials: 'same-origin',
  });
  let data = {};
  try {
    data = await resp.json();
  } catch {
    /* non-JSON error body */
  }
  if (!resp.ok || data.success === false) {
    throw new ApiError(data.log || `${resp.status} ${resp.statusText}`, resp.status);
  }
  return data;
}

export const api = {
  get: (path) => call('GET', path),
  post: (path, body) => call('POST', path, body ?? {}),
  patch: (path, body) => call('PATCH', path, body),
  del: (path, body) => call('DELETE', path, body),
};

// Route map — every path the SPA touches, in one place (the HTTP test
// asserts each exists on the server so the frontend can't drift).
export const routes = {
  envInfo: '/api/workgroup/env-info',
  workgroupExists: '/api/workgroup/exists',
  workgroupCreate: '/api/workgroup/create',
  namespaces: '/api/namespaces',
  activities: (ns) => `/api/activities/${ns}`,
  dashboardLinks: '/api/dashboard-links',
  metrics: (type) => `/api/metrics/${type}`,
  spawnerConfig: '/jupyter/api/config',
  notebooks: (ns) => `/jupyter/api/namespaces/${ns}/notebooks`,
  notebook: (ns, name) => `/jupyter/api/namespaces/${ns}/notebooks/${name}`,
  poddefaults: (ns) => `/jupyter/api/namespaces/${ns}/poddefaults`,
  pvcs: (ns) => `/volumes/api/namespaces/${ns}/pvcs`,
  pvc: (ns, name) => `/volumes/api/namespaces/${ns}/pvcs/${name}`,
  tensorboards: (ns) => `/tensorboards/api/namespaces/${ns}/tensorboards`,
  tensorboard: (ns, name) => `/tensorboards/api/namespaces/${ns}/tensorboards/${name}`,
  modelservers: (ns) => `/modelservers/api/namespaces/${ns}/modelservers`,
  modelserver: (ns, name) => `/modelservers/api/namespaces/${ns}/modelservers/${name}`,
  kfamBindings: '/kfam/v1/bindings',
};
