// Model servers view: list + create (model, checkpoint, optional
// topology/quant) + delete. The serving sibling of the tensorboards
// view; readiness and routed URL come from the ModelServer status.

import { api, routes } from '/static/api.js';
import { h, state, toast, reportError, render } from '/static/app.js';

export async function modelserversView() {
  const ns = state.namespace;
  if (!ns) return h('div', { class: 'card empty' }, 'No namespace selected.');
  const data = await api.get(routes.modelservers(ns));

  const rows = (data.modelservers || []).map((m) =>
    h(
      'tr',
      {},
      h(
        'td',
        {},
        h(
          'span',
          { class: 'status', title: m.warning || '' },
          h('span', { class: `dot ${m.ready ? 'ready' : 'waiting'}` }),
          m.ready ? 'ready' : m.warning ? 'error' : 'starting',
        ),
      ),
      h('td', {}, m.ready ? h('a', { href: m.url, target: '_blank', rel: 'noopener' }, m.name) : m.name),
      h('td', {}, m.model),
      h('td', {}, m.checkpoint || 'random (dev)'),
      h('td', {}, m.topology || 'cpu'),
      h('td', {}, m.quant || 'bf16'),
      h(
        'td',
        {},
        h(
          'button',
          {
            class: 'small danger',
            onclick: async () => {
              if (!confirm(`Delete model server ${m.name}?`)) return;
              try {
                await api.del(routes.modelserver(ns, m.name));
                toast(`Deleted ${m.name}`);
                render();
              } catch (err) {
                reportError(err);
              }
            },
          },
          'Delete',
        ),
      ),
    ),
  );

  const nameInput = h('input', { placeholder: 'my-server' });
  const modelInput = h('input', { placeholder: 'llama3-1b' });
  const ckptInput = h('input', { placeholder: 'pvc://train-out/run7 or gs://bucket/run7 (empty = random)' });
  const topoInput = h('input', { placeholder: 'v5e-4 (empty = cpu)' });
  const createBtn = h('button', { class: 'primary' }, 'Create');
  createBtn.addEventListener('click', async () => {
    createBtn.disabled = true;
    try {
      const body = {
        name: nameInput.value.trim(),
        model: modelInput.value.trim(),
        checkpoint: ckptInput.value.trim(),
      };
      if (topoInput.value.trim()) body.topology = topoInput.value.trim();
      await api.post(routes.modelservers(ns), body);
      toast(`Model server ${body.name} created`);
      render();
    } catch (err) {
      reportError(err);
      createBtn.disabled = false;
    }
  });

  return h(
    'div',
    {},
    h(
      'div',
      { class: 'card' },
      h('div', { class: 'toolbar' }, h('h2', {}, `Model servers in ${ns}`)),
      rows.length
        ? h(
            'table',
            { class: 'grid' },
            h(
              'thead',
              {},
              h(
                'tr',
                {},
                h('th', {}, 'Status'),
                h('th', {}, 'Name'),
                h('th', {}, 'Model'),
                h('th', {}, 'Checkpoint'),
                h('th', {}, 'TPU'),
                h('th', {}, 'Weights'),
                h('th', {}, ''),
              ),
            ),
            h('tbody', {}, rows),
          )
        : h('div', { class: 'empty' }, 'No model servers.'),
    ),
    h(
      'div',
      { class: 'card' },
      h('h3', {}, 'New model server'),
      h(
        'div',
        { class: 'form-grid' },
        h('label', {}, 'Name'),
        nameInput,
        h('label', {}, 'Model'),
        modelInput,
        h('label', {}, 'Checkpoint'),
        ckptInput,
        h('label', {}, 'TPU topology'),
        topoInput,
        h('div', { class: 'field-note' }, 'The server answers REST at /serving/<ns>/<name>/ once ready (continuous batching + warmup on by default).'),
        h('div', { class: 'span2' }, createBtn),
      ),
    ),
  );
}
