// Manage Contributors view (ref manage-users-view.js + KFAM): list the
// namespace's role bindings, add/remove contributors. Talks to the
// KFAM service routes directly (/kfam/v1/bindings), same as the
// reference dashboard proxies to KFAM.

import { api, routes } from '/static/api.js';
import { h, state, toast, reportError, render } from '/static/app.js';

export async function contributorsView() {
  const ns = state.namespace;
  if (!ns) return h('div', { class: 'card empty' }, 'No namespace selected.');
  let bindings = [];
  let readError = null;
  try {
    const data = await api.get(`${routes.kfamBindings}?namespace=${encodeURIComponent(ns)}`);
    bindings = data.bindings || [];
  } catch (err) {
    readError = err;
  }

  if (readError) {
    return h(
      'div',
      { class: 'card' },
      h('h2', {}, 'Manage Contributors'),
      h('p', { class: 'sub' }, `You need owner or admin rights on ${ns} to manage contributors.`),
      h('p', {}, String(readError.message)),
    );
  }

  const rows = bindings.map((b) =>
    h(
      'tr',
      {},
      h('td', {}, b.user),
      h('td', {}, b.role),
      h(
        'td',
        {},
        h(
          'button',
          {
            class: 'small danger',
            onclick: async () => {
              try {
                await api.del(routes.kfamBindings, { user: b.user, namespace: ns, role: b.role });
                toast(`Removed ${b.user}`);
                render();
              } catch (err) {
                reportError(err);
              }
            },
          },
          'Remove',
        ),
      ),
    ),
  );

  const userInput = h('input', { placeholder: 'teammate@example.com' });
  const roleSelect = h(
    'select',
    {},
    h('option', { value: 'edit', selected: '' }, 'edit'),
    h('option', { value: 'view' }, 'view'),
    h('option', { value: 'admin' }, 'admin'),
  );
  const addBtn = h('button', { class: 'primary' }, 'Add contributor');
  addBtn.addEventListener('click', async () => {
    addBtn.disabled = true;
    try {
      await api.post(routes.kfamBindings, {
        user: userInput.value.trim(),
        namespace: ns,
        role: roleSelect.value,
      });
      toast(`Added ${userInput.value.trim()}`);
      render();
    } catch (err) {
      reportError(err);
      addBtn.disabled = false;
    }
  });

  return h(
    'div',
    {},
    h(
      'div',
      { class: 'card' },
      h('div', { class: 'toolbar' }, h('h2', {}, `Contributors to ${ns}`)),
      rows.length
        ? h(
            'table',
            { class: 'grid' },
            h('thead', {}, h('tr', {}, h('th', {}, 'User'), h('th', {}, 'Role'), h('th', {}, ''))),
            h('tbody', {}, rows),
          )
        : h('div', { class: 'empty' }, 'No contributors besides the owner.'),
    ),
    h(
      'div',
      { class: 'card' },
      h('h3', {}, 'Add contributor'),
      h(
        'div',
        { class: 'form-grid' },
        h('label', {}, 'User'),
        userInput,
        h('label', {}, 'Role'),
        roleSelect,
        h('div', { class: 'span2' }, addBtn),
      ),
    ),
  );
}
