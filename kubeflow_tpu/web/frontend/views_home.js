// Home view (ref dashboard-view.js): usage tiles from /api/metrics,
// quick links from /api/dashboard-links, recent activity feed from
// /api/activities/{ns}.

import { api, routes } from '/static/api.js';
import { h, ago } from '/static/app.js';

export async function homeView({ state }) {
  const ns = state.namespace;
  const [metrics, links, activities] = await Promise.all([
    api.get(routes.metrics('summary')),
    api.get(routes.dashboardLinks),
    ns ? api.get(routes.activities(ns)) : Promise.resolve({ activities: [] }),
  ]);

  const tpuTiles = Object.entries(metrics.tpuHostsInUse || {}).map(([topo, hosts]) =>
    h('div', { class: 'tile' }, h('div', { class: 'n' }, hosts), h('div', { class: 't' }, `${topo} hosts in use`)),
  );

  const feed = (activities.activities || []).slice(0, 15).map((a) =>
    h(
      'tr',
      { class: `activity${a.type === 'Warning' ? ' warn' : ''}` },
      h('td', { class: 'when' }, ago(a.time)),
      h('td', { class: 'reason' }, a.reason),
      h('td', {}, `${a.kind}/${a.name}`),
      h('td', {}, a.message),
    ),
  );

  return h(
    'div',
    {},
    h(
      'div',
      { class: 'tile-row' },
      h('div', { class: 'tile' }, h('div', { class: 'n' }, metrics.notebooks ?? 0), h('div', { class: 't' }, 'notebooks')),
      h('div', { class: 'tile' }, h('div', { class: 'n' }, state.namespaces.length), h('div', { class: 't' }, 'namespaces you can access')),
      tpuTiles.length ? tpuTiles : h('div', { class: 'tile' }, h('div', { class: 'n' }, 0), h('div', { class: 't' }, 'TPU hosts in use')),
    ),
    h(
      'div',
      { class: 'card' },
      h('h3', {}, 'Quick shortcuts'),
      h(
        'div',
        { class: 'quick-links' },
        ((links.links || {}).quickLinks || []).map((l) =>
          h('a', { href: l.link.startsWith('/jupyter/new') ? '#/jupyter/new' : l.link }, l.desc),
        ),
      ),
    ),
    h(
      'div',
      { class: 'card' },
      h('h3', {}, `Recent activity in ${ns || '(no namespace)'}`),
      feed.length
        ? h('table', { class: 'grid' }, h('tbody', {}, feed))
        : h('div', { class: 'empty' }, 'No recent events.'),
    ),
  );
}
