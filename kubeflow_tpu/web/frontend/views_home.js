// Home view (ref dashboard-view.js): usage tiles from /api/metrics,
// quick links from /api/dashboard-links, recent activity feed from
// /api/activities/{ns}.

import { api, routes } from '/static/api.js';
import { h, ago, render } from '/static/app.js';

// Windowed usage chart (ref centraldashboard resource charts,
// metrics_service.ts:2-8): inline SVG — TPU hosts solid, notebooks
// dashed — over the selected 5/15/30/60/180-minute window.
export const METRIC_WINDOWS = [5, 15, 30, 60, 180];

function usageChart(points, windowMin) {
  const W = 640;
  const H = 140;
  const PAD = 26;
  const wrap = h('div', { class: 'chart', 'data-window': windowMin });
  if (!points || points.length < 2) {
    wrap.append(h('div', { class: 'empty' }, 'Collecting usage history…'));
    return wrap;
  }
  const t0 = points[0].t;
  const t1 = points[points.length - 1].t;
  const maxY = Math.max(1, ...points.map((p) => Math.max(p.tpuHostsInUse, p.notebooks)));
  const x = (t) => PAD + ((W - 2 * PAD) * (t - t0)) / Math.max(t1 - t0, 1);
  const y = (v) => H - PAD - ((H - 2 * PAD) * v) / maxY;
  const line = (key) =>
    points.map((p, i) => `${i ? 'L' : 'M'}${x(p.t).toFixed(1)},${y(p[key]).toFixed(1)}`).join(' ');
  wrap.innerHTML = `<svg viewBox="0 0 ${W} ${H}" role="img" aria-label="TPU usage over the last ${windowMin} minutes">
    <line x1="${PAD}" y1="${H - PAD}" x2="${W - PAD}" y2="${H - PAD}" class="axis"/>
    <line x1="${PAD}" y1="${PAD}" x2="${PAD}" y2="${H - PAD}" class="axis"/>
    <text x="${PAD - 4}" y="${PAD + 4}" text-anchor="end" class="tick">${maxY}</text>
    <text x="${PAD - 4}" y="${H - PAD}" text-anchor="end" class="tick">0</text>
    <path class="line tpu" d="${line('tpuHostsInUse')}" fill="none"/>
    <path class="line nbs" d="${line('notebooks')}" fill="none" stroke-dasharray="4 3"/>
  </svg>`;
  return wrap;
}

export async function homeView({ state }) {
  const ns = state.namespace;
  const windowMin = METRIC_WINDOWS.includes(state.metricsWindow) ? state.metricsWindow : 60;
  const [metrics, links, activities] = await Promise.all([
    api.get(`${routes.metrics('summary')}?window=${windowMin}`),
    api.get(routes.dashboardLinks),
    ns ? api.get(routes.activities(ns)) : Promise.resolve({ activities: [] }),
  ]);

  const tpuTiles = Object.entries(metrics.tpuHostsInUse || {}).map(([topo, hosts]) =>
    h('div', { class: 'tile' }, h('div', { class: 'n' }, hosts), h('div', { class: 't' }, `${topo} hosts in use`)),
  );

  const feed = (activities.activities || []).slice(0, 15).map((a) =>
    h(
      'tr',
      { class: `activity${a.type === 'Warning' ? ' warn' : ''}` },
      h('td', { class: 'when' }, ago(a.time)),
      h('td', { class: 'reason' }, a.reason),
      h('td', {}, `${a.kind}/${a.name}`),
      h('td', {}, a.message),
    ),
  );

  return h(
    'div',
    {},
    h(
      'div',
      { class: 'tile-row' },
      h('div', { class: 'tile' }, h('div', { class: 'n' }, metrics.notebooks ?? 0), h('div', { class: 't' }, 'notebooks')),
      h('div', { class: 'tile' }, h('div', { class: 'n' }, state.namespaces.length), h('div', { class: 't' }, 'namespaces you can access')),
      tpuTiles.length ? tpuTiles : h('div', { class: 'tile' }, h('div', { class: 'n' }, 0), h('div', { class: 't' }, 'TPU hosts in use')),
    ),
    h(
      'div',
      { class: 'card' },
      h('h3', {}, 'Usage history'),
      h(
        'div',
        { class: 'window-picker' },
        METRIC_WINDOWS.map((m) =>
          h(
            'button',
            {
              class: `win-btn${m === windowMin ? ' active' : ''}`,
              'data-minutes': m,
              onclick: () => {
                state.metricsWindow = m;
                render();
              },
            },
            m < 60 ? `${m}m` : `${m / 60}h`,
          ),
        ),
      ),
      usageChart(metrics.points, windowMin),
      h('div', { class: 'legend' }, '— TPU hosts   ┄ notebooks'),
    ),
    h(
      'div',
      { class: 'card' },
      h('h3', {}, 'Quick shortcuts'),
      h(
        'div',
        { class: 'quick-links' },
        ((links.links || {}).quickLinks || []).map((l) =>
          h('a', { href: l.link.startsWith('/jupyter/new') ? '#/jupyter/new' : l.link }, l.desc),
        ),
      ),
    ),
    h(
      'div',
      { class: 'card' },
      h('h3', {}, `Recent activity in ${ns || '(no namespace)'}`),
      feed.length
        ? h('table', { class: 'grid' }, h('tbody', {}, feed))
        : h('div', { class: 'empty' }, 'No recent events.'),
    ),
  );
}
