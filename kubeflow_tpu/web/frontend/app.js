// SPA shell + hash router (ref centraldashboard main-page.js /
// dashboard-view.js / manage-users-view.js and the CRUD apps' Angular
// pages, re-done frameworkless). Views render into #outlet; the
// namespace selector is global state shared by every view, like the
// reference's namespace-selector element.

import { api, routes, ApiError } from '/static/api.js';
import { homeView } from '/static/views_home.js';
import { notebooksView, notebookFormView, notebookDetailView } from '/static/views_notebooks.js';
import { volumesView } from '/static/views_volumes.js';
import { tensorboardsView } from '/static/views_tensorboards.js';
import { modelserversView } from '/static/views_modelservers.js';
import { contributorsView } from '/static/views_contributors.js';

export const state = {
  user: '',
  isClusterAdmin: false,
  namespaces: [],
  namespace: localStorage.getItem('kftpu.ns') || '',
};

const outlet = document.getElementById('outlet');
const nsSelect = document.getElementById('ns-select');

// -- helpers shared by views ----------------------------------------

export function h(tag, attrs = {}, ...children) {
  const el = document.createElement(tag);
  for (const [k, v] of Object.entries(attrs)) {
    if (k === 'class') el.className = v;
    else if (k.startsWith('on') && typeof v === 'function') {
      el.addEventListener(k.slice(2), v);
    } else if (v !== undefined && v !== null) el.setAttribute(k, v);
  }
  for (const c of children.flat()) {
    if (c === null || c === undefined) continue;
    el.append(c instanceof Node ? c : document.createTextNode(String(c)));
  }
  return el;
}

let toastTimer;
export function toast(message, isError = false) {
  const el = document.getElementById('toast');
  el.textContent = message;
  el.className = `toast${isError ? ' err' : ''}`;
  clearTimeout(toastTimer);
  toastTimer = setTimeout(() => el.classList.add('hidden'), 4500);
}

export function reportError(err) {
  toast(err instanceof ApiError ? err.message : String(err), true);
}

export function ago(epochSecs) {
  const d = Date.now() / 1000 - epochSecs;
  if (d < 60) return `${Math.max(1, Math.round(d))}s ago`;
  if (d < 3600) return `${Math.round(d / 60)}m ago`;
  if (d < 86400) return `${Math.round(d / 3600)}h ago`;
  return `${Math.round(d / 86400)}d ago`;
}

// -- router ---------------------------------------------------------

const views = {
  home: homeView,
  jupyter: notebooksView,
  'jupyter/new': notebookFormView,
  volumes: volumesView,
  tensorboards: tensorboardsView,
  modelservers: modelserversView,
  contributors: contributorsView,
};

function currentRoute() {
  const hash = location.hash.replace(/^#\//, '');
  return hash === '' ? 'home' : hash;
}

export async function render() {
  const route = currentRoute();
  let view = views[route];
  if (!view && route.startsWith('jupyter/detail/')) {
    const name = decodeURIComponent(route.slice('jupyter/detail/'.length));
    view = (ctx) => notebookDetailView(name, ctx);
  }
  view = view || views.home;
  for (const a of document.querySelectorAll('.nav-list a')) {
    a.classList.toggle(
      'active',
      a.dataset.route === (route.startsWith('jupyter') ? 'jupyter' : route),
    );
  }
  outlet.replaceChildren(h('div', { class: 'card' }, 'Loading…'));
  try {
    const node = await view({ state, outlet });
    outlet.replaceChildren(node);
  } catch (err) {
    outlet.replaceChildren(
      h('div', { class: 'card' }, h('h2', {}, 'Error'), String(err.message || err)),
    );
  }
}

// -- registration (workgroup_exists → create, ref registration-page.js)

async function ensureWorkgroup() {
  const info = await api.get(routes.workgroupExists);
  if (info.hasWorkgroup || state.namespaces.length) return;
  const suggested = (state.user || 'user').split('@')[0].replace(/[^a-z0-9-]/g, '-');
  const input = h('input', { value: suggested, 'aria-label': 'Namespace name' });
  const btn = h('button', { class: 'primary' }, 'Create workspace');
  const card = h(
    'div',
    { class: 'card register' },
    h('h2', {}, `Welcome, ${state.user}`),
    h('p', { class: 'sub' }, 'You have no workspace yet. Create your personal namespace to start spawning TPU notebooks.'),
    input,
    btn,
  );
  btn.addEventListener('click', async () => {
    btn.disabled = true;
    try {
      await api.post(routes.workgroupCreate, { namespace: input.value.trim() });
      toast(`Workspace ${input.value.trim()} created`);
      await bootstrap();
    } catch (err) {
      reportError(err);
      btn.disabled = false;
    }
  });
  outlet.replaceChildren(card);
  throw Object.assign(new Error('registration required'), { handled: true });
}

// -- bootstrap (ref dashboard env bootstrap, SURVEY §3.4) -----------

async function bootstrap() {
  const env = await api.get(routes.envInfo);
  state.user = env.user;
  state.isClusterAdmin = !!env.isClusterAdmin;
  state.namespaces = env.namespaces || [];
  document.getElementById('user-chip').textContent = state.user;
  document
    .getElementById('cluster-admin-badge')
    .classList.toggle('hidden', !state.isClusterAdmin);

  if (!state.namespaces.includes(state.namespace)) {
    state.namespace = state.namespaces[0] || '';
  }
  nsSelect.replaceChildren(
    ...state.namespaces.map((ns) =>
      h('option', { value: ns, ...(ns === state.namespace ? { selected: '' } : {}) }, ns),
    ),
  );

  try {
    await ensureWorkgroup();
  } catch (err) {
    if (err.handled) return; // registration card is showing
    throw err;
  }
  await render();
}

nsSelect.addEventListener('change', () => {
  state.namespace = nsSelect.value;
  localStorage.setItem('kftpu.ns', state.namespace);
  render();
});
window.addEventListener('hashchange', render);

bootstrap().catch((err) => {
  outlet.replaceChildren(
    h(
      'div',
      { class: 'card' },
      h('h2', {}, 'Cannot reach the platform API'),
      h('p', {}, String(err.message || err)),
      h('p', { class: 'sub' }, 'Check that you are signed in (the auth proxy must inject the kubeflow-userid header).'),
    ),
  );
});
