"""In-process HPO sweeps: run an objective function over a search space.

The notebook-user entry point (no control plane needed): the same
suggesters that drive the Experiment controller, executed inline.

    from kubeflow_tpu.hpo import Double, SearchSpace, run_sweep
    result = run_sweep(
        lambda a: train(lr=a["lr"]),           # returns the metric
        SearchSpace((Double("lr", 1e-5, 1e-2, log=True),)),
        n_trials=20, goal="minimize",
    )
    result.best_assignment, result.best_value
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable

from kubeflow_tpu.hpo.search import (
    SEEDED_ALGORITHMS,
    Assignment,
    SearchSpace,
    better,
    make_suggester,
)

log = logging.getLogger(__name__)


@dataclasses.dataclass
class TrialResult:
    assignment: Assignment
    value: float | None       # None = trial raised
    error: str = ""


@dataclasses.dataclass
class SweepResult:
    goal: str
    trials: list[TrialResult]

    @property
    def best(self) -> TrialResult:
        done = [t for t in self.trials if t.value is not None]
        if not done:
            raise RuntimeError("no trial completed successfully")
        out = done[0]
        for t in done[1:]:
            if better(self.goal, t.value, out.value):
                out = t
        return out

    @property
    def best_assignment(self) -> Assignment:
        return self.best.assignment

    @property
    def best_value(self) -> float:
        return self.best.value


def run_sweep(
    objective: Callable[[Assignment], float],
    space: SearchSpace,
    *,
    n_trials: int = 10,
    goal: str = "minimize",
    algorithm: str = "random",
    seed: int = 0,
    **algo_kwargs: Any,
) -> SweepResult:
    """Sequentially evaluate suggested assignments; exceptions in the
    objective mark the trial failed and the sweep continues."""
    better(goal, 0.0, 1.0)  # validates goal early
    if algorithm in SEEDED_ALGORITHMS:
        algo_kwargs.setdefault("seed", seed)
    suggester = make_suggester(algorithm, space, **algo_kwargs)
    trials: list[TrialResult] = []
    while len(trials) < n_trials:
        if hasattr(suggester, "observe"):
            # Adaptive algorithms (TPE) must see finished results or
            # they degrade to their random fallback forever.
            suggester.observe(
                [(t.assignment, t.value) for t in trials
                 if t.value is not None], goal)
        batch = suggester.suggest(min(8, n_trials - len(trials)))
        if not batch:
            break  # grid exhausted
        for a in batch:
            try:
                v = float(objective(a))
                trials.append(TrialResult(a, v))
            except Exception as e:  # noqa: BLE001 — user objective
                log.warning("trial %s failed: %s", a, e)
                trials.append(TrialResult(a, None, error=str(e)))
    return SweepResult(goal=goal, trials=trials)
