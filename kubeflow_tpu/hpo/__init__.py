"""HPO layer: search spaces, suggesters, local sweeps, Experiment CRs.

BASELINE config "Katib HPO sweep w/ PodDefault TPU-env injection": the
controllers live in kubeflow_tpu.controlplane.controllers.hpo; this
package is the algorithm core plus the notebook-local entry point.
"""

from kubeflow_tpu.hpo.search import (
    Categorical,
    Double,
    GridSuggester,
    Integer,
    RandomSuggester,
    SearchSpace,
    TpeSuggester,
    better,
    make_suggester,
)
from kubeflow_tpu.hpo.local import SweepResult, TrialResult, run_sweep
