"""HPO search spaces and suggestion algorithms (Katib-equivalent core).

The reference ships only a Katib smoke test
(`/root/reference/testing/katib_studyjob_test.py`) — the StudyJob CRD it
exercises lives in the separate katib repo. This module supplies the
algorithm layer for the TPU-native Experiment/Trial controllers and for
in-notebook local sweeps: deterministic, seeded suggesters (random,
grid) over typed parameter domains.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Any, Iterable, Mapping, Sequence

import numpy as np

Assignment = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class Double:
    name: str
    min: float
    max: float
    log: bool = False    # sample in log space (learning rates)

    def validate(self) -> None:
        if not (self.max > self.min):
            raise ValueError(f"{self.name}: max must exceed min")
        if self.log and self.min <= 0:
            raise ValueError(f"{self.name}: log scale needs min > 0")


@dataclasses.dataclass(frozen=True)
class Integer:
    name: str
    min: int
    max: int             # inclusive

    def validate(self) -> None:
        if not (self.max >= self.min):
            raise ValueError(f"{self.name}: max must be >= min")


@dataclasses.dataclass(frozen=True)
class Categorical:
    name: str
    values: tuple[Any, ...]

    def validate(self) -> None:
        if not self.values:
            raise ValueError(f"{self.name}: needs at least one value")


Parameter = Double | Integer | Categorical


@dataclasses.dataclass(frozen=True)
class SearchSpace:
    parameters: tuple[Parameter, ...]

    def __post_init__(self):
        names = [p.name for p in self.parameters]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate parameter names in {names}")
        for p in self.parameters:
            p.validate()

    def parse(self, assignment: Mapping[str, str]) -> Assignment:
        """Typed values from a Trial's string assignment (the CR stores
        strings); unknown names are ignored, out-of-domain or unmatched
        values raise ValueError (a hand-edited lr="0" on a log-scale
        Double would otherwise detonate later inside TPE's math.log)."""
        out: Assignment = {}
        for p in self.parameters:
            if p.name not in assignment:
                continue
            raw = assignment[p.name]
            if isinstance(p, (Double, Integer)):
                # range-check BEFORE integer truncation: "5.9" against
                # max=5 must raise, not silently become 5
                v = float(raw)
                if not p.min <= v <= p.max:
                    raise ValueError(
                        f"{p.name}: {v} outside [{p.min}, {p.max}]")
                out[p.name] = v if isinstance(p, Double) else int(v)
            else:
                matches = [v for v in p.values if str(v) == str(raw)]
                if not matches:
                    raise ValueError(
                        f"{p.name}: value {raw!r} not in {p.values}")
                out[p.name] = matches[0]
        return out


class RandomSuggester:
    """Independent uniform (log-uniform for Double(log=True)) sampling."""

    def __init__(self, space: SearchSpace, seed: int = 0):
        self.space = space
        self._rng = np.random.default_rng(seed)

    def advance(self, n: int) -> None:
        """Skip past n prior suggestions (controller replay)."""
        self.suggest(n)

    def suggest(self, n: int) -> list[Assignment]:
        out = []
        for _ in range(n):
            a: Assignment = {}
            for p in self.space.parameters:
                if isinstance(p, Double):
                    if p.log:
                        a[p.name] = float(np.exp(self._rng.uniform(
                            math.log(p.min), math.log(p.max))))
                    else:
                        a[p.name] = float(self._rng.uniform(p.min, p.max))
                elif isinstance(p, Integer):
                    a[p.name] = int(self._rng.integers(p.min, p.max + 1))
                else:
                    a[p.name] = p.values[
                        int(self._rng.integers(len(p.values)))]
            out.append(a)
        return out


class GridSuggester:
    """Cartesian grid; Doubles get `grid_points` samples (log-aware).
    Exhausts after the full grid — suggest() then returns []."""

    def __init__(self, space: SearchSpace, grid_points: int = 5):
        self.space = space
        axes: list[list[Any]] = []
        for p in space.parameters:
            if isinstance(p, Double):
                if p.log:
                    pts = np.exp(np.linspace(math.log(p.min),
                                             math.log(p.max), grid_points))
                else:
                    pts = np.linspace(p.min, p.max, grid_points)
                axes.append([float(x) for x in pts])
            elif isinstance(p, Integer):
                span = p.max - p.min + 1
                if span <= grid_points:
                    axes.append(list(range(p.min, p.max + 1)))
                else:
                    axes.append(sorted({
                        int(round(x)) for x in
                        np.linspace(p.min, p.max, grid_points)}))
            else:
                axes.append(list(p.values))
        self._grid = itertools.product(*axes)
        self._names = [p.name for p in space.parameters]

    def advance(self, n: int) -> None:
        """Skip past n prior suggestions (controller replay)."""
        self.suggest(n)

    def suggest(self, n: int) -> list[Assignment]:
        out = []
        for combo in itertools.islice(self._grid, n):
            out.append(dict(zip(self._names, combo)))
        return out


class TpeSuggester:
    """Tree-structured Parzen Estimator (Bergstra et al. 2011) — the
    algorithm behind Katib's "tpe"/"bayesianoptimization" modes.

    Completed trials split into a good set (top `gamma` fraction under
    the goal) and a bad set; per dimension, Parzen/kernel densities
    l(x) (good) and g(x) (bad) are fit, candidates are drawn from l and
    the candidate maximizing l(x)/g(x) wins — "look like the good
    trials, not like the bad ones". With fewer than `min_observations`
    results it falls back to seeded random exploration.

    Controller protocol: the suggester is recreated every reconcile.
    `observe()` feeds finished-trial (assignment, value) pairs; the
    replay call `suggest(len(existing_trials))` only advances an
    internal counter that salts the RNG, so fresh batches never repeat
    earlier randomness — cheap, and observation-dependent suggestions
    need no replayability (existing trials are already pinned to their
    assignments in the store).
    """

    def __init__(self, space: SearchSpace, seed: int = 0,
                 gamma: float = 0.25, n_candidates: int = 24,
                 min_observations: int = 8):
        self.space = space
        self.seed = seed
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.min_observations = min_observations
        self._good: list[Assignment] = []
        self._bad: list[Assignment] = []
        self._counter = 0

    def observe(self, observations: Sequence[tuple[Assignment, float]],
                goal: str) -> None:
        if not observations:
            return
        ranked = sorted(
            observations, key=lambda av: av[1],
            reverse=(goal == "maximize"))
        n_good = max(1, int(math.ceil(self.gamma * len(ranked))))
        self._good = [a for a, _ in ranked[:n_good]]
        self._bad = [a for a, _ in ranked[n_good:]]

    # -- per-dimension Parzen machinery -----------------------------------

    def _to_unit(self, p: Parameter, v: Any) -> float:
        """Map a Double/Integer value into [0, 1] (log-aware)."""
        if isinstance(p, Double) and p.log:
            return ((math.log(v) - math.log(p.min))
                    / (math.log(p.max) - math.log(p.min)))
        lo, hi = float(p.min), float(p.max)
        return (float(v) - lo) / max(hi - lo, 1e-12)

    def _from_unit(self, p: Parameter, u: float) -> Any:
        u = min(max(u, 0.0), 1.0)
        if isinstance(p, Double):
            if p.log:
                v = float(math.exp(
                    math.log(p.min)
                    + u * (math.log(p.max) - math.log(p.min))))
            else:
                v = float(p.min + u * (p.max - p.min))
            # exp/log round-trips can land an ulp past the declared
            # domain; suggestions must honor it exactly
            return min(max(v, p.min), p.max)
        return int(round(p.min + u * (p.max - p.min)))

    @staticmethod
    def _kde_logpdf(u: float, centers: list[float], bw: float) -> float:
        """Parzen density MIXED with a uniform prior (weight 0.25).

        The prior is load-bearing, not a nicety: where the bad set has
        no mass (domain edges, under-explored regions) a bare KDE ratio
        l/g explodes and every suggestion piles onto the clip boundary
        — observed as 16/16 candidates at lr == max. The uniform floor
        bounds the ratio where data is sparse, so the argmax lands
        where the GOOD density actually peaks."""
        if not centers:
            return 0.0  # pure prior: uniform over the unit interval
        kde = np.mean(np.exp(
            -0.5 * ((u - np.asarray(centers)) / bw) ** 2
        )) / (bw * math.sqrt(2 * math.pi))
        return float(np.log(0.75 * kde + 0.25))

    def _cat_probs(self, p: Categorical,
                   assignments: list[Assignment]) -> np.ndarray:
        counts = np.ones(len(p.values))  # +1 Dirichlet smoothing
        for a in assignments:
            if p.name in a:
                counts[p.values.index(a[p.name])] += 1
        return counts / counts.sum()

    def advance(self, n: int) -> None:
        """Controller replay: salt the RNG past n prior suggestions
        WITHOUT scoring candidates that would be thrown away."""
        self._counter += n

    def suggest(self, n: int) -> list[Assignment]:
        rng = np.random.default_rng((self.seed, self._counter))
        self._counter += n
        n_obs = len(self._good) + len(self._bad)
        if n_obs < self.min_observations:
            rand = RandomSuggester(self.space, seed=0)
            rand._rng = rng
            return rand.suggest(n)

        bw = max(0.1, 1.0 / max(len(self._good), 1) ** 0.5)
        # Per-dimension stats are invariant across candidates: one pass.
        dim: dict[str, Any] = {}
        for p in self.space.parameters:
            if isinstance(p, Categorical):
                dim[p.name] = (self._cat_probs(p, self._good),
                               self._cat_probs(p, self._bad))
            else:
                dim[p.name] = (
                    [self._to_unit(p, x[p.name])
                     for x in self._good if p.name in x],
                    [self._to_unit(p, x[p.name])
                     for x in self._bad if p.name in x])
        out = []
        for _ in range(n):
            best_a, best_score = None, -np.inf
            for _ in range(self.n_candidates):
                a: Assignment = {}
                score = 0.0
                for p in self.space.parameters:
                    if isinstance(p, Categorical):
                        lp, gp = dim[p.name]
                        i = int(rng.choice(len(p.values), p=lp))
                        a[p.name] = p.values[i]
                        score += math.log(lp[i]) - math.log(gp[i])
                    else:
                        centers, bad_centers = dim[p.name]
                        if centers:
                            u = float(np.clip(
                                rng.choice(centers)
                                + bw * rng.standard_normal(), 0, 1))
                        else:
                            u = float(rng.uniform())
                        a[p.name] = self._from_unit(p, u)
                        score += (self._kde_logpdf(u, centers, bw)
                                  - self._kde_logpdf(u, bad_centers, bw))
                if score > best_score:
                    best_a, best_score = a, score
            out.append(best_a)
        return out


SUGGESTERS = {"random": RandomSuggester, "grid": GridSuggester,
              "tpe": TpeSuggester, "bayesianoptimization": TpeSuggester}
# Algorithms whose constructor takes a seed — the single source of truth
# for callers (Experiment controller, run_sweep) deciding whether to
# thread spec.seed through; a new algorithm added above only needs this
# set updated here, not at every call site.
SEEDED_ALGORITHMS = frozenset(
    {"random", "tpe", "bayesianoptimization"})


def make_suggester(algorithm: str, space: SearchSpace, **kwargs):
    try:
        cls = SUGGESTERS[algorithm]
    except KeyError:
        raise ValueError(
            f"unknown algorithm {algorithm!r}; have {sorted(SUGGESTERS)}"
        ) from None
    return cls(space, **kwargs)


def better(goal: str, a: float, b: float) -> bool:
    """Is metric `a` better than `b` under goal 'minimize'/'maximize'?"""
    if goal == "minimize":
        return a < b
    if goal == "maximize":
        return a > b
    raise ValueError(f"goal must be minimize|maximize, got {goal!r}")
