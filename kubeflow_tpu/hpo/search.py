"""HPO search spaces and suggestion algorithms (Katib-equivalent core).

The reference ships only a Katib smoke test
(`/root/reference/testing/katib_studyjob_test.py`) — the StudyJob CRD it
exercises lives in the separate katib repo. This module supplies the
algorithm layer for the TPU-native Experiment/Trial controllers and for
in-notebook local sweeps: deterministic, seeded suggesters (random,
grid) over typed parameter domains.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Any, Iterable, Sequence

import numpy as np

Assignment = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class Double:
    name: str
    min: float
    max: float
    log: bool = False    # sample in log space (learning rates)

    def validate(self) -> None:
        if not (self.max > self.min):
            raise ValueError(f"{self.name}: max must exceed min")
        if self.log and self.min <= 0:
            raise ValueError(f"{self.name}: log scale needs min > 0")


@dataclasses.dataclass(frozen=True)
class Integer:
    name: str
    min: int
    max: int             # inclusive

    def validate(self) -> None:
        if not (self.max >= self.min):
            raise ValueError(f"{self.name}: max must be >= min")


@dataclasses.dataclass(frozen=True)
class Categorical:
    name: str
    values: tuple[Any, ...]

    def validate(self) -> None:
        if not self.values:
            raise ValueError(f"{self.name}: needs at least one value")


Parameter = Double | Integer | Categorical


@dataclasses.dataclass(frozen=True)
class SearchSpace:
    parameters: tuple[Parameter, ...]

    def __post_init__(self):
        names = [p.name for p in self.parameters]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate parameter names in {names}")
        for p in self.parameters:
            p.validate()


class RandomSuggester:
    """Independent uniform (log-uniform for Double(log=True)) sampling."""

    def __init__(self, space: SearchSpace, seed: int = 0):
        self.space = space
        self._rng = np.random.default_rng(seed)

    def suggest(self, n: int) -> list[Assignment]:
        out = []
        for _ in range(n):
            a: Assignment = {}
            for p in self.space.parameters:
                if isinstance(p, Double):
                    if p.log:
                        a[p.name] = float(np.exp(self._rng.uniform(
                            math.log(p.min), math.log(p.max))))
                    else:
                        a[p.name] = float(self._rng.uniform(p.min, p.max))
                elif isinstance(p, Integer):
                    a[p.name] = int(self._rng.integers(p.min, p.max + 1))
                else:
                    a[p.name] = p.values[
                        int(self._rng.integers(len(p.values)))]
            out.append(a)
        return out


class GridSuggester:
    """Cartesian grid; Doubles get `grid_points` samples (log-aware).
    Exhausts after the full grid — suggest() then returns []."""

    def __init__(self, space: SearchSpace, grid_points: int = 5):
        self.space = space
        axes: list[list[Any]] = []
        for p in space.parameters:
            if isinstance(p, Double):
                if p.log:
                    pts = np.exp(np.linspace(math.log(p.min),
                                             math.log(p.max), grid_points))
                else:
                    pts = np.linspace(p.min, p.max, grid_points)
                axes.append([float(x) for x in pts])
            elif isinstance(p, Integer):
                span = p.max - p.min + 1
                if span <= grid_points:
                    axes.append(list(range(p.min, p.max + 1)))
                else:
                    axes.append(sorted({
                        int(round(x)) for x in
                        np.linspace(p.min, p.max, grid_points)}))
            else:
                axes.append(list(p.values))
        self._grid = itertools.product(*axes)
        self._names = [p.name for p in space.parameters]

    def suggest(self, n: int) -> list[Assignment]:
        out = []
        for combo in itertools.islice(self._grid, n):
            out.append(dict(zip(self._names, combo)))
        return out


SUGGESTERS = {"random": RandomSuggester, "grid": GridSuggester}


def make_suggester(algorithm: str, space: SearchSpace, **kwargs):
    try:
        cls = SUGGESTERS[algorithm]
    except KeyError:
        raise ValueError(
            f"unknown algorithm {algorithm!r}; have {sorted(SUGGESTERS)}"
        ) from None
    return cls(space, **kwargs)


def better(goal: str, a: float, b: float) -> bool:
    """Is metric `a` better than `b` under goal 'minimize'/'maximize'?"""
    if goal == "minimize":
        return a < b
    if goal == "maximize":
        return a > b
    raise ValueError(f"goal must be minimize|maximize, got {goal!r}")
