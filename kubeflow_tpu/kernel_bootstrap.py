"""Kernel-start hook: form the gang's JAX process group automatically.

The jupyter-jax-tpu image bakes a SYSTEM IPython config
(`/etc/ipython/ipython_config.py`, from images/jupyter-jax-tpu/
ipython_config.py) whose exec_lines call `bootstrap()` at every kernel
start — system scope because `$HOME` is the user's workspace PVC
(web/form.py mounts it there), so anything seeded under
`~/.ipython/profile_default/startup/` would be shadowed by the volume.

This is the consumer side of the webhook's env injection
(controlplane/webhook.py): the reference's notebook images run plain
jupyterlab under s6 (`example-notebook-servers/jupyter/s6/services.d/
jupyterlab/run`) and have nothing to initialize; ours must rendezvous
`jax.distributed` BEFORE the first cell touches jax, or a multi-host
notebook silently computes on one host's chips.
"""

from __future__ import annotations

import sys

from kubeflow_tpu import distributed


def bootstrap() -> bool:
    """Initialize the gang process group from webhook env; loud either way
    it matters. Returns True when a multi-process group formed."""
    try:
        started = distributed.initialize_from_env()
    except ValueError as e:
        # Misconfigured gang: surface in the notebook, fail the kernel
        # hook loudly rather than letting cells run half-gang'd.
        print(f"[kubeflow-tpu] gang bootstrap FAILED: {e}", file=sys.stderr)
        raise
    if started:
        import jax

        print(
            "[kubeflow-tpu] jax.distributed initialized: "
            f"process {jax.process_index()}/{jax.process_count()}, "
            f"{jax.local_device_count()} local / "
            f"{jax.device_count()} global devices",
            file=sys.stderr,
        )
    return started
