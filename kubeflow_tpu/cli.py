"""kftpu: a kubectl-shaped CLI over the platform's /apis door.

The reference leans on kubectl for every operator interaction (its
images even bake kubectl in, `/root/reference/components/
example-notebook-servers/base/Dockerfile:1-67`, and the culler shells
out to it in DEV mode, `components/notebook-controller/pkg/culler/
culler.go:160-164`); this platform serves a kubectl-shaped REST door
(`web/apis_app.py`: versioned kinds, optimistic concurrency,
merge-patch) and this CLI is the thin client for it — stdlib-only
(urllib), so it runs anywhere the operator has Python.

    python -m kubeflow_tpu.cli get notebooks -n alice
    python -m kubeflow_tpu.cli get modelservers -n alice -o json
    python -m kubeflow_tpu.cli apply -f server.json
    python -m kubeflow_tpu.cli delete notebooks my-nb -n alice

Server + identity come from flags or env (KFTPU_SERVER, KFTPU_USER).
Mutations carry the /apis door's CSRF-exempt API-client header.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.request

GROUP = "kubeflow-tpu.dev"
API_CLIENT_HEADER = "X-KFTPU-API-CLIENT"


class ApiError(SystemExit):
    """HTTP-level failure with the status code preserved — apply's
    create-or-patch branch must switch on the CODE, never on message
    text (a namespace named 'team409' must not look like a conflict)."""

    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.status = code

# columns per plural for `get` table output; (header, path-into-obj)
_COLUMNS = {
    "notebooks": (("NAME", "metadata.name"),
                  ("TOPOLOGY", "spec.tpu.topology"),
                  ("READY", "status.ready_replicas")),
    "modelservers": (("NAME", "metadata.name"),
                     ("MODEL", "spec.model"),
                     ("READY", "status.ready"),
                     ("URL", "status.url")),
    "tensorboards": (("NAME", "metadata.name"),
                     ("LOGSPATH", "spec.logspath"),
                     ("READY", "status.ready")),
    "experiments": (("NAME", "metadata.name"),
                    ("PHASE", "status.phase"),
                    ("TRIALS", "status.trials_created"),
                    ("BEST", "status.best_value")),
    "trials": (("NAME", "metadata.name"),
               ("PHASE", "status.phase"),
               ("VALUE", "status.value")),
    "profiles": (("NAME", "metadata.name"),
                 ("OWNER", "spec.owner")),
    "pods": (("NAME", "metadata.name"), ("PHASE", "phase")),
}


def _dig(obj: dict, path: str):
    cur = obj
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return ""
        cur = cur[part]
    return cur


class Client:
    def __init__(self, server: str, user: str, version: str = "v1"):
        self.server = server.rstrip("/")
        self.user = user
        self.version = version

    def req(self, method: str, path: str, body: dict | None = None):
        url = f"{self.server}/apis/{GROUP}/{self.version}{path}"
        headers = {"kubeflow-userid": self.user}
        data = None
        if body is not None:
            data = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        if method != "GET":
            headers[API_CLIENT_HEADER] = "kftpu-cli"
        r = urllib.request.Request(url, data=data, headers=headers,
                                   method=method)
        try:
            with urllib.request.urlopen(r, timeout=30) as resp:
                raw = resp.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace").strip()
            raise ApiError(
                e.code,
                f"error: {e.code} {method} {path}: {detail[:300]}")
        except urllib.error.URLError as e:
            raise SystemExit(f"error: cannot reach {self.server}: "
                             f"{e.reason}")
        return json.loads(raw) if raw else {}

    def _path(self, plural: str, ns: str, name: str = "") -> str:
        base = ("/profiles" if plural == "profiles"
                else f"/namespaces/{ns}/{plural}")
        return f"{base}/{name}" if name else base


def cmd_get(c: Client, args) -> int:
    path = c._path(args.plural, args.namespace, args.name or "")
    out = c.req("GET", path)
    items = [out] if args.name else out.get("items", [])
    if args.output == "json":
        print(json.dumps(out if args.name else items, indent=2))
        return 0
    cols = _COLUMNS.get(args.plural,
                        (("NAME", "metadata.name"),))
    rows = [[str(_dig(i, p)) for _, p in cols] for i in items]
    widths = [max(len(h), *(len(r[j]) for r in rows), 1) if rows
              else len(h) for j, (h, _) in enumerate(cols)]
    print("  ".join(h.ljust(w) for (h, _), w in zip(cols, widths)))
    for r in rows:
        print("  ".join(v.ljust(w) for v, w in zip(r, widths)))
    return 0


def cmd_apply(c: Client, args) -> int:
    raw = (sys.stdin.read() if args.filename == "-"
           else open(args.filename).read())
    docs = json.loads(raw)
    if isinstance(docs, dict):
        docs = [docs]
    for doc in docs:
        kind = doc.get("kind", "")
        plural = (kind.lower() + "s") if kind else ""
        if not plural:
            raise SystemExit("error: document missing 'kind'")
        ns = doc.get("metadata", {}).get("namespace", args.namespace)
        name = doc.get("metadata", {}).get("name", "")
        path = c._path(plural, ns)
        # kubectl-apply semantics: create, or merge-patch on conflict
        try:
            c.req("POST", path, doc)
            print(f"{plural}/{name} created")
        except ApiError as e:
            if e.status != 409:
                raise
            patch: dict = {"spec": doc.get("spec", {})}
            meta = {k: v for k, v in doc.get("metadata", {}).items()
                    if k in ("labels", "annotations")}
            if meta:
                # the /apis door patches these metadata fields too;
                # dropping them would claim "configured" while
                # silently ignoring label/annotation edits
                patch["metadata"] = meta
            c.req("PATCH", f"{path}/{name}", patch)
            print(f"{plural}/{name} configured")
    return 0


def cmd_delete(c: Client, args) -> int:
    c.req("DELETE", c._path(args.plural, args.namespace, args.name))
    print(f"{args.plural}/{args.name} deleted")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kftpu")
    p.add_argument("--server",
                   default=os.environ.get("KFTPU_SERVER",
                                          "http://localhost:8082"))
    p.add_argument("--user",
                   default=os.environ.get("KFTPU_USER",
                                          "admin@example.com"))
    p.add_argument("--api-version", default="v1")
    sub = p.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("get", help="list or get resources")
    g.add_argument("plural")
    g.add_argument("name", nargs="?")
    g.add_argument("-n", "--namespace", default="default")
    g.add_argument("-o", "--output", choices=("table", "json"),
                   default="table")

    a = sub.add_parser("apply", help="create-or-patch from JSON")
    a.add_argument("-f", "--filename", required=True,
                   help="JSON file (or - for stdin); one doc or a list")
    a.add_argument("-n", "--namespace", default="default")

    d = sub.add_parser("delete", help="delete a resource")
    d.add_argument("plural")
    d.add_argument("name")
    d.add_argument("-n", "--namespace", default="default")

    args = p.parse_args(argv)
    c = Client(args.server, args.user, args.api_version)
    try:
        return {"get": cmd_get, "apply": cmd_apply,
                "delete": cmd_delete}[args.cmd](c, args)
    except BrokenPipeError:
        # `kftpu get ... | head` is not an error — and the guard must
        # live HERE so the console-script entry point (pyproject
        # [project.scripts]) gets it too, not just python -m
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
        return 0


if __name__ == "__main__":
    sys.exit(main())
