"""Pure-JAX inference engine: static-shape KV cache, scan decode.

TPU constraints drive the design (pallas guide / XLA semantics):
- The KV cache is a fixed [L, b, max_len, n_kv, hd] buffer; prefill and
  decode write into it with `dynamic_update_slice`. No dynamic shapes —
  one compile per (batch, max_len) bucket, reused across requests.
- Decode is a single `lax.scan` over token steps: one trace, one
  compile, no per-token Python dispatch.
- Attention over the cache masks invalid slots by position (kv_mask), so
  the same `dot_product_attention` op serves train and serve.

Llama and Gemma share a block param schema (wq/wk/wv/wo, w_gate/w_up/
w_down, attn_norm/mlp_norm, final_norm, embed); a `Family` adapter
captures the differences (gate activation, embedding scale, tied head),
so one engine serves both families.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.ops.attention import dot_product_attention
from kubeflow_tpu.ops.embedding import embed_lookup
from kubeflow_tpu.ops.norms import rms_norm
from kubeflow_tpu.ops.rotary import apply_rope, rope_frequencies
from kubeflow_tpu.serving.quant import qdot

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class Family:
    """Model-family adapter for the shared llama/gemma block schema.

    `mlp` overrides the block's FFN half entirely (signature
    `(cfg, layer_params, normed_h) -> delta`): the MoE family routes
    through experts there while the attention half, KV cache, and
    sampling machinery stay shared."""

    name: str
    gate_act: Callable[[jnp.ndarray], jnp.ndarray]
    scale_embed: bool          # multiply embeddings by sqrt(hidden)
    mlp: Callable[..., jnp.ndarray] | None = None


LLAMA_FAMILY = Family("llama", jax.nn.silu, scale_embed=False)
GEMMA_FAMILY = Family(
    "gemma", lambda x: jax.nn.gelu(x, approximate=True), scale_embed=True
)


def _moe_serving_mlp(cfg, p, h: jnp.ndarray) -> jnp.ndarray:
    """Dropless MoE FFN for decode (models/llama_moe.py block schema:
    router [D,E] + per-expert SwiGLU stacks [E,D,M]). Training's
    capacity factor trades dropped tokens for load balance; serving
    must never drop — capacity_factor = E/k makes capacity equal the
    token count, and a token occupies at most one slot per expert, so
    every assignment fits. Decode token counts are tiny (batch x 1),
    so the [T, E, T] dispatch tensors cost nothing."""
    import dataclasses as _dc

    from kubeflow_tpu.parallel import moe as moe_lib

    mcfg = _dc.replace(
        cfg.moe_config(),
        capacity_factor=cfg.num_experts / cfg.top_k)
    params = {k: p[k].astype(cfg.dtype)
              for k in ("router", "w_gate", "w_up", "w_down")}
    y, _aux = moe_lib.moe_mlp(params, h, mcfg)
    return y


MOE_LLAMA_FAMILY = Family(
    "llama-moe", jax.nn.silu, scale_embed=False, mlp=_moe_serving_mlp)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    max_len: int = 1024        # cache bucket; one compile per value
    temperature: float = 0.0   # 0 = greedy
    top_k: int = 0             # keep k highest-logit tokens; 0 = off
    top_p: float = 1.0         # nucleus: smallest set w/ cum prob >= p
    # When set, sequences that emit EOS keep emitting EOS for the rest of
    # the (fixed-length) scan, so callers can trim on first EOS.
    eos_token: int | None = None


def _per_row(v: jnp.ndarray) -> jnp.ndarray:
    """[] stays scalar; [b] gains a trailing axis to broadcast against
    [b, vocab] logits (per-row sampling knobs)."""
    return v[..., None] if getattr(v, "ndim", 0) >= 1 else v


def scaled_filtered_logits(logits: jnp.ndarray,
                           sp: "SamplingParams") -> jnp.ndarray:
    """Temperature-scale then top-k/top-p filter — the ONE definition of
    the sampled distribution's logits, shared by the engine's sampler
    and the speculative verifier (a drifted copy there would silently
    break speculative decoding's target-law exactness). The cond skips
    the filter's argsorts when every row has both knobs off
    (temperature-only sampling keeps its pre-filter cost)."""
    scaled = logits.astype(jnp.float32) / jnp.maximum(
        _per_row(sp.temperature), 1e-6)
    return jax.lax.cond(
        jnp.any((sp.top_k > 0) | (sp.top_p < 1.0)),
        lambda s: filter_logits(s, _per_row(sp.top_k),
                                _per_row(sp.top_p)),
        lambda s: s, scaled)


class SamplingParams(NamedTuple):
    """Sampling knobs as TRACED values: requests with different
    temperature/top_k/top_p reuse one compiled decode scan (static
    shapes, dynamic values — recompiling a 30s scan per slider move
    would be the wrong TPU trade). Each field is a scalar [] or a
    per-row [batch] vector, so ONE batch can mix greedy and sampled
    rows with different knobs (the dynamic batcher relies on this)."""

    temperature: jnp.ndarray   # []/[b] f32; <= 0 means greedy
    top_k: jnp.ndarray         # []/[b] i32; 0 disables
    top_p: jnp.ndarray         # []/[b] f32; >= 1 disables


def filter_logits(logits: jnp.ndarray, top_k: jnp.ndarray,
                  top_p: jnp.ndarray) -> jnp.ndarray:
    """Mask logits outside the top-k set and the top-p nucleus to -inf.

    Both knobs are dynamic. HF-style order: the caller temperature-
    scales first, then k, then p (computed on the softmax of what
    remains representable — scaling changes the nucleus, as it should).
    """
    vocab = logits.shape[-1]
    # Decide in the sorted domain, scatter the mask back through the
    # inverse permutation. (Comparing original-domain probs against a
    # sorted-domain cutoff would be ulp-fragile: softmax sums in a
    # different order on each side, and one ulp can empty the nucleus.)
    order = jnp.argsort(-logits, axis=-1)           # descending
    desc = jnp.take_along_axis(logits, order, axis=-1)
    idx = jnp.arange(vocab)
    # top-k: the first k sorted positions. k=0 -> keep all.
    keep_desc = jnp.where(top_k > 0, idx < top_k, True)
    # top-p: the smallest prefix of descending probs whose mass reaches
    # p, over the distribution REMAINING after top-k (HF sequential
    # semantics: k filters, renormalize, then the nucleus) — the first
    # surviving token always stays; p>=1 keeps all.
    probs_desc = jnp.where(keep_desc, jax.nn.softmax(desc, axis=-1), 0.0)
    probs_desc = probs_desc / jnp.sum(probs_desc, axis=-1, keepdims=True)
    before = jnp.cumsum(probs_desc, axis=-1) - probs_desc
    keep_desc &= before < top_p
    inv = jnp.argsort(order, axis=-1)
    keep = jnp.take_along_axis(
        jnp.broadcast_to(keep_desc, logits.shape), inv, axis=-1)
    return jnp.where(keep, logits, -jnp.inf)


class DecodeState:
    """KV cache + cursor, a pytree (jit-carryable).

    `pad` marks cache slots holding left-pad keys (excluded from
    attention); `offset` is each row's pad count, so a token in slot i
    has LOGICAL position i - offset (what rope sees). Both stay zero
    for unpadded batches — the variable-length path costs nothing when
    unused."""

    def __init__(self, k, v, length, pad=None, offset=None):
        self.k = k              # [L, b, max_len, n_kv, hd]
        self.v = v
        self.length = length    # [] int32 — filled slots
        # Only touch k.shape when defaulting: tree_unflatten passes all
        # five children, whose leaves may be non-arrays mid-transform
        # (jax.tree.map over dtypes etc.).
        if pad is None:
            pad = jnp.zeros((k.shape[1], k.shape[2]), bool)
        if offset is None:
            offset = jnp.zeros((k.shape[1],), jnp.int32)
        self.pad = pad
        self.offset = offset

    def tree_flatten(self):
        return (self.k, self.v, self.length, self.pad, self.offset), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    DecodeState, DecodeState.tree_flatten, DecodeState.tree_unflatten
)


def transformer_block(cfg, fam: Family, p, x, rope_positions, inv_freq,
                      write_kv, attn, proj=None):
    """One decoder block on `x` [b, s, h]: norms, QKV/output projections,
    rotary, gated MLP. The KV-cache write policy and the attention call
    are injected: prefill writes a contiguous [s]-slice at one shared
    scalar cursor (`_forward_cached`), the continuous-batching engine
    scatters a single step per row at per-slot cursors
    (serving/continuous.py). `proj(name, h, w)` optionally wraps every
    block matmul — multi-LoRA serving adds its per-row low-rank delta
    there (serving/multilora.py) — and defaults to the plain matmul.
    Keeping every matmul/norm/activation in ONE function is what makes
    the serving paths provably the same model — a drifted copy would
    silently change logits."""
    if proj is None:
        def proj(name, h, w):
            return qdot(h, w, cfg.dtype)

    b, s = x.shape[:2]
    h = rms_norm(x, p["attn_norm"], cfg.norm_eps)
    q = proj("wq", h, p["wq"]).reshape(b, s, cfg.num_heads, cfg.head_dim)
    k = proj("wk", h, p["wk"]).reshape(
        b, s, cfg.num_kv_heads, cfg.head_dim)
    v = proj("wv", h, p["wv"]).reshape(
        b, s, cfg.num_kv_heads, cfg.head_dim)
    q = apply_rope(q, rope_positions, inv_freq)
    k = apply_rope(k, rope_positions, inv_freq)
    k_cache, v_cache = write_kv(k, v)
    out = attn(q, k_cache, v_cache)
    x = x + proj("wo", out.reshape(b, s, cfg.q_dim), p["wo"])

    h = rms_norm(x, p["mlp_norm"], cfg.norm_eps)
    if fam.mlp is not None:
        x = x + fam.mlp(cfg, p, h)
    else:
        gate = fam.gate_act(proj("w_gate", h, p["w_gate"]))
        ff = gate * proj("w_up", h, p["w_up"])
        x = x + proj("w_down", ff, p["w_down"])
    return x, (k_cache, v_cache)


class InferenceEngine:
    """Batched greedy/temperature generation for a llama-family model.

    `cfg` is the model's LlamaConfig/GemmaConfig (shared field names).
    Jitted entry points are cached per (batch, prompt_len, max_new).
    """

    def __init__(self, params: Params, cfg, family: Family,
                 engine_config: EngineConfig = EngineConfig(),
                 adapter_pack=None):
        self.params = params
        self.cfg = cfg
        self.family = family
        self.ec = engine_config
        # Multi-LoRA: serving/multilora.AdapterPack of K resident
        # fine-tunes; requests select per row (id 0 = plain base).
        self.adapter_pack = adapter_pack
        # Params flow through every jitted entry point as an ARGUMENT
        # (deliberately NOT donated — self.params is reused every call).
        # Closing over self.params would embed the whole tree into the
        # lowered module as literal constants — at 500M params that is
        # a ~1 GB MLIR module whose TPU compile runs past 10 minutes
        # (measured: 75 s just to lower), vs seconds when the compiler
        # sees only shapes.
        self._generate_jit = jax.jit(
            self._generate, static_argnames=("max_new",)
        )

    # -- model internals ---------------------------------------------------

    def _embed(self, params, tokens):
        cfg = self.cfg
        # Mesh-aware (ops.embedding): a gather is fine single-chip, but a
        # sharded 256k-vocab Gemma table must contract via one-hot or the
        # SPMD partitioner replicates the full table per step.
        x = embed_lookup(params["embed"], tokens, cfg.dtype)
        if self.family.scale_embed:
            x = x * jnp.asarray(cfg.hidden_size ** 0.5, cfg.dtype)
        return x

    def _head(self, params, x):
        tied = "lm_head" not in params
        head = params["embed"].T if tied else params["lm_head"]
        return x.astype(jnp.float32) @ head.astype(jnp.float32)

    def _forward_cached(self, params, tokens, state: DecodeState, *,
                        prompt_mask=None, return_all: bool = False,
                        adapters=None, adapter_ids=None):
        """Run [b, s] tokens starting at state.length; returns
        (last-position logits [b, vocab], updated state) — or all
        positions' logits [b, s, vocab] with return_all (speculative
        decoding scores every drafted position in one pass).

        `prompt_mask` [b, s] bool (False = pad) enables variable-length
        rows in one batch. Pads must be LEFT-aligned (the final column
        is what the next-token logits read) — pad slots are excluded
        from every later attention and rope sees logical positions
        (slot - pad count), so a padded row computes exactly what the
        unpadded prompt would.

        `params` is threaded as an argument, never closed over — see
        the constructor note on compile-time cost."""
        cfg, fam = self.cfg, self.family
        b, s = tokens.shape
        start = state.length
        # Slot positions order the cache for causal masking; rope gets
        # logical positions (slot - offset) so padding never shifts a
        # token's rotary phase.
        positions = start + jnp.arange(s, dtype=jnp.int32)[None, :]
        positions = jnp.broadcast_to(positions, (b, s))
        pad, offset = state.pad, state.offset
        if prompt_mask is not None:
            offset = offset + jnp.sum(
                ~prompt_mask, axis=1, dtype=jnp.int32)
            pad = jax.lax.dynamic_update_slice(
                pad, ~prompt_mask, (0, start))
        rope_positions = jnp.maximum(positions - offset[:, None], 0)
        inv_freq = rope_frequencies(cfg.head_dim, theta=cfg.rope_theta)
        kv_positions = jnp.broadcast_to(
            jnp.arange(self.ec.max_len, dtype=jnp.int32)[None, :],
            (b, self.ec.max_len))
        kv_valid = (kv_positions < (start + s)) & ~pad

        x = self._embed(params, tokens)

        # The KV cache rides the layer scan as CARRY, not as scanned
        # xs/ys: stacking per-layer cache slices as scan outputs made
        # XLA materialize a fresh copy of the ENTIRE cache every
        # forward call — on a decode step that doubled HBM traffic
        # (full-cache write next to the unavoidable full-cache read),
        # capping decode MBU at ~half the roofline. Carried buffers
        # updated via dynamic_update_slice stay in place (the canonical
        # while-loop aliasing pattern), so the only cache WRITE per
        # step is the s new rows per layer.
        def layer(carry, scanned):
            x, k_all, v_all = carry
            if adapters is None:
                p, li = scanned
                proj = None
            else:
                from kubeflow_tpu.serving.multilora import lora_proj
                p, ab, li = scanned
                proj = lora_proj(ab, adapter_ids,
                                 self.adapter_pack.scaling, cfg)
            cell = {}

            def write_kv(k, v):
                k2 = jax.lax.dynamic_update_slice(
                    k_all, k[None].astype(k_all.dtype),
                    (li, 0, start, 0, 0))
                v2 = jax.lax.dynamic_update_slice(
                    v_all, v[None].astype(v_all.dtype),
                    (li, 0, start, 0, 0))
                cell["k"], cell["v"] = k2, v2
                return (jax.lax.dynamic_index_in_dim(
                            k2, li, 0, keepdims=False),
                        jax.lax.dynamic_index_in_dim(
                            v2, li, 0, keepdims=False))

            def attn(q, kc, vc):
                # contiguous_positions: this cache's cell index IS the
                # token position (kv_positions = arange(max_len)), the
                # declaration the fused decode kernel dispatches on
                return dot_product_attention(
                    q, kc, vc, positions, kv_positions,
                    causal=True, kv_mask=kv_valid,
                    window=getattr(cfg, "sliding_window", None),
                    contiguous_positions=True)

            x, _ = transformer_block(
                cfg, fam, p, x, rope_positions, inv_freq, write_kv,
                attn, proj)
            return (x, cell["k"], cell["v"]), None

        n_layers = cfg.num_layers
        layer_ids = jnp.arange(n_layers, dtype=jnp.int32)
        xs = ((params["blocks"], layer_ids) if adapters is None
              else (params["blocks"], adapters, layer_ids))
        (x, k_new, v_new), _ = jax.lax.scan(
            layer, (x, state.k, state.v), xs)
        x = rms_norm(x, params["final_norm"], cfg.norm_eps)
        logits = self._head(params, x if return_all else x[:, -1])
        return logits, DecodeState(k_new, v_new, start + s, pad, offset)

    # -- public API --------------------------------------------------------

    def init_state(self, batch: int) -> DecodeState:
        cfg = self.cfg
        shape = (cfg.num_layers, batch, self.ec.max_len,
                 cfg.num_kv_heads, cfg.head_dim)
        return DecodeState(
            jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype),
            jnp.zeros((), jnp.int32))

    def kv_cache_bytes(self, batch: int, cells: int | None = None) -> int:
        """KV-cache HBM for `batch` rows of `cells` cache cells (K+V,
        all layers; defaults to max_len — the dense worst case). The
        common yardstick for the paged bench and the observability
        docs: the dense engine always pays batch * max_len, the paged
        pool pays blocks_in_use * block_size."""
        cfg = self.cfg
        if cells is None:
            cells = self.ec.max_len
        itemsize = jnp.dtype(cfg.dtype).itemsize
        return (2 * cfg.num_layers * batch * cells
                * cfg.num_kv_heads * cfg.head_dim * itemsize)

    def _sample(self, logits, rng, sp: SamplingParams):
        """-> (tokens [b], logprobs [b]). The logprob is the chosen
        token's log-softmax under the RAW model distribution
        (temperature/filters don't rescale it — OpenAI convention).
        Computed UNCONDITIONALLY by design: the O(b·vocab) pass is <1%
        of the O(b·hidden·vocab) head matmul that produced the logits
        at real vocab/hidden sizes (tiny-CPU A/Bs exaggerate it), and
        a jit-static opt-in flag would double the warmed compile set
        of every serving entry point for that <1%."""
        # lax.cond, not jnp.where: an all-greedy decode must not pay
        # the sampled branch's full-vocab argsorts/cumsum/categorical
        # per step (256k vocab on Gemma) just to discard the result.
        # Mixed batches take the sampled branch and select per row.
        def greedy(_):
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def sampled(_):
            drawn = jax.random.categorical(
                rng, scaled_filtered_logits(logits, sp),
                axis=-1).astype(jnp.int32)
            return jnp.where(sp.temperature > 0.0, drawn, greedy(None))

        tok = jax.lax.cond(
            jnp.any(sp.temperature > 0.0), sampled, greedy, None)
        raw = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        lp = jnp.take_along_axis(raw, tok[:, None], axis=-1)[:, 0]
        return tok, lp

    def _resolve_sampling(
        self, temperature, top_k, top_p, rng: jax.Array | None,
        batch: int | None = None,
    ) -> tuple[SamplingParams, jax.Array]:
        """EngineConfig defaulting + validation + default-rng policy,
        shared with SpeculativeEngine so the two paths cannot drift.
        Each knob is a scalar or a per-row vector (mixed batches)."""
        temperature = np.asarray(
            self.ec.temperature if temperature is None else temperature,
            np.float32)
        top_k = np.asarray(
            self.ec.top_k if top_k is None else top_k, np.int64)
        top_p = np.asarray(
            self.ec.top_p if top_p is None else top_p, np.float32)
        for name, arr in (("temperature", temperature), ("top_k", top_k),
                          ("top_p", top_p)):
            if arr.ndim > 1:
                raise ValueError(f"{name} must be scalar or 1-D, "
                                 f"got shape {arr.shape}")
            if (arr.ndim == 1 and batch is not None
                    and len(arr) != batch):
                raise ValueError(
                    f"{name} has {len(arr)} entries for a batch of "
                    f"{batch}")
        if (top_k < 0).any():
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if (top_k >= 2**31).any():
            # validated as int64 above, stored int32 below: without this
            # check a library caller's huge top_k would silently wrap
            # negative (the HTTP server range-checks; the Python API
            # must reject identically)
            raise ValueError(f"top_k must be < 2**31, got {top_k}")
        if not ((0.0 < top_p) & (top_p <= 1.0)).all():
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if any(a.ndim == 1 for a in (temperature, top_k, top_p)):
            # one vector -> all vectors: [] vs [b] are different jit
            # signatures, and mixed combos would compile 2^3 variants
            n = (batch if batch is not None else max(
                a.shape[0] for a in (temperature, top_k, top_p)
                if a.ndim == 1))
            temperature = np.broadcast_to(temperature, (n,))
            top_k = np.broadcast_to(top_k, (n,))
            top_p = np.broadcast_to(top_p, (n,))
        sp = SamplingParams(
            temperature=jnp.asarray(temperature, jnp.float32),
            top_k=jnp.asarray(top_k, jnp.int32),
            top_p=jnp.asarray(top_p, jnp.float32),
        )
        if rng is None:
            if (temperature > 0.0).any():
                # Fresh entropy per request — a constant default key
                # would make every "sampled" completion identical; 63
                # seed bits keep birthday collisions out of reach while
                # staying inside np.int64 (jax.random.key rejects
                # Python ints >= 2**63).
                rng = jax.random.key(
                    int.from_bytes(os.urandom(8), "little") >> 1)
            else:
                # greedy: the cond's sampled branch never runs, so the
                # constant key is never drawn from at runtime
                rng = jax.random.key(0)
        return sp, rng

    def _prefill_sample(self, params, prompt, state, rng,
                        sp: SamplingParams, prompt_mask,
                        adapters=None, adapter_ids=None):
        """Prefill + sample token #1 (and its logprob). Shared head of
        generate and generate_stream so both follow the same rng
        discipline."""
        eos = self.ec.eos_token
        rng, sub = jax.random.split(rng)  # use-once key discipline
        logits, state = self._forward_cached(
            params, prompt, state, prompt_mask=prompt_mask,
            adapters=adapters, adapter_ids=adapter_ids)
        first, lp = self._sample(logits, sub, sp)
        done = (first == eos) if eos is not None else jnp.zeros(
            first.shape, bool)
        return state, first, rng, done, lp

    def _decode_chunk(self, params, state, tok, rng, done,
                      sp: SamplingParams, *, length: int,
                      adapters=None, adapter_ids=None):
        """`length` decode steps from carry. Returns the new carry, the
        [b, length] tokens and their logprobs (logprob entries past a
        row's first EOS describe the pre-forcing sampled token and are
        undefined for callers). The ONE step body both entry points
        scan over — stream-vs-oneshot equality is by construction."""
        eos = self.ec.eos_token

        def step(carry, _):
            state, tok, rng, done = carry
            rng, sub = jax.random.split(rng)
            logits, state = self._forward_cached(
                params, tok[:, None], state,
                adapters=adapters, adapter_ids=adapter_ids)
            nxt, lp = self._sample(logits, sub, sp)
            if eos is not None:
                # Sequences past EOS emit EOS forever (static shapes —
                # the scan always runs `length` steps; callers trim).
                nxt = jnp.where(done, jnp.asarray(eos, nxt.dtype), nxt)
                done = done | (nxt == eos)
            return (state, nxt, rng, done), (nxt, lp)

        (state, tok, rng, done), (rest, lps) = jax.lax.scan(
            step, (state, tok, rng, done), None, length=length)
        return (state, tok, rng, done, jnp.moveaxis(rest, 0, 1),
                jnp.moveaxis(lps, 0, 1))

    def _generate(self, params, prompt, state, rng, sp: SamplingParams,
                  prompt_mask, *, max_new: int,
                  adapters=None, adapter_ids=None):
        state, first, rng, done, lp1 = self._prefill_sample(
            params, prompt, state, rng, sp, prompt_mask,
            adapters, adapter_ids)
        state, _, _, _, rest, lps = self._decode_chunk(
            params, state, first, rng, done, sp, length=max_new - 1,
            adapters=adapters, adapter_ids=adapter_ids)
        toks = jnp.concatenate([first[:, None], rest], axis=1)
        lps = jnp.concatenate([lp1[:, None], lps], axis=1)
        return toks, lps, state

    def generate(
        self,
        prompt_tokens: jnp.ndarray,   # [b, s] int32
        *,
        max_new: int = 32,
        rng: jax.Array | None = None,
        temperature: float | None = None,
        top_k: int | None = None,
        top_p: float | None = None,
        prompt_mask: jnp.ndarray | None = None,  # [b, s] bool, False=pad
        prefill_chunk: int | None = None,
        adapter: "str | list[str] | None" = None,
        return_logprobs: bool = False,
    ) -> jnp.ndarray:
        """Generate `max_new` tokens after the prompt. Returns [b, max_new]
        (post-hoc EOS trimming is the caller's job — shapes stay static).

        temperature/top_k/top_p default from EngineConfig; per-call
        overrides are dynamic (no recompile across values).
        `prompt_mask` batches variable-length prompts: pads LEFT-aligned
        (False entries), each row decodes as if it were unpadded.
        `prefill_chunk` prefills long prompts in fixed slices (see
        prefill_chunked) — same tokens, chunk-bounded compile shapes
        and activation memory. `adapter` (needs an adapter_pack) picks
        a resident LoRA fine-tune — one name for the whole batch or
        one per row; ''/None rows decode the plain base.
        `return_logprobs` returns (tokens, logprobs): each chosen
        token's raw-model log-softmax (entries past a row's first EOS
        are undefined)."""
        sp, rng, prompt_mask, state = self._prep(
            prompt_tokens, max_new, rng, temperature, top_k, top_p,
            prompt_mask)
        adapters = adapter_ids = None
        if adapter is not None:
            if self.adapter_pack is None:
                raise ValueError("no adapter_pack loaded on this engine")
            names = ([adapter] * prompt_tokens.shape[0]
                     if isinstance(adapter, str) else list(adapter))
            if len(names) != prompt_tokens.shape[0]:
                raise ValueError(
                    f"{len(names)} adapter names for a batch of "
                    f"{prompt_tokens.shape[0]}")
            adapters = self.adapter_pack.blocks
            adapter_ids = jnp.asarray(
                [self.adapter_pack.resolve(n) for n in names], jnp.int32)
        if prefill_chunk is None:
            toks, lps, _ = self._generate_jit(
                self.params, prompt_tokens, state, rng, sp, prompt_mask,
                max_new=max_new, adapters=adapters,
                adapter_ids=adapter_ids)
            return (toks, lps) if return_logprobs else toks
        if prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got "
                             f"{prefill_chunk}")
        b, n = prompt_tokens.shape
        pad = (-n) % prefill_chunk
        if n + pad + max_new > self.ec.max_len:
            raise ValueError(
                f"chunk-padded prompt {n + pad} + max_new {max_new} "
                f"exceeds cache bucket {self.ec.max_len}")
        if pad:
            prompt_tokens = jnp.concatenate(
                [jnp.zeros((b, pad), prompt_tokens.dtype),
                 prompt_tokens], axis=1)
            prompt_mask = jnp.concatenate(
                [jnp.zeros((b, pad), bool), prompt_mask], axis=1)
        state, first, rng, done, lp1 = self.prefill_chunked(
            self.params, prompt_tokens, state, rng, sp, prompt_mask,
            chunk=prefill_chunk, adapters=adapters,
            adapter_ids=adapter_ids)
        _, _, _, _, rest, lps = self._chunk_jit(
            self.params, state, first, rng, done, sp,
            length=max_new - 1, adapters=adapters,
            adapter_ids=adapter_ids)
        toks = jnp.concatenate([first[:, None], rest], axis=1)
        if return_logprobs:
            return toks, jnp.concatenate([lp1[:, None], lps], axis=1)
        return toks

    def _prep(self, prompt_tokens, max_new, rng, temperature, top_k,
              top_p, prompt_mask):
        """Shared validation + sampling/state setup for both entry
        points."""
        b, s = prompt_tokens.shape
        if s + max_new > self.ec.max_len:
            raise ValueError(
                f"prompt {s} + max_new {max_new} exceeds cache bucket "
                f"{self.ec.max_len}")
        if prompt_mask is not None:
            if prompt_mask.shape != (b, s):
                raise ValueError(
                    f"prompt_mask shape {prompt_mask.shape} != {(b, s)}")
            m = np.asarray(prompt_mask, bool)
            if not (np.sort(m, axis=1) == m).all() or not m[:, -1].all():
                raise ValueError(
                    "prompt_mask pads must be LEFT-aligned (False... "
                    "then True...) with a real final token per row")
            prompt_mask = jnp.asarray(m)
        else:
            prompt_mask = jnp.ones((b, s), bool)
        sp, rng = self._resolve_sampling(temperature, top_k, top_p, rng,
                                         batch=b)
        return sp, rng, prompt_mask, self.init_state(b)

    def generate_stream(
        self,
        prompt_tokens: jnp.ndarray,   # [b, s] int32
        *,
        max_new: int = 32,
        chunk: int = 8,
        rng: jax.Array | None = None,
        temperature: float | None = None,
        top_k: int | None = None,
        top_p: float | None = None,
        prompt_mask: jnp.ndarray | None = None,
    ):
        """Yield [b, <=chunk] numpy token chunks as they decode.

        Same sampling law AND same rng split discipline as generate():
        with equal arguments the concatenated stream equals generate()'s
        prefix exactly (the shared _prefill_sample/_decode_chunk pair is
        the proof). Unlike generate(), the stream stops early once every
        row has hit EOS — a stream's length is allowed to be dynamic.
        Compiled programs per prompt shape: prefill, the full chunk,
        and one tail per distinct (max_new-1) % chunk — bounded by
        `chunk` total, never one per max_new value.

        Validation is eager (this is a plain method returning an inner
        generator): bad arguments raise HERE, not at first next().
        """
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        sp, rng, prompt_mask, state = self._prep(
            prompt_tokens, max_new, rng, temperature, top_k, top_p,
            prompt_mask)

        def _iter():
            state_, tok, rng_, done, _ = self._prefill_jit(
                self.params, prompt_tokens, state, rng, sp, prompt_mask)
            yield np.asarray(tok)[:, None]
            emitted = 1
            while emitted < max_new:
                if self.ec.eos_token is not None and bool(
                        np.asarray(done).all()):
                    return
                n = min(chunk, max_new - emitted)
                state_, tok, rng_, done, rest, _ = self._chunk_jit(
                    self.params, state_, tok, rng_, done, sp, length=n)
                yield np.asarray(rest)
                emitted += n

        return _iter()

    @functools.cached_property
    def _prefill_jit(self):
        return jax.jit(self._prefill_sample)

    @functools.cached_property
    def _chunk_jit(self):
        return jax.jit(self._decode_chunk, static_argnames=("length",))

    @functools.cached_property
    def _forward_jit(self):
        return jax.jit(self._forward_cached)

    def _score(self, params, tokens, state, prompt_mask):
        logits, _ = self._forward_cached(
            params, tokens, state, prompt_mask=prompt_mask,
            return_all=True)
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        # token i is predicted by position i-1: shift, gather, and
        # zero the pad positions so a masked row scores only its tokens
        tgt = tokens[:, 1:]
        got = jnp.take_along_axis(lp[:, :-1], tgt[:, :, None],
                                  axis=-1)[:, :, 0]
        return got * prompt_mask[:, 1:].astype(jnp.float32)

    @functools.cached_property
    def _score_jit(self):
        return jax.jit(self._score)

    def score(self, tokens: jnp.ndarray,
              prompt_mask: jnp.ndarray | None = None) -> jnp.ndarray:
        """Teacher-forced scoring: log P(token_i | tokens_<i) for every
        position past the first, [b, s-1] fp32 (pad positions 0) — the
        perplexity/eval path (lm-eval style), no decoding. One forward,
        `return_all` logits, no cache reuse across calls."""
        b, s = tokens.shape
        if s < 2:
            raise ValueError("scoring needs at least 2 tokens")
        if s > self.ec.max_len:
            raise ValueError(
                f"sequence {s} exceeds cache bucket {self.ec.max_len}")
        if prompt_mask is None:
            prompt_mask = jnp.ones((b, s), bool)
        return self._score_jit(self.params, tokens, self.init_state(b),
                               prompt_mask)

    def precompute_prefix(self, tokens: list[int]):
        """Run a shared prefix (system prompt) ONCE; returns a batch-1
        DecodeState at length=len(tokens). Admissions seeded from this
        state prefill only their suffix — the per-request cost of an
        N-token system prompt drops to zero after the first compute.
        Exact length (no bucketing): prefixes are few, registered at
        startup, and their state is reused for the server's life."""
        if not tokens:
            raise ValueError("prefix must be non-empty")
        if len(tokens) >= self.ec.max_len:
            raise ValueError(
                f"prefix {len(tokens)} leaves no cache room "
                f"(max_len {self.ec.max_len})")
        arr = jnp.asarray([tokens], jnp.int32)
        _, state = self._forward_jit(
            self.params, arr, self.init_state(1),
            prompt_mask=jnp.ones_like(arr, bool))
        return state

    def prefill_chunked(self, params, prompt, state, rng,
                        sp: SamplingParams, prompt_mask, *, chunk: int,
                        adapters=None, adapter_ids=None):
        """Prefill in fixed `chunk`-token slices through the
        incremental cache, then sample token #1 from the final slice.

        Long-context serving's standard shape-bounding move: a 32k
        prompt compiles ONE [b, chunk] program instead of one program
        (and one activation working set) per long-prompt bucket —
        chunk i attends the cache filled by chunks 0..i-1, which is
        exactly what `_forward_cached` computes. The final slice goes
        through `_prefill_sample`, so the rng discipline and sampled
        law equal the one-shot prefill bit for bit (earlier slices
        never consume rng). Rows whose pads span whole early slices
        are safe: a fully-masked row attends nothing (finite NEG_INF
        masking, no NaN) and its garbage positions are never sampled —
        only the final slice's last column is.

        `prompt` width must be a multiple of `chunk` (callers left-pad
        and extend `prompt_mask` accordingly)."""
        b, n = prompt.shape
        if n % chunk:
            raise ValueError(f"prompt width {n} not a multiple of "
                             f"chunk {chunk} (left-pad first)")
        for i in range(n // chunk - 1):
            sl = slice(i * chunk, (i + 1) * chunk)
            _, state = self._forward_jit(
                params, prompt[:, sl], state,
                prompt_mask=prompt_mask[:, sl],
                adapters=adapters, adapter_ids=adapter_ids)
        return self._prefill_jit(
            params, prompt[:, n - chunk:], state, rng, sp,
            prompt_mask[:, n - chunk:],
            adapters=adapters, adapter_ids=adapter_ids)
