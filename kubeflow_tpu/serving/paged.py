"""Paged KV-cache bookkeeping: block pool + radix prefix cache.

This module is pure host-side Python — no jax. The device arrays (the
block pool itself, `[L, num_blocks, block_size, n_kv, hd]`) live inside
`ContinuousEngine`'s `SlotState`; here we only track which physical
blocks are free, which are owned by an in-flight request, and which are
retained by the radix tree for cross-request prefix reuse.

Conventions
-----------
- Block 0 is the reserved *trash* block. Unallocated block-table entries
  point at it, and writes from retired-but-not-yet-reset slots land
  there harmlessly. It is never handed out by the pool.
- The radix tree has one node per *full* block: an edge is exactly
  `block_size` tokens. Partial-block prefixes are matched by comparing
  against a child's key and are handled by the caller as copy-on-write
  (the matched block seeds the prefill state; the new request writes its
  own fresh block, so the shared one is never mutated).
- `refs` on a node counts *active requests whose block table points at
  that physical block*. Only refcount-0 nodes may be evicted, and only
  leaves (evicting an interior node would orphan its children's token
  paths).

Write disjointness
------------------
The fused prefill/append kernel (`ops/pallas/prefill_append.py`)
rewrites every block it visits *in full* — including the cells below
each row's cursor, which it writes back as the content it read. That
is only safe under the invariant this module maintains by
construction: **a row's write range `[q_start, q_start + q_lens)`
lies in blocks no OTHER row's block table references.**

Concretely:

- New cells land only in *fresh* blocks the pool just allocated to
  exactly one request (`BlockPool` hands a block to one owner; the
  `_free_set` mirror makes double-allocation impossible).
- Radix-shared blocks sit strictly *below* every sharer's cursor:
  the tree only indexes full blocks of already-written prompt prefix,
  and a partial-block match is copy-on-write (the new request copies
  the cells into its own fresh block rather than appending into the
  shared one). A visited shared block is therefore read-only for all
  sharers, and the kernel's full-block rewrite reproduces its
  contents bit-for-bit.
- Concurrent rows in one fused dispatch come from different slots,
  whose table tails are disjoint fresh chains — so no two rows'
  write ranges can alias.

`tests/test_prefill_append_kernel.py` pins the consequences (shared
block survives both sharers' visits byte-identically; unvisited
blocks untouched) but the invariant itself is a *precondition* the
engine guarantees, not a behavior the kernel checks at runtime.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable

from kubeflow_tpu.obs.cachestats import canonical_prefix
from kubeflow_tpu.obs.cardinality import LabelGuard

__all__ = ["BlockPool", "HostSpillTier", "RadixPrefixCache",
           "TRASH_BLOCK"]

TRASH_BLOCK = 0


class BlockPool:
    """Free-list allocator over physical KV block ids `[1, num_blocks)`.

    Block 0 (trash) is reserved and never allocated. The pool knows
    nothing about the radix tree; blocks held by the tree are simply
    "in use" until `RadixPrefixCache.evict` returns them.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (1 trash + 1 usable), got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO off the tail; initialised so the first allocs are 1, 2, ...
        self._free = list(range(num_blocks - 1, 0, -1))
        # Membership mirror of _free: free() must reject a block that is
        # already free (double-free would hand the same physical block to
        # two owners and silently corrupt both sequences' KV).
        self._free_set = set(self._free)
        # Optional obs.CacheLedger: when attached, every alloc/free is
        # booked (frees to a CAUSE), giving the eviction-forensics
        # metrics their conservation guarantee at the only chokepoint
        # blocks actually pass through.
        self.ledger = None

    def attach_ledger(self, ledger) -> None:
        """Attach a lifecycle ledger. Must happen before the first
        alloc, or the ledger's birth count can't reconcile against
        `in_use` (the conservation invariant CI asserts)."""
        if self.in_use:
            raise ValueError(
                f"ledger attached with {self.in_use} blocks already live")
        self.ledger = ledger

    @property
    def capacity(self) -> int:
        """Usable blocks (excludes the trash block)."""
        return self.num_blocks - 1

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.capacity - len(self._free)

    def alloc(self, n: int) -> list[int] | None:
        """Take `n` blocks, or None (and take nothing) if fewer are free."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(out)
        if self.ledger is not None:
            self.ledger.note_alloc(out)
        return out

    def free(self, blocks, *, cause: str | None = None) -> None:
        """Return `blocks` to the pool. `cause` books the deaths in the
        attached ledger (see obs.EVICTION_CAUSES); a None cause lands in
        the ledger's `unattributed` bucket, which CI pins at zero — so
        every call site must say WHY the blocks died."""
        blocks = list(blocks)
        seen: set[int] = set()
        for b in blocks:
            if not (0 < b < self.num_blocks):
                raise ValueError(f"freeing out-of-range block {b}")
            if b in self._free_set or b in seen:
                raise ValueError(f"double-free of block {b}")
            seen.add(b)
        for b in blocks:
            self._free.append(b)
            self._free_set.add(b)
        if self.ledger is not None:
            self.ledger.note_free(blocks, cause)


class HostSpillTier:
    """Bytes-budgeted host-RAM LRU store for demoted KV block contents
    (the fleet cache tier's middle rung, PR 19).

    Entries are keyed by `(ns, token_path)` where `token_path` is the
    FULL token prefix ending at the block — content is a pure function
    of the token prefix by the insert-time canonical-form invariant,
    so the key alone names the payload and a restore is token-identical
    by construction. Payloads are opaque to this module (the batcher
    stores host-numpy `(k, v)` copies); this class only does the
    budget/LRU bookkeeping, so it stays jax-free like the rest of the
    file. `put` returns the keys the budget pushed out (oldest first)
    so the caller can book them as content deaths
    (`CacheLedger.note_spill_drop`)."""

    def __init__(self, budget_bytes: int, block_bytes: int):
        if budget_bytes < 0:
            raise ValueError(f"budget_bytes must be >= 0, got "
                             f"{budget_bytes}")
        if block_bytes < 1:
            raise ValueError(f"block_bytes must be >= 1, got "
                             f"{block_bytes}")
        self.budget_bytes = int(budget_bytes)
        self.block_bytes = int(block_bytes)
        # (ns, token_path tuple) -> payload; insertion order == LRU
        # order (move_to_end on every touch)
        self._entries: OrderedDict[tuple, object] = OrderedDict()

    @property
    def capacity_blocks(self) -> int:
        return self.budget_bytes // self.block_bytes

    @property
    def spilled_blocks(self) -> int:
        return len(self._entries)

    @property
    def spilled_bytes(self) -> int:
        return len(self._entries) * self.block_bytes

    def _key(self, ns: str, path) -> tuple:
        return (ns, tuple(int(t) for t in path))

    def contains(self, ns: str, path) -> bool:
        """Presence probe WITHOUT an LRU touch — planning peeks, only
        an actual demote/restore moves the clock."""
        return self._key(ns, path) in self._entries

    def put(self, ns: str, path, payload) -> list[tuple]:
        """Park one block's content; returns the `(ns, token_path)`
        keys the byte budget evicted to make room (possibly including
        this very entry when the budget can't hold even one block)."""
        key = self._key(ns, path)
        self._entries[key] = payload
        self._entries.move_to_end(key)
        dropped: list[tuple] = []
        while len(self._entries) * self.block_bytes > self.budget_bytes:
            victim, _ = self._entries.popitem(last=False)
            dropped.append(victim)
        return dropped

    def pop(self, ns: str, path):
        """Take one block's content out (a restore owns it now), or
        None if the budget already dropped it."""
        return self._entries.pop(self._key(ns, path), None)

    def clear(self) -> int:
        n = len(self._entries)
        self._entries.clear()
        return n


class _Node:
    __slots__ = ("key", "block", "children", "refs", "last_use", "parent")

    def __init__(self, key, block, parent):
        self.key = key          # tuple of block_size token ids (None at root)
        self.block = block      # physical block id (None at root)
        self.children = {}      # key tuple -> _Node
        self.refs = 0           # active requests pointing at self.block
        self.last_use = 0       # logical clock, for LRU eviction
        self.parent = parent


class RadixPrefixCache:
    """Token-prefix index over full KV blocks, with ref-counted sharing.

    `match` walks full-block edges and additionally reports a *partial*
    match inside the next edge (for copy-on-write seeding). `insert`
    adopts caller-owned blocks into the tree; blocks whose token path
    already exists are left with the caller (duplicates — free them).
    `evict` pops refcount-0 leaves in LRU order back to the pool.
    """

    def __init__(self, pool: BlockPool, *, heat_half_life: int = 64,
                 heat_max_entries: int = 512):
        self.pool = pool
        self.block_size = pool.block_size
        self.root = _Node(None, None, None)
        # Namespaced roots (tenant prefix isolation): ns "" is the
        # shared default tree (`self.root`, kept as an attribute for
        # back-compat); any other ns gets its own root on first use, so
        # two namespaces can never match each other's entries — not
        # even the timing side channel of a shared-prefix hit.
        self._roots: dict[str, _Node] = {"": self.root}
        self._clock = 0
        self.cached_blocks = 0  # blocks currently owned by the tree
        # Decayed per-prefix heat: (ns, first-block key) -> [score,
        # last-bump clock]. A prefix is named by its FIRST full block —
        # the same token slice the router's rendezvous affinity key
        # hashes, so replica digests join against routing keys. Scores
        # halve every `heat_half_life` radix-clock ticks (accesses),
        # and the table is pruned to its hottest half past
        # `heat_max_entries`, so memory is bounded regardless of
        # prompt diversity.
        self.heat_half_life = max(1, int(heat_half_life))
        self.heat_max_entries = max(2, int(heat_max_entries))
        self._heat: dict[tuple[str, tuple], list] = {}
        # hashed-mode guard: digests export prefixes as 16-hex blake2b
        # names, never raw tokens — bounded label cardinality by
        # construction
        self.heat_guard = LabelGuard(hashed=True)
        # Optional host-RAM spill tier (PR 19): when attached (with a
        # device-block reader), evict() demotes victim contents to the
        # tier instead of discarding them. The reader is best-effort —
        # any failure degrades that eviction to a plain discard.
        self.spill: HostSpillTier | None = None
        self.spill_reader: Callable[[int], object] | None = None

    def attach_spill(self, tier: HostSpillTier,
                     reader: Callable[[int], object]) -> None:
        """Attach a `HostSpillTier` plus a `reader(block_id) ->
        payload | None` that snapshots one device block's contents to
        host memory (the batcher closes it over the engine's
        `export_blocks`). From then on eviction demotes instead of
        discarding, booked as cause `spill`; a None/raising reader
        falls back to the old `lru` discard, so spill can never make
        eviction less correct — only cheaper to undo."""
        self.spill = tier
        self.spill_reader = reader

    # -- internals ---------------------------------------------------------

    def _root_for(self, ns: str) -> _Node:
        root = self._roots.get(ns)
        if root is None:
            root = self._roots[ns] = _Node(None, None, None)
        return root

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _touch(self, node: _Node) -> None:
        t = self._tick()
        # roots (any namespace) are the only nodes with key None
        while node is not None and node.key is not None:
            node.last_use = t
            node = node.parent

    def _decayed(self, ent: list, t: int) -> float:
        return ent[0] * 0.5 ** ((t - ent[1]) / self.heat_half_life)

    def _heat_bump(self, ns: str, key: tuple) -> None:
        t = self._clock
        ent = self._heat.get((ns, key))
        if ent is None:
            if len(self._heat) >= self.heat_max_entries:
                self._heat_prune(t)
            self._heat[(ns, key)] = [1.0, t]
        else:
            ent[0] = self._decayed(ent, t) + 1.0
            ent[1] = t

    def _heat_prune(self, t: int) -> None:
        """Keep only the hottest half (by decayed score) — amortized
        O(n log n) once per max_entries/2 novel prefixes."""
        ranked = sorted(self._heat.items(),
                        key=lambda kv: self._decayed(kv[1], t),
                        reverse=True)
        self._heat = dict(ranked[: self.heat_max_entries // 2])

    # -- queries -----------------------------------------------------------

    def match(self, tokens, *,
              ns: str = "") -> tuple[list["_Node"], "_Node | None", int]:
        """Longest cached prefix of `tokens` within namespace `ns`.

        Returns `(nodes, partial_node, partial_len)`: `nodes` are the
        fully-matched block edges in order; `partial_node` (if any) is a
        child whose key shares `partial_len in [1, block_size)` leading
        tokens with the remainder. Does NOT take refs — callers decide
        which nodes they depend on and `ref` those.
        """
        bs = self.block_size
        nodes: list[_Node] = []
        node = self._root_for(ns)
        i = 0
        while i + bs <= len(tokens):
            child = node.children.get(tuple(tokens[i : i + bs]))
            if child is None:
                break
            nodes.append(child)
            node = child
            i += bs
        partial_node, partial_len = None, 0
        rest = tuple(tokens[i : i + bs])
        if rest:
            for key, child in node.children.items():
                n = 0
                for a, b in zip(rest, key):
                    if a != b:
                        break
                    n += 1
                if n > partial_len:
                    partial_node, partial_len = child, n
        if nodes:
            self._touch(nodes[-1])
            self._heat_bump(ns, nodes[0].key)
        if partial_node is not None:
            self._touch(partial_node)
        return nodes, partial_node, partial_len

    # -- ref management ----------------------------------------------------

    def ref(self, nodes) -> None:
        for n in nodes:
            n.refs += 1
        if nodes:
            self._touch(nodes[-1])

    def unref(self, nodes) -> None:
        for n in nodes:
            n.refs -= 1
            assert n.refs >= 0, "refcount underflow"

    # -- growth ------------------------------------------------------------

    def insert(self, tokens, blocks: dict[int, int], *,
               hold: bool = False, ns: str = ""):
        """Index `tokens` (length must be a multiple of block_size) into
        namespace `ns` of the tree. `blocks[i]` is the caller-owned
        physical block holding tokens `[i*bs, (i+1)*bs)`; only consulted
        for edges that don't exist yet. Returns `(adopted, held_nodes)`
        where `adopted` is the set of block indices the tree took
        ownership of, and `held_nodes` the nodes created with an initial
        ref for the caller (only when `hold=True` — the caller's block
        table points at those blocks, so they must not be evicted
        underneath it).
        """
        bs = self.block_size
        assert len(tokens) % bs == 0, len(tokens)
        adopted: set[int] = set()
        held: list[_Node] = []
        node = self._root_for(ns)
        for i in range(len(tokens) // bs):
            key = tuple(tokens[i * bs : (i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                phys = blocks.get(i)
                if phys is None:
                    break  # caller had nothing for this edge; stop here
                child = _Node(key, phys, node)
                node.children[key] = child
                adopted.add(i)
                self.cached_blocks += 1
                if i == 0:
                    # a prefix's first cached appearance is its first
                    # heat point (later hits bump via match())
                    self._heat_bump(ns, key)
                if hold:
                    child.refs = 1
                    held.append(child)
            node = child
        if node.key is not None:
            self._touch(node)
        return adopted, held

    # -- shrink ------------------------------------------------------------

    def _path_tokens(self, node: _Node) -> tuple:
        """Full token prefix ending at `node`'s block, reconstructed
        by walking parent edges to the namespace root — the spill
        tier's key (content is a pure function of this path by the
        canonical-form invariant)."""
        keys = []
        while node is not None and node.key is not None:
            keys.append(node.key)
            node = node.parent
        out: list[int] = []
        for key in reversed(keys):
            out.extend(key)
        return tuple(out)

    def _demote(self, ns: str, victim: _Node) -> bool:
        """Try to park `victim`'s block content in the spill tier.
        Returns True when the content survives on the host (the free
        books as `spill`), False for a plain discard (`lru`). Reader
        failures — including a concurrently-donated device state —
        degrade to discard: spill is an optimization, never a new
        failure mode."""
        if self.spill is None or self.spill_reader is None:
            return False
        try:
            payload = self.spill_reader(victim.block)
        except Exception:  # noqa: BLE001 — best-effort device read
            payload = None
        if payload is None:
            return False
        dropped = self.spill.put(ns, self._path_tokens(victim), payload)
        if dropped and self.pool.ledger is not None:
            self.pool.ledger.note_spill_drop(len(dropped))
        return True

    def evict(self, need: int) -> int:
        """Free refcount-0 LRU leaves back to the pool until `need`
        blocks have been released (or no candidates remain). Returns
        how many were actually freed. With a spill tier attached each
        victim's content is demoted to host RAM first (death cause
        `spill` instead of `lru`), so a later request for the same
        prefix restores it with a host-to-device copy instead of
        recomputing the prefill."""
        freed = 0
        while freed < need:
            victim = None
            victim_ns = ""
            # evict across namespaces
            stack = [(ns, root) for ns, root in self._roots.items()]
            while stack:
                ns, n = stack.pop()
                stack.extend((ns, c) for c in n.children.values())
                if n.key is None or n.children or n.refs > 0:
                    continue
                if victim is None or n.last_use < victim.last_use:
                    victim, victim_ns = n, ns
            if victim is None:
                break
            spilled = self._demote(victim_ns, victim)
            del victim.parent.children[victim.key]
            self.pool.free([victim.block],
                           cause="spill" if spilled else "lru")
            self.cached_blocks -= 1
            freed += 1
        return freed

    def clear(self, *, cause: str = "refdrop") -> None:
        """Drop the whole tree, returning every cached block to the pool.

        Must be called whenever the device-side pool array is discarded
        (e.g. after a failed dispatch poisons the state): the tree's
        blocks describe content that no longer exists. That is a
        reference drop (the content died with the device state), not an
        LRU decision — hence the default cause.
        """
        blocks = []
        for root in self._roots.values():
            stack = list(root.children.values())
            while stack:
                n = stack.pop()
                blocks.append(n.block)
                stack.extend(n.children.values())
            root.children.clear()
        if blocks:
            self.pool.free(blocks, cause=cause)
        self.cached_blocks = 0

    # -- heat export -------------------------------------------------------

    def heat_digest(self, k: int = 16) -> list[dict]:
        """Top-`k` hottest prefixes by decayed score, exported as
        16-hex hashed names (via the hashed LabelGuard) — safe to put
        on heartbeats and `/v1/models` without leaking prompt tokens,
        and joinable against the router's `prefix_hash` of the same
        first-block token slice."""
        t = self._clock
        ranked = sorted(
            ((self._decayed(ent, t), ns, key)
             for (ns, key), ent in self._heat.items()),
            key=lambda x: x[0], reverse=True)
        return [
            {"prefix": self.heat_guard.admit(canonical_prefix(key, ns)),
             "score": round(score, 4)}
            for score, ns, key in ranked[: max(0, int(k))]
            if score > 1e-9
        ]
