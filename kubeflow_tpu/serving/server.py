"""Model-serving REST server (the TF-Serving-proxy replacement).

The reference exposed model inference as an HTTP service behind the same
Service/VirtualService machinery as notebooks
(`/root/reference/docs_dev/tf_serving.md:1-60`; prediction smoke test in
`/root/reference/testing/test_tf_serving.py:40-57`). TPU-native version:
an aiohttp app wrapping `InferenceEngine`, serving
  POST /v1/models/{name}:generate   {"tokens": [[...]], "max_new": N}
  POST /v1/models/{name}:generate   {"text": "...", ...} (byte tokenizer)
  GET  /v1/models                    model card listing
  GET  /healthz /readyz              gateway probes

Text in/out uses a dependency-free byte-level tokenizer (offset by
`BYTE_OFFSET` to keep specials 0..byte_offset-1 free) so the server
round-trips strings without downloaded vocabularies; real deployments
pass token IDs from their own tokenizer.
"""

from __future__ import annotations

import asyncio
import logging
import math
import secrets
import time
from typing import Any

import jax.numpy as jnp
import numpy as np
from aiohttp import web

from kubeflow_tpu import obs as obs_lib
from kubeflow_tpu.obs import endpoints as obs_endpoints
from kubeflow_tpu.serving.continuous import (
    ContinuousBatcher,
    MigratedAway,
    Overloaded,
    bucket_pow2,
)
from kubeflow_tpu.serving.engine import InferenceEngine
from kubeflow_tpu.serving import migration
from kubeflow_tpu.serving.speculative import SpeculativeEngine
from kubeflow_tpu.tenancy import (
    PRIORITIES,
    THROTTLE_REASONS,
    TenancyConfig,
    Throttled,
)

BYTE_OFFSET = 3  # 0=pad, 1=bos, 2=eos
BOS, EOS = 1, 2


def byte_encode(text: str) -> list[int]:
    return [BOS] + [b + BYTE_OFFSET for b in text.encode("utf-8")]


def byte_decode(tokens: list[int], on_dropped=None) -> str:
    # Ids outside the byte range are dropped, not crashed on. Specials
    # below the offset (pad/bos/eos) are expected in generated rows and
    # stay silent; vocab-TAIL ids (the model's vocab is larger than
    # 256+offset, so a sampled tail id means tokenizer/model drift) are
    # the ones worth surfacing — silent drops there hide drift, and
    # debugging a prefix-cache mismatch starts from the token stream.
    # Callers pass `on_dropped(count)` to count tail drops; the serving
    # app feeds `serving_tokenizer_dropped_tokens_total`.
    kept = [t - BYTE_OFFSET for t in tokens
            if BYTE_OFFSET <= t < BYTE_OFFSET + 256]
    if on_dropped is not None:
        tail = sum(1 for t in tokens if t >= BYTE_OFFSET + 256)
        if tail:
            on_dropped(tail)
    return bytes(kept).decode("utf-8", errors="replace")


ENGINES_KEY: web.AppKey = web.AppKey("engines", dict)
GPU_LOCK_KEY: web.AppKey = web.AppKey("gpu_lock", asyncio.Lock)
TOKENIZER_KEY: web.AppKey = web.AppKey("tokenizer", object)
BATCHERS_KEY: web.AppKey = web.AppKey("batchers", dict)
SPEC_KEY: web.AppKey = web.AppKey("speculative", dict)
OBS_KEY: web.AppKey = web.AppKey("obs", object)
DRAIN_KEY: web.AppKey = web.AppKey("drain_state", dict)
FLEET_REG_KEY: web.AppKey = web.AppKey("fleet_registration", dict)
TENANCY_KEY: web.AppKey = web.AppKey("tenancy", object)  # TenancyConfig|None
POOL_KEY: web.AppKey = web.AppKey("pool_role", str)  # disagg role
# Live-rollout plane (ISSUE 18): the version this replica advertises in
# fleet heartbeats, the injected weight-reloader callable (None → Orbax
# checkpoint restore), and the chaos-defect dict the loadtest's bad-
# version arm plants via /v1/reload to force an SLO burn.
MODEL_VERSION_KEY: web.AppKey = web.AppKey("model_version", str)
RELOADER_KEY: web.AppKey = web.AppKey("weight_reloader", object)
DEFECT_KEY: web.AppKey = web.AppKey("reload_defect", dict)

# Disaggregation roles (mirrors fleet.registry.POOLS — the serving
# side must stay importable without the fleet package and vice versa)
POOL_ROLES = ("mixed", "prefill", "decode")


# Replica SLO defaults (ISSUE 6). TTFT thresholds are per priority
# class — interactive traffic is the one the burn-rate gauge exists to
# defend; batch gets slack. Overridable per deployment via
# `create_serving_app(slo_ttft_s=...)` (the loadtest tunes interactive
# to the hardware it runs on).
SLO_TTFT_THRESHOLDS_S = {
    "interactive": 0.5,
    "standard": 2.0,
    "batch": 10.0,
}
SLO_ITL_THRESHOLD_S = 0.25
SLO_LATENCY_OBJECTIVE = 0.95   # 95% of requests under threshold
SLO_ERROR_OBJECTIVE = 0.99     # 99% of requests without a 5xx
# Speculative decoding pays for itself only while the draft keeps
# guessing right: every verified draft token is a good/bad event, and
# the burn rate pages when the accepted fraction drops below this
# objective (a stale or mismatched draft silently BURNS throughput —
# each rejected token is a wasted verify slot). Overridable per
# deployment via `create_serving_app(slo_spec_acceptance=...)`.
SLO_SPEC_ACCEPTANCE_OBJECTIVE = 0.5


class ServingObs:
    """Per-app observability bundle: metric registry + span tracer +
    the serving hot-path histograms (ISSUE 1). `/metrics` renders the
    registry, `/debug/traces` exports the tracer's ring; every request
    carries its trace id back in `X-Trace-Id`."""

    def __init__(self, registry=None, tracer=None, *, slo_ttft_s=None,
                 slo_spec_acceptance: float | None = None):
        # controlplane.metrics is pure Python (no jax/store state is
        # touched here) — the ONE Registry implementation serves all
        # three layers rather than a drifted serving copy.
        from kubeflow_tpu.controlplane.metrics import (
            Counter,
            Gauge,
            Registry,
        )

        self.registry = registry if registry is not None else Registry()
        self.tracer = tracer if tracer is not None else obs_lib.Tracer()
        self.request_latency = obs_lib.get_or_create_histogram(
            self.registry, "serving_request_duration_seconds",
            "Serving HTTP request latency by route/method")
        self.ttft = obs_lib.get_or_create_histogram(
            self.registry, "serving_time_to_first_token_seconds",
            "Request arrival to first generated token, per model "
            "(streaming: first token on the wire; one-shot: full "
            "generation, an upper bound)")
        self.batch_size = obs_lib.get_or_create_histogram(
            self.registry, "serving_batch_size",
            "Requests co-scheduled per engine invocation",
            buckets=obs_lib.SIZE_BUCKETS)
        # Paged-KV / radix-prefix-cache instrumentation (continuous
        # batcher only; the gauge is refreshed by a render-time
        # collector so /metrics always reports the live pool).
        self.prefix_hits = Counter(
            "serving_prefix_cache_hits_total",
            "Admissions that reused cached KV cells (radix prefix "
            "cache or a registered prefix)", self.registry)
        self.prefix_misses = Counter(
            "serving_prefix_cache_misses_total",
            "Admissions that prefilled their whole prompt (no cached "
            "prefix matched)", self.registry)
        self.kv_blocks = Gauge(
            "serving_kv_blocks_in_use",
            "KV pool blocks held by active requests plus the radix "
            "prefix cache, per model", self.registry)
        self.prefill_tokens = obs_lib.get_or_create_histogram(
            self.registry, "serving_prefill_tokens",
            "Per-admission prompt tokens by source: computed (suffix "
            "actually prefilled), reused (served from device-resident "
            "cached KV), restored (host spill tier, host->device "
            "copy), peer_fetched (imported from a peer replica via "
            "the X-KV-Peer heat hint)",
            buckets=obs_lib.TOKEN_BUCKETS)
        self.dropped_tokens = Counter(
            "serving_tokenizer_dropped_tokens_total",
            "Generated token ids outside the byte-decoder's range "
            "(vocab tail / specials) dropped from text responses — "
            "nonzero means tokenizer/model drift", self.registry)
        # One-shot info gauge (value is always 1; the information is
        # the label): which paged-attention impl decode resolved to —
        # xla gather or the fused pallas kernel. Set once per model at
        # app creation; joins cleanly against the per-model latency
        # series.
        self.attention_impl = Gauge(
            "serving_attention_impl",
            "Resolved paged-attention impl per model (info gauge: "
            "value 1, impl in the label)", self.registry)
        # Multi-tenant QoS series (continuous batcher with a tenancy
        # config only — tenant-blind deployments register the families
        # but emit no samples). Counters sync from the ledger's
        # cumulative stats at scrape time; the gauge reads live depth.
        self.tenant_queue_depth = Gauge(
            "serving_tenant_queue_depth",
            "Requests waiting in a tenant's admission sub-queue",
            self.registry)
        self.tenant_tokens = Counter(
            "serving_tenant_tokens_total",
            "Tokens generated per tenant and model", self.registry)
        self.tenant_throttled = Counter(
            "serving_tenant_throttled_total",
            "Admissions shed or deferred per tenant by reason: rate "
            "(request bucket empty, HTTP 429) or kv_quota (concurrent "
            "KV-block share spent, request waits)", self.registry)
        self.tenant_preemptions = Counter(
            "serving_tenant_preemptions_total",
            "Batch-class decodes evicted mid-generation to free a slot "
            "for interactive work, per tenant", self.registry)
        # Live KV-block migration (ISSUE 7): instant drain exports
        # in-flight sequences to peers; /v1/migrate/in imports them.
        # Failures always roll back (zero leaked blocks) and count
        # here by direction.
        self.migration_out = Counter(
            "serving_migration_out_total",
            "In-flight sequences exported to a peer replica on "
            "instant drain, per model", self.registry)
        self.migration_in = Counter(
            "serving_migration_in_total",
            "Migrated sequences imported into the local KV pool "
            "(cache-warm; the router re-dispatch resumes them), per "
            "model", self.registry)
        self.migration_failed = Counter(
            "serving_migration_failed_total",
            "Migration transfers that failed and rolled back, per "
            "model and direction (in: import rejected or wedged, "
            "out: no peer accepted the record)", self.registry)
        self.migration_blocks = Counter(
            "serving_migration_blocks_total",
            "KV pool blocks moved by live migration, per model and "
            "direction", self.registry)
        # Token-timeline companions (ISSUE 6): the continuous batcher's
        # on_itl/on_queue_wait hooks feed these, so the fleet view gets
        # the same numbers the per-request timeline endpoint shows.
        self.itl = obs_lib.get_or_create_histogram(
            self.registry, "serving_itl_seconds",
            "Inter-token latency: gap between consecutive decode "
            "tokens of one request, per model (gaps spanning a "
            "preempt/resume hole are excluded — those measure "
            "scheduling, see serving_queue_wait_seconds)")
        self.queue_wait = obs_lib.get_or_create_histogram(
            self.registry, "serving_queue_wait_seconds",
            "Enqueue to first admission into the decode batch, per "
            "model (scheduling delay; excludes prefill)")
        # Step-anatomy profiling plane (ISSUE 8): the continuous
        # batcher's PhaseProfiler decomposes every worker iteration
        # into named phases; these families carry the decomposition.
        # Phase/fn labels are CLOSED SETS (obs.profiling guards them),
        # zero-seeded per model at app creation.
        self.step_phase_seconds = obs_lib.get_or_create_histogram(
            self.registry, "serving_step_phase_seconds",
            "Wall time per worker-loop phase (admit, prefill, decode, "
            "sample, detokenize, preempt, resume, host_gap, idle), "
            "per model — phases record exclusive time, so summing "
            "them reconstructs loop wall time")
        self.step_tokens = obs_lib.get_or_create_histogram(
            self.registry, "serving_step_tokens",
            "Tokens attributed per phase and model (prefill: suffix "
            "tokens computed per grouped prefill; decode: tokens "
            "emitted per chunk)", buckets=obs_lib.TOKEN_BUCKETS)
        self.goodput = Gauge(
            "serving_goodput_ratio",
            "Decode device-time share of total non-idle step time, "
            "per model (the Podracer-style goodput ledger; 1.0 means "
            "every non-idle second decoded tokens)", self.registry)
        self.bubble = Gauge(
            "serving_bubble_fraction",
            "host_gap share of total non-idle step time, per model — "
            "the bubble dispatch-ahead exists to hide", self.registry)
        self.kv_high_water = Gauge(
            "serving_kv_blocks_high_water",
            "High-water mark of KV pool blocks in use since startup, "
            "per model (capacity headroom for the pool sizing knob)",
            self.registry)
        self.recompiles = Counter(
            "serving_recompiles_total",
            "Retraces of a watched jitted callable (a novel abstract "
            "shape signature past the fn's first) — nonzero RATE in "
            "steady state means the compile-shape bucketing leaked",
            self.registry)
        # KV-cache observatory (ISSUE 13): the block lifecycle ledger
        # (obs.cachestats.CacheLedger, attached to each batcher's
        # BlockPool) books every block death to a CAUSE; the cause set
        # is closed and zero-seeded per model, and the conservation
        # invariant — causes sum to total frees, `unattributed` == 0 —
        # is what `ci/obs_check cache` asserts from a live scrape.
        self.kv_evictions = Counter(
            "serving_kv_evictions_total",
            "KV pool blocks freed, by cause: lru (radix eviction), "
            "pressure (preemption), refdrop (normal retirement), "
            "divergence (duplicate content), migration (exported or "
            "rolled back). `unattributed` is a free site that forgot "
            "to book a cause — always zero, or it's a bug",
            self.registry)
        self.kv_admission_defers = Counter(
            "serving_kv_admission_defers_total",
            "Admissions pushed back for lack of KV blocks, by cause: "
            "kv_quota (tenant share spent) vs pool_exhausted (pool "
            "empty even after LRU eviction)", self.registry)
        # Fleet cache tier (ISSUE 19): host-RAM spill demotions and
        # restores are content movement, not deaths — they get their
        # own counters so the tier's traffic is visible next to the
        # eviction causes, plus a render-time occupancy gauge. Peer
        # block fetches (the router's X-KV-Peer hint) count by
        # OUTCOME (closed set: ok/miss/failed); any non-ok falls back
        # to plain prefill, so `failed` burning is a perf smell, not
        # a correctness one.
        self.kv_spill_demotions = Counter(
            "serving_kv_spill_demotions_total",
            "KV blocks demoted from the device pool into the host-RAM "
            "spill tier on eviction, per model (deaths booked to "
            "cause=spill in serving_kv_evictions_total)", self.registry)
        self.kv_spill_restores = Counter(
            "serving_kv_spill_restores_total",
            "Spilled KV blocks promoted back into the device pool on "
            "a prefix re-hit (host->device copy instead of prefill "
            "recompute), per model", self.registry)
        self.kv_spill_bytes = Gauge(
            "serving_kv_spill_bytes",
            "Host RAM currently holding spilled KV block contents, "
            "per model (bounded by --kv-spill-bytes)", self.registry)
        self.peer_fetch = Counter(
            "fleet_peer_fetch_total",
            "Replica-side KV block fetches from a peer named by the "
            "router's X-KV-Peer heat hint, by outcome: ok (blocks "
            "imported, prefill seeded), miss (peer no longer caches "
            "the prefix), failed (transport/geometry error — request "
            "fell back to plain prefill)", self.registry)
        self.kv_reuse_distance = obs_lib.get_or_create_histogram(
            self.registry, "serving_kv_reuse_distance_admissions",
            "Admissions between consecutive touches of the same cached "
            "KV block, per model — the working-set curve; mass beyond "
            "the pool's block count predicts misses an LRU pool of "
            "that size must take", buckets=obs_lib.REUSE_BUCKETS)
        self.kv_block_age = obs_lib.get_or_create_histogram(
            self.registry, "serving_kv_block_age_admissions",
            "Block age at death in admissions, per model — young "
            "deaths under pressure/lru mean the pool churns before "
            "reuse can pay off", buckets=obs_lib.REUSE_BUCKETS)
        # SLO burn rates (obs.slo): the engine IS the gauge metric —
        # registering it zero-seeds every slo x window series. TTFT
        # objectives are per priority class; error-rate likewise;
        # ITL is fleet-wide (a preempted batch decode and a healthy
        # interactive one share the decode loop).
        ttft_thr = dict(SLO_TTFT_THRESHOLDS_S)
        ttft_thr.update(slo_ttft_s or {})
        slos = [obs_lib.Slo(
                    f"serving_ttft_{cls}", SLO_LATENCY_OBJECTIVE,
                    threshold_s=ttft_thr[cls],
                    description=f"p95 TTFT for {cls} traffic under "
                                f"{ttft_thr[cls]:g} s")
                for cls in PRIORITIES]
        slos.append(obs_lib.Slo(
            "serving_itl", SLO_LATENCY_OBJECTIVE,
            threshold_s=SLO_ITL_THRESHOLD_S,
            description=f"p95 inter-token latency under "
                        f"{SLO_ITL_THRESHOLD_S:g} s"))
        slos.extend(obs_lib.Slo(
                        f"serving_errors_{cls}", SLO_ERROR_OBJECTIVE,
                        description=f"99% of {cls} requests answered "
                                    "without a 5xx")
                    for cls in PRIORITIES)
        spec_obj = SLO_SPEC_ACCEPTANCE_OBJECTIVE \
            if slo_spec_acceptance is None else float(slo_spec_acceptance)
        slos.append(obs_lib.Slo(
            "serving_spec_acceptance", spec_obj,
            description=f"{spec_obj:.0%} of verified draft tokens "
                        "accepted (below this the draft burns more "
                        "verify slots than it saves)"))
        # shared-registry rule: one burn-rate engine per registry (a
        # process hosting several apps feeds the first one)
        self.slo = obs_lib.get_or_create_slo_engine(self.registry, slos)
        # X-Tenant is a raw client header: anywhere it becomes a label
        # or span attribute it passes this guard, so a scanner minting
        # fresh values cannot mint unbounded timeseries.
        self.tenant_guard = obs_lib.LabelGuard()


_OBS_T0 = "obs_request_start"
_OBS_TTFT_DONE = "obs_ttft_recorded"


def _priority_class(request: web.Request) -> str:
    """Resolve the request's tenant priority class for SLO accounting.
    Tenant-blind deployments are all `standard` — the SLO families
    still zero-seed for every class, so dashboards don't change shape
    when tenancy is switched on."""
    tenancy = request.app.get(TENANCY_KEY)
    if tenancy is None:
        return "standard"
    return tenancy.resolve(request.headers.get("X-Tenant", "")).priority


def _observe_first_token(request: web.Request, model: str) -> None:
    """Record time-to-first-token ONCE per request (stream paths call
    on the first emitted token; the one-shot path after generate)."""
    sobs = request.app.get(OBS_KEY)
    t0 = request.get(_OBS_T0)
    if sobs is None or t0 is None or request.get(_OBS_TTFT_DONE):
        return
    request[_OBS_TTFT_DONE] = True
    dt = time.perf_counter() - t0
    labels = {"model": model}
    tenant_hdr = request.headers.get("X-Tenant")
    if tenant_hdr:
        # guarded: the label echoes a client-chosen value
        labels["tenant"] = sobs.tenant_guard.admit(tenant_hdr)
    sobs.ttft.observe(dt, **labels)
    sobs.slo.observe(f"serving_ttft_{_priority_class(request)}", dt)


@web.middleware
async def _obs_middleware(request: web.Request, handler):
    """Root span + latency histogram + X-Trace-Id for every serving
    response. Routes label by PATTERN (`/v1/models/{name}:generate`),
    never raw path — label cardinality must not scale with model names
    scanners probe for."""
    sobs: ServingObs = request.app[OBS_KEY]
    resource = getattr(request.match_info.route, "resource", None)
    route = getattr(resource, "canonical", None) or "unmatched"
    request[_OBS_T0] = time.perf_counter()
    status = 500
    # Cross-process propagation (ISSUE 6): a request routed through
    # the fleet router carries its trace context in headers; adopt it
    # so this replica's segment commits under the ROUTER's trace id
    # (span_from_remote validates the ids — an arbitrary client header
    # can't corrupt the ring).
    remote_tid = request.headers.get("X-Trace-Id", "")
    remote_psid = request.headers.get("X-Parent-Span", "")
    if remote_tid and remote_psid:
        span_cm = sobs.tracer.span_from_remote(
            "http.request", remote_tid, remote_psid,
            method=request.method, route=route)
    else:
        span_cm = sobs.tracer.span("http.request",
                                   method=request.method, route=route)
    with span_cm as span:
        tenant_hdr = request.headers.get("X-Tenant")
        if tenant_hdr:
            # guarded: the attribute echoes a client-chosen value
            span.attrs["tenant"] = sobs.tenant_guard.admit(tenant_hdr)
        try:
            resp = await handler(request)
            status = resp.status
            span.attrs["status"] = status
            if not resp.prepared:  # stream paths set it pre-prepare
                resp.headers.setdefault("X-Trace-Id", span.trace_id)
            return resp
        except web.HTTPException as exc:
            status = exc.status
            span.attrs["status"] = status
            exc.headers.setdefault("X-Trace-Id", span.trace_id)
            raise
        finally:
            sobs.request_latency.observe(
                time.perf_counter() - request[_OBS_T0],
                route=route, method=request.method)
            if route.startswith("/v1/models/"):
                # availability SLO counts model-inference traffic
                # only — probe/debug endpoints would dilute the budget
                sobs.slo.record(
                    f"serving_errors_{_priority_class(request)}",
                    status < 500)


class Batcher:
    """Dynamic request batching for one engine: concurrent generate
    requests collected within a small window run as ONE padded batch.

    Decode reads every weight once per step regardless of batch size, so
    co-scheduling N requests costs ~one request's bandwidth — the
    classic serving-throughput lever. Variable prompt lengths ride the
    engine's left-padded prompt_mask path; requests are grouped by
    sampling knobs (one SamplingParams per compiled batch) and run to
    the group's max max_new (each caller trims to its own ask).
    """

    def __init__(self, engine: InferenceEngine, gpu_lock: asyncio.Lock,
                 *, window_ms: float = 5.0, max_batch: int = 8):
        self.engine = engine
        self.gpu_lock = gpu_lock
        self.window_s = window_ms / 1000.0
        self.max_batch = max_batch
        self.calls = 0            # engine invocations (observability)
        self.requests = 0         # successfully batched requests
        self.on_batch = None      # hook(batch_size) per successful group
        self._queue: asyncio.Queue = asyncio.Queue()
        self._worker: asyncio.Task | None = None
        self._inflight: list = []  # dequeued but unresolved (see close)
        self._closed = False
        self._draining = False

    def in_flight(self) -> int:
        """Admitted-but-unfinished work (queued + dequeued-unresolved)."""
        return self._queue.qsize() + len(self._inflight)

    def begin_drain(self) -> None:
        """Stop admission; queued work still runs. Sticky until close()
        or end_drain()."""
        self._draining = True

    def end_drain(self) -> None:
        """Re-open admission after a completed drain (the /v1/reload
        drain-swap-resume cycle; a drain is only terminal with close)."""
        self._draining = False

    async def drain(self, timeout: float | None = None) -> bool:
        """Stop admission and wait for admitted work to resolve. Same
        contract as ContinuousBatcher.drain (False on timeout / dead
        worker with work left)."""
        self._draining = True
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.in_flight():
            if self._worker is None or self._worker.done():
                return False
            if deadline is not None and time.monotonic() > deadline:
                return False
            await asyncio.sleep(0.01)
        return True

    async def submit(self, tokens: list[int], max_new: int,
                     sampling: tuple) -> list[int]:
        if self._closed:
            raise RuntimeError("batcher is shut down")
        if self._draining:
            raise RuntimeError("batcher is draining")
        if self._worker is None or self._worker.done():
            self._worker = asyncio.get_event_loop().create_task(
                self._run())
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        await self._queue.put((tokens, max_new, sampling, fut))
        return await fut

    async def _run(self):
        while True:
            first = await self._queue.get()
            # Everything dequeued is tracked until its future resolves:
            # cancellation mid-window or mid-run must not strand callers
            # (close() fails whatever is left here).
            self._inflight = [first]
            await asyncio.sleep(self.window_s)  # let siblings arrive
            batch = [first]
            while (len(batch) < self.max_batch
                   and not self._queue.empty()):
                batch.append(self._queue.get_nowait())
            self._inflight = batch
            # Sampling knobs are per-row vectors (SamplingParams), so
            # requests with DIFFERENT temperature/top_k/top_p share one
            # batch; split only when padded prompt + max_new would
            # exceed the cache bucket (each request alone fits; their
            # COMBINATION might not).
            cap = self.engine.ec.max_len
            sub: list = []
            for item in batch:
                trial = sub + [item]
                need = (max(len(t) for t, _, _, _ in trial)
                        + max(mn for _, mn, _, _ in trial))
                if sub and need > cap:
                    await self._run_group(sub)
                    sub = [item]
                else:
                    sub = trial
            if sub:
                await self._run_group(sub)
            self._inflight = []

    # Round up to a power of two (>= 16), capped: bounded compile
    # shapes instead of one compile per novel (longest, max_new).
    # One definition (continuous.bucket_pow2) serves both batchers.
    _bucket = staticmethod(bucket_pow2)

    async def _run_group(self, items: list) -> None:
        cap = self.engine.ec.max_len
        longest = max(len(t) for t, _, _, _ in items)
        max_new = max(mn for _, mn, _, _ in items)
        # Bucket both dims so mixed traffic reuses a handful of
        # compiled shapes; extra prompt columns are masked pads, extra
        # new tokens are trimmed per request. Fall back to exact sizes
        # when the buckets would not fit the cache.
        max_new_b = self._bucket(max_new, cap - longest)
        longest_b = self._bucket(longest, cap - max_new_b)
        if longest_b < longest or max_new_b < max_new:
            longest_b, max_new_b = longest, max_new
        rows = 1
        while rows < len(items):
            rows *= 2  # batch dim buckets too (dummy rows, outputs dropped)
        arr = np.zeros((rows, longest_b), np.int32)
        mask = np.zeros((rows, longest_b), bool)
        mask[:, -1] = True  # dummy rows need one real token
        ec = self.engine.ec
        # filler rows get forced-greedy knobs (temp 0, no filters): a
        # sampled EngineConfig default on a dummy row would drag an
        # all-greedy batch into the sampled branch's per-step argsorts
        temp = np.zeros(rows, np.float32)
        top_k = np.zeros(rows, np.int64)
        top_p = np.ones(rows, np.float32)
        for i, (toks, _, sampling, _) in enumerate(items):
            mask[i, :] = False
            arr[i, longest_b - len(toks):] = toks
            mask[i, longest_b - len(toks):] = True
            s = dict(sampling)
            temp[i] = s.get("temperature", ec.temperature)
            top_k[i] = s.get("top_k", ec.top_k)
            top_p[i] = s.get("top_p", ec.top_p)
        max_new = max_new_b

        def run():
            return np.asarray(self.engine.generate(
                jnp.asarray(arr), max_new=max_new,
                prompt_mask=jnp.asarray(mask),
                temperature=temp, top_k=top_k, top_p=top_p))

        try:
            async with self.gpu_lock:
                out = await asyncio.get_event_loop().run_in_executor(
                    None, run)
            self.calls += 1
            self.requests += len(items)  # mean batch = requests/calls
            if self.on_batch is not None:
                self.on_batch(len(items))
            for i, (_, mn, _, fut) in enumerate(items):
                if not fut.done():
                    fut.set_result(out[i, :mn].tolist())
        except Exception as e:  # noqa: BLE001 — fail the waiting requests
            for _, _, _, fut in items:
                if not fut.done():
                    fut.set_exception(e)

    async def close(self) -> None:
        """Cancel the worker and fail everything unresolved — queued
        AND already dequeued (the worker holds items across the window
        sleep and the engine call; CancelledError bypasses _run_group's
        except, so those futures must be failed here)."""
        self._closed = True   # late submit() raises instead of hanging
        if self._worker is not None:
            self._worker.cancel()
            try:
                await self._worker
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        pending = list(self._inflight)
        self._inflight = []
        while not self._queue.empty():
            pending.append(self._queue.get_nowait())
        for _, _, _, fut in pending:
            if not fut.done():
                fut.set_exception(RuntimeError("server shutting down"))


def create_serving_app(engines: dict[str, InferenceEngine],
                       *, tokenizer=None, batch_window_ms: float = 0.0,
                       max_batch: int = 8, continuous: bool = False,
                       warmup: bool = False,
                       prefill_chunk: int | None = None,
                       prefill_chunk_tokens: int | None = None,
                       prefixes: dict[str, list[int]] | None = None,
                       max_pending: int | None = None,
                       pipeline_depth: int | None = None,
                       kv_block_size: int = 64,
                       kv_pool_blocks: int | None = None,
                       kv_spill_bytes: int | None = None,
                       paged_attention_impl: str = "auto",
                       drafts: dict[str, InferenceEngine] | None = None,
                       spec_decode: bool = False,
                       spec_gamma: int = 4,
                       registry=None, tracer=None,
                       drain_grace_s: float = 30.0,
                       tenancy: TenancyConfig | None = None,
                       slo_ttft_s: dict[str, float] | None = None,
                       slo_spec_acceptance: float | None = None,
                       pool: str = "mixed",
                       model_version: str = "",
                       reloader=None,
                       ) -> web.Application:
    """`tokenizer` (data.bpe.Tokenizer or anything with encode/decode)
    serves the "text" request mode; without one, the zero-training
    byte-level fallback applies. `batch_window_ms > 0` enables dynamic
    request batching: concurrent single-prompt requests within the
    window run as one padded batch per sampling group.
    `continuous=True` upgrades batching to slot-based continuous
    batching (serving/continuous.py): requests join/leave a persistent
    `max_batch`-slot decode batch at token boundaries — no window, no
    waiting for a group's longest member. `warmup=True` (continuous
    only) compiles the bounded serving shape set in on_startup, so
    readiness implies no first-arrival compile stalls — startup takes
    correspondingly longer. `drafts` maps model names to draft
    engines; a request with "speculative": true then decodes through
    SpeculativeEngine (latency lever; batch 1). `spec_decode=True`
    (continuous only) instead folds each model's draft into its
    continuous batcher: EVERY request decodes speculatively on the
    paged KV cache, `spec_gamma` draft tokens verified per round in
    one fused batched pass — token-identical to plain decode, and it
    composes with radix caching, preemption and migration. Requires a
    draft for every served model. `prefill_chunk_tokens` (continuous
    only) turns admission prefill into budget-size slices interleaved
    with decode chunks: no decode stall longer than the budget while a
    long prompt prefills (distinct from `prefill_chunk`, which only
    buckets the monolithic prefill's compile shapes). `kv_block_size` /
    `kv_pool_blocks` (continuous only) shape the paged KV cache: pow2
    tokens per block and total pool blocks per model (default: the
    dense equivalent, every slot can reach max_len — shrink the pool
    to cap KV HBM, admission then accounts by blocks free and defers
    requests the pool can't cover). `kv_spill_bytes` (continuous only)
    adds a bounded host-RAM spill tier under each model's pool: radix
    eviction demotes block contents to host numpy instead of
    discarding, and a returning prefix restores them with a
    host->device copy instead of recomputing prefill — size it from
    the reuse-distance histogram's mass beyond the pool (see
    docs/operator-guide.md). `paged_attention_impl`
    (continuous only) selects decode's attention path: "xla" (gather
    through the block table), "pallas" (fused kernel walking the table
    in-kernel; interpret mode off-TPU), or "auto" (pallas on TPU, xla
    elsewhere) — the resolved choice is exported as the
    `serving_attention_impl` info gauge. `registry`/`tracer`
    share an external metric registry / span tracer; by default the app
    owns fresh ones, exposed at `/metrics` and `/debug/traces`.
    `drain_grace_s` bounds how long shutdown (and POST /drain via
    cleanup) waits for in-flight generations before closing.
    `tenancy` (continuous only) is a `tenancy.TenancyConfig`: requests
    carry their tenant in the `X-Tenant` header (unknown/absent →
    `default`), admission becomes priority + weighted fair-share with
    per-tenant rate limits, KV-block shares, and batch-class
    preemption, and `/metrics` grows zero-seeded `serving_tenant_*`
    series. Without it the server is tenant-blind: FIFO admission,
    identical to before. `slo_ttft_s` overrides the per-priority-class
    TTFT SLO thresholds (`SLO_TTFT_THRESHOLDS_S`) feeding the
    `slo_burn_rate` gauges — e.g. `{"interactive": 0.2}`.
    `pool` declares the replica's disaggregation role (ISSUE 12):
    "mixed" (default) serves both phases exactly as before;
    "prefill"/"decode" (continuous only) advertise the role in fleet
    heartbeats so the pool-aware router sends prompts to the prefill
    pool and hands the filled KV blocks to decode replicas over
    `/v1/migrate/in`. The role changes ROUTING, not capability —
    either specialized replica can still serve a full generation, so
    pool imbalance degrades to symmetric behavior instead of 503s.
    `model_version` names the weights this replica boots with; it rides
    in fleet heartbeats (the rollout plane's confirmation signal) and
    is updated by `POST /v1/reload`. `reloader` is an optional
    `fn(name, engine, source) -> params` callable /v1/reload uses to
    materialize new weights (tests and the loadtest inject seed-based
    reloaders); without one, reload restores `source["checkpoint"]`
    via Orbax."""
    if pool not in POOL_ROLES:
        raise ValueError(
            f"pool must be one of {POOL_ROLES}, got {pool!r}")
    if pool != "mixed" and not continuous:
        raise ValueError(
            f"pool={pool!r} requires continuous=True (the handoff "
            "path ships paged KV blocks)")
    app = web.Application(middlewares=[_obs_middleware])
    app[POOL_KEY] = pool
    app[DRAIN_KEY] = {"draining": False, "grace_s": float(drain_grace_s)}
    app[MODEL_VERSION_KEY] = str(model_version or "")
    app[RELOADER_KEY] = reloader
    app[DEFECT_KEY] = {}
    sobs = ServingObs(registry=registry, tracer=tracer,
                      slo_ttft_s=slo_ttft_s,
                      slo_spec_acceptance=slo_spec_acceptance)
    app[OBS_KEY] = sobs
    app[ENGINES_KEY] = engines
    unknown = set(drafts or {}) - set(engines)
    if unknown:
        raise ValueError(f"drafts registered for unknown models "
                         f"{sorted(unknown)}")
    app[SPEC_KEY] = {name: SpeculativeEngine(engines[name], draft)
                     for name, draft in (drafts or {}).items()}
    tok_vocab = getattr(tokenizer, "vocab_size", None)
    if tok_vocab is not None:
        # Fail at startup, not per request: a tokenizer whose ids exceed
        # a model's vocab would 400 every text request with a confusing
        # "token ids must be in range" error.
        for name, eng in engines.items():
            if tok_vocab > eng.cfg.vocab_size:
                raise ValueError(
                    f"tokenizer vocab {tok_vocab} exceeds model "
                    f"{name!r} vocab {eng.cfg.vocab_size}")
    app[TOKENIZER_KEY] = tokenizer
    # One inference at a time per process: the device is the bottleneck,
    # and interleaved generate calls would just thrash compile caches.
    lock = asyncio.Lock()
    app[GPU_LOCK_KEY] = lock
    if not continuous and (warmup or prefill_chunk or prefixes
                           or prefill_chunk_tokens is not None
                           or spec_decode
                           or max_pending is not None
                           or pipeline_depth is not None
                           or kv_block_size != 64
                           or kv_pool_blocks is not None
                           or kv_spill_bytes is not None
                           or paged_attention_impl != "auto"
                           or tenancy is not None):
        # these knobs only exist on the continuous batcher; silently
        # ignoring them would ship a server missing configuration the
        # caller explicitly asked for (max_pending especially: the
        # caller believes overload sheds at that depth; tenancy
        # especially: the caller believes quotas are enforced)
        raise ValueError(
            "warmup/prefill_chunk/prefill_chunk_tokens/prefixes/"
            "max_pending/pipeline_depth/kv_block_size/kv_pool_blocks/"
            "kv_spill_bytes/paged_attention_impl/spec_decode/tenancy "
            "require continuous=True")
    if spec_decode:
        missing = set(engines) - set(drafts or {})
        if missing:
            # silently decoding some models speculatively and others
            # not would make the latency story per-model surprising
            raise ValueError(
                f"spec_decode=True requires a draft for every served "
                f"model; missing {sorted(missing)}")
    app[TENANCY_KEY] = tenancy
    if continuous:
        # prefill_chunk: long prompts admit in fixed slices — chunk-
        # multiple buckets, one [g, chunk] compile for every length.
        # prefixes: named system prompts whose KV computes once; a
        # request opts in with {"prefix": name}.
        app[BATCHERS_KEY] = {
            name: ContinuousBatcher(
                eng, lock, max_slots=max_batch,
                prefill_chunk=prefill_chunk,
                prefill_chunk_tokens=prefill_chunk_tokens,
                prefixes=prefixes,
                max_pending=256 if max_pending is None else max_pending,
                pipeline_depth=pipeline_depth,
                kv_block_size=kv_block_size,
                kv_pool_blocks=kv_pool_blocks,
                kv_spill_bytes=kv_spill_bytes,
                paged_attention_impl=paged_attention_impl,
                draft=(drafts or {}).get(name) if spec_decode else None,
                spec_gamma=spec_gamma,
                tenancy=tenancy)
            for name, eng in engines.items()}
        if warmup:
            async def _warm(app_):
                loop = asyncio.get_event_loop()
                for b in app_[BATCHERS_KEY].values():
                    await loop.run_in_executor(None, b.warmup)

            app.on_startup.append(_warm)
    else:
        app[BATCHERS_KEY] = (
            {name: Batcher(eng, lock, window_ms=batch_window_ms,
                           max_batch=max_batch)
             for name, eng in engines.items()}
            if batch_window_ms > 0 else {})
    for model_name, b in app[BATCHERS_KEY].items():
        if isinstance(b, Batcher):
            # coalescing evidence as a histogram, not just the
            # calls/requests counters list_models reports
            b.on_batch = (lambda n, _m=model_name:
                          sobs.batch_size.observe(n, model=_m))
        elif isinstance(b, ContinuousBatcher):
            def on_prefix(computed, reused, hit, tenant="",
                          restored=0, _m=model_name):
                fam = sobs.prefix_hits if hit else sobs.prefix_misses
                # the unlabeled (model-only) totals stay exactly what
                # they always were — the bench gate reads them; the
                # tenant-labelled series rides in the same family,
                # guard-capped (ISSUE 13)
                fam.inc(model=_m)
                fam.inc(model=_m, tenant=sobs.tenant_guard.admit(tenant))
                sobs.prefill_tokens.observe(
                    computed, model=_m, source="computed")
                # restored cells are radix hits whose content came off
                # the host spill tier — split them out of `reused` so
                # the two sources partition the cached cells exactly
                restored = max(0, min(int(restored), int(reused)))
                if reused - restored:
                    sobs.prefill_tokens.observe(
                        reused - restored, model=_m, source="reused")
                if restored:
                    sobs.prefill_tokens.observe(
                        restored, model=_m, source="restored")

            b.on_prefix = on_prefix

            # token-timeline companions: the batcher hands back every
            # decode gap and first-admission wait (ISSUE 6)
            def on_itl(gap, _m=model_name):
                sobs.itl.observe(gap, model=_m)
                sobs.slo.observe("serving_itl", gap)

            def on_queue_wait(wait, _m=model_name):
                sobs.queue_wait.observe(wait, model=_m)

            # every verified draft token is one good/bad event against
            # the spec-acceptance SLO (rejected = budget burned); the
            # series zero-seeds with the engine whether or not
            # spec_decode is on, so the dashboard shape is stable
            def on_spec_round(proposed, accepted):
                accepted = min(int(accepted), int(proposed))
                for _ in range(accepted):
                    sobs.slo.record("serving_spec_acceptance", True)
                for _ in range(int(proposed) - accepted):
                    sobs.slo.record("serving_spec_acceptance", False)

            b.on_itl = on_itl
            b.on_queue_wait = on_queue_wait
            b.on_spec_round = on_spec_round
            # seed zero samples so the exposition carries the series
            # (and a 0 reading) before the first admission
            sobs.prefix_hits.inc(0, model=model_name)
            sobs.prefix_misses.inc(0, model=model_name)
            _t0 = sobs.tenant_guard.admit("")  # tenant-blind bucket
            sobs.prefix_hits.inc(0, model=model_name, tenant=_t0)
            sobs.prefix_misses.inc(0, model=model_name, tenant=_t0)
            sobs.migration_out.inc(0, model=model_name)
            sobs.migration_in.inc(0, model=model_name)
            for _d in ("in", "out"):
                sobs.migration_failed.inc(
                    0, model=model_name, direction=_d)
                sobs.migration_blocks.inc(
                    0, model=model_name, direction=_d)
            # which attention impl decode resolved to, as an info
            # gauge; the tracer hook makes each decode chunk a
            # `decode.attention` span carrying the same label
            sobs.attention_impl.set(
                1, model=model_name, impl=b.cengine.attention_impl)
            b.tracer = sobs.tracer
            # Step-anatomy plane (ISSUE 8): zero-seed the full closed
            # phase/fn label sets so dashboards see every series from
            # the first scrape, then bind the profiler and
            # compile-watch hooks (same swallowed-exception contract
            # as on_prefix — see PhaseProfiler)
            for _p in obs_lib.SERVING_PHASES:
                sobs.step_phase_seconds.seed(model=model_name, phase=_p)
                sobs.step_tokens.seed(model=model_name, phase=_p)
            sobs.goodput.set(0.0, model=model_name)
            sobs.bubble.set(0.0, model=model_name)
            sobs.kv_high_water.set(0, model=model_name)
            for _fn in obs_lib.WATCHED_SERVING_FNS:
                sobs.recompiles.inc(0, model=model_name, fn=_fn)
            # cache observatory: zero-seed the CLOSED cause sets (incl.
            # `unattributed`, whose permanent zero is the conservation
            # contract) and the reuse/age histograms, then bind the
            # lifecycle ledger's hooks
            for _c in (*obs_lib.EVICTION_CAUSES, obs_lib.UNATTRIBUTED):
                sobs.kv_evictions.inc(0, model=model_name, cause=_c)
            for _c in obs_lib.DEFER_CAUSES:
                sobs.kv_admission_defers.inc(
                    0, model=model_name, cause=_c)
            sobs.kv_reuse_distance.seed(model=model_name)
            sobs.kv_block_age.seed(model=model_name)
            # fleet cache tier (ISSUE 19): zero-seed the closed
            # prefill-source and peer-fetch-outcome sets plus the
            # spill traffic counters, so the tier's absence reads as
            # explicit zeros rather than missing series
            for _s in obs_lib.PREFILL_SOURCES:
                sobs.prefill_tokens.seed(model=model_name, source=_s)
            for _o in obs_lib.PEER_FETCH_OUTCOMES:
                sobs.peer_fetch.inc(0, model=model_name, outcome=_o)
            sobs.kv_spill_demotions.inc(0, model=model_name)
            sobs.kv_spill_restores.inc(0, model=model_name)
            sobs.kv_spill_bytes.set(0, model=model_name)

            def on_free(cause, n, _m=model_name):
                sobs.kv_evictions.inc(n, model=_m, cause=cause)

            def on_reuse(dist, _m=model_name):
                sobs.kv_reuse_distance.observe(dist, model=_m)

            def on_age(age, _m=model_name):
                sobs.kv_block_age.observe(age, model=_m)

            def on_defer(cause, _m=model_name):
                sobs.kv_admission_defers.inc(model=_m, cause=cause)

            def on_spill(event, n, _m=model_name):
                # demote/restore are content movement between tiers;
                # "drop" (budget pushed an entry out of host RAM) has
                # no counter of its own — it shows up as the spilled
                # gauge falling without a restore
                if event == "demote":
                    sobs.kv_spill_demotions.inc(n, model=_m)
                elif event == "restore":
                    sobs.kv_spill_restores.inc(n, model=_m)

            b.cache_ledger.on_free = on_free
            b.cache_ledger.on_reuse = on_reuse
            b.cache_ledger.on_age = on_age
            b.cache_ledger.on_defer = on_defer
            b.cache_ledger.on_spill = on_spill

            def on_phase(phase, seconds, tokens, _m=model_name):
                # seconds is None for token-only attributions
                if seconds is not None:
                    sobs.step_phase_seconds.observe(
                        seconds, model=_m, phase=phase)
                if tokens:
                    sobs.step_tokens.observe(
                        tokens, model=_m, phase=phase)

            b.profiler.on_phase = on_phase
            b.compile_watch.tracer = sobs.tracer

            def on_recompile(fn, sig, _m=model_name):
                sobs.recompiles.inc(model=_m, fn=fn)

            b.compile_watch.on_recompile = on_recompile
    if continuous:
        def collect_kv_blocks():
            # gauge refreshed at render: /metrics reads the LIVE pool,
            # not the pool as of the last admission/retirement
            for _m, _b in app[BATCHERS_KEY].items():
                if isinstance(_b, ContinuousBatcher):
                    sobs.kv_blocks.set(_b.kv_blocks_in_use(), model=_m)
                    tier = _b._spill_tier
                    sobs.kv_spill_bytes.set(
                        tier.spilled_bytes if tier is not None else 0,
                        model=_m)

        sobs.registry.register_collector(collect_kv_blocks)

        def collect_goodput():
            # the goodput ledger is derived state: recompute at render
            # from the profiler's phase totals + high-water marks
            for _m, _b in app[BATCHERS_KEY].items():
                if isinstance(_b, ContinuousBatcher):
                    g = _b.profiler.goodput()
                    sobs.goodput.set(g["goodput_ratio"], model=_m)
                    sobs.bubble.set(g["bubble_fraction"], model=_m)
                    sobs.kv_high_water.set(
                        g["kv_blocks_high_water"], model=_m)

        sobs.registry.register_collector(collect_goodput)
    if tenancy is not None:
        # zero-seed the full per-tenant series set so dashboards see
        # every configured tenant (at 0) from the first scrape, and
        # pre-admit configured names into the label guard
        for _t in tenancy.names():
            sobs.tenant_guard.admit(_t)
            for _m in app[BATCHERS_KEY]:
                sobs.tenant_queue_depth.set(0, model=_m, tenant=_t)
                sobs.tenant_tokens.inc(0, model=_m, tenant=_t)
                sobs.tenant_preemptions.inc(0, model=_m, tenant=_t)
                sobs.prefix_hits.inc(0, model=_m, tenant=_t)
                sobs.prefix_misses.inc(0, model=_m, tenant=_t)
                for _r in THROTTLE_REASONS:
                    sobs.tenant_throttled.inc(
                        0, model=_m, tenant=_t, reason=_r)

        def _sync_counter(counter, total, **labels):
            # the ledger keeps cumulative totals; a counter can only
            # inc, so apply the delta since the last scrape
            cur = counter.value(**labels)
            if total > cur:
                counter.inc(total - cur, **labels)

        def collect_tenants():
            for _m, _b in app[BATCHERS_KEY].items():
                if not isinstance(_b, ContinuousBatcher):
                    continue
                for _t, s in _b.tenant_stats().items():
                    _t = sobs.tenant_guard.admit(_t)
                    sobs.tenant_queue_depth.set(
                        s.get("queued", 0), model=_m, tenant=_t)
                    _sync_counter(sobs.tenant_tokens, s["tokens"],
                                  model=_m, tenant=_t)
                    _sync_counter(sobs.tenant_preemptions,
                                  s["preempted"], model=_m, tenant=_t)
                    for _r, n in s["throttled"].items():
                        _sync_counter(sobs.tenant_throttled, n,
                                      model=_m, tenant=_t, reason=_r)

        sobs.registry.register_collector(collect_tenants)

    async def _close_batchers(app_):
        # ISSUE 3 bugfix: shutdown used to close() straight away, which
        # failed every in-flight generation with "server shutting down".
        # Drain first — stop admission, let admitted work decode to
        # completion within the grace window — THEN close (which only
        # has stragglers to fail, usually none).
        app_[DRAIN_KEY]["draining"] = True
        grace = app_[DRAIN_KEY]["grace_s"]
        for b in app_[BATCHERS_KEY].values():
            b.begin_drain()
        for b in app_[BATCHERS_KEY].values():
            if not await b.drain(timeout=grace):
                logging.getLogger(__name__).warning(
                    "shutdown drain timed out with %d request(s) "
                    "in flight; closing anyway", b.in_flight())
        for b in app_[BATCHERS_KEY].values():
            await b.close()

    app.on_cleanup.append(_close_batchers)

    async def request_timeline(request):
        # the TimelineStore keeps live AND finished requests (bounded,
        # oldest evicted): an operator pastes the X-Request-Id from a
        # slow response and reads where its time went
        rid = request.match_info["id"]
        for b in request.app[BATCHERS_KEY].values():
            if isinstance(b, ContinuousBatcher):
                tl = b.timelines.get(rid)
                if tl is not None:
                    return web.json_response(tl.to_dict())
        return web.json_response(
            {"error": f"no timeline for request {rid!r} (timelines "
                      "exist for continuous-batching requests only, "
                      "and the store is bounded)"},
            status=404)

    async def request_timelines_index(request):
        # enumeration surface for the scenario recorder: every id the
        # bounded stores still hold, oldest first per batcher
        ids: list[str] = []
        for b in request.app[BATCHERS_KEY].values():
            if isinstance(b, ContinuousBatcher):
                ids.extend(b.timelines.ids())
        return web.json_response({"requests": ids})

    async def debug_traces(request):
        # the shared traces handler plus this app's counter tracks
        # (ISSUE 8): phase budgets and pool fill ride the SAME Chrome
        # trace as the spans, namespaced per model
        try:
            payload = obs_lib.traces_response_payload(
                sobs.tracer, request.rel_url.query)
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e)) from None
        for _m, _b in request.app[BATCHERS_KEY].items():
            if isinstance(_b, ContinuousBatcher):
                obs_lib.merge_counter_tracks(
                    payload, _b.profiler.counter_events(prefix=_m))
                obs_lib.merge_counter_tracks(
                    payload,
                    _b.cache_ledger.counter_events(prefix=_m))
        return web.json_response(payload)

    async def debug_profile(request):
        # rolling step anatomy: per-phase p50/p95 + totals, the
        # goodput ledger, and per-fn retrace counts — the JSON the
        # "reading a step anatomy" walkthrough (docs/observability.md)
        # narrates
        models = {}
        for _m, _b in request.app[BATCHERS_KEY].items():
            if isinstance(_b, ContinuousBatcher):
                snap = _b.profiler.snapshot()
                snap["recompiles"] = _b.compile_watch.counts()
                snap["cache"] = _b.cache_anatomy()
                models[_m] = snap
        return web.json_response({"models": models})

    async def spec_toggle(request: web.Request):
        """POST /v1/spec {"enabled": bool} — runtime kill switch for
        speculative decoding on every model this replica serves (the
        fleet controller's disable_draft actuator fires this when the
        spec-acceptance SLO burns: a draft model that stops earning
        its keep costs a verify round per window for nothing). GET
        returns the current per-model state."""
        if request.method == "GET":
            return web.json_response({"models": _spec_state(request.app)})
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": "invalid JSON"},
                                     status=400)
        enabled = body.get("enabled") if isinstance(body, dict) else None
        if not isinstance(enabled, bool):
            return web.json_response(
                {"error": "body needs a boolean 'enabled'"}, status=400)
        for b in request.app[BATCHERS_KEY].values():
            if isinstance(b, ContinuousBatcher) \
                    and b.cengine.draft is not None:
                b.spec_enabled = enabled
        return web.json_response({"enabled": enabled,
                                  "models": _spec_state(request.app)})

    app.router.add_get("/healthz", healthz)
    app.router.add_get("/readyz", _ok)
    app.router.add_get("/v1/spec", spec_toggle)
    app.router.add_post("/v1/spec", spec_toggle)
    app.router.add_get("/metrics",
                       obs_endpoints.metrics_handler(sobs.registry))
    app.router.add_get("/debug/traces", debug_traces)
    app.router.add_get("/debug/profile", debug_profile)
    app.router.add_post("/drain", drain_endpoint)
    app.router.add_post("/v1/migrate/in", migrate_in)
    app.router.add_post("/v1/blocks/export", blocks_export)
    app.router.add_post("/v1/reload", reload_weights)
    app.router.add_get("/v1/models", list_models)
    app.router.add_get("/v1/requests/timelines",
                       request_timelines_index)
    app.router.add_get("/v1/requests/{id}/timeline", request_timeline)
    app.router.add_post("/v1/models/{name}:generate", generate)
    app.router.add_post("/v1/models/{name}:prefill", prefill_handoff)
    app.router.add_post("/v1/models/{name}:score", score)
    return app


async def _ok(request: web.Request):
    return web.json_response({"status": "ok"})


def _spec_state(app: web.Application) -> dict:
    """Per-model speculative-decoding state for /v1/spec."""
    out = {}
    for name, b in app[BATCHERS_KEY].items():
        has_draft = (isinstance(b, ContinuousBatcher)
                     and b.cengine.draft is not None)
        out[name] = {"draft": has_draft,
                     "spec_enabled": bool(has_draft and b.spec_enabled)}
    return out


def _in_flight(app: web.Application) -> int:
    return sum(b.in_flight() for b in app[BATCHERS_KEY].values())


def fleet_stats(app: web.Application) -> dict:
    """Routing/autoscale stats in the fleet heartbeat's vocabulary
    (summed over models — the fleet registry tracks replicas, not
    model shards). max_slots for the window batcher is its max_batch
    (the analog: requests co-scheduled per device call). `pool` is
    this replica's disaggregation role and `phase_seconds` folds the
    PhaseProfiler's cumulative totals into the two coarse phases the
    pool autoscaler splits on (prefill + chunked prefill vs decode +
    speculative draft/verify)."""
    queue_depth = active = max_slots = 0
    kv_free = kv_total = 0
    phase_prefill = phase_decode = 0.0
    cache_digest: list = []
    for b in app[BATCHERS_KEY].values():
        if isinstance(b, ContinuousBatcher):
            queue_depth += len(b._pending)
            active += len(b._active)
            max_slots += len(b._free) + len(b._active)
            kv_free += b.cengine.pool.num_free
            kv_total += b.cengine.num_blocks
            cache_digest.extend(b._radix.heat_digest(16))
            totals = b.profiler.totals()
            phase_prefill += (totals.get("prefill", 0.0)
                              + totals.get("prefill_chunk", 0.0))
            phase_decode += (totals.get("decode", 0.0)
                             + totals.get("draft", 0.0)
                             + totals.get("verify", 0.0))
        else:
            queue_depth += b._queue.qsize()
            active += len(b._inflight)
            max_slots += b.max_batch
    return {
        "queue_depth": queue_depth, "active_slots": active,
        "max_slots": max_slots, "kv_blocks_free": kv_free,
        "kv_blocks_total": kv_total,
        "draining": app[DRAIN_KEY]["draining"],
        "pool": app.get(POOL_KEY, "mixed"),
        # the rollout plane's confirmation signal: the RolloutManager
        # watches this label flip after a /v1/reload before promoting
        "version": app.get(MODEL_VERSION_KEY, ""),
        "phase_seconds": {"prefill": round(phase_prefill, 6),
                          "decode": round(phase_decode, 6)},
        # top-K hashed prefix heat (ISSUE 13): the router merges these
        # into the fleet heat map and scores counterfactual remote hits
        "cache_digest": cache_digest,
    }


async def healthz(request: web.Request):
    """Readiness with substance (the fleet router's health probe, and
    a gateway's): 200 only when the server admits work — not draining,
    engines loaded, admission queue below its shed depth. /readyz
    stays the bare liveness 200."""
    app = request.app
    if app[DRAIN_KEY]["draining"]:
        return web.json_response(
            {"status": "draining", "in_flight": _in_flight(app)},
            status=503)
    models = {}
    overloaded = False
    for name, b in app[BATCHERS_KEY].items():
        if isinstance(b, ContinuousBatcher):
            pending = len(b._pending)
            models[name] = {
                "pending": pending,
                "active_slots": len(b._active),
                "kv_blocks_free": b.cengine.pool.num_free,
                "kv_blocks_total": b.cengine.num_blocks,
            }
            overloaded = overloaded or pending >= b.max_pending
        else:
            models[name] = {"pending": b._queue.qsize(),
                            "active_slots": len(b._inflight)}
    if overloaded:
        return web.json_response(
            {"status": "overloaded", "models": models}, status=503)
    return web.json_response({"status": "ok", "models": models})


async def drain_endpoint(request: web.Request):
    """Stop admission NOW. Bodyless (legacy): in-flight generations
    keep decoding to completion and the response reports what is still
    in flight — the wait-out drain. With `{"migrate": true, "peers":
    [url, ...]}` (the router's instant-drain path): every active +
    pending sequence is EXPORTED (serving.migration wire records) and
    pushed round-robin to the peers' `/v1/migrate/in`, so the replica
    can exit in seconds instead of waiting out its longest generation.
    Sequences whose transfer fails everywhere still resume via the
    router's checkpoint failover (heartbeats carried their tokens-so-
    far) — migration only saves the peer the re-prefill. Standalone-
    usable either way: an operator can drain one server with one
    POST."""
    app = request.app
    app[DRAIN_KEY]["draining"] = True
    for b in app[BATCHERS_KEY].values():
        b.begin_drain()
    try:
        body = await request.json()
    except Exception:  # noqa: BLE001 — bodyless legacy drain
        body = {}
    if not (isinstance(body, dict) and body.get("migrate")):
        return web.json_response(
            {"draining": True, "in_flight": _in_flight(app)})
    import aiohttp

    peers = [str(p).rstrip("/") for p in body.get("peers", []) if p]
    sobs: ServingObs = app[OBS_KEY]
    t0 = time.monotonic()
    migrated = failed = 0
    async with aiohttp.ClientSession() as session:
        for name, b in app[BATCHERS_KEY].items():
            if not isinstance(b, ContinuousBatcher):
                continue
            with sobs.tracer.span("migrate.out", model=name):
                records = await b.export_sequences()
            for i, record in enumerate(records):
                ok = False
                for j in range(len(peers)):
                    peer = peers[(i + j) % len(peers)]
                    try:
                        async with session.post(
                                f"{peer}/v1/migrate/in",
                                json={"model": name, "record": record},
                                timeout=aiohttp.ClientTimeout(
                                    total=30)) as r:
                            if r.status == 200:
                                ok = True
                                break
                    except (aiohttp.ClientError, asyncio.TimeoutError,
                            OSError):
                        continue
                if ok:
                    migrated += 1
                    sobs.migration_out.inc(model=name)
                    kv = record.get("kv")
                    if kv:
                        sobs.migration_blocks.inc(
                            kv["n_full"], model=name, direction="out")
                else:
                    failed += 1
                    sobs.migration_failed.inc(model=name,
                                              direction="out")
    return web.json_response({
        "draining": True, "in_flight": _in_flight(app),
        "migrated": migrated, "failed": failed,
        "migrate_s": round(time.monotonic() - t0, 3)})


async def migrate_in(request: web.Request):
    """Import one migrated sequence (body: `{"model": name, "record":
    <serving.migration wire record>}`): validate geometry, allocate
    local blocks, scatter the KV payload, and index the prefix in the
    radix cache under the record's tenant namespace. The sequence is
    NOT enqueued here — the router re-dispatches the generation
    (replay prompt + remaining budget), which radix-hits the imported
    prefix and resumes token-identically under greedy sampling. Any
    failure — including a wedged transfer (`"wedge": true`, the chaos
    harness's mid-transfer fault) — rolls back completely: the
    destination pool frees every partially-imported block."""
    app = request.app
    try:
        body: dict[str, Any] = await request.json()
    except Exception:
        return web.json_response({"error": "invalid JSON"}, status=400)
    name = body.get("model", "")
    batcher = app[BATCHERS_KEY].get(name)
    if name not in app[ENGINES_KEY]:
        return web.json_response(
            {"error": f"no model {name!r}"}, status=404)
    if not isinstance(batcher, ContinuousBatcher):
        return web.json_response(
            {"error": "migration import requires continuous batching"},
            status=400)
    record = body.get("record")
    wedge = bool(body.get("wedge", False))
    sobs: ServingObs = app[OBS_KEY]
    try:
        with sobs.tracer.span("migrate.in", model=name, wedge=wedge):
            blocks = await batcher.import_sequence(record, wedge=wedge)
    except ValueError as e:
        sobs.migration_failed.inc(model=name, direction="in")
        return web.json_response({"error": str(e)}, status=400)
    except Exception as e:  # noqa: BLE001 — rolled back inside
        sobs.migration_failed.inc(model=name, direction="in")
        return web.json_response(
            {"error": f"{type(e).__name__}: {e}"}, status=500)
    sobs.migration_in.inc(model=name)
    if blocks:
        sobs.migration_blocks.inc(blocks, model=name, direction="in")
    rid = (str(record.get("request_id", ""))
           if isinstance(record, dict) else "")
    return web.json_response(
        {"imported": True, "blocks": blocks, "request_id": rid})


async def blocks_export(request: web.Request):
    """POST /v1/blocks/export — peer side of the fleet cache tier's
    pull path (ISSUE 19). Body: `migration.prefix_fetch_request`
    (`model`/`tokens`/`ns` plus the 16-hex first-block prefix hash the
    router's heat hint advertised). Exports this replica's cached
    full-block KV prefix of `tokens` as a migration wire record —
    exactly the `/v1/migrate/in` format with `out=[]`, so the
    requester imports it through `import_sequence` with geometry
    validation unchanged. 404 when the prefix is no longer cached
    (heat digests lag evictions); the requester books that as
    `outcome=miss` and prefills normally — this endpoint can make a
    remote hit cheap, never a local miss wrong."""
    app = request.app
    try:
        body: dict[str, Any] = await request.json()
    except Exception:
        return web.json_response({"error": "invalid JSON"}, status=400)
    name = body.get("model", "") if isinstance(body, dict) else ""
    if name not in app[ENGINES_KEY]:
        return web.json_response(
            {"error": f"no model {name!r}"}, status=404)
    batcher = app[BATCHERS_KEY].get(name)
    if not isinstance(batcher, ContinuousBatcher):
        return web.json_response(
            {"error": "block export requires continuous batching"},
            status=400)
    try:
        _model, tokens, ns = migration.validate_fetch_request(
            body, block_size=batcher.cengine.block_size)
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=400)
    sobs: ServingObs = app[OBS_KEY]
    rid = request.headers.get("X-Request-Id") or secrets.token_hex(8)
    with sobs.tracer.span("blocks.export", model=name):
        record = await batcher.export_prefix(tokens, ns=ns,
                                             request_id=rid)
    if record is None:
        return web.json_response(
            {"error": "prefix not cached"}, status=404)
    blocks = int(record["kv"]["n_full"]) if record.get("kv") else 0
    if blocks:
        sobs.migration_blocks.inc(blocks, model=name, direction="out")
    return web.json_response({"record": record, "blocks": blocks})


async def _peer_fetch_blocks(app, name: str, batcher, tokens,
                             peer: str) -> None:
    """Requester side of the fleet cache tier's pull path: the router
    said `peer`'s heat digest carries this prompt's first-block prefix
    (`X-KV-Peer`), so pull the cached blocks over
    `/v1/blocks/export` + `import_sequence` BEFORE admission — the
    prefill then radix-hits the imported prefix. Best-effort with the
    PR 12 degradation discipline: any failure (dead peer, geometry
    mismatch, stale digest, import race) books its outcome and falls
    through to plain prefill, token-identically. Only the shared
    namespace participates — heat hints join on un-namespaced prefix
    hashes, and tenant-isolated trees never leave their replica."""
    sobs: ServingObs = app[OBS_KEY]
    bs = batcher.cengine.block_size
    if len(tokens) < bs + 1:
        # no full block that planning could reuse (the planner always
        # leaves >= 1 token to prefill)
        return
    nodes, _partial, _plen = batcher._radix.match(tokens)
    if nodes:
        return  # locally cached already — the hint is stale
    try:
        req = migration.prefix_fetch_request(
            name, tokens, block_size=bs)
    except ValueError:
        return
    import aiohttp

    try:
        async with aiohttp.ClientSession() as session:
            async with session.post(
                    f"{peer.rstrip('/')}/v1/blocks/export", json=req,
                    timeout=aiohttp.ClientTimeout(total=30)) as r:
                if r.status == 404:
                    sobs.peer_fetch.inc(model=name, outcome="miss")
                    return
                if r.status != 200:
                    sobs.peer_fetch.inc(model=name, outcome="failed")
                    return
                payload = await r.json()
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
        sobs.peer_fetch.inc(model=name, outcome="failed")
        return
    record = (payload.get("record")
              if isinstance(payload, dict) else None)
    if record is None:
        sobs.peer_fetch.inc(model=name, outcome="miss")
        return
    try:
        with sobs.tracer.span("peer.fetch", model=name):
            blocks = await batcher.import_sequence(record)
    except Exception:  # noqa: BLE001 — import rolled back inside
        sobs.peer_fetch.inc(model=name, outcome="failed")
        return
    sobs.peer_fetch.inc(model=name, outcome="ok")
    if blocks:
        # booked at import time: these cells reach the prefill as a
        # radix hit, so they ALSO appear under source=reused at
        # admission — peer_fetched measures transfer traffic, the
        # admission sources measure what seeded each prefill
        sobs.prefill_tokens.observe(blocks * bs, model=name,
                                    source="peer_fetched")


# Mirrors fleet.rollout.valid_version — the serving side must stay
# importable without the fleet package (same pact as POOL_ROLES).
_VERSION_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyz"
    "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-")


def _valid_version(v: Any) -> bool:
    return (isinstance(v, str) and 0 < len(v) <= 64
            and all(c in _VERSION_CHARS for c in v))


def _params_mismatch(old, new) -> str:
    """Structural compatibility check before a weight swap: same
    treedef, same leaf shapes and dtypes. The compiled decode/prefill
    functions are shape-specialized on the param tree — swapping in a
    differently-shaped tree would either retrace everything or crash
    mid-decode, so a mismatch rejects the reload with the old weights
    still live. Returns "" when compatible, else the reason."""
    import jax

    old_leaves, old_def = jax.tree.flatten(old)
    new_leaves, new_def = jax.tree.flatten(new)
    if old_def != new_def:
        return ("parameter tree structure differs from the live "
                "model's")
    for i, (o, n) in enumerate(zip(old_leaves, new_leaves)):
        o_shape = getattr(o, "shape", None)
        n_shape = getattr(n, "shape", None)
        o_dtype = getattr(o, "dtype", None)
        n_dtype = getattr(n, "dtype", None)
        if o_shape != n_shape or o_dtype != n_dtype:
            return (f"leaf {i}: incoming {n_shape}/{n_dtype} vs live "
                    f"{o_shape}/{o_dtype}")
    return ""


def _default_reloader(name: str, engine: InferenceEngine,
                      source: dict):
    """Materialize replacement params from a version's source spec —
    the same Orbax partial-restore path `python -m kubeflow_tpu.serving
    --checkpoint` boots from (params subtree only; pulling the Adam
    moments through disk to throw away would double the IO). Runs in
    an executor thread: restore is blocking IO. Deployments with other
    weight sources (seed-init tests, the loadtest) inject their own
    `reloader=` instead."""
    ckpt_dir = source.get("checkpoint", "")
    if not ckpt_dir:
        raise ValueError(
            "reload source needs a 'checkpoint' directory (no "
            "custom reloader is installed on this replica)")
    import jax
    import orbax.checkpoint as ocp

    from kubeflow_tpu.train.checkpoint import STATE_ITEM

    mgr = ocp.CheckpointManager(ckpt_dir, item_names=(STATE_ITEM,))
    try:
        step = source.get("step")
        if not isinstance(step, int):
            step = mgr.latest_step()
        if step is None:
            raise ValueError(f"no committed checkpoint under "
                             f"{ckpt_dir!r}")
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            engine.params)
        restored = mgr.restore(step, args=ocp.args.Composite(**{
            STATE_ITEM: ocp.args.PyTreeRestore(
                {"params": abstract}, partial_restore=True),
        }))
    finally:
        mgr.close()
    return restored[STATE_ITEM]["params"]


def _resume_admission(app: web.Application, draining: bool) -> None:
    """Undo a reload's drain: re-open every batcher and restore the
    door flag (a replica that was ALREADY draining when the reload
    arrived stays draining)."""
    for b in app[BATCHERS_KEY].values():
        b.end_drain()
    app[DRAIN_KEY]["draining"] = draining


async def reload_weights(request: web.Request):
    """POST /v1/reload — drain-then-swap live weight reload (the
    rollout plane's replica-side primitive, ISSUE 18). Body:

        {"version": "step-12",           # required, [A-Za-z0-9._-]{1,64}
         "model": "llama-tiny",          # optional when one model served
         "source": {"checkpoint": dir,   # what to load — consumed by the
                    "step": 12},         #   installed reloader
         "defect": {"ttft_delay_s": 2}}  # optional chaos (bad-version arm)

    Choreography: stop admission (drain door + every batcher), wait out
    in-flight generations (grace-bounded — the ROUTER migrates KV off
    the replica via /drain BEFORE calling this, so the wait is normally
    zero), materialize the new params in an executor under the gpu
    lock, verify tree/shape/dtype compatibility, swap `engine.params`,
    invalidate the radix prefix cache (cached KV describes the old
    weights), re-open admission, adopt the version label, and force a
    fleet re-registration so the router sees the flip without waiting a
    heartbeat period. Every failure path resumes admission with the OLD
    weights — a failed reload must leave a serving replica, not a
    drained one. A reload also RESETS any planted defect: rolling back
    to the prior version heals the chaos arm by construction."""
    app = request.app
    try:
        body: dict[str, Any] = await request.json()
    except Exception:
        return web.json_response({"error": "invalid JSON"}, status=400)
    if not isinstance(body, dict):
        return web.json_response({"error": "body must be an object"},
                                 status=400)
    version = body.get("version", "")
    if not _valid_version(version):
        return web.json_response(
            {"error": "version must be 1..64 chars of [A-Za-z0-9._-]"},
            status=400)
    engines = app[ENGINES_KEY]
    name = body.get("model", "")
    if not name and len(engines) == 1:
        name = next(iter(engines))
    if name not in engines:
        return web.json_response(
            {"error": f"no model {name!r} (serving "
                      f"{sorted(engines)})"}, status=404)
    source = body.get("source")
    if source is not None and not isinstance(source, dict):
        return web.json_response({"error": "source must be an object"},
                                 status=400)
    defect = body.get("defect")
    if defect is not None:
        delay = defect.get("ttft_delay_s", 0.0) \
            if isinstance(defect, dict) else None
        if not isinstance(delay, (int, float)) \
                or isinstance(delay, bool) or not 0 <= delay <= 30:
            return web.json_response(
                {"error": "defect.ttft_delay_s must be a number in "
                          "[0, 30]"}, status=400)
    engine = engines[name]
    sobs: ServingObs = app[OBS_KEY]
    was_draining = app[DRAIN_KEY]["draining"]
    app[DRAIN_KEY]["draining"] = True
    grace = app[DRAIN_KEY]["grace_s"]
    batchers = app[BATCHERS_KEY]
    for b in batchers.values():
        b.begin_drain()
    for b in batchers.values():
        if not await b.drain(timeout=grace):
            _resume_admission(app, was_draining)
            return web.json_response(
                {"error": f"drain timed out with {b.in_flight()} "
                          "request(s) in flight; weights unchanged"},
                status=409)
    reloader = app[RELOADER_KEY] or _default_reloader
    t0 = time.monotonic()
    try:
        with sobs.tracer.span("weights.reload", model=name,
                              version=version):
            async with app[GPU_LOCK_KEY]:
                params = await asyncio.get_event_loop() \
                    .run_in_executor(
                        None, reloader, name, engine,
                        dict(source or {}))
            why = _params_mismatch(engine.params, params)
            if why:
                raise ValueError(f"incompatible weights: {why}")
            engine.params = params
            b = batchers.get(name)
            if isinstance(b, ContinuousBatcher):
                # in_flight()==0 here (drained above): safe to drop
                # every cached block — they hold the OLD model's KV
                b.flush_cache()
    except ValueError as e:
        _resume_admission(app, was_draining)
        return web.json_response({"error": str(e)}, status=400)
    except Exception as e:  # noqa: BLE001 — old weights stay live
        _resume_admission(app, was_draining)
        return web.json_response(
            {"error": f"{type(e).__name__}: {e}"}, status=500)
    _resume_admission(app, False)
    app[MODEL_VERSION_KEY] = version
    app[DEFECT_KEY].clear()
    if isinstance(defect, dict):
        app[DEFECT_KEY].update(defect)
    # push the new version label to the fleet registry NOW — the
    # RolloutManager's confirm step watches for it, and a heartbeat
    # period of staleness would just slow every rollout phase down
    reg_state = app.get(FLEET_REG_KEY)
    register_fn = (reg_state or {}).get("register_fn")
    if register_fn is not None:
        try:
            await register_fn()
        except Exception:  # noqa: BLE001 — the beat loop will retry
            pass
    return web.json_response({
        "reloaded": True, "model": name, "version": version,
        "reload_s": round(time.monotonic() - t0, 3)})


async def prefill_handoff(request: web.Request):
    """POST /v1/models/{name}:prefill — the prefill half of a
    disaggregated handoff (ISSUE 12). Body: the usual `tokens`/`text`
    prompt plus an optional `"peer"` URL (the decode replica the
    pool-aware router picked). The replica prefills the prompt through
    its normal admission path (chunked prefill + the fused
    prefill/append kernel fill paged KV blocks, which the radix cache
    indexes), exports the full-block prefix as a migration wire
    record with `out=[]`, and pushes it to the peer's
    `/v1/migrate/in`. The response reports whether the handoff landed;
    the ROUTER then dispatches the real generation to the decode pool,
    where the imported prefix radix-hits and only the partial tail
    block prefills. Best-effort by design: any failure here just
    costs the decode replica one ordinary prefill — correctness never
    depends on this endpoint."""
    app = request.app
    if app[DRAIN_KEY]["draining"]:
        return web.json_response(
            {"error": "server is draining"}, status=503,
            headers={"Retry-After": "5"})
    name = request.match_info["name"]
    engine = app[ENGINES_KEY].get(name)
    if engine is None:
        return web.json_response(
            {"error": f"no model {name!r}"}, status=404)
    batcher = app[BATCHERS_KEY].get(name)
    if not isinstance(batcher, ContinuousBatcher):
        return web.json_response(
            {"error": "prefill handoff requires continuous batching"},
            status=400)
    try:
        body: dict[str, Any] = await request.json()
    except Exception:
        return web.json_response({"error": "invalid JSON"}, status=400)
    parsed = _parse_token_lists(body, app[TOKENIZER_KEY], min_len=1)
    if isinstance(parsed, web.Response):
        return parsed
    token_lists, _text_mode = parsed
    if len(token_lists) != 1:
        return web.json_response(
            {"error": "prefill handoff is single-prompt"}, status=400)
    toks = [int(t) for t in token_lists[0]]
    vocab = engine.cfg.vocab_size
    if min(toks) < 0 or max(toks) >= vocab:
        return web.json_response(
            {"error": f"token ids must be in [0, {vocab})"}, status=400)
    if len(toks) + 1 > engine.ec.max_len:
        return web.json_response(
            {"error": f"prompt {len(toks)} + 1 exceeds model max_len "
                      f"{engine.ec.max_len}"}, status=400)
    peer = body.get("peer", "")
    if not isinstance(peer, str):
        return web.json_response(
            {"error": "peer must be a URL string"}, status=400)
    rid = request.headers.get("X-Request-Id") or secrets.token_hex(8)
    sampling: dict[str, Any] = {"request_id": rid}
    tenant_hdr = request.headers.get("X-Tenant", "")
    if tenant_hdr:
        sampling["tenant"] = tenant_hdr
    sobs: ServingObs = app[OBS_KEY]
    t0 = time.monotonic()
    try:
        # max_new=1: the cheapest submission that runs the full prefill
        # path and leaves the prompt's blocks indexed in the radix tree
        # (at admission). The single decode token is discarded — the
        # decode replica owns the generation.
        with sobs.tracer.span("prefill.handoff", model=name):
            await batcher.submit(toks, 1,
                                 tuple(sorted(sampling.items())))
    except Throttled as e:
        return web.json_response(
            {"error": str(e)}, status=429,
            headers={"Retry-After": _retry_after_s(batcher, e)})
    except Overloaded as e:
        return web.json_response(
            {"error": f"server overloaded: {e}"}, status=429,
            headers={"Retry-After": _retry_after_s(batcher, e)})
    except MigratedAway as e:
        return web.json_response(
            {"error": str(e), "migrated": True}, status=503,
            headers={"Retry-After": "0"})
    record = await batcher.export_prefix(toks, request_id=rid)
    blocks = nbytes = 0
    if record is not None and record.get("kv"):
        blocks = int(record["kv"]["n_full"])
        nbytes = len(record["kv"]["k"]) + len(record["kv"]["v"])
    handoff = False
    if record is not None and peer:
        import aiohttp

        try:
            async with aiohttp.ClientSession() as session:
                async with session.post(
                        f"{peer.rstrip('/')}/v1/migrate/in",
                        json={"model": name, "record": record},
                        timeout=aiohttp.ClientTimeout(total=30)) as r:
                    handoff = r.status == 200
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            handoff = False
        if handoff:
            sobs.migration_out.inc(model=name)
            if blocks:
                sobs.migration_blocks.inc(
                    blocks, model=name, direction="out")
        else:
            sobs.migration_failed.inc(model=name, direction="out")
    return web.json_response({
        "prefilled": True, "handoff": handoff, "blocks": blocks,
        "bytes": nbytes if handoff else 0,
        "handoff_s": round(time.monotonic() - t0, 6),
        "request_id": rid})


def sequence_checkpoints(app: web.Application) -> list[dict]:
    """Lightweight resume records (tokens only, no KV) for every
    admitted request across models — the crash-failover feed
    `enable_fleet_registration` attaches to each heartbeat. When the
    registry sweeper declares this replica dead, the router replays
    them on a healthy peer from exactly where the stream stopped."""
    out = []
    for name, b in app[BATCHERS_KEY].items():
        if isinstance(b, ContinuousBatcher):
            for ck in b.checkpoints():
                out.append({"model": name, **ck})
    return out


async def list_models(request: web.Request):
    out = []
    for name, eng in request.app[ENGINES_KEY].items():
        entry = {
            "name": name,
            "family": eng.family.name,
            "max_len": eng.ec.max_len,
            "vocab_size": eng.cfg.vocab_size,
            "hidden_size": eng.cfg.hidden_size,
            "num_layers": eng.cfg.num_layers,
        }
        if eng.adapter_pack is not None:
            entry["adapters"] = sorted(eng.adapter_pack.names)
        batcher = request.app[BATCHERS_KEY].get(name)
        if batcher is not None:
            # coalescing evidence: for the window Batcher, mean
            # effective batch = batched_requests / batcher_calls
            # (counted at group SUCCESS, so failures can't inflate it;
            # pinned by tests/test_serving.py). For the continuous
            # batcher, calls = decode steps and the analog is
            # occupancy = tokens emitted per step.
            entry["batcher_calls"] = batcher.calls
            entry["batched_requests"] = batcher.requests
            if isinstance(batcher, ContinuousBatcher):
                entry["batcher_mode"] = "continuous"
                entry["occupancy"] = round(batcher.occupancy(), 3)
                entry["pending"] = len(batcher._pending)
                entry["active_slots"] = len(batcher._active)
                entry["pipeline_depth"] = batcher.pipeline_depth
                entry["kv_block_size"] = batcher.cengine.block_size
                entry["kv_pool_blocks"] = batcher.cengine.num_blocks
                entry["prefix_cache"] = batcher.prefix_cache_stats()
                tstats = batcher.tenant_stats()
                if tstats:
                    entry["tenants"] = tstats
                if batcher._prefixes:
                    entry["prefixes"] = {
                        n: len(t) for n, t in batcher._prefixes.items()}
            else:
                entry["batcher_mode"] = "window"
        out.append(entry)
    return web.json_response({"models": out})


# Server-side decode granularity for SSE streams: fixed (not a client
# knob) so a client sweeping max_new can mint at most STREAM_CHUNK
# distinct tail-chunk programs per prompt shape (plus prefill + the
# full chunk) — bounded, never one compile per max_new value.
STREAM_CHUNK = 8

# Retry-After ceiling: past this, a client should re-resolve (hit the
# fleet router / another replica) rather than camp on one server.
RETRY_AFTER_CAP_S = 60


def _retry_after_s(batcher, exc) -> str:
    """Dynamic Retry-After for a 429, replacing the old hardcoded "1".
    Throttled carries the tenant bucket's actual refill time; for
    Overloaded (queue full) estimate when the backlog clears: queue
    depth x the recent per-request service time, spread over the slot
    count. Clamped to [1, RETRY_AFTER_CAP_S] whole seconds."""
    if isinstance(exc, Throttled):
        est = exc.retry_after
    else:
        slots = max(1, len(batcher._free) + len(batcher._active))
        # service_ewma is 0.0 until the first completion; fall back to
        # a second per request — the old constant, now a floor
        est = (len(batcher._pending) + 1) \
            * (batcher.service_ewma or 1.0) / slots
    return str(max(1, min(RETRY_AFTER_CAP_S, math.ceil(est))))


async def _stream_generate(request, engine, arr, max_new, sampling,
                           text_mode, tokenizer):
    """SSE token streaming: `data: {"tokens": [[...]]}` per decoded
    chunk, then `data: {"done": true, ...}`. Same sampling law as the
    one-shot path (engine.generate_stream's equality guarantee); the
    stream ends early once every row hits EOS."""
    import json as _json

    # Build the generator BEFORE sending SSE headers: generate_stream
    # validates eagerly, so an argument the handler's own checks missed
    # is still a clean 400 here — never a 200 that dies mid-stream.
    try:
        gen = engine.generate_stream(
            jnp.asarray(arr), max_new=max_new, chunk=STREAM_CHUNK,
            **sampling)
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=400)
    sobs = request.app[OBS_KEY]
    model = request.match_info.get("name", "")
    headers = {
        "Content-Type": "text/event-stream",
        "Cache-Control": "no-cache",
        "X-Accel-Buffering": "no",
    }
    # The obs middleware cannot add headers after prepare(); stream
    # responses carry their trace id from birth.
    trace_id = sobs.tracer.current_trace_id()
    if trace_id:
        headers["X-Trace-Id"] = trace_id
    resp = web.StreamResponse(headers=headers)
    await resp.prepare(request)
    loop = asyncio.get_event_loop()
    chunks: list[np.ndarray] = []
    error: str | None = None
    with sobs.tracer.span("stream.decode", model=model):
        while True:
            # Lock only around the device work, NOT the client write: a
            # slow-reading client must back-pressure its own stream,
            # never stall every other request behind the GPU lock.
            # Other requests interleave between chunks (each chunk call
            # is self-contained).
            try:
                async with request.app[GPU_LOCK_KEY]:
                    part = await loop.run_in_executor(
                        None, lambda: next(gen, None))
            except Exception as e:  # noqa: BLE001
                # Same terminal-event contract as _stream_continuous:
                # headers are out, so raising would abort the connection
                # indistinguishably from a network drop. Log server-side
                # — the raise-through path used to leave an aiohttp
                # traceback, and a device falling over mid-stream must
                # stay diagnosable from the server logs.
                logging.getLogger(__name__).exception(
                    "decode failed mid-stream")
                error = f"{type(e).__name__}: {e}"
                break
            if part is None:
                break
            chunks.append(part)
            _observe_first_token(request, model)
            await resp.write(
                b"data: " + _json.dumps(
                    {"tokens": part.tolist()}).encode() + b"\n\n")
    total = int(sum(c.shape[1] for c in chunks))
    if error is not None:
        final: dict[str, Any] = {"error": error, "total": total}
    else:
        final = {"done": True, "total": total}
        if text_mode and chunks:
            ids = np.concatenate(chunks, axis=1)[0].tolist()
            final["text"] = (tokenizer.decode(ids) if tokenizer
                             else byte_decode(
                                 ids,
                                 on_dropped=lambda n: sobs.dropped_tokens
                                 .inc(n, model=model)))
    await resp.write(b"data: " + _json.dumps(final).encode() + b"\n\n")
    await resp.write_eof()
    return resp


async def _stream_continuous(request, batcher, arr, max_new, sampling,
                             text_mode, tokenizer):
    """SSE token streaming through the continuous batcher: one event
    per decoded token (`data: {"tokens": [[t]]}`), then the same final
    `{"done": true, ...}` record as `_stream_generate`. Concurrent
    streams SHARE the slot batch — each consumer awaits only its own
    tokens, never the GPU lock (the batcher's worker owns that)."""
    import json as _json

    try:
        # enqueue BEFORE the SSE headers: admission errors (Overloaded
        # included) must be a clean 429/4xx, never a mid-stream abort —
        # a depth pre-check alone would race a concurrent admission
        fut, q = batcher.open_stream(
            arr[0].tolist(), max_new, tuple(sorted(sampling.items())))
    except Throttled as e:
        return web.json_response(
            {"error": str(e)}, status=429,
            headers={"Retry-After": _retry_after_s(batcher, e)})
    except Overloaded as e:
        return web.json_response(
            {"error": f"server overloaded: {e}"}, status=429,
            headers={"Retry-After": _retry_after_s(batcher, e)})
    sobs = request.app[OBS_KEY]
    model = request.match_info.get("name", "")
    headers = {
        "Content-Type": "text/event-stream",
        "Cache-Control": "no-cache",
        "X-Accel-Buffering": "no",
    }
    trace_id = sobs.tracer.current_trace_id()
    if trace_id:
        headers["X-Trace-Id"] = trace_id
    rid = sampling.get("request_id")
    if rid:
        headers["X-Request-Id"] = rid
    resp = web.StreamResponse(headers=headers)
    await resp.prepare(request)
    ids: list[int] = []
    error: str | None = None
    try:
        with sobs.tracer.span("stream.continuous", model=model):
            while True:
                tok = await q.get()
                if tok is None:
                    break
                ids.append(tok)
                _observe_first_token(request, model)
                await resp.write(
                    b"data: " + _json.dumps({"tokens": [[tok]]}).encode()
                    + b"\n\n")
        try:
            await fut  # surface admission/step errors after drain
        except Exception as e:  # noqa: BLE001
            # Headers are already sent: a raise here would abort the
            # connection, indistinguishable from a network drop. Emit
            # a deterministic terminal error event instead (and keep
            # the server-side trail — see _stream_generate).
            logging.getLogger(__name__).exception(
                "continuous decode failed mid-stream")
            error = f"{type(e).__name__}: {e}"
    finally:
        if not fut.done():
            fut.cancel()  # consumer gone: release the slot
    if error is not None:
        final: dict[str, Any] = {"error": error, "total": len(ids)}
    else:
        final = {"done": True, "total": len(ids)}
        if text_mode and ids:
            final["text"] = (tokenizer.decode(ids) if tokenizer
                             else byte_decode(
                                 ids,
                                 on_dropped=lambda n: sobs.dropped_tokens
                                 .inc(n, model=model)))
    await resp.write(b"data: " + _json.dumps(final).encode() + b"\n\n")
    await resp.write_eof()
    return resp


def _parse_token_lists(body: dict, tokenizer, *, min_len: int):
    """Materialize token rows from "text" or "tokens" — the ONE
    definition of request-token parsing for the generate and score
    doors (drifted copies once meant the two validated differently).
    Returns (token_lists, text_mode) or a 400 Response. `min_len` is
    the per-row floor: 1 for generation, 2 for teacher-forced scoring
    (a single token has nothing to predict)."""
    text_mode = "text" in body
    if text_mode:
        if not isinstance(body["text"], str):
            return web.json_response(
                {"error": "'text' must be a string"}, status=400)
        token_lists = [tokenizer.encode(body["text"], bos=True)
                       if tokenizer else byte_encode(body["text"])]
        if len(token_lists[0]) < min_len:
            return web.json_response(
                {"error": f"text encodes to fewer than {min_len} "
                          "tokens (at least 2 needed to score)"
                 if min_len > 1 else "text encodes to no tokens"},
                status=400)
    elif "tokens" in body:
        token_lists = body["tokens"]
        if (not isinstance(token_lists, list) or not token_lists
                or not all(
                    isinstance(t, list) and len(t) >= min_len
                    and all(isinstance(x, int) and not isinstance(x, bool)
                            for x in t)
                    for t in token_lists)):
            return web.json_response(
                {"error": "tokens must be a non-empty list of integer "
                          f"token-id lists with at least {min_len} "
                          "token(s) each"}, status=400)
    else:
        return web.json_response(
            {"error": "body needs 'text' or 'tokens'"}, status=400)
    return token_lists, text_mode


async def score(request: web.Request):
    """Teacher-forced scoring: log P(token_i | prefix) for a given
    sequence — the perplexity/eval door (lm-eval style). Body:
    {"tokens": [[...]]} or {"text": "..."}; response: per-position
    logprobs (s-1 per row), each row's total, and token count."""
    if request.app[DRAIN_KEY]["draining"]:
        return web.json_response(
            {"error": "server is draining"}, status=503,
            headers={"Retry-After": "5"})
    name = request.match_info["name"]
    engine = request.app[ENGINES_KEY].get(name)
    if engine is None:
        return web.json_response(
            {"error": f"no model {name!r}"}, status=404)
    try:
        body: dict[str, Any] = await request.json()
    except Exception:
        return web.json_response({"error": "invalid JSON"}, status=400)
    tokenizer = request.app[TOKENIZER_KEY]
    parsed = _parse_token_lists(body, tokenizer, min_len=2)
    if isinstance(parsed, web.Response):
        return parsed
    token_lists, _ = parsed
    if len({len(t) for t in token_lists}) != 1:
        return web.json_response(
            {"error": "all rows must share a length (static shapes)"},
            status=400)
    if len(token_lists[0]) > engine.ec.max_len:
        return web.json_response(
            {"error": f"sequence {len(token_lists[0])} exceeds model "
                      f"max_len {engine.ec.max_len}"}, status=400)
    vocab = engine.cfg.vocab_size
    try:
        arr = np.asarray(token_lists, dtype=np.int32)
    except OverflowError:
        return web.json_response(
            {"error": f"token ids must be in [0, {vocab})"}, status=400)
    if arr.min() < 0 or arr.max() >= vocab:
        return web.json_response(
            {"error": f"token ids must be in [0, {vocab})"}, status=400)

    sobs: ServingObs = request.app[OBS_KEY]
    with sobs.tracer.span("engine.score", model=name,
                          batch=int(arr.shape[0])):
        async with request.app[GPU_LOCK_KEY]:
            lps = await asyncio.get_event_loop().run_in_executor(
                None, sobs.tracer.wrap(
                    lambda: np.asarray(engine.score(jnp.asarray(arr))),
                    "device.score"))
    return web.json_response({
        "logprobs": [[round(float(x), 6) for x in row] for row in lps],
        "total": [round(float(row.sum()), 6) for row in lps],
        "count": int(arr.shape[1] - 1),
    })


async def generate(request: web.Request):
    if request.app[DRAIN_KEY]["draining"]:
        # admission stops at the door; in-flight work keeps decoding.
        # 503 (not 429): the SERVER is going away — a client or the
        # fleet router should try another replica, not wait this one out
        return web.json_response(
            {"error": "server is draining"}, status=503,
            headers={"Retry-After": "5"})
    name = request.match_info["name"]
    engine = request.app[ENGINES_KEY].get(name)
    if engine is None:
        return web.json_response(
            {"error": f"no model {name!r}"}, status=404)
    # Chaos defect planted by /v1/reload (the rollout loadtest's bad-
    # version arm): a deliberate TTFT stall the canary judge must catch.
    _delay = request.app[DEFECT_KEY].get("ttft_delay_s", 0.0)
    if _delay:
        await asyncio.sleep(float(_delay))
    # tenant identity is a HEADER, not a body field: proxies (the fleet
    # router) forward it without parsing the payload, and a gateway can
    # inject it from auth without rewriting bodies. Absent/unknown
    # resolves to the `default` tenant inside the batcher.
    tenant_hdr = request.headers.get("X-Tenant", "")
    req_id: str | None = None  # minted on continuous-batcher paths
    try:
        body: dict[str, Any] = await request.json()
    except Exception:
        return web.json_response({"error": "invalid JSON"}, status=400)

    tokenizer = request.app[TOKENIZER_KEY]
    parsed = _parse_token_lists(body, tokenizer, min_len=1)
    if isinstance(parsed, web.Response):
        return parsed
    token_lists, text_mode = parsed

    max_new = body.get("max_new", 16)
    if not isinstance(max_new, int) or isinstance(max_new, bool) \
            or max_new < 1:
        return web.json_response(
            {"error": "max_new must be a positive integer"}, status=400)

    # Per-request sampling (dynamic in the compiled scan — no recompile).
    sampling: dict[str, Any] = {}
    temperature = body.get("temperature")
    if temperature is not None:
        # isfinite also rejects NaN/Infinity, which json.loads accepts
        # and which would otherwise pass a `< 0` check silently.
        if not isinstance(temperature, (int, float)) \
                or isinstance(temperature, bool) \
                or not math.isfinite(temperature) or temperature < 0:
            return web.json_response(
                {"error": "temperature must be a finite number >= 0"},
                status=400)
        sampling["temperature"] = float(temperature)
    top_k = body.get("top_k")
    if top_k is not None:
        if not isinstance(top_k, int) or isinstance(top_k, bool) \
                or top_k < 0 or top_k >= 2**31:
            return web.json_response(
                {"error": "top_k must be an integer in [0, 2**31)"},
                status=400)
        sampling["top_k"] = top_k
    top_p = body.get("top_p")
    if top_p is not None:
        if not isinstance(top_p, (int, float)) \
                or isinstance(top_p, bool) or not 0.0 < top_p <= 1.0:
            return web.json_response(
                {"error": "top_p must be in (0, 1]"}, status=400)
        sampling["top_p"] = float(top_p)
    adapter = body.get("adapter", "")
    if not isinstance(adapter, str):
        return web.json_response(
            {"error": "adapter must be a string"}, status=400)
    if adapter:
        if engine.adapter_pack is None:
            return web.json_response(
                {"error": f"model {name!r} has no adapters loaded"},
                status=400)
        try:
            engine.adapter_pack.resolve(adapter)
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
    prefix = body.get("prefix", "")
    if not isinstance(prefix, str):
        return web.json_response(
            {"error": "prefix must be a string"}, status=400)
    logprobs = body.get("logprobs", False)
    if not isinstance(logprobs, bool):
        return web.json_response(
            {"error": "logprobs must be a boolean"}, status=400)
    stop = body.get("stop", [])
    if (not isinstance(stop, list) or len(stop) > 4
            or not all(isinstance(s, list) and 0 < len(s) <= 16
                       and all(isinstance(t, int)
                               and not isinstance(t, bool) for t in s)
                       for s in stop)):
        return web.json_response(
            {"error": "stop must be up to 4 non-empty token-id lists "
                      "of at most 16 tokens"}, status=400)
    lens = {len(t) for t in token_lists}
    if len(lens) != 1:
        return web.json_response(
            {"error": "all prompts in a batch must share a length "
                      "(static shapes); pad client-side"}, status=400)
    prompt_len = lens.pop()
    if prompt_len + max_new > engine.ec.max_len:
        return web.json_response(
            {"error": f"prompt {prompt_len} + max_new {max_new} exceeds "
                      f"model max_len {engine.ec.max_len}"}, status=400)
    if prefix:
        pbatcher = request.app[BATCHERS_KEY].get(name)
        if not isinstance(pbatcher, ContinuousBatcher):
            return web.json_response(
                {"error": "prefix requires continuous batching"},
                status=400)
        if prefix not in pbatcher._prefixes:
            return web.json_response(
                {"error": f"unknown prefix {prefix!r}; registered: "
                          f"{sorted(pbatcher._prefixes)}"}, status=400)
        if adapter:
            return web.json_response(
                {"error": "prefix does not compose with adapter"},
                status=400)
        if len(token_lists) != 1:
            return web.json_response(
                {"error": "prefix requests are single-prompt"},
                status=400)
        if body.get("speculative", False) is True:
            return web.json_response(
                {"error": "prefix does not compose with speculative"},
                status=400)
        plen = len(pbatcher._prefixes[prefix])
        if plen + prompt_len + max_new > engine.ec.max_len:
            return web.json_response(
                {"error": f"prefix {plen} + prompt {prompt_len} + "
                          f"max_new {max_new} exceeds model max_len "
                          f"{engine.ec.max_len}"}, status=400)
        sampling["prefix"] = prefix
    vocab = engine.cfg.vocab_size
    try:
        arr = np.asarray(token_lists, dtype=np.int32)
    except OverflowError:
        return web.json_response(
            {"error": f"token ids must be in [0, {vocab})"}, status=400)
    if arr.min() < 0 or arr.max() >= vocab:
        return web.json_response(
            {"error": f"token ids must be in [0, {vocab})"}, status=400)

    # Fleet cache tier (ISSUE 19): the router attaches X-KV-Peer when
    # a peer's heat digest carries this prompt's first-block prefix
    # and the chosen replica's doesn't — pull the hot blocks before
    # admission so the prefill radix-hits instead of recomputing.
    # Strictly best-effort: every failure path degrades to the plain
    # prefill this request would have run anyway.
    peer_hint = request.headers.get("X-KV-Peer", "")
    if (peer_hint and not prefix and arr.shape[0] == 1
            and not request.app[DRAIN_KEY]["draining"]):
        peer_batcher = request.app[BATCHERS_KEY].get(name)
        if isinstance(peer_batcher, ContinuousBatcher):
            await _peer_fetch_blocks(request.app, name, peer_batcher,
                                     arr[0].tolist(), peer_hint)

    speculative = body.get("speculative", False)
    if not isinstance(speculative, bool):
        return web.json_response(
            {"error": "speculative must be a boolean"}, status=400)
    # max_new is jit-static on the speculative and direct paths (the
    # Batcher already buckets its groups): bucket it the same way so a
    # client sweeping max_new mints O(log max_len) compiles, not one
    # per value, while holding the GPU lock. Generation runs to the
    # bucket; the response is trimmed back to the client's ask below.
    max_new_req = max_new
    max_new = Batcher._bucket(max_new, engine.ec.max_len - prompt_len)
    if max_new < max_new_req:  # cap clamped below the ask — cannot happen
        max_new = max_new_req  # (capacity was checked), but stay safe
    gamma = body.get("gamma", 4)
    if not isinstance(gamma, int) or isinstance(gamma, bool) or gamma < 1:
        return web.json_response(
            {"error": "gamma must be a positive integer"}, status=400)
    stream = body.get("stream", False)
    if not isinstance(stream, bool):
        return web.json_response(
            {"error": "stream must be a boolean"}, status=400)
    if stream:
        if speculative:
            return web.json_response(
                {"error": "stream does not compose with speculative"},
                status=400)
        if stop:
            # a streamed stop would need partial-match buffering to
            # avoid emitting a half-completed stop sequence; explicit
            # 400 beats silently different trimming semantics
            return web.json_response(
                {"error": "stop does not compose with stream"},
                status=400)
        if logprobs:
            return web.json_response(
                {"error": "logprobs does not compose with stream"},
                status=400)
        cbatcher = request.app[BATCHERS_KEY].get(name)
        if isinstance(cbatcher, ContinuousBatcher) and arr.shape[0] == 1:
            # a continuous-batched stream shares the slot batch with
            # every other request instead of holding the GPU per chunk
            if adapter:
                sampling["adapter"] = adapter
            if tenant_hdr:
                # rides the sampling channel like adapter/prefix; the
                # batcher pops it back out before grouping
                sampling["tenant"] = tenant_hdr
            # timeline key; _stream_continuous echoes X-Request-Id.
            # The fleet router mints its own id so a failover resume
            # keeps the same timeline — honor it when present.
            sampling["request_id"] = (
                request.headers.get("X-Request-Id")
                or secrets.token_hex(8))
            return await _stream_continuous(
                request, cbatcher, arr, max_new_req, sampling,
                text_mode, tokenizer)
        if adapter:
            return web.json_response(
                {"error": "adapter streaming requires continuous "
                          "batching (create_serving_app continuous)"},
                status=400)
        return await _stream_generate(
            request, engine, arr, max_new_req, sampling, text_mode,
            tokenizer)

    resp_extra: dict[str, Any] = {}
    if speculative and logprobs:
        return web.json_response(
            {"error": "logprobs does not compose with speculative"},
            status=400)
    if speculative and adapter:
        return web.json_response(
            {"error": "adapter does not compose with speculative"},
            status=400)
    if speculative:
        spec = request.app[SPEC_KEY].get(name)
        if spec is None:
            return web.json_response(
                {"error": f"no draft model registered for {name!r}"},
                status=400)
        if arr.shape[0] != 1:
            return web.json_response(
                {"error": "speculative decoding is batch-1"}, status=400)
        # gamma is jit-static: bucket it to a power of two <= 8 BEFORE
        # the capacity check, so a client sweeping gamma cannot mint
        # unbounded compiles while holding the GPU lock (gamma is
        # purely a perf knob — bucketing never changes the output law)
        g = 1
        while g * 2 <= min(gamma, 8):
            g *= 2
        gamma = g
        # the draft's cache must hold the window too (it is usually the
        # smaller model — and often configured with a smaller bucket).
        # The bucketed max_new shrinks back toward the exact ask before
        # rejecting: only the CLIENT's numbers may cause a 400.
        cap = min(engine.ec.max_len, spec.draft.ec.max_len)
        if prompt_len + max_new + gamma > cap:
            max_new = max(cap - prompt_len - gamma, max_new_req)
        if prompt_len + max_new_req + gamma > cap:
            return web.json_response(
                {"error": f"prompt {prompt_len} + max_new {max_new_req} "
                          f"+ gamma {gamma} exceeds model max_len {cap}"},
                status=400)

        def run_spec():
            toks_, stats = spec.generate(
                jnp.asarray(arr), max_new=max_new, gamma=gamma,
                **sampling)
            return np.asarray(toks_), stats

        sobs: ServingObs = request.app[OBS_KEY]
        with sobs.tracer.span("engine.speculative", model=name,
                              gamma=gamma, max_new=max_new):
            async with request.app[GPU_LOCK_KEY]:
                toks, stats = await asyncio.get_event_loop(
                ).run_in_executor(
                    None, sobs.tracer.wrap(run_spec, "device.generate"))
        _observe_first_token(request, name)
        # SpeculativeEngine does not special-case EOS; match the plain
        # path's contract (post-EOS tail pinned to EOS) server-side so
        # the two modes are interchangeable for clients.
        eos = engine.ec.eos_token
        if eos is not None:
            hits = np.where(toks[0] == eos)[0]
            if hits.size:
                toks = toks.copy()
                toks[0, hits[0]:] = eos
        resp_extra["speculative"] = {
            "acceptance_rate": round(stats.acceptance_rate, 4),
            "proposed": int(stats.proposed),
            "accepted": int(stats.accepted),
            "gamma": gamma,  # the EFFECTIVE (bucketed) window
        }
    elif (batcher := request.app[BATCHERS_KEY].get(name)) is not None \
            and arr.shape[0] == 1 \
            and (not adapter or isinstance(batcher, ContinuousBatcher)) \
            and (not logprobs or isinstance(batcher, ContinuousBatcher)):
        # single-prompt requests ride the dynamic batcher; explicit
        # client-side batches keep their one-shot path. Adapter
        # requests ride the CONTINUOUS batcher (per-slot ids); under a
        # window batcher they fall through to the direct path, which
        # supports adapters batch-uniformly.
        if adapter:
            sampling["adapter"] = adapter
        submit_sampling = dict(sampling)
        if tenant_hdr and isinstance(batcher, ContinuousBatcher):
            # NOT under the window Batcher: its sampling tuple is the
            # coalescing group key, and a per-tenant key would split
            # batches by identity for no scheduling benefit
            submit_sampling["tenant"] = tenant_hdr
        if isinstance(batcher, ContinuousBatcher):
            # server-minted id keys the token timeline
            # (/v1/requests/{id}/timeline); echoed as X-Request-Id.
            # Router-supplied ids win so failover resumes share one
            # timeline across replicas.
            req_id = (request.headers.get("X-Request-Id")
                      or secrets.token_hex(8))
            submit_sampling["request_id"] = req_id
        if stop and isinstance(batcher, ContinuousBatcher):
            # the continuous batcher retires the slot the moment a
            # stop sequence completes (compute freed); the window
            # batcher runs its group to the group max and the shared
            # post-trim below applies the semantics
            submit_sampling["stop"] = tuple(tuple(s) for s in stop)
        sobs: ServingObs = request.app[OBS_KEY]
        try:
            with sobs.tracer.span("batcher.submit", model=name,
                                  max_new=max_new_req):
                if logprobs and isinstance(batcher, ContinuousBatcher):
                    ids, req_lps = await batcher.submit(
                        arr[0].tolist(), max_new_req,
                        tuple(sorted(submit_sampling.items())),
                        with_logprobs=True)
                    lp_rows = [list(req_lps)]
                else:
                    ids = await batcher.submit(
                        arr[0].tolist(), max_new_req,
                        tuple(sorted(submit_sampling.items())))
                    lp_rows = None
        except Throttled as e:
            return web.json_response(
                {"error": str(e)}, status=429,
                headers={"Retry-After": _retry_after_s(batcher, e)})
        except Overloaded as e:
            return web.json_response(
                {"error": f"server overloaded: {e}"}, status=429,
                headers={"Retry-After": _retry_after_s(batcher, e)})
        except MigratedAway as e:
            # instant drain shipped this sequence to a peer; the
            # router treats the 503 as retryable and resumes from its
            # checkpoint (or the migrated prefix) elsewhere
            return web.json_response(
                {"error": str(e), "migrated": True}, status=503,
                headers={"Retry-After": "0"})
        _observe_first_token(request, name)
        toks = np.asarray([ids], np.int32)
    else:
        if adapter:
            sampling["adapter"] = adapter  # engine.generate kwarg

        def run_direct():
            out = engine.generate(jnp.asarray(arr), max_new=max_new,
                                  return_logprobs=logprobs, **sampling)
            if logprobs:
                t, lp = out
                return np.asarray(t), np.asarray(lp)
            return np.asarray(out), None

        sobs = request.app[OBS_KEY]
        with sobs.tracer.span("engine.generate", model=name,
                              batch=int(arr.shape[0]),
                              max_new=max_new):
            async with request.app[GPU_LOCK_KEY]:
                toks, lp_arr = await asyncio.get_event_loop(
                ).run_in_executor(
                    None, sobs.tracer.wrap(run_direct, "device.generate"))
        sobs.batch_size.observe(arr.shape[0], model=name)
        _observe_first_token(request, name)
        lp_rows = (lp_arr[:, :max_new_req].tolist()
                   if lp_arr is not None else None)
    toks = toks[:, :max_new_req]  # trim the bucket back to the ask
    rows = toks.tolist()
    if speculative:
        lp_rows = None
    if stop:
        # OpenAI semantics on every path: output ends BEFORE the
        # earliest stop-sequence occurrence (the continuous batcher
        # already trimmed its suffix; re-scanning is a no-op there)
        rows = [_apply_stop(r, stop) for r in rows]
        if lp_rows is not None:
            lp_rows = [lp[:len(r)] for lp, r in zip(lp_rows, rows)]
    resp: dict[str, Any] = {"tokens": rows, **resp_extra}
    if logprobs and lp_rows is not None:
        # uniform contract on every path: entries cover tokens up to
        # AND INCLUDING the row's first EOS — the direct path's
        # post-EOS tail describes pre-forcing samples of the padded
        # EOS tokens, which would silently corrupt a client's sequence
        # total (the continuous path already stops computing there)
        eos = engine.ec.eos_token
        out_lps = []
        for lp, r in zip(lp_rows, rows):
            n = len(r)
            if eos is not None and eos in r:
                n = r.index(eos) + 1
            out_lps.append([round(float(x), 6) for x in lp[:n]])
        resp["logprobs"] = out_lps
    if text_mode:
        resp["text"] = (tokenizer.decode(rows[0]) if tokenizer
                        else byte_decode(
                            rows[0],
                            on_dropped=lambda n: sobs.dropped_tokens
                            .inc(n, model=name)))
    return web.json_response(
        resp, headers={"X-Request-Id": req_id} if req_id else None)


def _apply_stop(row: list[int], stop: list[list[int]]) -> list[int]:
    """Cut `row` before the earliest occurrence of any stop sequence."""
    cut = None
    for seq in stop:
        n = len(seq)
        for i in range(len(row) - n + 1):
            if row[i:i + n] == seq:
                cut = i if cut is None else min(cut, i)
                break
    return row if cut is None else row[:cut]


def enable_fleet_registration(app: web.Application, router_url: str,
                              advertise_url: str, *,
                              replica_id: str | None = None,
                              period_s: float = 2.0) -> None:
    """Wire this replica into a fleet router (kubeflow_tpu.fleet):
    register on startup, heartbeat `fleet_stats` every `period_s`
    (re-registering when the router answers 404 — it restarted and
    lost its table), deregister on cleanup. Router unavailability is
    never fatal: the replica serves standalone and keeps retrying —
    the router and replicas boot in either order."""
    import aiohttp

    router = router_url.rstrip("/")
    state: dict[str, Any] = {
        "router": router, "advertise": advertise_url,
        "id": replica_id or advertise_url, "period_s": period_s,
        "session": None, "task": None,
    }
    app[FLEET_REG_KEY] = state
    log = logging.getLogger(__name__)

    def _payload(app_) -> dict:
        return {"id": state["id"], "url": state["advertise"],
                "models": sorted(app_[ENGINES_KEY]),
                "checkpoints": sequence_checkpoints(app_),
                **fleet_stats(app_)}

    async def _register(app_) -> bool:
        try:
            async with state["session"].post(
                    f"{router}/fleet/register", json=_payload(app_),
                    timeout=aiohttp.ClientTimeout(total=5)) as r:
                return r.status == 200
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            return False

    async def _register_now() -> bool:
        # /v1/reload forces an immediate re-registration so the router
        # sees the new version label without waiting a heartbeat period
        if state["session"] is None:
            return False
        return await _register(app)

    state["register_fn"] = _register_now

    async def _beat_loop(app_):
        while True:
            await asyncio.sleep(state["period_s"])
            try:
                async with state["session"].post(
                        f"{router}/fleet/heartbeat",
                        json=_payload(app_),
                        timeout=aiohttp.ClientTimeout(total=5)) as r:
                    if r.status == 404:
                        await _register(app_)
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                pass  # router down/restarting: keep beating

    async def _start(app_):
        state["session"] = aiohttp.ClientSession()
        if not await _register(app_):
            log.warning("fleet: could not register with router %s "
                        "(will keep retrying via heartbeat)", router)
        state["task"] = asyncio.get_event_loop().create_task(
            _beat_loop(app_))

    async def _stop(app_):
        task = state["task"]
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        if state["session"] is not None:
            try:
                async with state["session"].post(
                        f"{router}/fleet/deregister",
                        json={"id": state["id"]},
                        timeout=aiohttp.ClientTimeout(total=5)):
                    pass
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                pass
            await state["session"].close()

    app.on_startup.append(_start)
    # deregister BEFORE the drain-and-close hook: the router must stop
    # routing here while the drain window is still finishing in-flight
    app.on_cleanup.insert(0, _stop)
