"""Model-serving REST server (the TF-Serving-proxy replacement).

The reference exposed model inference as an HTTP service behind the same
Service/VirtualService machinery as notebooks
(`/root/reference/docs_dev/tf_serving.md:1-60`; prediction smoke test in
`/root/reference/testing/test_tf_serving.py:40-57`). TPU-native version:
an aiohttp app wrapping `InferenceEngine`, serving
  POST /v1/models/{name}:generate   {"tokens": [[...]], "max_new": N}
  POST /v1/models/{name}:generate   {"text": "...", ...} (byte tokenizer)
  GET  /v1/models                    model card listing
  GET  /healthz /readyz              gateway probes

Text in/out uses a dependency-free byte-level tokenizer (offset by
`BYTE_OFFSET` to keep specials 0..byte_offset-1 free) so the server
round-trips strings without downloaded vocabularies; real deployments
pass token IDs from their own tokenizer.
"""

from __future__ import annotations

import asyncio
import math
from typing import Any

import jax.numpy as jnp
import numpy as np
from aiohttp import web

from kubeflow_tpu.serving.engine import InferenceEngine

BYTE_OFFSET = 3  # 0=pad, 1=bos, 2=eos
BOS, EOS = 1, 2


def byte_encode(text: str) -> list[int]:
    return [BOS] + [b + BYTE_OFFSET for b in text.encode("utf-8")]


def byte_decode(tokens: list[int]) -> str:
    # Ids outside the byte range (specials below, vocab tail above — the
    # model's vocab is larger than 256+offset) are dropped, not crashed on.
    raw = bytes(t - BYTE_OFFSET for t in tokens
                if BYTE_OFFSET <= t < BYTE_OFFSET + 256)
    return raw.decode("utf-8", errors="replace")


ENGINES_KEY: web.AppKey = web.AppKey("engines", dict)
GPU_LOCK_KEY: web.AppKey = web.AppKey("gpu_lock", asyncio.Lock)
TOKENIZER_KEY: web.AppKey = web.AppKey("tokenizer", object)


def create_serving_app(engines: dict[str, InferenceEngine],
                       *, tokenizer=None) -> web.Application:
    """`tokenizer` (data.bpe.Tokenizer or anything with encode/decode)
    serves the "text" request mode; without one, the zero-training
    byte-level fallback applies."""
    app = web.Application()
    app[ENGINES_KEY] = engines
    tok_vocab = getattr(tokenizer, "vocab_size", None)
    if tok_vocab is not None:
        # Fail at startup, not per request: a tokenizer whose ids exceed
        # a model's vocab would 400 every text request with a confusing
        # "token ids must be in range" error.
        for name, eng in engines.items():
            if tok_vocab > eng.cfg.vocab_size:
                raise ValueError(
                    f"tokenizer vocab {tok_vocab} exceeds model "
                    f"{name!r} vocab {eng.cfg.vocab_size}")
    app[TOKENIZER_KEY] = tokenizer
    # One inference at a time per process: the device is the bottleneck,
    # and interleaved generate calls would just thrash compile caches.
    app[GPU_LOCK_KEY] = asyncio.Lock()
    app.router.add_get("/healthz", _ok)
    app.router.add_get("/readyz", _ok)
    app.router.add_get("/v1/models", list_models)
    app.router.add_post("/v1/models/{name}:generate", generate)
    return app


async def _ok(request: web.Request):
    return web.json_response({"status": "ok"})


async def list_models(request: web.Request):
    out = []
    for name, eng in request.app[ENGINES_KEY].items():
        out.append({
            "name": name,
            "family": eng.family.name,
            "max_len": eng.ec.max_len,
            "vocab_size": eng.cfg.vocab_size,
            "hidden_size": eng.cfg.hidden_size,
            "num_layers": eng.cfg.num_layers,
        })
    return web.json_response({"models": out})


async def generate(request: web.Request):
    name = request.match_info["name"]
    engine = request.app[ENGINES_KEY].get(name)
    if engine is None:
        return web.json_response(
            {"error": f"no model {name!r}"}, status=404)
    try:
        body: dict[str, Any] = await request.json()
    except Exception:
        return web.json_response({"error": "invalid JSON"}, status=400)

    tokenizer = request.app[TOKENIZER_KEY]
    text_mode = "text" in body
    if text_mode:
        if not isinstance(body["text"], str):
            return web.json_response({"error": "'text' must be a string"},
                                     status=400)
        token_lists = [tokenizer.encode(body["text"], bos=True)
                       if tokenizer else byte_encode(body["text"])]
    elif "tokens" in body:
        token_lists = body["tokens"]
        if (not isinstance(token_lists, list) or not token_lists
                or not all(
                    isinstance(t, list) and t
                    and all(isinstance(x, int) and not isinstance(x, bool)
                            for x in t)
                    for t in token_lists)):
            return web.json_response(
                {"error": "tokens must be a non-empty list of non-empty "
                          "integer token-id lists"}, status=400)
    else:
        return web.json_response(
            {"error": "body needs 'text' or 'tokens'"}, status=400)

    max_new = body.get("max_new", 16)
    if not isinstance(max_new, int) or isinstance(max_new, bool) \
            or max_new < 1:
        return web.json_response(
            {"error": "max_new must be a positive integer"}, status=400)

    # Per-request sampling (dynamic in the compiled scan — no recompile).
    sampling: dict[str, Any] = {}
    temperature = body.get("temperature")
    if temperature is not None:
        # isfinite also rejects NaN/Infinity, which json.loads accepts
        # and which would otherwise pass a `< 0` check silently.
        if not isinstance(temperature, (int, float)) \
                or isinstance(temperature, bool) \
                or not math.isfinite(temperature) or temperature < 0:
            return web.json_response(
                {"error": "temperature must be a finite number >= 0"},
                status=400)
        sampling["temperature"] = float(temperature)
    top_k = body.get("top_k")
    if top_k is not None:
        if not isinstance(top_k, int) or isinstance(top_k, bool) \
                or top_k < 0 or top_k >= 2**31:
            return web.json_response(
                {"error": "top_k must be an integer in [0, 2**31)"},
                status=400)
        sampling["top_k"] = top_k
    top_p = body.get("top_p")
    if top_p is not None:
        if not isinstance(top_p, (int, float)) \
                or isinstance(top_p, bool) or not 0.0 < top_p <= 1.0:
            return web.json_response(
                {"error": "top_p must be in (0, 1]"}, status=400)
        sampling["top_p"] = float(top_p)
    lens = {len(t) for t in token_lists}
    if len(lens) != 1:
        return web.json_response(
            {"error": "all prompts in a batch must share a length "
                      "(static shapes); pad client-side"}, status=400)
    prompt_len = lens.pop()
    if prompt_len + max_new > engine.ec.max_len:
        return web.json_response(
            {"error": f"prompt {prompt_len} + max_new {max_new} exceeds "
                      f"model max_len {engine.ec.max_len}"}, status=400)
    vocab = engine.cfg.vocab_size
    try:
        arr = np.asarray(token_lists, dtype=np.int32)
    except OverflowError:
        return web.json_response(
            {"error": f"token ids must be in [0, {vocab})"}, status=400)
    if arr.min() < 0 or arr.max() >= vocab:
        return web.json_response(
            {"error": f"token ids must be in [0, {vocab})"}, status=400)

    async with request.app[GPU_LOCK_KEY]:
        toks = await asyncio.get_event_loop().run_in_executor(
            None,
            lambda: np.asarray(
                engine.generate(jnp.asarray(arr), max_new=max_new,
                                **sampling)),
        )
    resp: dict[str, Any] = {"tokens": toks.tolist()}
    if text_mode:
        resp["text"] = (tokenizer.decode(toks[0].tolist()) if tokenizer
                        else byte_decode(toks[0].tolist()))
    return web.json_response(resp)
