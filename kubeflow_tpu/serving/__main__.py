"""Serving server CLI: `python -m kubeflow_tpu.serving`.

The deployable entry point the ModelServer controller's pods run —
and the standalone way to stand the REST server up from a train
checkpoint (the reference's analog was the removed TF-Serving binary,
`/root/reference/docs_dev/tf_serving.md:1-60`).

    python -m kubeflow_tpu.serving --model llama-tiny --random --port 8000
    python -m kubeflow_tpu.serving --model llama3-1b \
        --checkpoint /ckpt/run7 --continuous --warmup --quant int8

--checkpoint points at a train.Checkpointer directory (Orbax OCDBT);
the latest step's params are restored (optimizer state is skipped).
--random initializes fresh params — the smoke/dev path that lets the
controller's e2e run without weights.
"""

from __future__ import annotations

import argparse
import os
import sys


def model_registry():
    """name -> (config, init_fn, family). (Importing this module pulls
    jax regardless — the serving package __init__ imports the engine —
    which is why the ModelServer CONTROLLER mirrors MODEL_NAMES as a
    literal instead of importing it; tests pin the two together.)"""
    from kubeflow_tpu.models import gemma, llama, llama_moe
    from kubeflow_tpu.serving.engine import (
        GEMMA_FAMILY, LLAMA_FAMILY, MOE_LLAMA_FAMILY,
    )

    return {
        "llama-tiny": (llama.LLAMA_TINY, llama.init, LLAMA_FAMILY),
        "llama3-1b": (llama.LLAMA3_1B, llama.init, LLAMA_FAMILY),
        "llama3-8b": (llama.LLAMA3_8B, llama.init, LLAMA_FAMILY),
        "gemma-tiny": (gemma.GEMMA_TINY, gemma.init, GEMMA_FAMILY),
        "gemma-2b": (gemma.GEMMA_2B, gemma.init, GEMMA_FAMILY),
        "mixtral-tiny": (llama_moe.MIXTRAL_TINY, llama_moe.init,
                         MOE_LLAMA_FAMILY),
    }


MODEL_NAMES = tuple(model_registry())


def _load_params(args, init1):
    """`init1` is a rng-only closure over (init_fn, cfg)."""
    import jax

    if args.random:
        return init1(jax.random.key(args.seed))
    import orbax.checkpoint as ocp

    from kubeflow_tpu.train.checkpoint import STATE_ITEM

    mgr = ocp.CheckpointManager(args.checkpoint,
                                item_names=(STATE_ITEM,))
    step = mgr.latest_step()
    if step is None:
        raise SystemExit(f"no checkpoint under {args.checkpoint}")
    abstract = jax.eval_shape(
        init1, jax.ShapeDtypeStruct((2,), "uint32"))
    # partial restore: serving wants the params SUBTREE only — pulling
    # the Adam moments (2x params) through disk and HBM to throw away
    # would double a large model's startup IO
    restored = mgr.restore(step, args=ocp.args.Composite(**{
        STATE_ITEM: ocp.args.PyTreeRestore(
            {"params": abstract}, partial_restore=True),
    }))
    mgr.close()
    return restored[STATE_ITEM]["params"]


def _make_reloader(init_fn, cfg, quant: str):
    """Build the /v1/reload weight materializer for this process: a
    checkpoint source goes through the same Orbax partial-restore path
    as boot (plus the boot-time quantization, so a reload can't
    silently de-quantize a server started with --quant); a seed source
    (`{"seed": N}`) re-initializes — the loadtest/chaos path that
    needs distinguishable weights without writing checkpoints."""
    def _reload(name, engine, source):
        import jax

        if "seed" in source:
            params = init_fn(jax.random.key(int(source["seed"])), cfg)
        else:
            ckpt_dir = source.get("checkpoint", "")
            if not ckpt_dir:
                raise ValueError(
                    "reload source needs 'checkpoint' or 'seed'")
            import orbax.checkpoint as ocp

            from kubeflow_tpu.train.checkpoint import STATE_ITEM

            # boot's _load_params shape: abstract from init_fn (NOT
            # engine.params, which may be int8-quantized already),
            # params subtree only, pinned to source["step"] when given
            mgr = ocp.CheckpointManager(ckpt_dir,
                                        item_names=(STATE_ITEM,))
            try:
                step = source.get("step")
                if not isinstance(step, int):
                    step = mgr.latest_step()
                if step is None:
                    raise ValueError(
                        f"no checkpoint under {ckpt_dir!r}")
                abstract = jax.eval_shape(
                    lambda k: init_fn(k, cfg),
                    jax.ShapeDtypeStruct((2,), "uint32"))
                restored = mgr.restore(
                    step, args=ocp.args.Composite(**{
                        STATE_ITEM: ocp.args.PyTreeRestore(
                            {"params": abstract},
                            partial_restore=True),
                    }))
            finally:
                mgr.close()
            params = restored[STATE_ITEM]["params"]
        if quant == "int8":
            from kubeflow_tpu.serving.quant import quantize_blocks

            params = quantize_blocks(params)
        return params

    return _reload


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m kubeflow_tpu.serving")
    p.add_argument("--model", default="llama-tiny", choices=MODEL_NAMES)
    p.add_argument("--name", default="",
                   help="served model name (default: --model)")
    src = p.add_mutually_exclusive_group()
    src.add_argument("--checkpoint", default="",
                     help="train.Checkpointer directory")
    src.add_argument("--random", action="store_true",
                     help="fresh random params (smoke/dev)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--max-len", type=int, default=1024)
    p.add_argument("--eos", type=int, default=None)
    p.add_argument("--continuous", action="store_true")
    p.add_argument("--warmup", action="store_true")
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--batch-window-ms", type=float, default=0.0)
    p.add_argument("--prefill-chunk", type=int, default=0)
    p.add_argument("--prefill-chunk-tokens", type=int, default=0,
                   help="chunked prefill token budget (continuous "
                        "only): admission prefill feeds at most this "
                        "many prompt tokens per worker iteration, "
                        "interleaved with decode chunks — bounds the "
                        "decode stall a long prompt imposes. 0 = "
                        "monolithic admission prefill")
    p.add_argument("--kv-spill-bytes", type=int, default=0,
                   help="host-RAM KV spill tier byte budget "
                        "(continuous only): radix eviction demotes "
                        "block contents to host memory instead of "
                        "discarding, and a returning prefix restores "
                        "them with a host->device copy instead of "
                        "recomputing prefill. Size from the "
                        "reuse-distance histogram's mass beyond the "
                        "pool (docs/operator-guide.md). 0 = off")
    p.add_argument("--spec-decode", action="store_true",
                   help="speculative decoding on the paged KV cache "
                        "(continuous only): every request drafts "
                        "--spec-gamma tokens with --draft-model and "
                        "verifies them in one fused batched pass — "
                        "token-identical to plain decode")
    p.add_argument("--draft-model", default="",
                   choices=("",) + MODEL_NAMES,
                   help="draft model for --spec-decode (must share "
                        "the target's vocab)")
    p.add_argument("--draft-checkpoint", default="",
                   help="train.Checkpointer directory for the draft "
                        "params (default: random init — smoke/dev)")
    p.add_argument("--spec-gamma", type=int, default=4,
                   help="draft tokens proposed per speculative round")
    p.add_argument("--pipeline-depth", type=int, default=0,
                   help="decode dispatch-ahead depth (0 = backend-"
                        "aware default: 2 on TPU, 1 elsewhere)")
    p.add_argument("--paged-attention-impl", default="auto",
                   choices=("auto", "xla", "pallas"),
                   help="decode attention over the paged KV pool "
                        "(continuous only): xla gathers each row's "
                        "full window through the block table, pallas "
                        "walks the table in-kernel (interpret mode "
                        "off-TPU), auto = pallas on TPU")
    p.add_argument("--quant", choices=("", "int8"), default="")
    p.add_argument("--tokenizer", default="",
                   help="data.bpe tokenizer file (text mode); 'auto' "
                        "uses tokenizer.json beside --checkpoint when "
                        "present (tools/prepare_data.py's output name), "
                        "byte fallback otherwise")
    p.add_argument("--cpu", action="store_true",
                   help="pin the CPU backend (hermetic smoke; pins "
                        "jax.config BEFORE backend init)")
    p.add_argument("--drain-grace-s", type=float, default=30.0,
                   help="shutdown waits this long for in-flight "
                        "generations before closing")
    p.add_argument("--tenants", default="",
                   help="tenancy config JSON file (continuous only): "
                        "per-tenant weights, priorities, rate limits, "
                        "KV shares — see kubeflow_tpu.tenancy. "
                        "Requests select a tenant with the X-Tenant "
                        "header; absent/unknown maps to 'default'")
    p.add_argument("--pool", default="mixed",
                   choices=("mixed", "prefill", "decode"),
                   help="disaggregation role (continuous only for "
                        "prefill/decode): 'prefill' replicas serve "
                        ":prefill handoffs and ship KV blocks to the "
                        "decode pool; 'decode' replicas receive them; "
                        "'mixed' serves both phases (default)")
    p.add_argument("--fleet-router", default="",
                   help="fleet router base URL; the replica registers "
                        "and heartbeats there (kubeflow_tpu.fleet)")
    p.add_argument("--advertise", default="",
                   help="URL the fleet router should reach this "
                        "replica at (default http://HOST:PORT)")
    p.add_argument("--model-version", default="",
                   help="model version label this replica boots with "
                        "(rides in fleet heartbeats; POST /v1/reload "
                        "updates it live — the rollout plane's "
                        "confirmation signal, ISSUE 18)")
    args = p.parse_args(argv)
    if not args.checkpoint and not args.random:
        p.error("pass --checkpoint DIR or --random")
    if args.warmup and not args.continuous:
        # create_serving_app only wires warmup for the continuous
        # batcher; silently ignoring the flag would break the "Ready
        # means compiled" promise
        p.error("--warmup requires --continuous")
    if args.paged_attention_impl != "auto" and not args.continuous:
        p.error("--paged-attention-impl requires --continuous")
    if args.prefill_chunk_tokens and not args.continuous:
        p.error("--prefill-chunk-tokens requires --continuous")
    if args.kv_spill_bytes and not args.continuous:
        # the spill tier hangs off the continuous batcher's block
        # pool; silently ignoring the budget would serve with the
        # recompute-on-evict behavior the operator paid RAM to avoid
        p.error("--kv-spill-bytes requires --continuous")
    if args.spec_decode and not args.continuous:
        p.error("--spec-decode requires --continuous")
    if args.spec_decode and not args.draft_model:
        p.error("--spec-decode requires --draft-model")
    if args.draft_model and not args.spec_decode:
        p.error("--draft-model requires --spec-decode")
    if args.tenants and not args.continuous:
        # the QoS scheduler replaces the CONTINUOUS batcher's queue;
        # silently ignoring the file would serve without the quotas
        # the operator configured
        p.error("--tenants requires --continuous")
    if args.advertise and not args.fleet_router:
        p.error("--advertise requires --fleet-router")
    if args.pool != "mixed" and not args.continuous:
        # the handoff path ships paged KV blocks, which only the
        # continuous engine has
        p.error("--pool prefill/decode requires --continuous")

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from aiohttp import web

    from kubeflow_tpu.serving.engine import EngineConfig, InferenceEngine
    from kubeflow_tpu.serving.server import (
        create_serving_app,
        enable_fleet_registration,
    )

    cfg, init_fn, family = model_registry()[args.model]
    params = _load_params(args, lambda k: init_fn(k, cfg))
    if args.quant == "int8":
        from kubeflow_tpu.serving.quant import quantize_blocks

        params = quantize_blocks(params)
    engine = InferenceEngine(
        params, cfg, family,
        EngineConfig(max_len=args.max_len, eos_token=args.eos))
    tokenizer = None
    tok_ref = args.tokenizer
    if tok_ref == "auto":
        # The prepare_data -> train -> serve loop drops its tokenizer
        # at the last hop unless someone carries it: prefer the trained
        # tokenizer saved beside the checkpoint over the byte fallback.
        tok_ref = ""
        if args.checkpoint:
            from etils import epath

            cand = epath.Path(args.checkpoint) / "tokenizer.json"
            if cand.exists():
                tok_ref = str(cand)
    if tok_ref:
        from etils import epath

        from kubeflow_tpu.data.bpe import Tokenizer

        # epath, not open(): the checkpoint (and its tokenizer) can
        # live on gs:// — same reasoning as train/checkpoint.py's
        # data-state probe.
        tokenizer = Tokenizer.loads(epath.Path(tok_ref).read_text())
    tenancy = None
    if args.tenants:
        from kubeflow_tpu.tenancy import load_config

        tenancy = load_config(args.tenants)
    name = args.name or args.model
    drafts = None
    if args.spec_decode:
        dcfg, dinit, dfamily = model_registry()[args.draft_model]
        if args.draft_checkpoint:
            dargs = argparse.Namespace(
                random=False, seed=args.seed,
                checkpoint=args.draft_checkpoint)
            dparams = _load_params(dargs, lambda k: dinit(k, dcfg))
        else:
            # random draft: proposals are junk (low acceptance) but the
            # plumbing — and token parity — is exactly production's
            dparams = dinit(jax.random.key(args.seed + 1), dcfg)
        # draft must cover the target's sequence space: verify appends
        # through the SAME cursor positions
        drafts = {name: InferenceEngine(
            dparams, dcfg, dfamily,
            EngineConfig(max_len=args.max_len, eos_token=args.eos))}
    app = create_serving_app(
        {name: engine},
        tokenizer=tokenizer,
        batch_window_ms=args.batch_window_ms,
        max_batch=args.max_batch,
        continuous=args.continuous,
        warmup=args.warmup,
        prefill_chunk=args.prefill_chunk or None,
        prefill_chunk_tokens=args.prefill_chunk_tokens or None,
        kv_spill_bytes=args.kv_spill_bytes or None,
        pipeline_depth=args.pipeline_depth or None,
        paged_attention_impl=args.paged_attention_impl,
        drafts=drafts,
        spec_decode=args.spec_decode,
        spec_gamma=args.spec_gamma,
        drain_grace_s=args.drain_grace_s,
        tenancy=tenancy,
        pool=args.pool,
        model_version=args.model_version,
        reloader=_make_reloader(init_fn, cfg, args.quant),
    )
    if args.fleet_router:
        enable_fleet_registration(
            app, args.fleet_router,
            args.advertise or f"http://{args.host}:{args.port}")
    print(f"serving {args.name or args.model} "
          f"({'random' if args.random else args.checkpoint}) on "
          f"{args.host}:{args.port} backend={jax.default_backend()} "
          f"tokenizer={tok_ref or 'byte'}",
          flush=True)
    web.run_app(app, host=args.host, port=args.port, print=None)
    return 0


if __name__ == "__main__":
    sys.exit(main())
