"""Multi-LoRA serving: N fine-tuned adapters resident over ONE base.

The S-LoRA idea restated TPU-first: fine-tunes of the same base differ
only by rank-r adapters (~0.1% of params), so serving N of them as N
merged models wastes N× HBM. Instead the adapters are STACKED into one
pack and every request carries an adapter id; the decode batch mixes
requests for different fine-tunes (and the plain base) in one SPMD
program:

    y = h @ W  +  scaling * (h @ A[id]) @ B[id]

- Pack layout is layer-leading ([L, K, d, r]) so the SAME `lax.scan`
  layer loop slices adapters beside the block weights — no second loop,
  no dynamic shapes.
- `A[id]` is a per-row gather over the K axis: each row reads only its
  own adapter's weights (HBM cost ∝ selected adapters, not K).
- Index 0 is reserved as an all-zeros adapter: base-model requests ride
  the same program and the delta contributes exactly nothing — one
  compiled path, no cond.
- The low-rank delta is applied UNMERGED (two skinny matmuls) — unlike
  training, which merges W+AB per step (train/lora.py): serving cannot
  merge per request without materializing a full per-request W.

Reference parity: none (the reference has no serving runtime at all);
this closes the train→serve loop for `train/lora.py` checkpoints.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.serving.quant import qdot
from kubeflow_tpu.train.lora import LoraConfig, _TARGET_DIMS

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class AdapterPack:
    """K named adapters stacked per target: A [L, K, d_in, r],
    B [L, K, r, d_out]; id 0 is the reserved zero adapter ("")."""

    blocks: Params
    scaling: float
    names: dict[str, int]        # adapter name -> pack index (1-based)

    def resolve(self, name: str) -> int:
        """'' or None -> the zero adapter; unknown names raise."""
        if not name:
            return 0
        try:
            return self.names[name]
        except KeyError:
            raise ValueError(
                f"unknown adapter {name!r}; loaded: "
                f"{sorted(self.names)}") from None


def build_pack(cfg, lora_cfg: LoraConfig,
               adapters: dict[str, Params],
               dtype=None) -> AdapterPack:
    """Stack `train/lora.py`-layout adapter trees ({"blocks": {name:
    {"A": [L, d_in, r], "B": [L, r, d_out]}}}) into one pack. Every
    adapter must cover the same targets at the same rank (one gather
    index must address one homogeneous array)."""
    if not adapters:
        raise ValueError("need at least one adapter")
    names = sorted(adapters)
    targets = list(lora_cfg.targets)
    L = cfg.num_layers
    blocks: Params = {}
    for t in targets:
        d_in = getattr(cfg, _TARGET_DIMS[t][0])
        d_out = getattr(cfg, _TARGET_DIMS[t][1])
        a_stack = [np.zeros((L, d_in, lora_cfg.rank), np.float32)]
        b_stack = [np.zeros((L, lora_cfg.rank, d_out), np.float32)]
        for n in names:
            try:
                ab = adapters[n]["blocks"][t]
            except KeyError:
                raise ValueError(
                    f"adapter {n!r} missing target {t!r}") from None
            a, b = np.asarray(ab["A"], np.float32), np.asarray(
                ab["B"], np.float32)
            if a.shape != a_stack[0].shape or b.shape != b_stack[0].shape:
                raise ValueError(
                    f"adapter {n!r} target {t!r}: shape "
                    f"{a.shape}/{b.shape} != expected "
                    f"{a_stack[0].shape}/{b_stack[0].shape} "
                    "(same rank/targets required across the pack)")
            a_stack.append(a)
            b_stack.append(b)
        dt = dtype if dtype is not None else cfg.dtype
        # [K+1, L, ...] -> layer-leading [L, K+1, ...] for the scan
        blocks[t] = {
            "A": jnp.asarray(np.stack(a_stack, axis=0), dt
                             ).swapaxes(0, 1),
            "B": jnp.asarray(np.stack(b_stack, axis=0), dt
                             ).swapaxes(0, 1),
        }
    return AdapterPack(
        blocks=blocks,
        scaling=lora_cfg.scaling,
        names={n: i + 1 for i, n in enumerate(names)},
    )


def lora_proj(layer_pack: Params, ids, scaling: float, cfg):
    """Projection hook for `engine.transformer_block`: base matmul plus
    the per-row low-rank delta. `layer_pack` is one layer's slice
    ({name: {"A": [K, d_in, r], "B": [K, r, d_out]}}), `ids` [b] int32.
    Targets without adapters fall through to the plain matmul."""

    def proj(name: str, h, w):
        y = qdot(h, w, cfg.dtype)
        ab = layer_pack.get(name)
        if ab is None:
            return y
        a = ab["A"][ids].astype(cfg.dtype)     # [b, d_in, r] gather
        b = ab["B"][ids].astype(cfg.dtype)     # [b, r, d_out]
        delta = jnp.einsum("bsr,bro->bso",
                           jnp.einsum("bsd,bdr->bsr", h, a), b)
        return y + jnp.asarray(scaling, cfg.dtype) * delta

    return proj
