"""Versioned wire format for live KV-block migration.

One record describes one in-flight generation completely enough for a
peer replica to resume it token-identically under greedy sampling:

- `tokens` — the full replay prompt: the original prompt (including
  any registered-prefix expansion) plus every token emitted so far.
  This is the batcher's `kv_toks` log, the same sequence the paged
  blocks' canonical form is keyed by.
- `out` / `lps` — what the source already emitted (and its chosen-token
  logprobs), so the resumed stream starts exactly where the source
  stopped: `max_new - len(out)` tokens remain.
- `kv` — base64 payloads of the guaranteed-written FULL blocks (cells
  `[0, n_full * block_size)` of `tokens`), exported straight from the
  pool in canonical form. Tokens past the full-block line re-prefill on
  the destination; records for pending (never-admitted) requests carry
  `kv: null` and cost the peer one ordinary prefill.
- `geometry` — the exporter's pool layout. The importer validates it
  against its own pool BEFORE allocating anything: scattering a
  payload with a different block size / head count / head dim would
  silently corrupt every sequence that later seeds from those blocks.

Payloads travel as float32 (lossless for the bf16/f32 pools this
engine runs) and are cast to the destination pool dtype on import.
This module is pure host-side Python — no jax — so the router, the
loadtest and the chaos harness can all speak the format without
pulling in a device runtime.
"""

from __future__ import annotations

import base64

import numpy as np

from kubeflow_tpu.obs.cachestats import prefix_hash

__all__ = [
    "MIGRATION_WIRE_VERSION",
    "pool_geometry",
    "validate_geometry",
    "encode_kv",
    "decode_kv",
    "pack_record",
    "unpack_record",
    "prefix_fetch_request",
    "validate_fetch_request",
]

MIGRATION_WIRE_VERSION = 1

_GEOMETRY_KEYS = ("block_size", "num_kv_heads", "head_dim",
                  "num_layers")


def pool_geometry(cengine) -> dict:
    """The geometry tuple a `ContinuousEngine`'s pool is laid out in —
    what `validate_geometry` compares wire records against."""
    cfg = cengine.engine.cfg
    return {
        "block_size": int(cengine.block_size),
        "num_kv_heads": int(cfg.num_kv_heads),
        "head_dim": int(cfg.head_dim),
        "num_layers": int(cfg.num_layers),
    }


def validate_geometry(geom: dict, cengine) -> None:
    """Raise ValueError when a record's geometry disagrees with the
    local pool — checked before any block is allocated, so a foreign
    payload can never corrupt the pool."""
    if not isinstance(geom, dict):
        raise ValueError(
            f"migration geometry must be a dict, got {type(geom).__name__}")
    local = pool_geometry(cengine)
    for key in _GEOMETRY_KEYS:
        got = geom.get(key)
        if got != local[key]:
            raise ValueError(
                f"migration geometry mismatch: {key}={got!r} (wire) vs "
                f"{local[key]} (local pool) — importing this payload "
                "would corrupt the destination KV pool")


def encode_kv(k, v) -> dict:
    """Pack block payloads (`[L, n, block_size, n_kv, hd]` each) into
    a JSON-safe dict. float32 on the wire: lossless for bf16/f32
    pools, and a plain dtype every peer can decode."""
    k32 = np.ascontiguousarray(np.asarray(k), dtype=np.float32)
    v32 = np.ascontiguousarray(np.asarray(v), dtype=np.float32)
    if k32.shape != v32.shape or k32.ndim != 5:
        raise ValueError(
            f"encode_kv: k {k32.shape} / v {v32.shape} must be equal "
            "5-d [L, n, block_size, n_kv, hd] payloads")
    return {
        "n_full": int(k32.shape[1]),
        "shape": [int(d) for d in k32.shape],
        "k": base64.b64encode(k32.tobytes()).decode("ascii"),
        "v": base64.b64encode(v32.tobytes()).decode("ascii"),
    }


def decode_kv(kv: dict) -> tuple[np.ndarray, np.ndarray]:
    """Inverse of `encode_kv`. Raises ValueError when the byte count
    disagrees with the declared shape (truncated/corrupt transfer)."""
    try:
        shape = tuple(int(d) for d in kv["shape"])
        k_raw = base64.b64decode(kv["k"])
        v_raw = base64.b64decode(kv["v"])
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"malformed migration kv payload: {e}") from e
    want = int(np.prod(shape)) * 4
    if len(k_raw) != want or len(v_raw) != want:
        raise ValueError(
            f"migration kv payload truncated: shape {shape} needs "
            f"{want} bytes, got k={len(k_raw)} v={len(v_raw)}")
    k = np.frombuffer(k_raw, np.float32).reshape(shape)
    v = np.frombuffer(v_raw, np.float32).reshape(shape)
    return k, v


def pack_record(*, request_id: str, tenant: str, ns: str,
                tokens: list[int], out: list[int], lps: list[float],
                max_new: int, sampling: dict, geometry: dict,
                kv=None) -> dict:
    """Build one wire record. `kv` is an `(k, v)` array pair (encoded
    here) or None for tokens-only records."""
    return {
        "version": MIGRATION_WIRE_VERSION,
        "request_id": str(request_id),
        "tenant": str(tenant),
        "ns": str(ns),
        "tokens": [int(t) for t in tokens],
        "prompt_len": len(tokens) - len(out),
        "out": [int(t) for t in out],
        "lps": [float(x) for x in lps],
        "max_new": int(max_new),
        "sampling": dict(sampling),
        "geometry": dict(geometry),
        "kv": encode_kv(*kv) if kv is not None else None,
    }


def prefix_fetch_request(model: str, tokens, *, ns: str = "",
                         block_size: int) -> dict:
    """Body for a peer-side `POST /v1/blocks/export` (the fleet cache
    tier's pull path, ISSUE 19): the requesting replica asks a peer —
    named by the router's `X-KV-Peer` heat hint — for the cached KV
    blocks covering `tokens`. `prefix` is the 16-hex hash of the
    FIRST full block (the same `prefix_hash` the heat digests and the
    router's affinity key use), so the peer can cheaply verify the
    request names the prefix its digest advertised."""
    toks = [int(t) for t in tokens]
    if len(toks) < block_size:
        raise ValueError(
            f"prefix fetch needs >= one full block ({block_size} "
            f"tokens), got {len(toks)}")
    return {
        "model": str(model),
        "tokens": toks,
        "ns": str(ns),
        "prefix": prefix_hash(toks[:block_size], ns),
    }


def validate_fetch_request(body: dict, *,
                           block_size: int) -> tuple[str, list[int], str]:
    """Peer-side validation of a `/v1/blocks/export` body: shape-check
    the fields and recompute the first-block prefix hash — a mismatch
    means the requester and this pool disagree on block size or the
    body was mangled, and exporting would ship blocks the requester
    can't place. Returns `(model, tokens, ns)`; raises ValueError."""
    if not isinstance(body, dict):
        raise ValueError(
            f"fetch request must be a dict, got {type(body).__name__}")
    tokens = body.get("tokens")
    if (not isinstance(tokens, list) or not tokens
            or not all(isinstance(t, int) and not isinstance(t, bool)
                       for t in tokens)):
        raise ValueError("fetch request needs a non-empty integer "
                         "token list")
    ns = body.get("ns", "")
    if not isinstance(ns, str):
        raise ValueError("fetch request ns must be a string")
    if len(tokens) < block_size:
        raise ValueError(
            f"fetch request covers no full block: {len(tokens)} "
            f"tokens < block_size {block_size}")
    want = prefix_hash(tokens[:block_size], ns)
    if body.get("prefix") != want:
        raise ValueError(
            "fetch request prefix hash does not match its own tokens "
            "— block-size disagreement or mangled body")
    return str(body.get("model", "")), [int(t) for t in tokens], ns


def unpack_record(record: dict) -> dict:
    """Validate a wire record's envelope (version, required fields,
    basic types) and return it normalized. KV payloads stay encoded —
    `decode_kv` is the importer's call, after geometry validation."""
    if not isinstance(record, dict):
        raise ValueError(
            f"migration record must be a dict, got {type(record).__name__}")
    ver = record.get("version")
    if ver != MIGRATION_WIRE_VERSION:
        raise ValueError(
            f"unsupported migration wire version {ver!r} "
            f"(this replica speaks {MIGRATION_WIRE_VERSION})")
    for key in ("request_id", "tokens", "out", "max_new", "sampling",
                "geometry"):
        if key not in record:
            raise ValueError(f"migration record missing field {key!r}")
    tokens = record["tokens"]
    out = record["out"]
    if not isinstance(tokens, list) or not isinstance(out, list):
        raise ValueError("migration record tokens/out must be lists")
    if len(out) > len(tokens):
        raise ValueError(
            f"migration record: {len(out)} emitted tokens cannot "
            f"exceed the {len(tokens)}-token replay prompt")
    if len(out) >= int(record["max_new"]) and len(out) > 0:
        raise ValueError(
            "migration record: generation already complete "
            f"({len(out)}/{record['max_new']} tokens) — nothing to "
            "migrate")
    return {
        "request_id": str(record["request_id"]),
        "tenant": str(record.get("tenant", "")),
        "ns": str(record.get("ns", "")),
        "tokens": [int(t) for t in tokens],
        "prompt_len": int(record.get("prompt_len",
                                     len(tokens) - len(out))),
        "out": [int(t) for t in out],
        "lps": [float(x) for x in record.get("lps", [])],
        "max_new": int(record["max_new"]),
        "sampling": dict(record["sampling"]),
        "geometry": dict(record["geometry"]),
        "kv": record.get("kv"),
    }
