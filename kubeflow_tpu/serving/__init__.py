"""Serving layer: KV-cache inference engine, REST server, model export.

Reference parity: the reference's serving story is the removed TF-Serving
component (`/root/reference/docs_dev/tf_serving.md:1-60`, tested by
`/root/reference/testing/test_tf_serving.py`) fronted by the same
Service/VirtualService machinery as notebooks. The TPU-native redesign
(SURVEY.md §2b "Model serving"): a pure-JAX engine with a static-shape
KV cache (bucketed prefill, `lax.scan` decode — XLA-friendly, no dynamic
shapes), slot-based continuous batching (`continuous.py`), multi-LoRA
adapter packs (`multilora.py`), speculative decoding, int8 weight-only
quant, an aiohttp REST server the gateway can route to (generate with
stop/logprobs/adapters/prefixes, `:score`, SSE streams, 429
backpressure), a deployable CLI (`python -m kubeflow_tpu.serving`), and
ahead-of-time export via `jax.export` (StableHLO) with jax2tf/SavedModel
available when TensorFlow is present.
"""

from kubeflow_tpu.serving.continuous import (
    ContinuousBatcher,
    ContinuousEngine,
    Overloaded,
    SlotState,
)
from kubeflow_tpu.serving.engine import (
    DecodeState,
    EngineConfig,
    InferenceEngine,
    SamplingParams,
    filter_logits,
    GEMMA_FAMILY,
    LLAMA_FAMILY,
    MOE_LLAMA_FAMILY,
)
from kubeflow_tpu.serving.multilora import AdapterPack, build_pack
from kubeflow_tpu.serving.quant import QTensor, quantize_blocks
from kubeflow_tpu.serving.speculative import SpecStats, SpeculativeEngine
