"""Ahead-of-time model export for serving.

TPU-native first: `jax.export` serializes the jitted forward to portable
StableHLO bytes (versioned, reloadable with jax.export.deserialize — the
artifact a serving pod loads without retracing Python). The reference's
SavedModel path (`/root/reference/docs_dev/tf_serving.md`) is kept as an
optional jax2tf export, gated on TensorFlow being installed (it is not
part of this image's baked dependency set).
"""

from __future__ import annotations

import os
from typing import Any, Callable

import jax
from jax import export as jax_export  # submodule; not an auto-imported jax attr


def export_stablehlo(
    fn: Callable[..., Any],
    example_args: tuple,
    path: str,
) -> int:
    """Serialize `jit(fn)` for `example_args` shapes to `path`.

    Returns the artifact size in bytes. Reload with `load_stablehlo`.
    """
    exported = jax_export.export(jax.jit(fn))(*example_args)
    blob = exported.serialize()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(blob)
    return len(blob)


def load_stablehlo(path: str):
    """Deserialize an exported artifact; `.call(*args)` runs it."""
    with open(path, "rb") as f:
        return jax_export.deserialize(f.read())


def export_saved_model(
    fn: Callable[..., Any],
    example_args: tuple,
    path: str,
) -> None:
    """jax2tf → TF SavedModel (the reference's serving format). Raises a
    clear error when TensorFlow is absent instead of failing mid-trace."""
    try:
        import tensorflow as tf  # noqa: F401
        from jax.experimental import jax2tf
    except ImportError as e:
        raise RuntimeError(
            "SavedModel export needs tensorflow; this image does not ship "
            "it. Use export_stablehlo (jax-native) instead."
        ) from e
    module = tf.Module()
    tf_fn = jax2tf.convert(fn, with_gradient=False)
    module.f = tf.function(
        tf_fn,
        autograph=False,
        input_signature=[
            tf.TensorSpec(a.shape, tf.as_dtype(a.dtype)) for a in example_args
        ],
    )
    tf.saved_model.save(module, path)
