"""Weight-only int8 quantization for serving: halve decode's HBM bill.

Decode reads every parameter once per emitted token — it is bandwidth-
bound, so bytes ARE latency (bench.py's MBU roofline). Symmetric
per-output-channel int8 cuts the block weights to 1/2 the bytes of
bf16 (1/4 of fp32) at ~0.3% RMS weight error; XLA fuses the
int8-load -> convert -> scale chain into the consuming matmul, so HBM
sees int8 while the MXU still computes in the activation dtype.

Design: `QTensor` is a pytree (q: int8, scale: per-channel) whose
`.astype(dtype)` returns the dequantized array — exactly the call the
engine already makes on every weight (`h @ p["wq"].astype(cfg.dtype)`),
so the engine runs unmodified, and `lax.scan` over stacked per-layer
blocks slices q and scale together. Only the seven block matmul weights
quantize; embed/lm_head (quality-critical) and the tiny norms stay in
their source dtype.

Scope: serving only. Training through rounded weights needs STE
machinery this deliberately does not have — quantize a checkpoint at
load time (`quantize_blocks`), never the training params.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]

BLOCK_MATMUL_WEIGHTS = (
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


@jax.tree_util.register_pytree_node_class
class QTensor:
    """int8 weights + per-output-channel scales, dequantized on read.

    Shapes: q is the original weight shape [..., in, out]; scale is
    [..., 1, out] (contraction axis reduced, keepdims) so dequant is a
    broadcast multiply however many stacked leading axes exist — which
    is also what lets lax.scan slice a stacked [L, in, out] QTensor
    into per-layer [in, out] QTensors.
    """

    def __init__(self, q: jnp.ndarray, scale: jnp.ndarray):
        self.q = q
        self.scale = scale

    @property
    def shape(self):
        return self.q.shape

    @property
    def nbytes(self) -> int:
        return self.q.size * self.q.dtype.itemsize \
            + self.scale.size * self.scale.dtype.itemsize

    def astype(self, dtype) -> jnp.ndarray:
        # The engine's only read path. XLA fuses this into the consumer:
        # int8 leaves HBM, the multiply rides the convert.
        return self.q.astype(dtype) * self.scale.astype(dtype)

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)

    def __repr__(self):
        return f"QTensor(q={self.q.shape} {self.q.dtype}, " \
               f"scale={self.scale.shape})"


def qdot(h: jnp.ndarray, w, dtype) -> jnp.ndarray:
    """`h @ w` in `dtype`, keeping QTensor weights int8 all the way to
    the matmul.

    `h @ (q * scale) == (h @ q) * scale` exactly, because the scale is
    per-OUTPUT-channel (constant along the contraction axis). The left
    form materializes a full-width [in, out] dequantized weight (the
    elementwise multiply cannot fuse into a dot operand), so HBM pays
    bf16 prices and int8 delivers ~1.3x; the right form feeds the dot a
    bare int8-load -> convert (which XLA does fuse into the operand
    read) and applies the scale to the [.., out] RESULT — HBM sees
    int8, and bandwidth-bound decode gets the full ~2x byte saving.
    VERDICT r04 weak #3."""
    if isinstance(w, QTensor):
        y = h @ w.q.astype(dtype)
        return y * w.scale.astype(dtype)[..., 0, :]
    return h @ w.astype(dtype)


def quantize(w: jnp.ndarray, *, scale_dtype=jnp.bfloat16) -> QTensor:
    """Symmetric per-output-channel int8 over the contraction axis -2."""
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=-2, keepdims=True)
    # Round the scale to its STORAGE dtype before quantizing: dequant
    # multiplies by the stored scale, so quantizing against the fp32
    # scale would add |q| * (scale - stored) — up to ~0.5 scale at bf16
    # — on top of the rounding half-step.
    scale = (jnp.maximum(amax, 1e-30) / 127.0).astype(scale_dtype)
    q = jnp.clip(jnp.round(w32 / scale.astype(jnp.float32)),
                 -127, 127).astype(jnp.int8)
    return QTensor(q, scale)


def quantize_blocks(params: Params) -> Params:
    """Serving params with the seven block matmul weights as QTensors.
    Everything else (embed, lm_head, norms) passes through untouched."""
    out = dict(params)
    blocks = dict(params["blocks"])
    for name in BLOCK_MATMUL_WEIGHTS:
        blocks[name] = quantize(blocks[name])
    out["blocks"] = blocks
    return out


def param_bytes(params: Params) -> int:
    """HBM bytes a decode step reads for weights (QTensor-aware)."""
    return sum(
        leaf.nbytes for leaf in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QTensor)))
