"""Speculative decoding: a small draft model proposes, the target
verifies — decode latency drops without changing the output law.

Decode is HBM-bandwidth-bound: every step reads all params to emit ONE
token. Speculative decoding (Leviathan et al. 2023; Chen et al. 2023)
lets a cheap draft model propose `gamma` tokens autoregressively, then
the target scores all `gamma+1` positions in ONE forward pass (same
param read as a single decode step — that is the whole trick on TPU:
the verify pass rides the MXU at sequence length gamma+1 instead of 1).
The accept/reject rule preserves the target's sampling distribution
EXACTLY — accepted-token prefixes are distributed as if the target had
sampled alone; greedy in = greedy out.

TPU shape discipline: everything is static — the propose/verify loop is
a `lax.while_loop` with a fixed-capacity output buffer, the draft scan
always runs `gamma` steps, the verify pass always scores `gamma+1`
positions, and partial acceptance "rolls back" by moving the KV-cache
cursor (slots past `length` are masked by kv_valid and overwritten by
the next write — no copies).

The reference has no serving at all (SURVEY.md §2b; docs_dev/
tf_serving.md describes the removed TF-Serving proxy); this layers on
engine.py's KV-cache scan.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from kubeflow_tpu.serving.engine import (
    DecodeState,
    InferenceEngine,
    SamplingParams,
    _per_row,
    scaled_filtered_logits,
)


class SpecStats(NamedTuple):
    emitted: jnp.ndarray    # [] i32 — tokens produced (>= max_new)
    accepted: jnp.ndarray   # [] i32 — drafted tokens accepted
    proposed: jnp.ndarray   # [] i32 — drafted tokens proposed

    @property
    def acceptance_rate(self) -> float:
        return float(self.accepted) / max(float(self.proposed), 1.0)


def _dist(logits: jnp.ndarray, sp: SamplingParams) -> jnp.ndarray:
    """[..., vocab] logits -> the sampling distribution under sp.

    Greedy is the temperature->0 limit: a one-hot on the argmax. Using
    distributions (not samples) everywhere lets one accept/reject code
    path serve greedy and sampled decoding — for one-hots the ratio
    test degenerates to exact token match, which is greedy equivalence.
    """
    vocab = logits.shape[-1]

    def greedy(_):
        return jax.nn.one_hot(
            jnp.argmax(logits, axis=-1), vocab, dtype=jnp.float32)

    def sampled(_):
        probs = jax.nn.softmax(scaled_filtered_logits(logits, sp), axis=-1)
        # per-row vectors mix greedy and sampled rows (same contract as
        # InferenceEngine._sample — the shared resolver allows both)
        return jnp.where(_per_row(sp.temperature) > 0.0, probs,
                         greedy(None))

    return jax.lax.cond(
        jnp.any(sp.temperature > 0.0), sampled, greedy, None)


def _draw(rng: jax.Array, probs: jnp.ndarray) -> jnp.ndarray:
    """Sample [...]-shaped tokens from [..., vocab] probabilities.
    log(0) = -inf slots are unsampleable; one-hots draw deterministically."""
    return jax.random.categorical(
        rng, jnp.log(probs), axis=-1).astype(jnp.int32)


class SpeculativeEngine:
    """Wraps a (target, draft) engine pair. Batch 1 only: acceptance
    counts diverge across sequences, and per-sequence cache cursors
    would destroy the single-scalar `length` invariant — speculative
    decoding is a latency tool, and latency means small batch."""

    def __init__(self, target: InferenceEngine, draft: InferenceEngine):
        if target.cfg.vocab_size != draft.cfg.vocab_size:
            raise ValueError(
                f"target vocab {target.cfg.vocab_size} != draft vocab "
                f"{draft.cfg.vocab_size}")
        self.target = target
        self.draft = draft
        self._jit = jax.jit(
            self._speculate, static_argnames=("max_new", "gamma"))

    def generate(
        self,
        prompt_tokens: jnp.ndarray,   # [1, s] int32
        *,
        max_new: int = 32,
        gamma: int = 4,
        rng: jax.Array | None = None,
        temperature: float | None = None,
        top_k: int | None = None,
        top_p: float | None = None,
    ) -> tuple[jnp.ndarray, SpecStats]:
        """Returns ([1, max_new] tokens, SpecStats). Output follows the
        target's sampling law for the given knobs (EngineConfig of the
        TARGET supplies defaults; EOS early-exit is not special-cased —
        trim client-side as with InferenceEngine.generate)."""
        b, s = prompt_tokens.shape
        if b != 1:
            raise ValueError(f"speculative decoding is batch-1 (got {b})")
        if gamma < 1:
            raise ValueError(f"gamma must be >= 1, got {gamma}")
        # Worst case one verify window extends gamma+1 past the current
        # cursor, and the cursor can reach s + max_new - 1.
        need = s + max_new + gamma
        for name, eng in (("target", self.target), ("draft", self.draft)):
            if need > eng.ec.max_len:
                raise ValueError(
                    f"prompt {s} + max_new {max_new} + gamma {gamma} "
                    f"exceeds {name} cache bucket {eng.ec.max_len}")
        # TARGET EngineConfig supplies defaults; shared resolver keeps
        # validation/seeding policy identical to InferenceEngine.generate.
        sp, rng = self.target._resolve_sampling(
            temperature, top_k, top_p, rng, batch=1)
        out, stats = self._jit(
            self.target.params, self.draft.params,
            prompt_tokens, self.target.init_state(1),
            self.draft.init_state(1), rng, sp,
            max_new=max_new, gamma=gamma)
        return out, SpecStats(*[x for x in stats])

    # -- the jitted propose/verify loop -----------------------------------

    def _speculate(self, tparams, dparams, prompt, tstate, dstate, rng,
                   sp: SamplingParams, *, max_new: int, gamma: int):
        # Both param trees arrive as jit ARGUMENTS (engine.py note: a
        # closed-over param tree becomes a literal in the lowered
        # module and wrecks compile time at real model sizes).
        target, draft = self.target, self.draft
        cap = max_new + gamma  # worst case the last round overshoots

        # Prefill both caches; the target samples the first token.
        tlogits, tstate = target._forward_cached(tparams, prompt, tstate)
        rng, sub = jax.random.split(rng)
        first = _draw(sub, _dist(tlogits, sp))          # [1]
        _, dstate = draft._forward_cached(dparams, prompt, dstate)

        out = jnp.zeros((1, cap), jnp.int32)
        out = jax.lax.dynamic_update_slice(out, first[:, None], (0, 0))

        def cond(carry):
            return carry[3] < max_new

        def body(carry):
            tstate, dstate, out, n, last, rng, acc, prop = carry

            # Propose: gamma draft steps from the last emitted token.
            def dstep(c, _):
                dstate, tok, rng = c
                logits, dstate = draft._forward_cached(
                    dparams, tok[:, None], dstate)
                q = _dist(logits, sp)                   # [1, vocab]
                rng, sub = jax.random.split(rng)
                d = _draw(sub, q)                       # [1]
                return (dstate, d, rng), (d[0], q[0])

            (dstate, _, rng), (drafted, qs) = jax.lax.scan(
                dstep, (dstate, last, rng), None, length=gamma)
            # drafted: [gamma] i32; qs: [gamma, vocab] f32

            # Verify: one target pass over [last, d_1..d_gamma] scores
            # every drafted position plus the bonus position.
            tin = jnp.concatenate([last, drafted], axis=0)[None, :]
            all_logits, tstate = target._forward_cached(
                tparams, tin, tstate, return_all=True)  # [1, gamma+1, V]
            ps = _dist(all_logits[0], sp)               # [gamma+1, vocab]

            # Accept d_i with prob min(1, p_{i-1}(d_i) / q_{i-1}(d_i));
            # k = length of the accepted prefix.
            rng, sub = jax.random.split(rng)
            us = jax.random.uniform(sub, (gamma,))
            p_d = jnp.take_along_axis(
                ps[:gamma], drafted[:, None], axis=-1)[:, 0]
            q_d = jnp.take_along_axis(qs, drafted[:, None], axis=-1)[:, 0]
            accept = us * q_d < p_d   # u < p/q without the 0/0 hazard
            k = jnp.sum(jnp.cumprod(accept.astype(jnp.int32)))

            # One extra token always lands: the bonus draw from the
            # position after a fully-accepted window, or the residual
            # max(p-q, 0) resample at the first rejection.
            rng, sub = jax.random.split(rng)

            def bonus(_):
                return _draw(sub, ps[gamma][None, :])

            def resample(_):
                pk = jax.lax.dynamic_index_in_dim(ps, k, keepdims=False)
                qk = jax.lax.dynamic_index_in_dim(qs, jnp.minimum(
                    k, gamma - 1), keepdims=False)
                diff = jnp.clip(pk - qk, 0.0, None)
                # all-zero residual (p==q to rounding): fall back to p
                safe = jnp.where(diff.sum() > 0, diff, pk)
                return _draw(sub, safe[None, :])

            extra = jax.lax.cond(k == gamma, bonus, resample, None)  # [1]

            # Emit d_1..d_k then extra — a fixed-width window write;
            # positions past the cursor get overwritten next round.
            emit = jnp.append(drafted, 0).at[k].set(extra[0])
            out = jax.lax.dynamic_update_slice(out, emit[None, :], (0, n))

            # Roll back caches to the accepted prefix: the verify pass
            # wrote gamma+1 target slots (1+k valid), the draft wrote
            # gamma slots (min(1+k, gamma) valid).
            tstate = DecodeState(
                tstate.k, tstate.v, tstate.length - gamma + k,
                tstate.pad, tstate.offset)
            dstate = DecodeState(
                dstate.k, dstate.v,
                dstate.length - gamma + jnp.minimum(1 + k, gamma),
                dstate.pad, dstate.offset)
            # Full-window acceptance leaves the draft one token behind:
            # the scan fed [last, d_1..d_{gamma-1}], so d_gamma was never
            # processed and the next round's proposals would condition on
            # a prefix with a hole — collapsing acceptance from round 2
            # on. Feed it unconditionally (static shapes); when k < gamma
            # the write lands past the rolled-back cursor, stays invalid,
            # and is overwritten by the next round's first write.
            _, dfed = draft._forward_cached(
                dparams, drafted[gamma - 1][None, None], dstate)
            dstate = DecodeState(
                dfed.k, dfed.v,
                jnp.where(k == gamma, dfed.length, dstate.length),
                dfed.pad, dfed.offset)

            return (tstate, dstate, out, n + k + 1, extra, rng,
                    acc + k, prop + jnp.asarray(gamma, jnp.int32))

        zero = jnp.zeros((), jnp.int32)
        (_, _, out, n, _, _, acc, prop) = jax.lax.while_loop(
            cond, body,
            (tstate, dstate, out, jnp.ones((), jnp.int32), first, rng,
             zero, zero))
        return out[:, :max_new], (n, acc, prop)
