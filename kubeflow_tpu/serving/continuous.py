"""Slot-based continuous batching: requests join and leave the decode
batch at token boundaries.

The reference's serving design is one-request-at-a-time TF-Serving
behind an HTTP proxy (`/root/reference/docs_dev/tf_serving.md:1-60`,
`testing/test_tf_serving.py`); its only batching lever is client-side.
The window `Batcher` (server.py) already improves on that, but a late
arrival still waits for the whole in-flight generation, and one short
request in a group waits for its longest neighbor.

This module is the TPU-idiomatic fix (the JetStream pattern): keep ONE
compiled decode step over a fixed `[slots]` batch alive and make
admission DATA, not shape —

- A new request prefills alone through the engine's existing
  `_prefill_sample` jit (one compile per power-of-two prompt bucket),
  then its KV rows are scattered into a free slot
  (`ContinuousEngine._insert`, slot index traced ⇒ one compile total).
- Every decode step advances ALL slots at once at per-slot cursors
  (`SlotState.length` is a vector where `DecodeState.length` is a
  scalar); a request exits the moment IT hits EOS or its own max_new,
  freeing the slot for the next arrival at the very next token.
- Freed slots keep computing garbage — static shapes are the TPU
  contract, and a masked-out row costs the same as the Batcher's dummy
  rows. Decode is HBM-bound (each step reads every weight once for the
  whole batch), so a wasted row is ~free; an idle CHIP between window
  groups is not.

Model math is shared with the engine via `engine.transformer_block`
(norms/projections/rotary/MLP injected with this module's per-row
scatter write + per-row masks), so the two serving paths cannot drift.
"""

from __future__ import annotations

import asyncio
import collections
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.ops.attention import dot_product_attention
from kubeflow_tpu.ops.norms import rms_norm
from kubeflow_tpu.ops.rotary import rope_frequencies
from kubeflow_tpu.serving.engine import (
    InferenceEngine,
    SamplingParams,
    transformer_block,
)


def pow2_ceil(n: int) -> int:
    """Smallest power of two >= n (n >= 1) — the group-size law shared
    by prefill padding and admission-scatter padding (one compiled
    program per pow2 size, not per novel count)."""
    p = 1
    while p < n:
        p *= 2
    return p


def bucket_pow2(n: int, cap: int) -> int:
    """Round up to a power of two (>= 16), capped — bounded compile
    shapes instead of one compile per novel length. Shared by the
    window Batcher and the continuous engine's prefill."""
    return min(max(pow2_ceil(n), 16), cap)


class SlotState:
    """Per-slot KV cache + cursors, a pytree (jit-carryable).

    The decode-batch analog of `engine.DecodeState`, with every cursor
    widened to a per-slot vector: slots sit at DIFFERENT sequence
    positions, which is the whole point of continuous batching.
    """

    def __init__(self, k, v, length, offset, pad, tok, aid=None):
        self.k = k            # [L, S, max_len, n_kv, hd]
        self.v = v
        self.length = length  # [S] int32 — filled cache slots per row
        self.offset = offset  # [S] int32 — left-pad count (rope shift)
        self.pad = pad        # [S, max_len] bool — padded cache cells
        self.tok = tok        # [S] int32 — last sampled token per row
        if aid is None:       # multi-LoRA adapter id (0 = plain base)
            aid = jnp.zeros(length.shape, jnp.int32)
        self.aid = aid        # [S] int32

    def tree_flatten(self):
        return (self.k, self.v, self.length, self.offset, self.pad,
                self.tok, self.aid), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    SlotState, SlotState.tree_flatten, SlotState.tree_unflatten
)


class ContinuousEngine:
    """Device half of continuous batching for one `InferenceEngine`.

    Three compiled programs, all shape-stable for the server's life:
    prefill (per prompt bucket — the engine's own `_prefill_jit`),
    `_insert` (slot index is traced data), and `_step` (one token for
    all S slots). The host half (`ContinuousBatcher`) owns admission,
    budgets, and EOS retirement — policies live in Python, tensors on
    device.
    """

    def __init__(self, engine: InferenceEngine, max_slots: int = 8,
                 prefill_chunk: int | None = None):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got "
                             f"{prefill_chunk}")
        self.engine = engine
        self.S = max_slots
        # Long-prompt admissions prefill in fixed slices (engine.
        # prefill_chunked): buckets become chunk MULTIPLES, so every
        # long prompt reuses the one [g, chunk] program instead of
        # minting a power-of-two bucket compile per length class.
        self.prefill_chunk = prefill_chunk
        # KV buffers dominate serving HBM: donate the old state so step
        # and insert update in place instead of holding two copies
        # (same policy as the Trainer's donated TrainState). The
        # adapter pack rides as an ARGUMENT, not a closure — closed-over
        # arrays bake into the lowered module as constants (see the
        # params note in engine.InferenceEngine.__init__).
        self._step_jit = jax.jit(self._step, donate_argnums=(2,),
                                 static_argnames=("steps",))
        self._insert_jit = jax.jit(self._insert, donate_argnums=(0,))
        self._insert_many_jit = jax.jit(self._insert_many,
                                        donate_argnums=(0,))

    # -- state ------------------------------------------------------------

    def init_slots(self) -> SlotState:
        cfg, ec = self.engine.cfg, self.engine.ec
        shape = (cfg.num_layers, self.S, ec.max_len,
                 cfg.num_kv_heads, cfg.head_dim)
        return SlotState(
            jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype),
            jnp.zeros((self.S,), jnp.int32),
            jnp.zeros((self.S,), jnp.int32),
            jnp.zeros((self.S, ec.max_len), bool),
            jnp.zeros((self.S,), jnp.int32),
        )

    # -- admission --------------------------------------------------------

    def bucket_for(self, n_tokens: int, max_new: int,
                   reserve: int = 0) -> int:
        """Prefill bucket for one request: power-of-two (or, past one
        chunk with chunked prefill enabled, the ceil chunk multiple),
        falling back to the EXACT length when the bucket plus this
        request's max_new would overrun the cache (bucket pads occupy
        cache cells, so a bucket the admission check never saw could
        silently clamp the last decode writes otherwise). `reserve` is
        cache already spoken for (a shared prefix's length)."""
        cap = self.engine.ec.max_len - reserve
        c = self.prefill_chunk
        if c and n_tokens > c:
            bc = -(-n_tokens // c) * c
            if bc + max_new <= cap:
                return bc
            return n_tokens  # exact single-shot; capacity-checked upstream
        b = bucket_pow2(n_tokens, max(cap - max_new, 0))
        return b if b >= n_tokens else n_tokens

    def prefill_batch(self, token_lists: list[list[int]], bucket: int,
                      samplings: list[dict[str, Any]], rng: jax.Array,
                      adapter_ids: list[int] | None = None,
                      prefix_state=None):
        """Prefill g prompts sharing one bucket in a single dispatch
        and sample each prompt's first token. Returns (batch-g
        DecodeState, first tokens [g], done [g]) ready for `insert`.
        Batching admissions matters under load: per-request prefill
        dispatch is the continuous design's other overhead tax next to
        per-token stepping. `adapter_ids` (multi-LoRA) selects each
        row's resident fine-tune; when the engine carries an
        adapter_pack the adapter arguments are ALWAYS passed (zeros by
        default) so warmup and traffic share one jit signature.
        `prefix_state` (a batch-1 `engine.precompute_prefix` result)
        seeds every row with shared-prefix KV: only the suffix
        prefills, and since `state.length` is traced data the SAME
        compiled prefill program serves prefixed and plain
        admissions."""
        eng = self.engine
        g = len(token_lists)
        arr = np.zeros((g, bucket), np.int32)
        mask = np.zeros((g, bucket), bool)
        for i, toks in enumerate(token_lists):
            arr[i, bucket - len(toks):] = toks
            mask[i, bucket - len(toks):] = True
        ec = eng.ec
        sp, rng = eng._resolve_sampling(
            np.asarray([s.get("temperature", ec.temperature)
                        for s in samplings], np.float32),
            np.asarray([s.get("top_k", ec.top_k)
                        for s in samplings], np.int64),
            np.asarray([s.get("top_p", ec.top_p)
                        for s in samplings], np.float32),
            rng, batch=g)
        adapters = ids = None
        if eng.adapter_pack is not None:
            adapters = eng.adapter_pack.blocks
            ids = jnp.asarray(adapter_ids if adapter_ids is not None
                              else [0] * g, jnp.int32)
        if prefix_state is None:
            state0 = eng.init_state(g)
        else:
            from kubeflow_tpu.serving.engine import DecodeState
            ps = prefix_state
            state0 = DecodeState(
                jnp.repeat(ps.k, g, axis=1), jnp.repeat(ps.v, g, axis=1),
                ps.length, jnp.repeat(ps.pad, g, axis=0),
                jnp.repeat(ps.offset, g, axis=0))
        c = self.prefill_chunk
        if c and bucket > c and bucket % c == 0:
            state, first, _, done, lps = eng.prefill_chunked(
                eng.params, jnp.asarray(arr), state0, rng,
                sp, jnp.asarray(mask), chunk=c,
                adapters=adapters, adapter_ids=ids)
        else:
            state, first, _, done, lps = eng._prefill_jit(
                eng.params, jnp.asarray(arr), state0, rng, sp,
                jnp.asarray(mask), adapters=adapters, adapter_ids=ids)
        return state, first, done, lps

    def prefill(self, tokens: list[int], max_new: int,
                sampling: dict[str, Any], rng: jax.Array):
        """Single-request admission (the g=1 case of prefill_batch)."""
        return self.prefill_batch(
            [tokens], self.bucket_for(len(tokens), max_new),
            [sampling], rng)

    def _insert(self, st: SlotState, slot, pstate, row, first, aid):
        """Scatter row `row` of a prefilled batch-g DecodeState into
        slot `slot`. All indices are traced — one compile per prefill
        batch size g serves every (slot, row, adapter) combination."""
        prow = jax.lax.dynamic_slice_in_dim(pstate.k, row, 1, axis=1)
        k = jax.lax.dynamic_update_slice(
            st.k, prow, (0, slot, 0, 0, 0))
        vrow = jax.lax.dynamic_slice_in_dim(pstate.v, row, 1, axis=1)
        v = jax.lax.dynamic_update_slice(
            st.v, vrow, (0, slot, 0, 0, 0))
        length = st.length.at[slot].set(pstate.length.astype(jnp.int32))
        offset = st.offset.at[slot].set(pstate.offset[row])
        pad = st.pad.at[slot].set(pstate.pad[row])
        tok = st.tok.at[slot].set(first[row])
        aid_v = st.aid.at[slot].set(aid)
        return SlotState(k, v, length, offset, pad, tok, aid_v)

    def insert(self, st: SlotState, slot: int, pstate, first,
               row: int = 0, aid: int = 0) -> SlotState:
        return self._insert_jit(st, jnp.asarray(slot, jnp.int32), pstate,
                                jnp.asarray(row, jnp.int32), first,
                                jnp.asarray(aid, jnp.int32))

    def _insert_many(self, st: SlotState, slots, pstate, rows, first,
                     aids):
        """A whole admission group's scatters in one program (a scan
        over `_insert`) — one device dispatch per group instead of one
        per request, the admission-side sibling of the group prefill."""

        def body(st, xs):
            slot, row, aid = xs
            return self._insert(st, slot, pstate, row, first, aid), None

        st, _ = jax.lax.scan(body, st, (slots, rows, aids))
        return st

    def insert_many(self, st: SlotState, slots: list[int], pstate,
                    rows: list[int], first,
                    aids: list[int] | None = None) -> SlotState:
        """Insert prefilled rows `rows` into `slots` in ONE dispatch.
        Compiles one cheap program per group SIZE (bounded by
        max_slots); the batcher's admission path uses this, the g=1
        `insert` stays for benches and direct callers."""
        n = len(slots)
        if len(rows) != n or (aids is not None and len(aids) != n):
            raise ValueError(
                f"insert_many: {n} slots vs {len(rows)} rows"
                + (f" vs {len(aids)} aids" if aids is not None else ""))
        return self._insert_many_jit(
            st, jnp.asarray(slots, jnp.int32), pstate,
            jnp.asarray(rows, jnp.int32), first,
            jnp.asarray(aids if aids is not None else [0] * n,
                        jnp.int32))

    def warmup(self, buckets=(16,), step_sizes=(1,)) -> int:
        """Compile a serving shape set ahead of traffic: prefill and
        insert for every power-of-two group size x REGISTERED prompt
        bucket, and the decode step for every chunk size. Warming
        turns first-arrival compile stalls into startup cost for the
        covered buckets; prompts that land in an UNREGISTERED bucket
        (longer than the warmed set, or an exact-length fallback)
        still compile on first arrival — cover the deployment's real
        prompt-length distribution via `buckets` rather than warming
        every bucket up to max_len (each [g, bucket] prefill compile
        costs real startup time on TPU). Returns the number of
        programs warmed."""
        eng = self.engine
        rng = jax.random.key(0)
        st = self.init_slots()
        sp = eng._resolve_sampling(
            np.zeros(self.S, np.float32), np.zeros(self.S, np.int64),
            np.ones(self.S, np.float32), rng, batch=self.S)[0]
        n = 0
        g = 1
        greedy = {"temperature": 0.0, "top_k": 0, "top_p": 1.0}
        while g <= self.S:
            for b in buckets:
                pstate, first, _, _ = self.prefill_batch(
                    [[0]] * g, b, [greedy] * g, rng)
                # admissions insert as a GROUP (insert_many), padded
                # to a power of two by the batcher — warming each pow2
                # size covers EVERY arrival count
                st = self.insert_many(
                    st, list(range(g)), pstate, list(range(g)), first)
                n += 2
            g *= 2
        for steps in step_sizes:
            st, _, _, rng = self.step(st, sp, rng, steps)
            n += 1
        return n

    # -- decode -----------------------------------------------------------

    def _decode_one(self, params, adapters, st: SlotState,
                    sp: SamplingParams, rng):
        """One decode token for ALL slots at per-slot cursors.

        Mirrors `engine._forward_cached`'s s=1 case with every scalar
        cursor vectorized: rope positions, causal masks and cache
        writes are per-row. Retired slots compute garbage (masked by
        the host); their cursors clamp at max_len so a long-idle slot
        can never scatter out of bounds.
        """
        eng = self.engine
        cfg, fam, ec = eng.cfg, eng.family, eng.ec
        S = self.S
        rng, sub = jax.random.split(rng)

        positions = st.length[:, None]                      # [S, 1]
        rope_positions = jnp.maximum(positions - st.offset[:, None], 0)
        inv_freq = rope_frequencies(cfg.head_dim, theta=cfg.rope_theta)
        kv_positions = jnp.broadcast_to(
            jnp.arange(ec.max_len, dtype=jnp.int32)[None, :],
            (S, ec.max_len))
        # causal q>=kv masking hides stale cells beyond each row's
        # cursor (a reused slot's old tail); pads are never attended.
        kv_valid = ~st.pad
        rows = jnp.arange(S)
        write_at = jnp.minimum(st.length, ec.max_len - 1)

        x = eng._embed(params, st.tok[:, None])

        # Cache as scan CARRY with in-place row scatters — same
        # rationale as engine._forward_cached: ys-stacked cache slices
        # rewrote the whole cache every token, doubling decode HBM
        # traffic. Here the per-step write is S rows per layer.
        def layer(carry, scanned):
            x, k_all, v_all = carry
            if adapters is None:
                p, li = scanned
                proj = None
            else:
                from kubeflow_tpu.serving.multilora import lora_proj
                p, ab, li = scanned
                proj = lora_proj(ab, st.aid,
                                 eng.adapter_pack.scaling, cfg)
            cell = {}

            def write_kv(k, v):
                k2 = k_all.at[li, rows, write_at].set(
                    k[:, 0].astype(k_all.dtype))
                v2 = v_all.at[li, rows, write_at].set(
                    v[:, 0].astype(v_all.dtype))
                cell["k"], cell["v"] = k2, v2
                return (jax.lax.dynamic_index_in_dim(
                            k2, li, 0, keepdims=False),
                        jax.lax.dynamic_index_in_dim(
                            v2, li, 0, keepdims=False))

            def attn(q, kc, vc):
                # cell index == token position here too (see
                # engine._forward_cached) — enables the fused decode
                # kernel on TPU
                return dot_product_attention(
                    q, kc, vc, positions, kv_positions,
                    causal=True, kv_mask=kv_valid,
                    window=getattr(cfg, "sliding_window", None),
                    contiguous_positions=True)

            x, _ = transformer_block(
                cfg, fam, p, x, rope_positions, inv_freq, write_kv,
                attn, proj)
            return (x, cell["k"], cell["v"]), None

        layer_ids = jnp.arange(cfg.num_layers, dtype=jnp.int32)
        xs = ((params["blocks"], layer_ids) if adapters is None
              else (params["blocks"], adapters, layer_ids))
        (x, k_new, v_new), _ = jax.lax.scan(
            layer, (x, st.k, st.v), xs)
        x = rms_norm(x, params["final_norm"], cfg.norm_eps)
        logits = eng._head(params, x[:, -1])
        nxt, lp = eng._sample(logits, sub, sp)
        st = SlotState(
            k_new, v_new,
            jnp.minimum(st.length + 1, ec.max_len),
            st.offset, st.pad, nxt.astype(jnp.int32), st.aid)
        return st, nxt, lp, rng

    def _step(self, params, adapters, st: SlotState, sp: SamplingParams,
              rng, *, steps: int):
        """`steps` decode tokens for all slots in ONE dispatch (a
        lax.scan over `_decode_one`) — chunking amortizes per-token
        host dispatch; admission happens between dispatches, so a
        queued request waits at most steps-1 tokens for a freed slot
        (the host's worker chooses steps). The token sequence is
        IDENTICAL for any chunking — the scan body is the single-step
        program, and retirement only changes what the host keeps,
        never what the device computes."""

        def body(carry, _):
            st, rng = carry
            st, tok, lp, rng = self._decode_one(params, adapters, st,
                                                sp, rng)
            return (st, rng), (tok, lp)

        (st, rng), (toks, lps) = jax.lax.scan(
            body, (st, rng), None, length=steps)
        return (st, jnp.moveaxis(toks, 0, 1),
                jnp.moveaxis(lps, 0, 1), rng)  # [S, steps] each

    def step(self, st: SlotState, sp: SamplingParams, rng,
             steps: int = 1):
        """-> (state, tokens [S, steps], logprobs [S, steps], rng)."""
        pack = self.engine.adapter_pack
        return self._step_jit(self.engine.params,
                              None if pack is None else pack.blocks,
                              st, sp, rng, steps=steps)


class Overloaded(RuntimeError):
    """Admission queue is full — callers should shed load (HTTP 429)."""


class _Slot:
    """Host-side record for one admitted request."""

    __slots__ = ("fut", "out", "lps", "max_new", "queue", "stop")

    def __init__(self, fut, max_new: int, queue, stop=()):
        self.fut = fut
        self.out: list[int] = []
        self.lps: list[float] = []  # chosen-token logprobs, out-aligned
        self.max_new = max_new
        self.queue = queue  # per-request token stream (None for oneshot)
        self.stop = stop    # token-id sequences that end generation


class ContinuousBatcher:
    """Host orchestrator: admission, per-request budgets, EOS
    retirement. API-compatible with server.Batcher (`submit`, `close`,
    `.calls`/`.requests` counters), so `create_serving_app` can swap it
    in without touching the handler.

    `.calls` counts decode steps and `.requests` admitted requests —
    `requests / calls` is NOT a mean batch here; the continuous
    analog `tokens_emitted / calls` (mean occupied slots per step) is
    exported as `.occupancy()`.
    """

    def __init__(self, engine: InferenceEngine, gpu_lock: asyncio.Lock,
                 *, max_slots: int = 8, chunk: int = 4,
                 prefill_chunk: int | None = None,
                 prefixes: dict[str, list[int]] | None = None,
                 max_pending: int = 256,
                 pipeline_depth: int | None = None,
                 window_ms: float = 0.0):
        # window_ms accepted (and ignored) for constructor parity with
        # Batcher: admission is per-token here, there is no window.
        del window_ms
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        # Dispatch-ahead depth: with depth 2 the worker queues the next
        # decode chunk while the previous one is still computing, so
        # host-side emit/retirement work overlaps device time instead
        # of idling the chip between chunks. The price is bounded
        # speculation: a slot that retires early (EOS/stop) may decode
        # up to (depth-1) x chunk garbage tokens before the host sees
        # it — the free-row cost model this engine is built on. Depth 1
        # restores strict per-chunk retirement.
        #
        # Default is backend-aware (measured, docs/perf-notes.md): on
        # an accelerator the overlap hides host time behind device
        # time; on CPU "device" compute shares the host's cores, so
        # speculation only adds waste (-6% on the loadtest A/B).
        if pipeline_depth is None:
            pipeline_depth = 2 if jax.default_backend() == "tpu" else 1
        if pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {pipeline_depth}")
        self.pipeline_depth = pipeline_depth
        # The worker decodes up to `chunk` tokens per dispatch (one
        # scanned program) — per-token host dispatch is the continuous
        # design's overhead tax. Admission happens between dispatches:
        # a queued request waits at most chunk-1 tokens at depth 1, up
        # to ~pipeline_depth x chunk under dispatch-ahead (a freed
        # slot is only observed once its chunk drains) — still far
        # under a window group's full-generation wait. Compiles stay
        # bounded: one program per steps value in [1, chunk].
        self.chunk = chunk
        self.cengine = ContinuousEngine(engine, max_slots,
                                        prefill_chunk=prefill_chunk)
        # Shared prefixes (system prompts): token lists registered at
        # construction; each computes its KV ONCE, lazily, on first use
        # (device work belongs under the gpu lock, not in __init__).
        self._prefixes = dict(prefixes or {})
        for pname, ptoks in self._prefixes.items():
            if not ptoks or len(ptoks) >= engine.ec.max_len:
                raise ValueError(
                    f"prefix {pname!r}: length {len(ptoks)} invalid "
                    f"for max_len {engine.ec.max_len}")
        self._prefix_states: dict[str, Any] = {}
        self.engine = engine
        self.gpu_lock = gpu_lock
        self.calls = 0            # decode steps (device invocations)
        self.requests = 0         # admitted requests
        self.tokens_emitted = 0
        self._pending: collections.deque = collections.deque()
        # Backpressure: an unbounded admission queue turns overload
        # into unbounded client latency AND unbounded host memory;
        # past this depth _enqueue raises Overloaded (HTTP 429).
        self.max_pending = max_pending
        self._wake = asyncio.Event()
        self._active: dict[int, _Slot] = {}
        self._free = list(range(max_slots))
        self._st: SlotState | None = None
        # greedy filler knobs on free slots: a sampled leftover would
        # drag an all-greedy step into the sampled branch's argsorts
        self._temp = np.zeros(max_slots, np.float32)
        self._topk = np.zeros(max_slots, np.int32)
        self._topp = np.ones(max_slots, np.float32)
        # SamplingParams rebuild (3 host->device transfers) only when a
        # knob actually changed — at steady occupancy every decode
        # chunk reuses the cached device arrays.
        self._sp_cache: SamplingParams | None = None
        self._sp_dirty = True
        self._rng = jax.random.key(
            int.from_bytes(os.urandom(8), "little") >> 1)
        self._worker: asyncio.Task | None = None
        self._closed = False

    def occupancy(self) -> float:
        return self.tokens_emitted / self.calls if self.calls else 0.0

    def warmup(self, buckets=None) -> int:
        """Blocking ahead-of-traffic compile of the full shape set
        (call before serving traffic; the app's on_startup hook does
        when create_serving_app(warmup=True)). With chunked prefill
        enabled the default bucket set includes a two-chunk prompt so
        the chunk-loop and tail programs warm too."""
        if buckets is None:
            buckets = [16]
            c = self.cengine.prefill_chunk
            if c and 2 * c <= self.engine.ec.max_len and 2 * c != 16:
                buckets.append(2 * c)
        return self.cengine.warmup(
            buckets=tuple(buckets), step_sizes=range(1, self.chunk + 1))

    # -- public API -------------------------------------------------------

    async def submit(self, tokens: list[int], max_new: int,
                     sampling: tuple, *, with_logprobs: bool = False):
        """Generate `max_new` tokens for one prompt; resolves when THIS
        request finishes (other slots keep decoding). The result is
        EOS-padded to exactly max_new — interchangeable with the window
        Batcher's fixed-shape contract (a request that hits EOS early
        stops COMPUTING early here; the pad is host-side) — with or
        without logprobs, so the response SHAPE never depends on the
        server's batcher mode. Requests with stop sequences return the
        TRIMMED output unpadded — stopping short is the ask.
        with_logprobs=True returns (tokens, logprobs); logprobs stays
        unpadded (entries exist only for computed tokens, through the
        first EOS)."""
        fut = self._enqueue(tokens, max_new, sampling, queue=None)
        out, lps = await fut
        eos = self.engine.ec.eos_token
        if eos is not None and len(out) < max_new \
                and not dict(sampling).get("stop"):
            out = out + [eos] * (max_new - len(out))
        return (out, lps) if with_logprobs else out

    def open_stream(self, tokens: list[int], max_new: int,
                    sampling: tuple):
        """Enqueue a streaming request NOW (admission errors — incl.
        Overloaded — raise here, synchronously) and return (fut,
        queue). The server calls this BEFORE sending SSE headers so
        overload is a clean 429, never a mid-stream abort."""
        q: asyncio.Queue = asyncio.Queue()
        return self._enqueue(tokens, max_new, sampling, queue=q), q

    async def stream(self, tokens: list[int], max_new: int,
                     sampling: tuple):
        """Async-iterate tokens as they decode (SSE feed). The stream
        ends at EOS or max_new; the caller owns trimming/decoding."""
        fut, q = self.open_stream(tokens, max_new, sampling)
        try:
            while True:
                item = await q.get()
                if item is None:
                    break
                yield item
            await fut  # surface admission/step errors after drain
        finally:
            # a consumer that stops iterating (client disconnect mid-
            # SSE) must release its slot — otherwise it decodes to
            # max_new into a dead queue and reconnect-loop clients
            # could pin every slot
            if not fut.done():
                fut.cancel()

    def _enqueue(self, tokens, max_new, sampling, *, queue):
        if self._closed:
            raise RuntimeError("batcher is shut down")
        if len(self._pending) >= self.max_pending:
            raise Overloaded(
                f"{len(self._pending)} requests already queued "
                f"(max_pending={self.max_pending})")
        cap = self.engine.ec.max_len
        if len(tokens) + max_new > cap:
            raise ValueError(
                f"prompt {len(tokens)} + max_new {max_new} exceeds "
                f"model max_len {cap}")
        sampling = dict(sampling)
        # multi-LoRA: the adapter name rides the sampling channel;
        # resolve (and reject unknowns) HERE, before a slot is spent
        adapter = sampling.get("adapter", "")
        pack = self.engine.adapter_pack
        if adapter and pack is None:
            raise ValueError(
                f"adapter {adapter!r} requested but no adapter pack "
                "is loaded on this engine")
        aid = pack.resolve(adapter) if pack else 0
        prefix = sampling.get("prefix", "")
        if prefix:
            if prefix not in self._prefixes:
                raise ValueError(
                    f"unknown prefix {prefix!r}; registered: "
                    f"{sorted(self._prefixes)}")
            if adapter:
                # prefix KV is computed with the BASE weights; reusing
                # it under an adapter would silently serve a hybrid
                raise ValueError(
                    "prefix does not compose with adapter (the shared "
                    "KV is base-model KV)")
            plen = len(self._prefixes[prefix])
            if plen + len(tokens) + max_new > cap:
                raise ValueError(
                    f"prefix {plen} + prompt {len(tokens)} + max_new "
                    f"{max_new} exceeds model max_len {cap}")
        if self._worker is None or self._worker.done():
            self._worker = asyncio.get_event_loop().create_task(
                self._run())
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending.append(
            (tokens, max_new, sampling, fut, queue, aid, prefix))
        self._wake.set()
        return fut

    # -- worker -----------------------------------------------------------

    def _sp(self) -> SamplingParams:
        if self._sp_dirty or self._sp_cache is None:
            self._sp_cache = SamplingParams(
                temperature=jnp.asarray(self._temp),
                top_k=jnp.asarray(self._topk),
                top_p=jnp.asarray(self._topp))
            self._sp_dirty = False
        return self._sp_cache

    def _release(self, slot: int) -> None:
        """Return a slot to the pool with greedy filler knobs (a
        leftover sampled temperature would drag all-greedy steps into
        the sampled branch's full-vocab argsorts)."""
        self._active.pop(slot, None)
        self._free.append(slot)
        self._temp[slot], self._topk[slot], self._topp[slot] = 0, 0, 1.0
        self._sp_dirty = True

    def _finish(self, slot: int, rec: _Slot) -> None:
        self._release(slot)
        if rec.queue is not None and not rec.fut.done():
            rec.queue.put_nowait(None)
        if not rec.fut.done():
            rec.fut.set_result((rec.out[:rec.max_new],
                                rec.lps[:rec.max_new]))

    def _emit(self, slot: int, rec: _Slot, token: int, lp: float, *,
              decode: bool = True) -> None:
        rec.out.append(token)
        rec.lps.append(lp)
        if decode:
            # admission-time first tokens (prefill) stay out of the
            # occupancy numerator — calls counts decode steps only
            self.tokens_emitted += 1
        if rec.queue is not None and not rec.fut.done():
            rec.queue.put_nowait(token)
        # stop sequences: the moment a sequence completes as the
        # output's suffix, trim it off (OpenAI semantics) and retire
        # the slot — the compute win the window batcher can't have
        # (its group runs to the group max regardless)
        for seq in rec.stop:
            n = len(seq)
            if n and len(rec.out) >= n and rec.out[-n:] == list(seq):
                rec.out = rec.out[:-n]
                rec.lps = rec.lps[:-n]
                self._finish(slot, rec)
                return
        eos = self.engine.ec.eos_token
        if len(rec.out) >= rec.max_new or (eos is not None
                                           and token == eos):
            self._finish(slot, rec)

    @staticmethod
    def _fail(fut, queue, exc) -> None:
        if queue is not None and not fut.done():
            queue.put_nowait(None)  # unblock a stream() consumer
        if not fut.done():
            fut.set_exception(exc)

    def _fail_all(self, exc) -> None:
        """Slot state is unrecoverable (donated buffers consumed by a
        failed dispatch): fail every active request deterministically
        and drop the state so the next admission re-inits."""
        for slot, rec in list(self._active.items()):
            self._release(slot)
            self._fail(rec.fut, rec.queue, exc)
        self._st = None

    async def _get_prefix_state(self, name: str):
        """Lazily compute (once) a registered prefix's KV."""
        if name in self._prefix_states:
            return self._prefix_states[name]
        loop = asyncio.get_event_loop()
        async with self.gpu_lock:
            st = await loop.run_in_executor(
                None, self.engine.precompute_prefix, self._prefixes[name])
        self._prefix_states[name] = st
        return st

    async def _admit_group(self, items: list) -> None:
        """Admit up to len(self._free) requests; items sharing a
        prefill bucket AND prefix share ONE prefill dispatch, and the
        group's slot scatters share one insert_many dispatch. A prefill
        failure fails its bucket group only; an insert failure fails
        its whole admit group (and every active request too when the
        donated buffers were consumed — see the except block)."""
        loop = asyncio.get_event_loop()
        groups: dict[tuple, list] = {}
        for item in items:
            prefix = item[6]
            reserve = len(self._prefixes[prefix]) if prefix else 0
            b = self.cengine.bucket_for(len(item[0]), item[1], reserve)
            groups.setdefault((b, prefix), []).append(item)
        for (b, prefix), group in groups.items():
            self._rng, sub = jax.random.split(self._rng)
            # pad the group to a power of two with greedy dummy rows:
            # prefill/insert shapes come from a SET of log2(max_slots)
            # sizes instead of one compile per novel group size (the
            # same row bucketing the window Batcher does)
            gp = pow2_ceil(len(group))
            lists = [it[0] for it in group] + [[0]] * (gp - len(group))
            samps = ([it[2] for it in group]
                     + [{"temperature": 0.0, "top_k": 0, "top_p": 1.0}]
                     * (gp - len(group)))
            ids = [it[5] for it in group] + [0] * (gp - len(group))

            def run_prefill(pstate0=None, lists=lists, b=b, samps=samps,
                            sub=sub, ids=ids):
                # host sync (np.asarray) INSIDE the executor: jax
                # dispatch is async, so syncing on the loop thread
                # would block the whole HTTP server for the device time
                pstate, first, _, lps = self.cengine.prefill_batch(
                    lists, b, samps, sub, ids, pstate0)
                return pstate, np.asarray(first), np.asarray(lps)

            try:
                pstate0 = (await self._get_prefix_state(prefix)
                           if prefix else None)
                async with self.gpu_lock:
                    pstate, firsts, flps = await loop.run_in_executor(
                        None, run_prefill, pstate0)
            except Exception as e:  # noqa: BLE001
                for _, _, _, fut, queue, _, _ in group:
                    self._fail(fut, queue, e)
                continue
            admit = [(row, item) for row, item in enumerate(group)
                     if not item[3].done()]  # skip cancelled-in-prefill
            if not admit:
                continue
            slots = [self._free.pop() for _ in admit]
            # Pad the scatter list to a power of two by REPEATING the
            # last (slot, row, aid) triple — re-inserting the same row
            # into the same slot is idempotent under the sequential
            # scan — so insert_many's compile set stays the warmed
            # log2(max_slots) sizes instead of one program per novel
            # arrival count (a mid-traffic TPU compile stalls every
            # active decode for seconds).
            pad = pow2_ceil(len(admit)) - len(admit)
            ins_slots = slots + [slots[-1]] * pad
            ins_rows = [r for r, _ in admit] + [admit[-1][0]] * pad
            ins_aids = ([it[5] for _, it in admit]
                        + [admit[-1][1][5]] * pad)
            try:
                if self._st is None:
                    self._st = self.cengine.init_slots()
                async with self.gpu_lock:
                    # ONE dispatch for the whole group's scatters (the
                    # admission-side sibling of the group prefill)
                    self._st = await loop.run_in_executor(
                        None, self.cengine.insert_many, self._st,
                        ins_slots, pstate, ins_rows, firsts, ins_aids)
            except Exception as e:  # noqa: BLE001
                self._free.extend(slots)
                for _, (_, _, _, fut, queue, _, _) in admit:
                    self._fail(fut, queue, e)
                # insert donates self._st: a failure that fired AFTER
                # dispatch leaves the old buffers consumed, and keeping
                # them would crash the NEXT decode step with a
                # confusing deleted-buffer error. A failure BEFORE
                # dispatch (bad shapes, host-side raise) leaves them
                # intact — then only this group dies. Distinguish the
                # two instead of guessing.
                if self._st is not None and any(
                        leaf.is_deleted() for leaf in
                        jax.tree.leaves(self._st)
                        if hasattr(leaf, "is_deleted")):
                    self._fail_all(RuntimeError(
                        f"slot state lost to donated insert: {e}"))
                continue
            for slot, (row, (tokens, max_new, sampling, fut, queue,
                             aid, _)) in zip(slots, admit):
                self.requests += 1
                rec = _Slot(fut, max_new, queue,
                            stop=tuple(tuple(s) for s in
                                       sampling.get("stop", ())))
                self._active[slot] = rec
                ec = self.engine.ec
                self._temp[slot] = sampling.get(
                    "temperature", ec.temperature)
                self._topk[slot] = sampling.get("top_k", ec.top_k)
                self._topp[slot] = sampling.get("top_p", ec.top_p)
                self._sp_dirty = True
                self._emit(slot, rec, int(firsts[row]),
                           float(flps[row]), decode=False)

    def _plan_steps(self, inflight) -> int:
        """Next chunk size: bounded by the longest remaining budget NOT
        already covered by in-flight chunks (per slot — a slot admitted
        after a dispatch isn't covered by it). 0 = nothing useful to
        dispatch ahead."""
        if not self._active:
            return 0
        best = 0
        for slot, rec in self._active.items():
            cover = sum(r["steps"] for r in inflight
                        if r["snap"].get(slot) is rec)
            best = max(best, rec.max_new - len(rec.out) - cover)
        return min(self.chunk, best) if best > 0 else 0

    async def _dispatch_chunk(self, loop, steps: int) -> dict:
        """Dispatch one decode chunk WITHOUT host sync: device arrays
        come back as futures, the device starts computing, and the
        host keeps working. The snapshot maps slot -> the _Slot RECORD
        active at dispatch: chunk tokens are valid only for that exact
        request. Identity (not slot id) matters — a slot freed by a
        retirement and re-admitted while this chunk is in flight
        carries a NEW request whose tokens start with the next
        dispatch; emitting this chunk's row into it would corrupt its
        stream (caught by test_stop_sequences_retire_slots_early)."""
        sp = self._sp()
        snap = dict(self._active)

        def run_step(st=self._st, sp=sp, steps=steps):
            # The rng chains THROUGH the compiled step (it splits
            # internally and returns the next key) — no host-side
            # jax.random.split dispatch per chunk.
            return self.cengine.step(st, sp, self._rng, steps)

        async with self.gpu_lock:
            st, toks, lps, rng = await loop.run_in_executor(
                None, run_step)
            self._st = st
            self._rng = rng
        self.calls += steps
        return {"toks": toks, "lps": lps, "steps": steps, "snap": snap}

    @staticmethod
    async def _sync_chunk(loop, rec: dict) -> None:
        """Force a chunk's results to host (in the executor: jax
        dispatch is async and syncing on the loop thread would block
        the whole HTTP server for the device time)."""
        rec["toks"], rec["lps"] = await loop.run_in_executor(
            None, lambda: (np.asarray(rec["toks"]),
                           np.asarray(rec["lps"])))

    def _process_chunk(self, rec: dict) -> None:
        toks = np.asarray(rec["toks"])
        lps = np.asarray(rec["lps"])
        for slot, srec in list(self._active.items()):
            if rec["snap"].get(slot) is not srec:
                continue  # admitted after dispatch: tokens not its own
            if srec.fut.done():  # caller cancelled mid-decode
                self._finish(slot, srec)
                continue
            for j in range(rec["steps"]):
                self._emit(slot, srec, int(toks[slot, j]),
                           float(lps[slot, j]))
                if slot not in self._active:
                    break  # retired mid-chunk; tail is trimmed

    async def _run(self) -> None:
        loop = asyncio.get_event_loop()
        # Chunks in flight on device, oldest first. Depth > 1 keeps the
        # chip busy while the host emits/retires the previous chunk.
        inflight: collections.deque = collections.deque()
        while True:
            if not self._active and not self._pending and not inflight:
                self._wake.clear()
                await self._wake.wait()
            # admit up to the free-slot count; dead futures are skipped
            if self._free and self._pending:
                take: list = []
                while self._pending and len(take) < len(self._free):
                    item = self._pending.popleft()
                    if not item[3].done():
                        take.append(item)
                if take:
                    await self._admit_group(take)
            try:
                # drain whatever already finished, without blocking.
                # INSIDE the try: an async-dispatched chunk that failed
                # on device reports ready and raises at materialization
                # — that must reach _fail_all like every other failure,
                # not kill the worker and hang every future.
                while inflight and inflight[0]["toks"].is_ready():
                    self._process_chunk(inflight.popleft())
                steps = self._plan_steps(inflight)
                if steps and len(inflight) < self.pipeline_depth:
                    inflight.append(
                        await self._dispatch_chunk(loop, steps))
                elif inflight:
                    # nothing useful to dispatch ahead: block on the
                    # oldest chunk and process it
                    head = inflight.popleft()
                    await self._sync_chunk(loop, head)
                    self._process_chunk(head)
            except Exception as e:  # noqa: BLE001 — fail active requests
                self._fail_all(e)  # donated buffers may be mid-flight
                inflight.clear()
                continue
            # let submissions/cancellations interleave between steps
            await asyncio.sleep(0)

    async def close(self) -> None:
        self._closed = True
        if self._worker is not None:
            self._worker.cancel()
            try:
                await self._worker
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        for slot, rec in list(self._active.items()):
            self._active.pop(slot, None)
            if rec.queue is not None and not rec.fut.done():
                rec.queue.put_nowait(None)
            if not rec.fut.done():
                rec.fut.set_exception(RuntimeError("server shutting down"))
        while self._pending:
            _, _, _, fut, queue, _, _ = self._pending.popleft()
            if queue is not None and not fut.done():
                queue.put_nowait(None)
            if not fut.done():
                fut.set_exception(RuntimeError("server shutting down"))
