"""Slot-based continuous batching: requests join and leave the decode
batch at token boundaries.

The reference's serving design is one-request-at-a-time TF-Serving
behind an HTTP proxy (`/root/reference/docs_dev/tf_serving.md:1-60`,
`testing/test_tf_serving.py`); its only batching lever is client-side.
The window `Batcher` (server.py) already improves on that, but a late
arrival still waits for the whole in-flight generation, and one short
request in a group waits for its longest neighbor.

This module is the TPU-idiomatic fix (the JetStream pattern): keep ONE
compiled decode step over a fixed `[slots]` batch alive and make
admission DATA, not shape —

- A new request prefills alone through the engine's existing
  `_prefill_sample` jit (one compile per power-of-two prompt bucket),
  then its KV rows are scattered into a free slot
  (`ContinuousEngine._insert`, slot index traced ⇒ one compile total).
- Every decode step advances ALL slots at once at per-slot cursors
  (`SlotState.length` is a vector where `DecodeState.length` is a
  scalar); a request exits the moment IT hits EOS or its own max_new,
  freeing the slot for the next arrival at the very next token.
- Freed slots keep computing garbage — static shapes are the TPU
  contract, and a masked-out row costs the same as the Batcher's dummy
  rows. Decode is HBM-bound (each step reads every weight once for the
  whole batch), so a wasted row is ~free; an idle CHIP between window
  groups is not.

Model math is shared with the engine via `engine.transformer_block`
(norms/projections/rotary/MLP injected with this module's per-row
scatter write + per-row masks), so the two serving paths cannot drift.

KV memory is PAGED (the vLLM/SGLang move): instead of a dense
[L, S, max_len] buffer, slots address a shared pool of fixed-size
blocks through per-slot block tables, decode gathers K/V through the
table (`ops.paged_attention`), and prefilled rows are compacted
(bucket left-pads stripped) as they're scattered into blocks — so a
block's content is a pure function of its token prefix. That canonical
form feeds the automatic RADIX PREFIX CACHE (serving/paged.py): prompt
blocks are indexed by token prefix at admission and donated back to
the tree at retirement, and a new request reuses every cached cell it
shares with ANY earlier one, prefilling only its suffix. The one-shot
`InferenceEngine` keeps its dense cache — batch-1 generate has no
sharing to exploit.
"""

from __future__ import annotations

import asyncio
import collections
import os
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.ops.attention import (
    dot_product_attention,
    paged_attention,
    paged_prefill_attention,
    resolve_paged_attention_impl,
    resolve_paged_prefill_impl,
)
from kubeflow_tpu.ops.norms import rms_norm
from kubeflow_tpu.ops.rotary import rope_frequencies
from kubeflow_tpu.serving.engine import (
    DecodeState,
    InferenceEngine,
    SamplingParams,
    transformer_block,
)
from kubeflow_tpu.obs.cachestats import CacheLedger
from kubeflow_tpu.obs.profiling import CompileWatch, PhaseProfiler
from kubeflow_tpu.obs.timeline import RequestTimeline, TimelineStore
from kubeflow_tpu.serving import migration
from kubeflow_tpu.serving.paged import (BlockPool, HostSpillTier,
                                        RadixPrefixCache)
from kubeflow_tpu.serving.speculative import _dist, _draw
from kubeflow_tpu.tenancy.ledger import TenantLedger
from kubeflow_tpu.tenancy.scheduler import FairShareQueue, ReqMeta


def pow2_ceil(n: int) -> int:
    """Smallest power of two >= n (n >= 1) — the group-size law shared
    by prefill padding and admission-scatter padding (one compiled
    program per pow2 size, not per novel count)."""
    p = 1
    while p < n:
        p *= 2
    return p


def bucket_pow2(n: int, cap: int) -> int:
    """Round up to a power of two (>= 16), capped — bounded compile
    shapes instead of one compile per novel length. Shared by the
    window Batcher and the continuous engine's prefill."""
    return min(max(pow2_ceil(n), 16), cap)


class SlotState:
    """Per-slot KV cache + cursors, a pytree (jit-carryable).

    The decode-batch analog of `engine.DecodeState`, with every cursor
    widened to a per-slot vector: slots sit at DIFFERENT sequence
    positions, which is the whole point of continuous batching.
    """

    def __init__(self, k, v, length, offset, pad, tok, aid=None,
                 block_table=None, frozen=None):
        self.k = k            # [L, num_blocks, block_size, n_kv, hd]
        self.v = v            # (paged pool; block 0 is the trash block)
        self.length = length  # [S] int32 — filled cache cells per row
        self.offset = offset  # [S] int32 — left-pad count (rope shift)
        self.pad = pad        # [S, W] bool — padded cache cells
        self.tok = tok        # [S] int32 — last sampled token per row
        if aid is None:       # multi-LoRA adapter id (0 = plain base)
            aid = jnp.zeros(length.shape, jnp.int32)
        self.aid = aid        # [S] int32
        # [S, blocks_per_slot] int32 — physical block per logical block.
        # Cell c of slot s lives at pool[:, table[s, c // bs], c % bs]:
        # the paged indirection that lets slots share prefix blocks and
        # frees HBM accounting from the dense S * max_len worst case.
        self.block_table = block_table
        # [S] bool — mid-chunked-prefill rows. A frozen row rides along
        # in decode/speculative dispatches but is fully masked there:
        # its KV writes are routed to the trash block and its cursors
        # (length, tok) never move — only `append_rows` advances it.
        if frozen is None:
            frozen = jnp.zeros(length.shape, bool)
        self.frozen = frozen

    def tree_flatten(self):
        return (self.k, self.v, self.length, self.offset, self.pad,
                self.tok, self.aid, self.block_table, self.frozen), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    SlotState, SlotState.tree_flatten, SlotState.tree_unflatten
)


class DraftSlots:
    """Per-slot DRAFT-model KV cache for continuous speculative
    decoding, a pytree (jit-carryable).

    The draft cache stays DENSE ([L, S, draft_max_len, n_kv, hd]) where
    the target cache is paged: the draft model is small by design, so
    its cache is a rounding error next to the target pool, and paging
    it would add a second block table to every rollback. Rows are
    compacted like the target's (cell index == logical position, offset
    0), and `length` tracks the TARGET row's cursor exactly — after
    every speculative round both caches agree on how many tokens are
    committed, which is the whole rollback contract."""

    def __init__(self, k, v, length):
        self.k = k            # [L, S, W_draft, n_kv_d, hd_d]
        self.v = v
        self.length = length  # [S] int32 — committed cells per row

    def tree_flatten(self):
        return (self.k, self.v, self.length), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    DraftSlots, DraftSlots.tree_flatten, DraftSlots.tree_unflatten
)


class ContinuousEngine:
    """Device half of continuous batching for one `InferenceEngine`.

    Three compiled programs, all shape-stable for the server's life:
    prefill (per prompt bucket — the engine's own `_prefill_jit`),
    `_insert` (slot index is traced data), and `_step` (one token for
    all S slots). The host half (`ContinuousBatcher`) owns admission,
    budgets, and EOS retirement — policies live in Python, tensors on
    device.
    """

    def __init__(self, engine: InferenceEngine, max_slots: int = 8,
                 prefill_chunk: int | None = None,
                 block_size: int = 64, num_blocks: int | None = None,
                 paged_attention_impl: str = "auto",
                 pool: BlockPool | None = None,
                 draft: InferenceEngine | None = None):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if draft is not None:
            # continuous speculative decoding (ISSUE 9): the accept
            # rule compares draft and target distributions tokenwise,
            # and the draft cache row mirrors the target row cursor
            if draft.cfg.vocab_size != engine.cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {draft.cfg.vocab_size} != target "
                    f"vocab {engine.cfg.vocab_size}")
            if draft.ec.max_len < engine.ec.max_len:
                raise ValueError(
                    f"draft max_len {draft.ec.max_len} < target "
                    f"max_len {engine.ec.max_len}: the draft cache row "
                    "must cover every target cursor position")
            if engine.adapter_pack is not None:
                raise ValueError(
                    "speculative decoding does not compose with a "
                    "multi-LoRA adapter pack (the verify pass would "
                    "score base-model logits against adapter rows)")
        self.draft = draft
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got "
                             f"{prefill_chunk}")
        if block_size < 2 or block_size & (block_size - 1):
            raise ValueError(
                f"block_size must be a power of two >= 2, got {block_size}")
        # Resolve the attention impl ONCE at construction (validates
        # the name too): the decode closure passes it through every
        # trace, and serving labels its metrics with the resolved
        # value. "auto" = pallas on TPU, xla elsewhere.
        self.paged_attention_impl = paged_attention_impl
        self.attention_impl = resolve_paged_attention_impl(
            paged_attention_impl)
        # chunked-prefill / draft-verify writes go through the fused
        # prefill/append op — same knob, separately resolved (the
        # prefill kernel has its own availability probe)
        self.prefill_impl = resolve_paged_prefill_impl(
            paged_attention_impl)
        self.engine = engine
        self.S = max_slots
        # Paged KV geometry. The cache is a POOL of fixed-size blocks
        # [L, num_blocks, block_size, n_kv, hd] plus a per-slot block
        # table; block 0 is the reserved trash block (unallocated table
        # entries point there, so a retired-but-unreset slot's garbage
        # writes land harmlessly). The default pool is the dense
        # equivalent (every slot can hold max_len) — shrink num_blocks
        # to cap KV HBM below S * max_len when real requests are short.
        self.block_size = block_size
        self.blocks_per_slot = -(-engine.ec.max_len // block_size)
        self.kv_width = self.blocks_per_slot * block_size
        if num_blocks is None:
            num_blocks = (pool.num_blocks if pool is not None
                          else 1 + max_slots * self.blocks_per_slot)
        if num_blocks < 1 + self.blocks_per_slot:
            raise ValueError(
                f"num_blocks {num_blocks} < {1 + self.blocks_per_slot} "
                f"(trash + one slot's worth at max_len "
                f"{engine.ec.max_len} / block_size {block_size}): a "
                "single max-length request could never be admitted")
        self.num_blocks = num_blocks
        if pool is not None:
            # A caller-supplied pool must agree with the geometry
            # `ops.paged_attention` will see (tables/masks are laid out
            # in `blocks_per_slot * block_size` cells over a
            # `[num_blocks, block_size]` pool). A mismatch used to
            # surface only as an opaque gather/reshape shape error deep
            # inside jit on the first decode step.
            if (pool.block_size != block_size
                    or pool.num_blocks != num_blocks):
                raise ValueError(
                    f"BlockPool geometry (num_blocks="
                    f"{pool.num_blocks}, block_size={pool.block_size}) "
                    f"does not match the engine's paged-attention "
                    f"layout (num_blocks={num_blocks}, block_size="
                    f"{block_size}, blocks_per_slot="
                    f"{self.blocks_per_slot}): block tables and KV "
                    f"masks would disagree with the pool shape")
            self.pool = pool
        else:
            self.pool = BlockPool(num_blocks, block_size)
        # Long-prompt admissions prefill in fixed slices (engine.
        # prefill_chunked): buckets become chunk MULTIPLES, so every
        # long prompt reuses the one [g, chunk] program instead of
        # minting a power-of-two bucket compile per length class.
        self.prefill_chunk = prefill_chunk
        # KV buffers dominate serving HBM: donate the old state so step
        # and insert update in place instead of holding two copies
        # (same policy as the Trainer's donated TrainState). The
        # adapter pack rides as an ARGUMENT, not a closure — closed-over
        # arrays bake into the lowered module as constants (see the
        # params note in engine.InferenceEngine.__init__).
        self._step_jit = jax.jit(self._step, donate_argnums=(2,),
                                 static_argnames=("steps",))
        self._insert_jit = jax.jit(self._insert, donate_argnums=(0,))
        self._insert_many_jit = jax.jit(self._insert_many,
                                        donate_argnums=(0,))
        self._gather_seed_jit = jax.jit(self._gather_seed)
        self._reset_jit = jax.jit(self._reset_slots, donate_argnums=(0,))
        # migration (serving/migration.py): export gathers block
        # payloads without touching the state; import scatters them in
        # place (donated, like insert/step — KV dominates serving HBM)
        self._export_jit = jax.jit(self._export_blocks)
        self._import_jit = jax.jit(self._import_blocks,
                                   donate_argnums=(0,))
        # chunked prefill (ISSUE 9): adopt points a frozen slot at its
        # planned blocks, copy_cells seeds a partial CoW block, and
        # append_rows feeds budget-size prompt slices through the fused
        # prefill/append path between decode chunks
        self._append_jit = jax.jit(self._append_rows,
                                   donate_argnums=(2,))
        self._adopt_jit = jax.jit(self._adopt, donate_argnums=(0,))
        self._copy_cells_jit = jax.jit(self._copy_cells,
                                       donate_argnums=(0,))
        if draft is not None:
            self._spec_draft_jit = jax.jit(
                self._spec_draft, donate_argnums=(1,),
                static_argnames=("gamma",))
            self._spec_verify_jit = jax.jit(
                self._spec_verify, donate_argnums=(2, 3),
                static_argnames=("gamma",))
            self._dinsert_jit = jax.jit(self._draft_insert,
                                        donate_argnums=(0,))

    # -- state ------------------------------------------------------------

    def init_slots(self) -> SlotState:
        cfg = self.engine.cfg
        shape = (cfg.num_layers, self.num_blocks, self.block_size,
                 cfg.num_kv_heads, cfg.head_dim)
        return SlotState(
            jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype),
            jnp.zeros((self.S,), jnp.int32),
            jnp.zeros((self.S,), jnp.int32),
            jnp.zeros((self.S, self.kv_width), bool),
            jnp.zeros((self.S,), jnp.int32),
            None,
            jnp.zeros((self.S, self.blocks_per_slot), jnp.int32),
        )

    def kv_block_bytes(self) -> int:
        """HBM bytes one pool block holds (K+V, all layers) — the unit
        `serving_kv_blocks_in_use` and bench_decode_paged report in."""
        cfg = self.engine.cfg
        itemsize = jnp.dtype(cfg.dtype).itemsize
        return (2 * cfg.num_layers * self.block_size
                * cfg.num_kv_heads * cfg.head_dim * itemsize)

    # -- admission --------------------------------------------------------

    def bucket_for(self, n_tokens: int, max_new: int,
                   reserve: int = 0) -> int:
        """Prefill bucket for one request: power-of-two (or, past one
        chunk with chunked prefill enabled, the ceil chunk multiple),
        falling back to the EXACT length when the bucket plus this
        request's max_new would overrun the cache (bucket pads occupy
        cache cells, so a bucket the admission check never saw could
        silently clamp the last decode writes otherwise). `reserve` is
        cache already spoken for (a shared prefix's length)."""
        cap = self.engine.ec.max_len - reserve
        c = self.prefill_chunk
        if c and n_tokens > c:
            bc = -(-n_tokens // c) * c
            if bc + max_new <= cap:
                return bc
            return n_tokens  # exact single-shot; capacity-checked upstream
        b = bucket_pow2(n_tokens, max(cap - max_new, 0))
        return b if b >= n_tokens else n_tokens

    def prefill_batch(self, token_lists: list[list[int]], bucket: int,
                      samplings: list[dict[str, Any]], rng: jax.Array,
                      adapter_ids: list[int] | None = None,
                      prefix_state=None):
        """Prefill g prompts sharing one bucket in a single dispatch
        and sample each prompt's first token. Returns (batch-g
        DecodeState, first tokens [g], done [g]) ready for `insert`.
        Batching admissions matters under load: per-request prefill
        dispatch is the continuous design's other overhead tax next to
        per-token stepping. `adapter_ids` (multi-LoRA) selects each
        row's resident fine-tune; when the engine carries an
        adapter_pack the adapter arguments are ALWAYS passed (zeros by
        default) so warmup and traffic share one jit signature.
        `prefix_state` (a batch-1 `engine.precompute_prefix` result)
        seeds every row with shared-prefix KV: only the suffix
        prefills, and since `state.length` is traced data the SAME
        compiled prefill program serves prefixed and plain
        admissions."""
        eng = self.engine
        g = len(token_lists)
        arr = np.zeros((g, bucket), np.int32)
        mask = np.zeros((g, bucket), bool)
        for i, toks in enumerate(token_lists):
            arr[i, bucket - len(toks):] = toks
            mask[i, bucket - len(toks):] = True
        ec = eng.ec
        sp, rng = eng._resolve_sampling(
            np.asarray([s.get("temperature", ec.temperature)
                        for s in samplings], np.float32),
            np.asarray([s.get("top_k", ec.top_k)
                        for s in samplings], np.int64),
            np.asarray([s.get("top_p", ec.top_p)
                        for s in samplings], np.float32),
            rng, batch=g)
        adapters = ids = None
        if eng.adapter_pack is not None:
            adapters = eng.adapter_pack.blocks
            ids = jnp.asarray(adapter_ids if adapter_ids is not None
                              else [0] * g, jnp.int32)
        if prefix_state is None:
            state0 = eng.init_state(g)
        elif prefix_state.k.shape[1] == g:
            # already batch-g (a gather_seed radix-cache seed)
            state0 = prefix_state
        else:
            ps = prefix_state
            state0 = DecodeState(
                jnp.repeat(ps.k, g, axis=1), jnp.repeat(ps.v, g, axis=1),
                ps.length, jnp.repeat(ps.pad, g, axis=0),
                jnp.repeat(ps.offset, g, axis=0))
        c = self.prefill_chunk
        if c and bucket > c and bucket % c == 0:
            state, first, _, done, lps = eng.prefill_chunked(
                eng.params, jnp.asarray(arr), state0, rng,
                sp, jnp.asarray(mask), chunk=c,
                adapters=adapters, adapter_ids=ids)
        else:
            state, first, _, done, lps = eng._prefill_jit(
                eng.params, jnp.asarray(arr), state0, rng, sp,
                jnp.asarray(mask), adapters=adapters, adapter_ids=ids)
        return state, first, done, lps

    def prefill(self, tokens: list[int], max_new: int,
                sampling: dict[str, Any], rng: jax.Array):
        """Single-request admission (the g=1 case of prefill_batch)."""
        return self.prefill_batch(
            [tokens], self.bucket_for(len(tokens), max_new),
            [sampling], rng)

    def _insert(self, st: SlotState, slot, pstate, row, first, aid,
                table, seed_len):
        """Scatter row `row` of a prefilled batch-g DecodeState into
        the pool blocks listed in `table`, and point slot `slot` at
        them. All indices are traced — one compile per prefill batch
        size g serves every (slot, row, adapter, table) combination.

        The row is COMPACTED on the way in: prefill left-pads prompts
        to their bucket, so cells [seed_len, seed_len + npad) of the
        dense row are padding. The gather below drops them, making
        pool blocks a pure function of the token prefix — cell index
        == logical position, offset 0, no pads. That canonical form is
        what lets the radix tree share blocks across requests whose
        prompts merely share tokens (their bucket pads differ).

        The write covers EVERY cell of every block in `table` — unused
        tail entries must be the trash block (0). Fully overwriting the
        table is a safety invariant: a freed block may still receive
        in-flight garbage writes from its previous slot's last decode
        chunk, and this insert is ordered after that chunk by the state
        donation chain, so it always lands last.
        """
        eng = self.engine
        ec = eng.ec
        L = eng.cfg.num_layers
        bs, mb, w = self.block_size, self.blocks_per_slot, self.kv_width
        npad = pstate.offset[row].astype(jnp.int32)
        j = jnp.arange(w, dtype=jnp.int32)
        src = jnp.minimum(jnp.where(j < seed_len, j, j + npad),
                          ec.max_len - 1)
        prow_k = jax.lax.dynamic_slice_in_dim(pstate.k, row, 1, axis=1)
        prow_v = jax.lax.dynamic_slice_in_dim(pstate.v, row, 1, axis=1)
        ck = jnp.take(prow_k[:, 0], src, axis=1)  # [L, w, n_kv, hd]
        cv = jnp.take(prow_v[:, 0], src, axis=1)
        ck = ck.reshape(L, mb, bs, *ck.shape[2:])
        cv = cv.reshape(L, mb, bs, *cv.shape[2:])
        k = st.k.at[:, table].set(ck.astype(st.k.dtype))
        v = st.v.at[:, table].set(cv.astype(st.v.dtype))
        length = st.length.at[slot].set(
            (pstate.length - npad).astype(jnp.int32))
        offset = st.offset.at[slot].set(0)
        pad = st.pad.at[slot].set(False)
        tok = st.tok.at[slot].set(first[row])
        aid_v = st.aid.at[slot].set(aid)
        bt = st.block_table.at[slot].set(table)
        frozen = st.frozen.at[slot].set(False)
        return SlotState(k, v, length, offset, pad, tok, aid_v, bt,
                         frozen)

    def _auto_table(self, slot: int) -> np.ndarray:
        """Canonical block table for engine-managed allocation (direct
        `insert` callers: benches, tests, warmup): slot s owns blocks
        [1 + s*MB, 1 + (s+1)*MB), the dense-equivalent layout. With a
        pool smaller than the default the mapping wraps (aliases) —
        fine for warmup (content is throwaway) but direct callers who
        need correctness should keep the default pool size or pass
        explicit tables. The batcher always passes explicit tables."""
        usable = self.num_blocks - 1
        base = slot * self.blocks_per_slot
        return np.asarray(
            [1 + (base + j) % usable
             for j in range(self.blocks_per_slot)], np.int32)

    def insert(self, st: SlotState, slot: int, pstate, first,
               row: int = 0, aid: int = 0, *, table=None,
               seed_len: int = 0) -> SlotState:
        if table is None:
            table = self._auto_table(slot)
        return self._insert_jit(st, jnp.asarray(slot, jnp.int32), pstate,
                                jnp.asarray(row, jnp.int32), first,
                                jnp.asarray(aid, jnp.int32),
                                jnp.asarray(table, jnp.int32),
                                jnp.asarray(seed_len, jnp.int32))

    def _insert_many(self, st: SlotState, slots, pstate, rows, first,
                     aids, tables, seed_lens):
        """A whole admission group's scatters in one program (a scan
        over `_insert`) — one device dispatch per group instead of one
        per request, the admission-side sibling of the group prefill."""

        def body(st, xs):
            slot, row, aid, table, seed_len = xs
            return self._insert(st, slot, pstate, row, first, aid,
                                table, seed_len), None

        st, _ = jax.lax.scan(body, st,
                             (slots, rows, aids, tables, seed_lens))
        return st

    def insert_many(self, st: SlotState, slots: list[int], pstate,
                    rows: list[int], first,
                    aids: list[int] | None = None, *, tables=None,
                    seed_lens: list[int] | None = None) -> SlotState:
        """Insert prefilled rows `rows` into `slots` in ONE dispatch.
        Compiles one cheap program per group SIZE (bounded by
        max_slots); the batcher's admission path uses this, the g=1
        `insert` stays for benches and direct callers. `tables` ([n,
        blocks_per_slot] physical block ids, trash-padded) and
        `seed_lens` (cells [0, seed_len) of each row are an already-
        compact shared-prefix seed) default to the engine-managed
        dense-equivalent layout with no seed."""
        n = len(slots)
        if len(rows) != n or (aids is not None and len(aids) != n):
            raise ValueError(
                f"insert_many: {n} slots vs {len(rows)} rows"
                + (f" vs {len(aids)} aids" if aids is not None else ""))
        if tables is None:
            tables = np.stack([self._auto_table(s) for s in slots])
        if seed_lens is None:
            seed_lens = [0] * n
        return self._insert_many_jit(
            st, jnp.asarray(slots, jnp.int32), pstate,
            jnp.asarray(rows, jnp.int32), first,
            jnp.asarray(aids if aids is not None else [0] * n,
                        jnp.int32),
            jnp.asarray(tables, jnp.int32),
            jnp.asarray(seed_lens, jnp.int32))

    def _gather_seed(self, k_pool, v_pool, chains, m):
        """Assemble a batch-g prefill seed (`DecodeState`) from cached
        pool blocks: row i's cells [0, m) are read through block chain
        `chains[i]` (trash-padded past ceil(m / block_size)). Offset 0
        and no pads by the blocks' canonical-form invariant."""
        g = chains.shape[0]
        max_len = self.engine.ec.max_len
        k = k_pool[:, chains]  # [L, g, MB, bs, n_kv, hd]
        v = v_pool[:, chains]
        k = k.reshape(*k.shape[:2], self.kv_width, *k.shape[4:])
        v = v.reshape(*v.shape[:2], self.kv_width, *v.shape[4:])
        return DecodeState(
            k[:, :, :max_len], v[:, :, :max_len],
            m.astype(jnp.int32),
            jnp.zeros((g, max_len), bool),
            jnp.zeros((g,), jnp.int32))

    def gather_seed(self, st: SlotState, chains, m: int) -> DecodeState:
        return self._gather_seed_jit(st.k, st.v,
                                     jnp.asarray(chains, jnp.int32),
                                     jnp.asarray(m, jnp.int32))

    def _reset_slots(self, st: SlotState, slots):
        """Point retired slots back at the trash block and zero their
        cursors. Ordered after the slots' last in-flight decode chunk
        by the donation chain, this guarantees a freed block sees no
        further writes once it's re-allocated (or adopted by the radix
        tree) — the paged design's one cross-slot hazard."""
        bt = st.block_table.at[slots].set(0)
        length = st.length.at[slots].set(0)
        offset = st.offset.at[slots].set(0)
        pad = st.pad.at[slots].set(False)
        frozen = st.frozen.at[slots].set(False)
        return SlotState(st.k, st.v, length, offset, pad, st.tok,
                         st.aid, bt, frozen)

    def reset_slots(self, st: SlotState, slots: list[int]) -> SlotState:
        """Host entry: pads the slot list to a power of two by
        repeating (idempotent) so compiles stay bounded."""
        n = pow2_ceil(len(slots))
        padded = list(slots) + [slots[-1]] * (n - len(slots))
        return self._reset_jit(st, jnp.asarray(padded, jnp.int32))

    # -- migration --------------------------------------------------------

    def _export_blocks(self, k_pool, v_pool, ids):
        return k_pool[:, ids], v_pool[:, ids]

    def export_blocks(self, st: SlotState, block_ids):
        """Host copies of the K/V payloads held by physical blocks
        `block_ids` — `(k, v)`, each `[L, n, block_size, n_kv, hd]`
        numpy, in id order. The transfer unit of live sequence
        migration (serving/migration.py): one device gather + one
        transfer covers an arbitrary id list (one cheap compile per
        list LENGTH). Does not touch the state."""
        ids = jnp.asarray(list(block_ids), jnp.int32)
        k, v = self._export_jit(st.k, st.v, ids)
        return np.asarray(k), np.asarray(v)

    def _import_blocks(self, st: SlotState, ids, k, v):
        kp = st.k.at[:, ids].set(k.astype(st.k.dtype))
        vp = st.v.at[:, ids].set(v.astype(st.v.dtype))
        return SlotState(kp, vp, st.length, st.offset, st.pad, st.tok,
                         st.aid, st.block_table, st.frozen)

    def import_blocks(self, st: SlotState, block_ids, k, v) -> SlotState:
        """Scatter migrated block payloads into locally-allocated
        blocks `block_ids` (donates `st` — in-place pool update, same
        policy as insert/step). Payloads keep the exporter's canonical
        form (cell index == logical token position), so imported
        blocks are immediately radix-shareable. Raises ValueError when
        the payload shape disagrees with this pool's block geometry —
        a silent shape coercion here would corrupt every sequence that
        later seeds from these blocks."""
        cfg = self.engine.cfg
        want = (cfg.num_layers, len(list(block_ids)), self.block_size,
                cfg.num_kv_heads, cfg.head_dim)
        k = np.asarray(k)
        v = np.asarray(v)
        if tuple(k.shape) != want or tuple(v.shape) != want:
            raise ValueError(
                f"import_blocks: payload shape k={tuple(k.shape)} "
                f"v={tuple(v.shape)} does not match pool block "
                f"geometry [L, n, block_size, n_kv, hd] = {want}")
        return self._import_jit(st,
                                jnp.asarray(list(block_ids), jnp.int32),
                                jnp.asarray(k), jnp.asarray(v))

    def warmup(self, buckets=(16,), step_sizes=(1,)) -> int:
        """Compile a serving shape set ahead of traffic: prefill and
        insert for every power-of-two group size x REGISTERED prompt
        bucket, and the decode step for every chunk size. Warming
        turns first-arrival compile stalls into startup cost for the
        covered buckets; prompts that land in an UNREGISTERED bucket
        (longer than the warmed set, or an exact-length fallback)
        still compile on first arrival — cover the deployment's real
        prompt-length distribution via `buckets` rather than warming
        every bucket up to max_len (each [g, bucket] prefill compile
        costs real startup time on TPU). Returns the number of
        programs warmed."""
        eng = self.engine
        rng = jax.random.key(0)
        st = self.init_slots()
        sp = eng._resolve_sampling(
            np.zeros(self.S, np.float32), np.zeros(self.S, np.int64),
            np.ones(self.S, np.float32), rng, batch=self.S)[0]
        n = 0
        g = 1
        greedy = {"temperature": 0.0, "top_k": 0, "top_p": 1.0}
        while g <= self.S:
            for b in buckets:
                pstate, first, _, _ = self.prefill_batch(
                    [[0]] * g, b, [greedy] * g, rng)
                # admissions insert as a GROUP (insert_many), padded
                # to a power of two by the batcher — warming each pow2
                # size covers EVERY arrival count
                st = self.insert_many(
                    st, list(range(g)), pstate, list(range(g)), first)
                n += 2
            g *= 2
        for steps in step_sizes:
            st, _, _, rng = self.step(st, sp, rng, steps)
            n += 1
        # the batcher resets retired slots' block tables between
        # chunks — warm that program too (pow2-padded, so size 1
        # covers every retirement count)
        st = self.reset_slots(st, [0])
        return n + 1

    # -- decode -----------------------------------------------------------

    def _decode_one(self, params, adapters, st: SlotState,
                    sp: SamplingParams, rng):
        """One decode token for ALL slots at per-slot cursors.

        Mirrors `engine._forward_cached`'s s=1 case with every scalar
        cursor vectorized: rope positions, causal masks and cache
        writes are per-row. Retired slots compute garbage (masked by
        the host); their cursors clamp at max_len so a long-idle slot
        can never scatter out of bounds.
        """
        eng = self.engine
        cfg, fam, ec = eng.cfg, eng.family, eng.ec
        S = self.S
        rng, sub = jax.random.split(rng)

        positions = st.length[:, None]                      # [S, 1]
        rope_positions = jnp.maximum(positions - st.offset[:, None], 0)
        inv_freq = rope_frequencies(cfg.head_dim, theta=cfg.rope_theta)
        kv_positions = jnp.broadcast_to(
            jnp.arange(self.kv_width, dtype=jnp.int32)[None, :],
            (S, self.kv_width))
        # causal q>=kv masking hides stale cells beyond each row's
        # cursor (a reused slot's old tail); pads are never attended.
        kv_valid = ~st.pad
        write_at = jnp.minimum(st.length, ec.max_len - 1)
        # paged write coordinates: logical cell -> (physical block,
        # offset) through each row's block table. Frozen rows (mid
        # chunked prefill) write to the trash block instead — a decode
        # step must never touch cells `append_rows` will fill.
        rows = jnp.arange(S)
        write_blk = jnp.where(
            st.frozen, 0,
            st.block_table[rows, write_at // self.block_size])
        write_off = write_at % self.block_size

        x = eng._embed(params, st.tok[:, None])

        # Cache as scan CARRY with in-place row scatters — same
        # rationale as engine._forward_cached: ys-stacked cache slices
        # rewrote the whole cache every token, doubling decode HBM
        # traffic. Here the per-step write is S rows per layer.
        def layer(carry, scanned):
            x, k_all, v_all = carry
            if adapters is None:
                p, li = scanned
                proj = None
            else:
                from kubeflow_tpu.serving.multilora import lora_proj
                p, ab, li = scanned
                proj = lora_proj(ab, st.aid,
                                 eng.adapter_pack.scaling, cfg)
            cell = {}

            def write_kv(k, v):
                # one [S]-row scatter into the shared block pool:
                # slot s's token lands at (table[s, at//bs], at%bs)
                k2 = k_all.at[li, write_blk, write_off].set(
                    k[:, 0].astype(k_all.dtype))
                v2 = v_all.at[li, write_blk, write_off].set(
                    v[:, 0].astype(v_all.dtype))
                cell["k"], cell["v"] = k2, v2
                return (jax.lax.dynamic_index_in_dim(
                            k2, li, 0, keepdims=False),
                        jax.lax.dynamic_index_in_dim(
                            v2, li, 0, keepdims=False))

            def attn(q, kp, vp):
                # kp/vp are this layer's block POOL; the paged path
                # gathers each row's K/V through its block table.
                # Insert-time compaction keeps cell index == logical
                # token position, so masking semantics (and bits — see
                # paged_attention's docstring) match the dense path.
                return paged_attention(
                    q, kp, vp, st.block_table, positions, kv_positions,
                    causal=True, kv_mask=kv_valid,
                    window=getattr(cfg, "sliding_window", None),
                    impl=self.attention_impl)

            x, _ = transformer_block(
                cfg, fam, p, x, rope_positions, inv_freq, write_kv,
                attn, proj)
            return (x, cell["k"], cell["v"]), None

        layer_ids = jnp.arange(cfg.num_layers, dtype=jnp.int32)
        xs = ((params["blocks"], layer_ids) if adapters is None
              else (params["blocks"], adapters, layer_ids))
        (x, k_new, v_new), _ = jax.lax.scan(
            layer, (x, st.k, st.v), xs)
        x = rms_norm(x, params["final_norm"], cfg.norm_eps)
        logits = eng._head(params, x[:, -1])
        nxt, lp = eng._sample(logits, sub, sp)
        # frozen rows keep their cursors: length marks the prefilled
        # frontier and tok the NEXT prompt token — a decode step's
        # garbage sample must not clobber either
        st = SlotState(
            k_new, v_new,
            jnp.where(st.frozen, st.length,
                      jnp.minimum(st.length + 1, ec.max_len)),
            st.offset, st.pad,
            jnp.where(st.frozen, st.tok, nxt.astype(jnp.int32)),
            st.aid, st.block_table, st.frozen)
        return st, nxt, lp, rng

    def _step(self, params, adapters, st: SlotState, sp: SamplingParams,
              rng, *, steps: int):
        """`steps` decode tokens for all slots in ONE dispatch (a
        lax.scan over `_decode_one`) — chunking amortizes per-token
        host dispatch; admission happens between dispatches, so a
        queued request waits at most steps-1 tokens for a freed slot
        (the host's worker chooses steps). The token sequence is
        IDENTICAL for any chunking — the scan body is the single-step
        program, and retirement only changes what the host keeps,
        never what the device computes."""

        def body(carry, _):
            st, rng = carry
            st, tok, lp, rng = self._decode_one(params, adapters, st,
                                                sp, rng)
            return (st, rng), (tok, lp)

        (st, rng), (toks, lps) = jax.lax.scan(
            body, (st, rng), None, length=steps)
        return (st, jnp.moveaxis(toks, 0, 1),
                jnp.moveaxis(lps, 0, 1), rng)  # [S, steps] each

    def step(self, st: SlotState, sp: SamplingParams, rng,
             steps: int = 1):
        """-> (state, tokens [S, steps], logprobs [S, steps], rng)."""
        pack = self.engine.adapter_pack
        return self._step_jit(self.engine.params,
                              None if pack is None else pack.blocks,
                              st, sp, rng, steps=steps)

    # -- chunked prefill (fused paged append) -----------------------------

    def _paged_forward(self, params, adapters, st: SlotState, slots,
                       tokens, n_valid, start):
        """Forward `[g, s]` tokens for slot rows `slots` THROUGH the
        paged pool: each layer's K/V projections are written into the
        rows' block tables at cells [start, start + n_valid) and
        attended in the same fused op (ops.paged_prefill_attention).
        Shared by chunked prefill (`_append_rows`) and the speculative
        verify pass (`_spec_verify`) so the two paths cannot drift.
        Returns (final-norm hidden states [g, s, D], k_pool, v_pool).

        Write disjointness holds by construction: a row only ever
        writes cells at/above its own cursor, which land in its
        exclusively-owned fresh blocks — radix-shared blocks all sit
        strictly below the cursor (see the kernel's docstring)."""
        eng = self.engine
        cfg, fam = eng.cfg, eng.family
        table = st.block_table[slots]
        aid = st.aid[slots]
        s = tokens.shape[1]
        positions = (start[:, None]
                     + jnp.arange(s, dtype=jnp.int32)[None, :])
        rope_positions = jnp.maximum(
            positions - st.offset[slots][:, None], 0)
        inv_freq = rope_frequencies(cfg.head_dim, theta=cfg.rope_theta)
        kv_valid = ~st.pad[slots]
        x = eng._embed(params, tokens)

        def layer(carry, scanned):
            x, k_all, v_all = carry
            if adapters is None:
                p, li = scanned
                proj = None
            else:
                from kubeflow_tpu.serving.multilora import lora_proj
                p, ab, li = scanned
                proj = lora_proj(ab, aid, eng.adapter_pack.scaling, cfg)
            cell = {}

            def write_kv(k, v):
                # defer the write: the fused op scatters K/V through
                # the block table and attends in one pass
                cell["new"] = (k, v)
                return (jax.lax.dynamic_index_in_dim(
                            k_all, li, 0, keepdims=False),
                        jax.lax.dynamic_index_in_dim(
                            v_all, li, 0, keepdims=False))

            def attn(q, kp, vp):
                kn, vn = cell["new"]
                out, kp2, vp2 = paged_prefill_attention(
                    q, kn, vn, kp, vp, table, start, n_valid,
                    kv_mask=kv_valid,
                    window=getattr(cfg, "sliding_window", None),
                    impl=self.prefill_impl)
                cell["k"] = jax.lax.dynamic_update_index_in_dim(
                    k_all, kp2, li, 0)
                cell["v"] = jax.lax.dynamic_update_index_in_dim(
                    v_all, vp2, li, 0)
                return out

            x, _ = transformer_block(
                cfg, fam, p, x, rope_positions, inv_freq, write_kv,
                attn, proj)
            return (x, cell["k"], cell["v"]), None

        layer_ids = jnp.arange(cfg.num_layers, dtype=jnp.int32)
        xs = ((params["blocks"], layer_ids) if adapters is None
              else (params["blocks"], adapters, layer_ids))
        (x, k_new, v_new), _ = jax.lax.scan(
            layer, (x, st.k, st.v), xs)
        x = rms_norm(x, params["final_norm"], cfg.norm_eps)
        return x, k_new, v_new

    def _append_rows(self, params, adapters, st: SlotState, slots,
                     tokens, n_valid, finish, sp, rng):
        """One chunked-prefill slice: feed `tokens[i, :n_valid[i]]` of
        each listed slot's remaining prompt through the paged pool,
        advancing the row cursor by n_valid. Rows with `finish` sample
        their first output token and unfreeze; others stay frozen (the
        decode step keeps masking them). Padding rows (a repeated slot
        with n_valid 0) are no-ops: `.add(0)` moves nothing and their
        sampled token is discarded by `finish=False`."""
        eng, ec = self.engine, self.engine.ec
        rng, sub = jax.random.split(rng)
        start = st.length[slots]
        x, k_new, v_new = self._paged_forward(
            params, adapters, st, slots, tokens, n_valid, start)
        last = jnp.maximum(n_valid - 1, 0)
        x_last = jnp.take_along_axis(
            x, last[:, None, None], axis=1)[:, 0]
        logits = eng._head(params, x_last)
        sp_rows = SamplingParams(temperature=sp.temperature[slots],
                                 top_k=sp.top_k[slots],
                                 top_p=sp.top_p[slots])
        nxt, lp = eng._sample(logits, sub, sp_rows)
        length = jnp.minimum(st.length.at[slots].add(n_valid),
                             ec.max_len)
        newtok = jnp.where(finish, nxt.astype(jnp.int32),
                           st.tok[slots])
        tok = st.tok.at[slots].set(newtok)
        frozen = st.frozen.at[slots].set(
            jnp.where(finish, False, st.frozen[slots]))
        st = SlotState(k_new, v_new, length, st.offset, st.pad, tok,
                       st.aid, st.block_table, frozen)
        return st, nxt, lp, rng

    def append_rows(self, st: SlotState, slots, tokens, n_valid,
                    finish, sp: SamplingParams, rng):
        """Host entry for one chunked-prefill slice. -> (state,
        first_token [g], logprob [g], rng); first_token/logprob are
        only meaningful for rows with finish=True."""
        pack = self.engine.adapter_pack
        return self._append_jit(
            self.engine.params,
            None if pack is None else pack.blocks,
            st, jnp.asarray(slots, jnp.int32),
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(n_valid, jnp.int32),
            jnp.asarray(finish, bool), sp, rng)

    def _adopt(self, st: SlotState, slot, table, seed_len, tok, aid):
        """Point `slot` at its planned block `table` with `seed_len`
        cells already seeded from the radix cache, FROZEN for chunked
        prefill: decode steps mask the row until `append_rows` has fed
        the whole suffix. `tok` is the next prompt token (kept for the
        cursor invariant; append feeds tokens explicitly)."""
        return SlotState(
            st.k, st.v,
            st.length.at[slot].set(seed_len),
            st.offset.at[slot].set(0),
            st.pad.at[slot].set(False),
            st.tok.at[slot].set(tok),
            st.aid.at[slot].set(aid),
            st.block_table.at[slot].set(table),
            st.frozen.at[slot].set(True))

    def adopt_slot(self, st: SlotState, slot: int, table, seed_len: int,
                   tok: int, aid: int = 0) -> SlotState:
        return self._adopt_jit(
            st, jnp.asarray(slot, jnp.int32),
            jnp.asarray(table, jnp.int32),
            jnp.asarray(seed_len, jnp.int32),
            jnp.asarray(tok, jnp.int32), jnp.asarray(aid, jnp.int32))

    def _copy_cells(self, st: SlotState, src, dst, n):
        """Copy cells [0, n) of pool block `src` into block `dst` —
        the copy half of copy-on-write for a partially-matched radix
        block: the new request seeds its own fresh block from the
        shared one and diverges there."""
        i = jnp.arange(self.block_size)
        sel = (i < n)[None, :, None, None]
        kd = jnp.where(sel, st.k[:, src], st.k[:, dst])
        vd = jnp.where(sel, st.v[:, src], st.v[:, dst])
        return SlotState(
            st.k.at[:, dst].set(kd), st.v.at[:, dst].set(vd),
            st.length, st.offset, st.pad, st.tok, st.aid,
            st.block_table, st.frozen)

    def copy_cells(self, st: SlotState, src: int, dst: int,
                   n: int) -> SlotState:
        return self._copy_cells_jit(
            st, jnp.asarray(src, jnp.int32), jnp.asarray(dst, jnp.int32),
            jnp.asarray(n, jnp.int32))

    # -- speculative decoding on paged KV ---------------------------------

    def init_draft_slots(self) -> DraftSlots:
        cfg = self.draft.cfg
        shape = (cfg.num_layers, self.S, self.draft.ec.max_len,
                 cfg.num_kv_heads, cfg.head_dim)
        return DraftSlots(
            jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype),
            jnp.zeros((self.S,), jnp.int32))

    def _draft_decode_one(self, dparams, dst: DraftSlots, feed):
        """One draft-model token for ALL slots against the dense
        per-slot draft cache (the draft-side mirror of `_decode_one`).
        Cell index == position, so causal masking alone hides stale
        tail cells; every cell is written before it is first attended."""
        deng = self.draft
        cfg, fam = deng.cfg, deng.family
        W = deng.ec.max_len
        S = self.S
        positions = dst.length[:, None]
        inv_freq = rope_frequencies(cfg.head_dim, theta=cfg.rope_theta)
        kv_positions = jnp.broadcast_to(
            jnp.arange(W, dtype=jnp.int32)[None, :], (S, W))
        write_at = jnp.minimum(dst.length, W - 1)
        rows = jnp.arange(S)
        x = deng._embed(dparams, feed[:, None])

        def layer(carry, scanned):
            x, k_all, v_all = carry
            p, li = scanned
            cell = {}

            def write_kv(k, v):
                k2 = k_all.at[li, rows, write_at].set(
                    k[:, 0].astype(k_all.dtype))
                v2 = v_all.at[li, rows, write_at].set(
                    v[:, 0].astype(v_all.dtype))
                cell["k"], cell["v"] = k2, v2
                return (jax.lax.dynamic_index_in_dim(
                            k2, li, 0, keepdims=False),
                        jax.lax.dynamic_index_in_dim(
                            v2, li, 0, keepdims=False))

            def attn(q, kp, vp):
                return dot_product_attention(
                    q, kp, vp, positions, kv_positions, causal=True,
                    window=getattr(cfg, "sliding_window", None),
                    contiguous_positions=True)

            x, _ = transformer_block(
                cfg, fam, p, x, positions, inv_freq, write_kv, attn,
                None)
            return (x, cell["k"], cell["v"]), None

        layer_ids = jnp.arange(cfg.num_layers, dtype=jnp.int32)
        (x, k_new, v_new), _ = jax.lax.scan(
            layer, (x, dst.k, dst.v), (dparams["blocks"], layer_ids))
        x = rms_norm(x, dparams["final_norm"], cfg.norm_eps)
        logits = deng._head(dparams, x[:, -1])
        dst = DraftSlots(k_new, v_new,
                         jnp.minimum(dst.length + 1, W))
        return dst, logits

    def _draft_insert(self, dst: DraftSlots, slot, pstate, npad):
        """Compact row 0 of a batch-1 draft prefill `DecodeState` into
        draft-cache row `slot` (bucket left-pads stripped, mirroring
        `_insert`'s canonical form)."""
        W = self.draft.ec.max_len
        j = jnp.arange(W, dtype=jnp.int32)
        src = jnp.minimum(j + npad, W - 1)
        ck = jnp.take(pstate.k[:, 0], src, axis=1)
        cv = jnp.take(pstate.v[:, 0], src, axis=1)
        return DraftSlots(
            dst.k.at[:, slot].set(ck.astype(dst.k.dtype)),
            dst.v.at[:, slot].set(cv.astype(dst.v.dtype)),
            dst.length.at[slot].set(
                (pstate.length - npad).astype(jnp.int32)))

    def draft_prefill(self, dst: DraftSlots, slot: int,
                      tokens: list[int], rng):
        """Seed draft-cache row `slot` with `tokens`' KV (one draft
        prefill dispatch + one compacting scatter). -> (dst, rng)."""
        deng = self.draft
        b = max(bucket_pow2(len(tokens), deng.ec.max_len), len(tokens))
        arr = np.zeros((1, b), np.int32)
        mask = np.zeros((1, b), bool)
        arr[0, b - len(tokens):] = tokens
        mask[0, b - len(tokens):] = True
        sp, rng = deng._resolve_sampling(
            np.zeros(1, np.float32), np.zeros(1, np.int64),
            np.ones(1, np.float32), rng, batch=1)
        out = deng._prefill_jit(
            deng.params, jnp.asarray(arr), deng.init_state(1), rng, sp,
            jnp.asarray(mask), adapters=None, adapter_ids=None)
        dst = self._dinsert_jit(dst, jnp.asarray(slot, jnp.int32),
                                out[0],
                                jnp.asarray(b - len(tokens), jnp.int32))
        return dst, rng

    def _spec_draft(self, dparams, dst: DraftSlots, tok, sp, rng, *,
                    gamma):
        """Draft `gamma` tokens per slot autoregressively. Returns
        (dst, drafted [S, gamma], q-dists [S, gamma, V], rng) — the
        full draft distributions ride along for the residual resample
        in `_spec_verify`."""
        rng, sub = jax.random.split(rng)

        def body(carry, r):
            dstate, feed = carry
            dstate, logits = self._draft_decode_one(dparams, dstate,
                                                    feed)
            q = _dist(logits, sp)
            d = _draw(r, q)
            return (dstate, d), (d, q)

        (dst, _), (dts, qts) = jax.lax.scan(
            body, (dst, tok), jax.random.split(sub, gamma))
        return (dst, jnp.moveaxis(dts, 0, 1),
                jnp.moveaxis(qts, 0, 1), rng)

    def spec_draft(self, st: SlotState, dst: DraftSlots,
                   sp: SamplingParams, rng, gamma: int):
        return self._spec_draft_jit(self.draft.params, dst, st.tok,
                                    sp, rng, gamma=gamma)

    def _spec_verify(self, params, dparams, st: SlotState,
                     dst: DraftSlots, drafted, qs, sp, rng, *, gamma):
        """Target-verify the drafted window through the paged pool and
        roll both caches back to the accepted frontier.

        The accept/bonus/residual math is the one-shot
        `SpeculativeEngine._speculate` rule vectorized over slots
        (Leviathan et al.): accept drafted[j] while u*q < p; on full
        acceptance draw the bonus token from the target's gamma-th
        distribution, otherwise resample from the clipped residual
        p - q (all-zero rows fall back to p). Rejected tokens' KV cells
        sit strictly above the rolled-back cursors and are rewritten
        before they can ever be attended — rollback is cursor motion,
        not data motion, which is what makes it CoW-safe: shared radix
        blocks all live below the cursor and are never touched.

        Frozen (mid-chunked-prefill) rows ride along fully masked:
        their cursors and tokens never move, and their verify writes
        land above their prefill frontier where `append_rows` rewrites
        them before first attend."""
        eng = self.engine
        ec = eng.ec
        S = self.S
        rng, r_us, r_x = jax.random.split(rng, 3)
        tin = jnp.concatenate([st.tok[:, None], drafted], axis=1)
        slots = jnp.arange(S, dtype=jnp.int32)
        n_valid = jnp.full((S,), gamma + 1, jnp.int32)
        x, k_pool, v_pool = self._paged_forward(
            params, None, st, slots, tin, n_valid, st.length)
        all_logits = eng._head(params, x)          # [S, gamma+1, V]
        ps = jax.vmap(lambda lg: _dist(lg, sp),
                      in_axes=1, out_axes=1)(all_logits)
        us = jax.random.uniform(r_us, (S, gamma))
        p_d = jnp.take_along_axis(
            ps[:, :gamma], drafted[..., None], axis=2)[..., 0]
        q_d = jnp.take_along_axis(qs, drafted[..., None], axis=2)[..., 0]
        accept = us * q_d < p_d
        k = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                    axis=1)                        # [S] accepted count
        pk = jnp.take_along_axis(ps, k[:, None, None], axis=1)[:, 0]
        qk = jnp.take_along_axis(
            qs, jnp.minimum(k, gamma - 1)[:, None, None], axis=1)[:, 0]
        resid = jnp.clip(pk - qk, 0.0, None)
        resid = jnp.where(
            jnp.sum(resid, axis=1, keepdims=True) > 0.0, resid, pk)
        dist = jnp.where((k == gamma)[:, None], ps[:, gamma], resid)
        extra = _draw(r_x, dist)                   # [S]
        rows = jnp.arange(S)
        emit = jnp.concatenate(
            [drafted, jnp.zeros((S, 1), jnp.int32)], axis=1)
        emit = emit.at[rows, k].set(extra)
        lsm = jax.nn.log_softmax(all_logits, axis=-1)
        lps = jnp.take_along_axis(lsm, emit[..., None], axis=2)[..., 0]
        length = jnp.where(
            st.frozen, st.length,
            jnp.minimum(st.length + k + 1, ec.max_len))
        tok = jnp.where(st.frozen, st.tok, extra.astype(jnp.int32))
        st = SlotState(k_pool, v_pool, length, st.offset, st.pad, tok,
                       st.aid, st.block_table, st.frozen)
        # draft rollback: the scan advanced every row by gamma; keep
        # the k+1 cells the accepted tokens fed (capped at gamma),
        # then feed the last drafted token unconditionally — its write
        # only COMMITS (advances length) on full acceptance, otherwise
        # it lands above the kept cursor and is rewritten next round
        dlen = dst.length - gamma + jnp.minimum(k + 1, gamma)
        dst = DraftSlots(dst.k, dst.v, dlen)
        dfed, _ = self._draft_decode_one(dparams, dst,
                                         drafted[:, gamma - 1])
        dst = DraftSlots(dfed.k, dfed.v,
                         jnp.where(k == gamma, dfed.length, dlen))
        return st, dst, emit, lps, k, rng

    def spec_verify(self, st: SlotState, dst: DraftSlots, drafted, qs,
                    sp: SamplingParams, rng, gamma: int):
        """-> (state, draft state, emitted [S, gamma+1], logprobs
        [S, gamma+1], accepted counts [S], rng). Row i's valid emitted
        tokens are emit[i, :k[i] + 1]."""
        return self._spec_verify_jit(
            self.engine.params, self.draft.params, st, dst, drafted,
            qs, sp, rng, gamma=gamma)


class Overloaded(RuntimeError):
    """Admission queue is full — callers should shed load (HTTP 429)."""


class MigratedAway(RuntimeError):
    """The request's state was exported to a peer replica (instant
    drain). Not a failure: the router resumes the generation on the
    peer from the migrated KV, and clients never see this exception —
    the server maps it to a retryable error the router absorbs."""

    def __init__(self, request_id: str = ""):
        super().__init__(
            f"request {request_id or '<unknown>'} migrated to a peer "
            "replica")
        self.request_id = request_id


class _Slot:
    """Host-side record for one admitted request."""

    __slots__ = ("fut", "out", "lps", "max_new", "queue", "stop",
                 "kv_toks", "owned", "node_refs", "freed",
                 "meta", "sampling", "aid", "block_charge",
                 "prefilling")

    def __init__(self, fut, max_new: int, queue, stop=()):
        self.fut = fut
        self.out: list[int] = []
        self.lps: list[float] = []  # chosen-token logprobs, out-aligned
        self.max_new = max_new
        self.queue = queue  # per-request token stream (None for oneshot)
        self.stop = stop    # token-id sequences that end generation
        # tenancy/preemption bookkeeping: the scheduling record, plus
        # enough of the original request (sampling knobs, adapter id)
        # to re-enqueue it if this decode gets preempted
        self.meta: ReqMeta | None = None
        self.sampling: dict | None = None
        self.aid = 0
        self.block_charge = 0  # pool blocks charged to the tenant ledger
        # paged-KV bookkeeping: the tokens whose KV this slot's blocks
        # hold (full prompt incl. any registered prefix, then every
        # emitted token UNTRIMMED — stop-sequence trimming edits `out`,
        # not the cache), the exclusively-owned physical blocks by
        # logical block index, and the radix nodes this request holds
        # refs on (shared prefix chain + in-flight-indexed own blocks).
        self.kv_toks: list[int] = []
        self.owned: dict[int, int] = {}
        self.node_refs: list = []
        self.freed = False  # block bookkeeping already released
        # chunked prefill: {"suffix": [...], "fed": n} while the prompt
        # is still being fed in budget slices; None once decodable.
        # Mid-prefill the slot's device row is FROZEN and the record is
        # excluded from decode snapshots, preemption, and KV export.
        self.prefilling: dict | None = None


class ContinuousBatcher:
    """Host orchestrator: admission, per-request budgets, EOS
    retirement. API-compatible with server.Batcher (`submit`, `close`,
    `.calls`/`.requests` counters), so `create_serving_app` can swap it
    in without touching the handler.

    `.calls` counts decode steps and `.requests` admitted requests —
    `requests / calls` is NOT a mean batch here; the continuous
    analog `tokens_emitted / calls` (mean occupied slots per step) is
    exported as `.occupancy()`.
    """

    def __init__(self, engine: InferenceEngine, gpu_lock: asyncio.Lock,
                 *, max_slots: int = 8, chunk: int = 4,
                 prefill_chunk: int | None = None,
                 prefill_chunk_tokens: int | None = None,
                 prefixes: dict[str, list[int]] | None = None,
                 max_pending: int = 256,
                 pipeline_depth: int | None = None,
                 window_ms: float = 0.0,
                 kv_block_size: int = 64,
                 kv_pool_blocks: int | None = None,
                 paged_attention_impl: str = "auto",
                 draft: InferenceEngine | None = None,
                 spec_gamma: int = 4,
                 kv_spill_bytes: int | None = None,
                 tenancy=None, clock=None):
        # window_ms accepted (and ignored) for constructor parity with
        # Batcher: admission is per-token here, there is no window.
        del window_ms
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        # Chunked prefill (ISSUE 9): instead of prefilling a whole
        # prompt in one dispatch while every active decode stalls, the
        # worker feeds at most `prefill_chunk_tokens` prompt tokens per
        # loop iteration through the fused paged append path,
        # interleaved with decode chunks — the per-step token budget
        # that keeps the decode batch dense. None keeps the monolithic
        # admission prefill. (Distinct from `prefill_chunk`, which only
        # slices the MONOLITHIC prefill's compile shapes.)
        if prefill_chunk_tokens is not None and prefill_chunk_tokens < 1:
            raise ValueError(
                f"prefill_chunk_tokens must be >= 1, got "
                f"{prefill_chunk_tokens}")
        self.prefill_chunk_tokens = prefill_chunk_tokens
        # Speculative decoding on paged KV (ISSUE 9): with a draft
        # engine, every decode iteration becomes a draft(gamma) +
        # verify(gamma+1) round batched across slots; accepted tokens
        # append through the block tables, rejections roll the cursors
        # back. Replaces chunk-scan decode (spec rounds are the chunk).
        if spec_gamma < 1:
            raise ValueError(f"spec_gamma must be >= 1, got {spec_gamma}")
        self.spec_gamma = spec_gamma
        # Dispatch-ahead depth: with depth 2 the worker queues the next
        # decode chunk while the previous one is still computing, so
        # host-side emit/retirement work overlaps device time instead
        # of idling the chip between chunks. The price is bounded
        # speculation: a slot that retires early (EOS/stop) may decode
        # up to (depth-1) x chunk garbage tokens before the host sees
        # it — the free-row cost model this engine is built on. Depth 1
        # restores strict per-chunk retirement.
        #
        # Default is backend-aware (measured, docs/perf-notes.md): on
        # an accelerator the overlap hides host time behind device
        # time; on CPU "device" compute shares the host's cores, so
        # speculation only adds waste (-6% on the loadtest A/B).
        if pipeline_depth is None:
            pipeline_depth = 2 if jax.default_backend() == "tpu" else 1
        if pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {pipeline_depth}")
        self.pipeline_depth = pipeline_depth
        # The worker decodes up to `chunk` tokens per dispatch (one
        # scanned program) — per-token host dispatch is the continuous
        # design's overhead tax. Admission happens between dispatches:
        # a queued request waits at most chunk-1 tokens at depth 1, up
        # to ~pipeline_depth x chunk under dispatch-ahead (a freed
        # slot is only observed once its chunk drains) — still far
        # under a window group's full-generation wait. Compiles stay
        # bounded: one program per steps value in [1, chunk].
        self.chunk = chunk
        self.cengine = ContinuousEngine(
            engine, max_slots, prefill_chunk=prefill_chunk,
            block_size=kv_block_size, num_blocks=kv_pool_blocks,
            paged_attention_impl=paged_attention_impl, draft=draft)
        # Automatic radix prefix cache over the block pool: every
        # admitted prompt's full blocks are indexed by token prefix
        # (at admission, so even in-flight prefills are sharable), and
        # retirement donates a request's blocks back to the tree. A new
        # prompt sharing a cached prefix seeds its prefill from those
        # blocks and only computes the suffix. Refcount-0 blocks are
        # LRU-evicted when admission needs the space — the automatic
        # generalization of the manual `prefixes` registration (which
        # stays as a pre-warm hint).
        self._radix = RadixPrefixCache(self.cengine.pool)
        # Block lifecycle ledger (ISSUE 13): attached to the pool
        # before any alloc, so every block birth/death is booked to a
        # cause and births − frees reconciles against pool.in_use (the
        # eviction-forensics conservation invariant). The server binds
        # its on_* hooks to /metrics families; /debug/profile and
        # bench read snapshot() via cache_anatomy().
        self.cache_ledger = CacheLedger()
        self.cengine.pool.attach_ledger(self.cache_ledger)
        # Host-RAM spill tier (ISSUE 19): with a byte budget, radix
        # eviction demotes block contents to host numpy instead of
        # discarding (deaths booked `spill`), and admission planning
        # promotes them back with a host->device copy when the same
        # prefix returns (`note_restore`). Conservation extends to
        # content: (births - restores) - (non-spill deaths + drops)
        # == live + spilled. None disables the tier entirely.
        if kv_spill_bytes is not None and kv_spill_bytes < 0:
            raise ValueError(
                f"kv_spill_bytes must be >= 0, got {kv_spill_bytes}")
        self._spill_tier: HostSpillTier | None = None
        if kv_spill_bytes is not None:
            self._spill_tier = HostSpillTier(
                kv_spill_bytes, self.cengine.kv_block_bytes())
            self._radix.attach_spill(self._spill_tier,
                                     self._spill_reader)
        self._dirty: list[int] = []  # freed slots awaiting table reset
        self.prefix_hits = 0      # admissions that reused cached cells
        self.prefix_misses = 0
        self.tokens_prefilled = 0  # suffix tokens actually computed
        self.tokens_reused = 0     # prompt cells served from cache
        # optional hook(computed: int, reused: int, hit: bool,
        # tenant: str), called per admission — the server wires metrics
        # (including the tenant-labelled hit/miss series) through this
        self.on_prefix = None
        # Per-request token timelines (obs.timeline): every request
        # gets a RequestTimeline stamped with its structural events
        # plus every emitted token's timestamp; the bounded store backs
        # `/v1/requests/{id}/timeline`. The injectable clock lets tests
        # assert exact ITL math. Like on_prefix, the optional hooks —
        # on_itl(gap_s) per decode token, on_queue_wait(wait_s) per
        # first admission — feed server histograms and must never kill
        # the worker.
        self._clock = clock or time.monotonic
        self.timelines = TimelineStore()
        self.on_itl = None
        self.on_queue_wait = None
        # on_spec_round(proposed: int, accepted: int) — per speculative
        # verify round; the server feeds the spec-acceptance SLO
        self.on_spec_round = None
        # Runtime kill switch for speculative decoding (the fleet
        # controller's disable_draft actuator flips it via POST
        # /v1/spec). Off: spec rounds and draft-cache seeding stop,
        # plain decode continues; the draft engine and its caches stay
        # allocated. Re-enabling mid-flight is safe only at low load —
        # slots admitted while disabled have no draft KV row, so spec
        # rounds would verify against a stale draft cache; prefer to
        # re-enable when the batcher drains.
        self.spec_enabled = True
        # optional obs.Tracer: when set (the server wires it), every
        # decode-chunk dispatch opens a `decode.attention` span in the
        # executor thread, tagged with the RESOLVED attention impl —
        # traces show which kernel served a step
        self.tracer = None
        # Step-anatomy profiler (ISSUE 8): always on — pure-python
        # phase accounting is a few clock reads per iteration. The
        # server binds `/metrics` histograms through profiler.on_phase
        # (the on_prefix hook idiom) and `/debug/profile` reads
        # profiler.snapshot(); bench --attribution reads it directly.
        # Shares the injectable clock so tests reconcile profiler
        # totals against timeline stamps on one timebase.
        self.profiler = PhaseProfiler(clock=self._clock)
        # Compile-watch: every jitted callable on this batcher's hot
        # path keys calls by abstract shape signature; a novel
        # signature past each fn's first is a retrace — counted here,
        # surfaced as serving_recompiles_total{fn} once the server
        # binds compile_watch.on_recompile. (warmup() walks the bounded
        # compile set through these wrappers, so the counters start at
        # the warmed-shape count; steady state is flat — the alert is
        # on the RATE.)
        self.compile_watch = CompileWatch()
        ce = self.cengine
        ce._step_jit = self.compile_watch.watch(
            ce._step_jit, "decode_step")
        ce._insert_many_jit = self.compile_watch.watch(
            ce._insert_many_jit, "insert_many")
        ce._gather_seed_jit = self.compile_watch.watch(
            ce._gather_seed_jit, "gather_seed")
        ce._reset_jit = self.compile_watch.watch(
            ce._reset_jit, "reset_slots")
        engine._prefill_jit = self.compile_watch.watch(
            engine._prefill_jit, "prefill")
        ce._append_jit = self.compile_watch.watch(
            ce._append_jit, "prefill_append")
        if ce.draft is not None:
            ce._spec_draft_jit = self.compile_watch.watch(
                ce._spec_draft_jit, "spec_draft")
            ce._spec_verify_jit = self.compile_watch.watch(
                ce._spec_verify_jit, "spec_verify")
        # Shared prefixes (system prompts): token lists registered at
        # construction; each computes its KV ONCE, lazily, on first use
        # (device work belongs under the gpu lock, not in __init__).
        self._prefixes = dict(prefixes or {})
        for pname, ptoks in self._prefixes.items():
            if not ptoks or len(ptoks) >= engine.ec.max_len:
                raise ValueError(
                    f"prefix {pname!r}: length {len(ptoks)} invalid "
                    f"for max_len {engine.ec.max_len}")
        self._prefix_states: dict[str, Any] = {}
        self.engine = engine
        self.gpu_lock = gpu_lock
        self.calls = 0            # decode steps (device invocations)
        self.requests = 0         # admitted requests
        self.tokens_emitted = 0
        # Multi-tenant QoS (kubeflow_tpu.tenancy): with a TenancyConfig
        # the FIFO pending deque becomes a priority + weighted
        # fair-share queue and a per-tenant ledger enforces rate limits
        # and KV shares; interactive arrivals may PREEMPT the youngest
        # batch-class decode (see _maybe_preempt). Tenant-blind
        # deployments (tenancy=None) keep the exact FIFO deque.
        self.tenancy = tenancy
        self._ledger = (TenantLedger(tenancy)
                        if tenancy is not None else None)
        if tenancy is not None:
            self._pending: Any = FairShareQueue(tenancy, self._ledger)
        else:
            self._pending = collections.deque()
        self.preemptions = 0      # batch decodes evicted for interactive
        self._interactive_blocked = False  # interactive plan deferred
        self._seq = 0             # admission sequence (preempt youngest)
        # EWMA of enqueue->finish service time, feeding the dynamic
        # Retry-After on Overloaded 429s
        self.service_ewma = 0.0
        # Backpressure: an unbounded admission queue turns overload
        # into unbounded client latency AND unbounded host memory;
        # past this depth _enqueue raises Overloaded (HTTP 429).
        self.max_pending = max_pending
        self._wake = asyncio.Event()
        self._active: dict[int, _Slot] = {}
        self._free = list(range(max_slots))
        self._st: SlotState | None = None
        # chunked-prefill progress queue (slot ids, FIFO: the oldest
        # admission finishes first, minimizing its TTFT) and the draft
        # model's per-slot cache (lazily built, like _st)
        self._prefill_q: collections.deque[int] = collections.deque()
        self._dst = None
        self.spec_proposed = 0  # drafted tokens proposed across rounds
        self.spec_accepted = 0  # drafted tokens accepted by the target
        # greedy filler knobs on free slots: a sampled leftover would
        # drag an all-greedy step into the sampled branch's argsorts
        self._temp = np.zeros(max_slots, np.float32)
        self._topk = np.zeros(max_slots, np.int32)
        self._topp = np.ones(max_slots, np.float32)
        # SamplingParams rebuild (3 host->device transfers) only when a
        # knob actually changed — at steady occupancy every decode
        # chunk reuses the cached device arrays.
        self._sp_cache: SamplingParams | None = None
        self._sp_dirty = True
        self._rng = jax.random.key(
            int.from_bytes(os.urandom(8), "little") >> 1)
        self._worker: asyncio.Task | None = None
        self._closed = False
        self._draining = False
        # migration halt: export_sequences() asks the worker to park
        # at its next loop boundary (never mid-admission — a cancel
        # there would strand requests in the worker's local buffers)
        self._halt = False
        # Admitted-but-unfinished request count. NOT derivable from
        # _pending/_active: the worker holds requests in local buffers
        # between popleft and slot assignment (prefill pipelining), so
        # drain() polling those containers would declare victory with a
        # request mid-prefill. Every record's fut resolves terminally
        # on every path (emit, error, cancel, close), so a done
        # callback is the one watertight decrement point.
        self._admitted = 0

    def occupancy(self) -> float:
        return self.tokens_emitted / self.calls if self.calls else 0.0

    def kv_blocks_in_use(self) -> int:
        """Pool blocks held by active requests + the radix cache (the
        `serving_kv_blocks_in_use` gauge; x `kv_block_bytes()` for
        HBM)."""
        return self.cengine.pool.in_use

    def prefix_cache_stats(self) -> dict:
        return {
            "hits": self.prefix_hits,
            "misses": self.prefix_misses,
            "tokens_prefilled": self.tokens_prefilled,
            "tokens_reused": self.tokens_reused,
            "cached_blocks": self._radix.cached_blocks,
            "blocks_in_use": self.cengine.pool.in_use,
            # host spill tier occupancy (0s when the tier is off)
            "spilled_blocks": (self._spill_tier.spilled_blocks
                               if self._spill_tier is not None else 0),
            "spilled_bytes": (self._spill_tier.spilled_bytes
                              if self._spill_tier is not None else 0),
            # top-K decayed prefix heat, 16-hex hashed names — the
            # per-replica half of the fleet heat map (`/fleet/cache`)
            "heat": self._radix.heat_digest(16),
        }

    def cache_anatomy(self) -> dict:
        """Cache-observatory snapshot for `/debug/profile` and bench:
        the lifecycle ledger (eviction causes, reuse-distance/age
        quantiles, defer causes, conservation fields) plus the prefix
        heat digest."""
        return {
            "ledger": self.cache_ledger.snapshot(),
            "heat": self._radix.heat_digest(16),
        }

    def warmup(self, buckets=None) -> int:
        """Blocking ahead-of-traffic compile of the full shape set
        (call before serving traffic; the app's on_startup hook does
        when create_serving_app(warmup=True)). With chunked prefill
        enabled the default bucket set includes a two-chunk prompt so
        the chunk-loop and tail programs warm too."""
        if buckets is None:
            buckets = [16]
            c = self.cengine.prefill_chunk
            if c and 2 * c <= self.engine.ec.max_len and 2 * c != 16:
                buckets.append(2 * c)
        return self.cengine.warmup(
            buckets=tuple(buckets), step_sizes=range(1, self.chunk + 1))

    # -- public API -------------------------------------------------------

    async def submit(self, tokens: list[int], max_new: int,
                     sampling: tuple, *, with_logprobs: bool = False):
        """Generate `max_new` tokens for one prompt; resolves when THIS
        request finishes (other slots keep decoding). The result is
        EOS-padded to exactly max_new — interchangeable with the window
        Batcher's fixed-shape contract (a request that hits EOS early
        stops COMPUTING early here; the pad is host-side) — with or
        without logprobs, so the response SHAPE never depends on the
        server's batcher mode. Requests with stop sequences return the
        TRIMMED output unpadded — stopping short is the ask.
        with_logprobs=True returns (tokens, logprobs); logprobs stays
        unpadded (entries exist only for computed tokens, through the
        first EOS)."""
        fut = self._enqueue(tokens, max_new, sampling, queue=None)
        out, lps = await fut
        eos = self.engine.ec.eos_token
        if eos is not None and len(out) < max_new \
                and not dict(sampling).get("stop"):
            out = out + [eos] * (max_new - len(out))
        return (out, lps) if with_logprobs else out

    def open_stream(self, tokens: list[int], max_new: int,
                    sampling: tuple):
        """Enqueue a streaming request NOW (admission errors — incl.
        Overloaded — raise here, synchronously) and return (fut,
        queue). The server calls this BEFORE sending SSE headers so
        overload is a clean 429, never a mid-stream abort."""
        q: asyncio.Queue = asyncio.Queue()
        return self._enqueue(tokens, max_new, sampling, queue=q), q

    async def stream(self, tokens: list[int], max_new: int,
                     sampling: tuple):
        """Async-iterate tokens as they decode (SSE feed). The stream
        ends at EOS or max_new; the caller owns trimming/decoding."""
        fut, q = self.open_stream(tokens, max_new, sampling)
        try:
            while True:
                item = await q.get()
                if item is None:
                    break
                yield item
            await fut  # surface admission/step errors after drain
        finally:
            # a consumer that stops iterating (client disconnect mid-
            # SSE) must release its slot — otherwise it decodes to
            # max_new into a dead queue and reconnect-loop clients
            # could pin every slot
            if not fut.done():
                fut.cancel()

    def _enqueue(self, tokens, max_new, sampling, *, queue):
        if self._closed:
            raise RuntimeError("batcher is shut down")
        if self._draining:
            raise RuntimeError("batcher is draining")
        if len(self._pending) >= self.max_pending:
            raise Overloaded(
                f"{len(self._pending)} requests already queued "
                f"(max_pending={self.max_pending})")
        cap = self.engine.ec.max_len
        if len(tokens) + max_new > cap:
            raise ValueError(
                f"prompt {len(tokens)} + max_new {max_new} exceeds "
                f"model max_len {cap}")
        sampling = dict(sampling)
        # the tenant identity rides the sampling channel (like adapter
        # and prefix do) but is popped back out — it is routing
        # metadata, not a sampling knob
        tenant = sampling.pop("tenant", "")
        # the request id rides the sampling channel the same way; the
        # server mints it (X-Request-Id) — direct batcher callers get a
        # sequence-derived fallback so timelines always have a key
        request_id = str(sampling.pop("request_id", "")) \
            or f"req-{self._seq:06d}"
        spec = (self.tenancy.resolve(tenant)
                if self.tenancy is not None else None)
        if self._ledger is not None:
            # rate-limit door: raises tenancy.Throttled (HTTP 429 with
            # the bucket's refill time) before anything is spent
            self._ledger.check_request(spec.name)
        # multi-LoRA: the adapter name rides the sampling channel;
        # resolve (and reject unknowns) HERE, before a slot is spent
        adapter = sampling.get("adapter", "")
        pack = self.engine.adapter_pack
        if adapter and pack is None:
            raise ValueError(
                f"adapter {adapter!r} requested but no adapter pack "
                "is loaded on this engine")
        aid = pack.resolve(adapter) if pack else 0
        prefix = sampling.get("prefix", "")
        if prefix:
            if prefix not in self._prefixes:
                raise ValueError(
                    f"unknown prefix {prefix!r}; registered: "
                    f"{sorted(self._prefixes)}")
            if adapter:
                # prefix KV is computed with the BASE weights; reusing
                # it under an adapter would silently serve a hybrid
                raise ValueError(
                    "prefix does not compose with adapter (the shared "
                    "KV is base-model KV)")
            plen = len(self._prefixes[prefix])
            if plen + len(tokens) + max_new > cap:
                raise ValueError(
                    f"prefix {plen} + prompt {len(tokens)} + max_new "
                    f"{max_new} exceeds model max_len {cap}")
        if self._worker is None or self._worker.done():
            self._worker = asyncio.get_event_loop().create_task(
                self._run())
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._admitted += 1
        fut.add_done_callback(lambda _f: self._req_done())
        tl = RequestTimeline(
            request_id,
            tenant=spec.name if spec is not None else tenant,
            prompt_tokens=len(tokens), max_new=max_new,
            clock=self._clock)
        meta = ReqMeta(
            tenant=spec.name if spec is not None else "",
            priority=spec.priority if spec is not None else "standard",
            weight=spec.weight if spec is not None else 1.0,
            cost=float(max_new),
            t_enqueue=self._clock(),
            seq=self._seq,
            ns=(spec.name if spec is not None and spec.prefix_isolation
                else ""),
            request_id=request_id, timeline=tl)
        self._seq += 1
        tl.event("enqueue", tokens=len(tokens), max_new=max_new,
                 priority=meta.priority)
        self.timelines.add(tl)
        self._pending.append(
            (tokens, max_new, sampling, fut, queue, aid, prefix, meta))
        self._wake.set()
        return fut

    def _req_done(self) -> None:
        self._admitted -= 1

    # -- worker -----------------------------------------------------------

    def _sp(self) -> SamplingParams:
        if self._sp_dirty or self._sp_cache is None:
            self._sp_cache = SamplingParams(
                temperature=jnp.asarray(self._temp),
                top_k=jnp.asarray(self._topk),
                top_p=jnp.asarray(self._topp))
            self._sp_dirty = False
        return self._sp_cache

    def _release(self, slot: int, *, cause: str = "refdrop") -> None:
        """Return a slot to the pool with greedy filler knobs (a
        leftover sampled temperature would drag all-greedy steps into
        the sampled branch's full-vocab argsorts). Releases the slot's
        KV blocks (deaths booked to `cause` — refdrop for ordinary
        retirement, pressure for preemption, migration for export) and
        marks its device-side block table dirty (reset to trash before
        the next admission, so the freed blocks stop receiving the
        retired slot's garbage decode writes)."""
        rec = self._active.pop(slot, None)
        self._free.append(slot)
        self._temp[slot], self._topk[slot], self._topp[slot] = 0, 0, 1.0
        self._sp_dirty = True
        if rec is not None:
            self._release_blocks(rec, cause=cause)
            self._dirty.append(slot)

    def _release_blocks(self, rec: _Slot, *,
                        cause: str = "refdrop") -> None:
        """Drop a request's claim on pool blocks: unref its radix
        nodes (tree-owned blocks stay cached, evictable once idle) and
        free the exclusively-owned ones. Idempotent."""
        if rec.freed:
            return
        rec.freed = True
        if self._ledger is not None and rec.meta is not None:
            self._ledger.note_slot_released(rec.meta.tenant,
                                            rec.block_charge)
        if rec.node_refs:
            self._radix.unref(rec.node_refs)
            rec.node_refs = []
        if rec.owned:
            self.cengine.pool.free(rec.owned.values(), cause=cause)
            rec.owned = {}

    def _cache_blocks(self, rec: _Slot) -> None:
        """At clean retirement, donate the request's full KV blocks to
        the radix tree instead of freeing them — the automatic prefix
        cache. Only cells [0, len(kv_toks) - 1) are guaranteed written
        (the final token's KV may still be in flight), so only full
        blocks below that line are indexed; in-flight garbage writes
        land strictly above it (the slot's cursor never moves back),
        so adopted blocks are immutable. Must run BEFORE
        `_release_blocks` frees the rest."""
        if rec.freed or not rec.kv_toks or rec.prefilling is not None:
            # mid-chunked-prefill retirement (cancel): cells past the
            # fed frontier are unwritten — nothing safely cacheable
            return
        bs = self.cengine.block_size
        n_full = (len(rec.kv_toks) - 1) // bs
        if n_full <= 0:
            return
        blocks = {i: rec.owned[i] for i in range(n_full)
                  if i in rec.owned}
        adopted, _ = self._radix.insert(
            rec.kv_toks[:n_full * bs], blocks,
            ns=rec.meta.ns if rec.meta is not None else "")
        for i in adopted:
            del rec.owned[i]
        # Blocks we OFFERED but the tree declined already have an edge
        # for the same token path (a concurrent twin prefill won the
        # insert): this copy's content is a duplicate — book its death
        # as `divergence`, distinct from the slot's ordinary refdrop
        # tail (the final partial block et al, freed by _release).
        dup = [blocks[i] for i in blocks if i not in adopted]
        if dup:
            for i in list(rec.owned):
                if rec.owned[i] in dup:
                    del rec.owned[i]
            self.cengine.pool.free(dup, cause="divergence")

    def _index_inflight(self, rec: _Slot) -> None:
        """At admission, index the prompt's full blocks in the radix
        tree immediately — a concurrent request sharing the prefix can
        seed from them while this one is still decoding (device order
        is safe: its gather is dispatched after our insert). Created
        nodes start with a ref held by this request (`hold=True`): the
        tree must not evict a block our own table points at."""
        bs = self.cengine.block_size
        n_full = len(rec.kv_toks) // bs
        if n_full <= 0:
            return
        blocks = {i: rec.owned[i] for i in range(n_full)
                  if i in rec.owned}
        adopted, held = self._radix.insert(
            rec.kv_toks[:n_full * bs], blocks, hold=True,
            ns=rec.meta.ns if rec.meta is not None else "")
        for i in adopted:
            del rec.owned[i]
        rec.node_refs.extend(held)

    def _finish(self, slot: int, rec: _Slot) -> None:
        self._cache_blocks(rec)
        self._release(slot)
        if rec.meta is not None:
            dt = self._clock() - rec.meta.t_enqueue
            self.service_ewma = (0.8 * self.service_ewma + 0.2 * dt
                                 if self.service_ewma > 0 else dt)
            if self._ledger is not None:
                self._ledger.note_completed(rec.meta.tenant)
            if rec.meta.timeline is not None:
                rec.meta.timeline.event("finish", tokens=len(rec.out))
        if rec.queue is not None and not rec.fut.done():
            rec.queue.put_nowait(None)
        if not rec.fut.done():
            rec.fut.set_result((rec.out[:rec.max_new],
                                rec.lps[:rec.max_new]))

    def _emit(self, slot: int, rec: _Slot, token: int, lp: float, *,
              decode: bool = True) -> None:
        rec.out.append(token)
        rec.lps.append(lp)
        rec.kv_toks.append(token)  # cache-content log, never trimmed
        if rec.meta is not None and rec.meta.timeline is not None:
            gap = rec.meta.timeline.token()
            # first token (and first after a preempt/resume hole)
            # returns None: not an inter-token latency
            if decode and gap is not None and self.on_itl is not None:
                try:
                    self.on_itl(gap)
                except Exception:  # noqa: BLE001 — metrics hook
                    pass           # must never kill the worker
        if self._ledger is not None and rec.meta is not None:
            # tokens/s pacing: generated tokens charge the bucket; a
            # tenant in debt stops being popped until it refills
            self._ledger.charge_tokens(rec.meta.tenant, 1)
        if decode:
            # admission-time first tokens (prefill) stay out of the
            # occupancy numerator — calls counts decode steps only
            self.tokens_emitted += 1
        if rec.queue is not None and not rec.fut.done():
            rec.queue.put_nowait(token)
        # stop sequences: the moment a sequence completes as the
        # output's suffix, trim it off (OpenAI semantics) and retire
        # the slot — the compute win the window batcher can't have
        # (its group runs to the group max regardless)
        for seq in rec.stop:
            n = len(seq)
            if n and len(rec.out) >= n and rec.out[-n:] == list(seq):
                rec.out = rec.out[:-n]
                rec.lps = rec.lps[:-n]
                self._finish(slot, rec)
                return
        eos = self.engine.ec.eos_token
        if len(rec.out) >= rec.max_new or (eos is not None
                                           and token == eos):
            self._finish(slot, rec)

    @staticmethod
    def _fail(fut, queue, exc) -> None:
        if queue is not None and not fut.done():
            queue.put_nowait(None)  # unblock a stream() consumer
        if not fut.done():
            fut.set_exception(exc)

    def _fail_all(self, exc) -> None:
        """Slot state is unrecoverable (donated buffers consumed by a
        failed dispatch): fail every active request deterministically
        and drop the state so the next admission re-inits."""
        for slot, rec in list(self._active.items()):
            self._release(slot)
            self._fail(rec.fut, rec.queue, exc)
        self._st = None
        self._dst = None
        self._prefill_q.clear()
        # the pool array just died with the state: cached tree blocks
        # describe content that no longer exists — drop them, and the
        # pending table resets with them (nothing left to reset)
        self._radix.clear()
        self._dirty.clear()

    def _maybe_preempt(self) -> None:
        """When an interactive request is waiting and can't admit —
        every slot is busy, or its block plan just deferred — evict the
        YOUNGEST batch-class decode. Its full KV blocks are donated to
        the radix tree first, so re-admission replays the prefix from
        cache and only recomputes the partial tail: the cheap
        preemption the paged/radix layer was built to enable. One
        victim per worker iteration keeps it bounded; the next
        iteration preempts again if the pressure persists."""
        if self._ledger is None:
            return
        blocked = self._interactive_blocked
        self._interactive_blocked = False
        if self._free and not blocked:
            return
        if not self._pending.has_waiting("interactive"):
            return
        victim, vseq = None, -1
        for slot, rec in self._active.items():
            m = rec.meta
            if m is None or m.priority != "batch" or rec.fut.done():
                continue
            if rec.prefilling is not None:
                # mid-chunked-prefill: its blocks hold no complete KV
                # to cache and its replay would cost a full re-prefill
                # for zero decode progress reclaimed — never a victim
                continue
            if m.seq > vseq:
                victim, vseq = slot, m.seq
        if victim is not None:
            self._preempt(victim)

    def _preempt(self, slot: int) -> None:
        with self.profiler.phase("preempt"):
            self._preempt_inner(slot)

    def _preempt_inner(self, slot: int) -> None:
        """Evict one active decode and re-enqueue it at the head of
        its tenant's queue. The clean-retirement path minus resolving
        the future: cache the full blocks, release the slot (its table
        resets to trash before the next admission reuses the freed
        blocks — same invariant as normal retirement, which is why the
        worker preempts BEFORE the dirty-slot reset step). Replay is
        token-identical under greedy decoding: the resumed prompt is
        prompt + everything emitted so far, its prefix KV comes back
        bit-exact from the cache, and the recomputed suffix produces
        the same argmax continuation."""
        rec = self._active[slot]
        meta = rec.meta
        self._cache_blocks(rec)
        self._release(slot, cause="pressure")
        self.preemptions += 1
        if self._ledger is not None:
            self._ledger.note_preempted(meta.tenant)
        if meta.timeline is not None:
            meta.timeline.event("preempt", slot=slot,
                                emitted=len(rec.out))
        meta.resume = {"out": list(rec.out), "lps": list(rec.lps),
                       "max_new": rec.max_new}
        # the re-enqueued item plans blocks with the REMAINING budget
        # (full already holds the emitted tokens) and its fair-share
        # cost drops to the remainder so the tenant isn't double-billed
        remaining = max(1, rec.max_new - len(rec.out))
        meta.cost = float(remaining)
        self._pending.appendleft(
            (list(rec.kv_toks), remaining, rec.sampling, rec.fut,
             rec.queue, rec.aid, "", meta))
        self._wake.set()

    def tenant_stats(self) -> dict:
        """Per-tenant live usage + queue depth ({} when tenant-blind)
        — the `serving_tenant_*` collector and `/v1/models` read this."""
        if self._ledger is None:
            return {}
        stats = self._ledger.stats()
        for tenant, depth in self._pending.depths().items():
            stats.setdefault(tenant, {})["queued"] = depth
        return stats

    async def _get_prefix_state(self, name: str):
        """Lazily compute (once) a registered prefix's KV, memoized as
        a single-flight task per name: concurrent first users await the
        SAME device computation instead of each re-running
        `precompute_prefix` through the executor (the old check-then-
        compute raced across its awaits and could prefill the prefix
        once per concurrent miss). A failed compute is evicted so the
        next use retries."""
        task = self._prefix_states.get(name)
        if task is None:
            loop = asyncio.get_event_loop()

            async def compute():
                async with self.gpu_lock:
                    return await loop.run_in_executor(
                        None, self.engine.precompute_prefix,
                        self._prefixes[name])

            task = loop.create_task(compute())
            self._prefix_states[name] = task
        try:
            return await task
        except Exception:
            if self._prefix_states.get(name) is task:
                self._prefix_states.pop(name)
            raise

    def _spill_reader(self, block: int):
        """Device->host snapshot of one pool block's K/V payload —
        the reader `RadixPrefixCache.evict` demotes through. Returns
        `(k, v)` numpy `[L, 1, bs, n_kv, hd]`, or None when there is
        no device state yet. Runs synchronously on the caller's
        thread; a concurrently-donated state raises (deleted buffer),
        which the cache treats as "demote failed, discard instead"."""
        if self._st is None:
            return None
        return self.cengine.export_blocks(self._st, [block])

    async def _restore_spilled(self, item) -> None:
        """Promote this request's spilled full-block prefix back into
        the pool BEFORE block planning, so `_plan_blocks` radix-hits
        it exactly as if the blocks had never been evicted. Restores
        are token-identical by the canonical-form invariant: the tier
        key is the full token prefix, and the payload re-enters the
        pool through the same `import_blocks` scatter migration uses.
        Best-effort throughout — any failure (pool full, donated
        state, partial insert) degrades to plain prefill of the
        missing cells and never raises into admission. Books
        `note_restore` for adopted blocks and stamps `meta.restored`
        so the admission's `on_prefix` can split the metric source."""
        tier = self._spill_tier
        if tier is None or tier.spilled_blocks == 0 or item[6]:
            return
        tokens, meta = item[0], item[7]
        full = [int(t) for t in tokens]
        ns = meta.ns
        bs = self.cengine.block_size
        nodes, _pnode, _plen = self._radix.match(full, ns=ns)
        # walk the tier forward from the cached frontier; the planner
        # always leaves >= 1 token to prefill, so a block whose last
        # cell is the final prompt token is useless — stop before it
        i = len(nodes) * bs
        end = i
        while (end + bs <= len(full) - 1
               and tier.contains(ns, full[:end + bs])):
            end += bs
        n = (end - i) // bs
        if n <= 0:
            return
        pool = self.cengine.pool
        fresh = pool.alloc(n)
        if fresh is None:
            # evicting to restore can itself demote colder blocks —
            # the tier's LRU decides which contents deserve host RAM
            self._radix.evict(n - pool.num_free)
            fresh = pool.alloc(n)
            if fresh is None:
                return
        payloads = []
        for j in range(n):
            p = tier.pop(ns, full[:i + (j + 1) * bs])
            if p is None:
                # budget dropped it between probe and pop (a demote
                # during our own evict above) — restore what we have
                break
            payloads.append(p)
        if not payloads:
            pool.free(fresh, cause="refdrop")
            return
        if len(payloads) < n:
            pool.free(fresh[len(payloads):], cause="refdrop")
            fresh = fresh[:len(payloads)]
            n = len(payloads)
        k = np.concatenate([p[0] for p in payloads], axis=1)
        v = np.concatenate([p[1] for p in payloads], axis=1)
        loop = asyncio.get_event_loop()
        done = False
        booked = False
        try:
            if self._st is None:
                self._st = self.cengine.init_slots()

            def run_restore():
                # read self._st INSIDE the lock: import_blocks donates
                # the buffers (same discipline as import_sequence)
                return self.cengine.import_blocks(self._st, fresh, k, v)

            async with self.gpu_lock:
                self._st = await loop.run_in_executor(None, run_restore)
            # every popped payload left the tier and reached the
            # device: that IS the restore, whether or not the tree
            # adopts each block below (duplicates die as divergence)
            self.cache_ledger.note_restore(n)
            booked = True
            blocks = {len(nodes) + j: b for j, b in enumerate(fresh)}
            adopted, _ = self._radix.insert(full[:end], blocks, ns=ns)
            dup = [b for j, b in blocks.items() if j not in adopted]
            done = True
        finally:
            if not done:
                # import failed: the blocks never became cached
                # content, and the popped payloads are gone — content
                # deaths unless the restore was already booked
                pool.free(fresh, cause="refdrop")
                if not booked:
                    self.cache_ledger.note_spill_drop(n)
                if self._st is not None and any(
                        leaf.is_deleted() for leaf in
                        jax.tree.leaves(self._st)
                        if hasattr(leaf, "is_deleted")):
                    self._fail_all(RuntimeError(
                        "slot state lost to donated spill restore"))
        if dup:
            # someone re-cached (part of) this prefix while we copied:
            # the tree kept its blocks, ours are duplicates
            pool.free(dup, cause="divergence")
        if n > len(dup):
            meta.restored += (n - len(dup)) * bs

    def _plan_blocks(self, item):
        """Match one request against the radix cache and reserve its
        physical blocks. Returns a plan dict, or None when the pool
        can't cover it even after evicting idle cached blocks — the
        caller defers the request until retirements free space.

        Plan fields: `full` (prompt incl. registered prefix — the
        token stream the slot's KV will hold), `suffix` (what prefill
        must actually compute), `m` (cached cells seeding the prefill:
        cell index == token index by the blocks' canonical form),
        `chain` (ref'd radix nodes backing cells [0, m - m % bs)),
        `extra` (ref'd node whose block holds a PARTIAL tail of the
        match — read-only seed source; the diverging request writes
        its own fresh block, which is the copy-on-write), `fresh`
        (newly allocated blocks), `table` (the slot's physical block
        table, trash-padded)."""
        tokens, max_new, _sampling, _fut, _queue, _aid, prefix, meta = item
        ceng = self.cengine
        bs, mb = ceng.block_size, ceng.blocks_per_slot
        chain: list = []
        extra = None
        m = 0
        if prefix:
            # registered-prefix path: seeded from the precomputed
            # batch-1 state (base-model KV), not the radix tree
            full = list(self._prefixes[prefix]) + list(tokens)
            suffix = list(tokens)
            m = len(self._prefixes[prefix])
        else:
            full = list(tokens)
            if self._st is not None:
                nodes, pnode, plen = self._radix.match(full, ns=meta.ns)
                # always leave >= 1 token to prefill: sampling the
                # first output needs a forward pass over something
                m = min(len(nodes) * bs + plen, len(full) - 1)
                cut = m // bs
                if cut < len(nodes):
                    # cap bit inside the full-block chain: the node at
                    # the cut becomes the partial (copy-on-write) seed
                    extra = nodes[cut] if m % bs else None
                    nodes = nodes[:cut]
                elif m % bs:
                    extra = pnode
                chain = nodes
            suffix = full[m:]
        n_total = -(-min(len(full) + max_new,
                         self.engine.ec.max_len) // bs)
        n_fresh = n_total - len(chain)
        if self._ledger is not None:
            # per-tenant KV share: a tenant already holding blocks may
            # not take the pool past its share — defer until its own
            # retirements free some. A tenant holding NOTHING always
            # admits (the share bounds CONCURRENT holdings; deferring a
            # lone oversized request forever would just wedge it).
            lim = self._ledger.block_limit(meta.tenant,
                                           ceng.pool.capacity)
            held = self._ledger.blocks_held(meta.tenant)
            if lim is not None and held > 0 and held + n_fresh > lim:
                self._ledger.note_throttled(meta.tenant, "kv_quota")
                self.cache_ledger.note_defer("kv_quota")
                return None
        fresh = ceng.pool.alloc(n_fresh)
        if fresh is None:
            self._radix.evict(n_fresh - ceng.pool.num_free)
            fresh = ceng.pool.alloc(n_fresh)
            if fresh is None:
                self.cache_ledger.note_defer("pool_exhausted")
                return None
        self._radix.ref(chain)
        if extra is not None:
            self._radix.ref([extra])
        # cache-ledger clock: one tick per admitted request; reused
        # chain/CoW blocks record their reuse distance in admissions
        self.cache_ledger.note_admission()
        reused = [n.block for n in chain]
        if extra is not None:
            reused.append(extra.block)
        if reused:
            self.cache_ledger.note_reuse(reused)
        table = np.zeros(mb, np.int32)
        phys = [n.block for n in chain] + fresh
        table[:len(phys)] = phys
        return {"full": full, "suffix": suffix, "m": m, "chain": chain,
                "extra": extra, "fresh": fresh, "table": table}

    def _drop_plan(self, plan) -> None:
        """Roll back `_plan_blocks` reservations (admission failed or
        the request was cancelled before insert)."""
        self._radix.unref(plan["chain"])
        if plan["extra"] is not None:
            self._radix.unref([plan["extra"]])
        if plan["fresh"]:
            self.cengine.pool.free(plan["fresh"], cause="refdrop")

    async def _admit_group(self, items: list) -> None:
        # `admit` phase wraps the whole admission pass; the grouped
        # prefill/gather device call inside is its own nested `prefill`
        # phase (nesting subtracts: admit records planning + insert
        # only, never double-counts prefill time)
        with self.profiler.phase("admit"):
            await self._admit_group_inner(items)

    async def _admit_group_inner(self, items: list) -> None:
        """Admit up to len(self._free) requests; items sharing a
        prefill bucket, prefix AND cached-seed length share ONE prefill
        dispatch, and the group's slot scatters share one insert_many
        dispatch. A prefill failure fails its bucket group only; an
        insert failure fails its whole admit group (and every active
        request too when the donated buffers were consumed — see the
        except block). Admission is now accounted in BLOCKS, not just
        slots: a request whose worst-case block need outruns the pool
        (even after evicting idle cached blocks) is deferred back to
        the queue head until retirements free blocks — later, smaller
        requests may admit past it (the slot-only admission had no
        such case: every slot held max_len by construction)."""
        loop = asyncio.get_event_loop()
        if self.prefill_chunk_tokens:
            # chunked-prefill mode: non-prefix requests adopt a frozen
            # slot now and feed their prompt in budget slices between
            # decode chunks. Registered-prefix requests keep the
            # monolithic path (their KV seed lives in a dense prefix
            # state, not pool blocks) and fall through below.
            items = await self._admit_chunked(loop, items)
            if not items:
                return
        plans = []
        deferred = []
        for item in items:
            try:
                await self._restore_spilled(item)
            except Exception:  # noqa: BLE001 — restore is best-effort
                pass           # plain prefill covers whatever's missing
            plan = self._plan_blocks(item)
            if plan is None:
                deferred.append(item)
                if item[7].priority == "interactive":
                    # an interactive request couldn't get blocks: let
                    # the worker consider preempting a batch decode
                    # even though free SLOTS exist
                    self._interactive_blocked = True
            else:
                plans.append((item, plan))
        for item in reversed(deferred):
            self._pending.appendleft(item)
        groups: dict[tuple, list] = {}
        for item, plan in plans:
            prefix = item[6]
            reserve = plan["m"]
            b = self.cengine.bucket_for(len(plan["suffix"]), item[1],
                                        reserve)
            groups.setdefault((b, prefix, plan["m"]), []).append(
                (item, plan))
        for (b, prefix, m), group in groups.items():
            self._rng, sub = jax.random.split(self._rng)
            # pad the group to a power of two with greedy dummy rows:
            # prefill/insert shapes come from a SET of log2(max_slots)
            # sizes instead of one compile per novel group size (the
            # same row bucketing the window Batcher does)
            gp = pow2_ceil(len(group))
            npad_rows = gp - len(group)
            lists = [pl["suffix"] for _, pl in group] + [[0]] * npad_rows
            samps = ([it[2] for it, _ in group]
                     + [{"temperature": 0.0, "top_k": 0, "top_p": 1.0}]
                     * npad_rows)
            ids = [it[5] for it, _ in group] + [0] * npad_rows

            def run_prefill(pstate0=None, lists=lists, b=b, samps=samps,
                            sub=sub, ids=ids):
                # host sync (np.asarray) INSIDE the executor: jax
                # dispatch is async, so syncing on the loop thread
                # would block the whole HTTP server for the device time
                pstate, first, _, lps = self.cengine.prefill_batch(
                    lists, b, samps, sub, ids, pstate0)
                return pstate, np.asarray(first), np.asarray(lps)

            ptoks = sum(len(pl["suffix"]) for _, pl in group)
            try:
                with self.profiler.phase("prefill", tokens=ptoks):
                    if prefix:
                        pstate0 = await self._get_prefix_state(prefix)
                    elif m > 0:
                        # seed rows from cached pool blocks: gather
                        # each row's chain (+ partial CoW block) into a
                        # batch-g DecodeState. self._st exists — a
                        # non-empty radix tree implies blocks were
                        # inserted into it.
                        mb = self.cengine.blocks_per_slot
                        chains = np.zeros((gp, mb), np.int32)
                        for i, (_, pl) in enumerate(group):
                            phys = [n.block for n in pl["chain"]]
                            if pl["extra"] is not None:
                                phys.append(pl["extra"].block)
                            chains[i, :len(phys)] = phys

                        def run_gather(st=self._st, chains=chains,
                                       m=m):
                            return self.cengine.gather_seed(
                                st, chains, m)

                        async with self.gpu_lock:
                            pstate0 = await loop.run_in_executor(
                                None, run_gather)
                    else:
                        pstate0 = None
                    async with self.gpu_lock:
                        pstate, firsts, flps = \
                            await loop.run_in_executor(
                                None, run_prefill, pstate0)
            except Exception as e:  # noqa: BLE001
                for it, pl in group:
                    self._drop_plan(pl)
                    self._fail(it[3], it[4], e)
                continue
            admit = []
            for row, (item, plan) in enumerate(group):
                if item[3].done():  # cancelled while prefilling
                    self._drop_plan(plan)
                else:
                    admit.append((row, item, plan))
            if not admit:
                continue
            slots = [self._free.pop() for _ in admit]
            # Pad the scatter list to a power of two by REPEATING the
            # last (slot, row, aid, table, seed) tuple — re-inserting
            # the same row into the same slot is idempotent under the
            # sequential scan — so insert_many's compile set stays the
            # warmed log2(max_slots) sizes instead of one program per
            # novel arrival count (a mid-traffic TPU compile stalls
            # every active decode for seconds).
            pad = pow2_ceil(len(admit)) - len(admit)
            ins_slots = slots + [slots[-1]] * pad
            ins_rows = [r for r, _, _ in admit] + [admit[-1][0]] * pad
            ins_aids = ([it[5] for _, it, _ in admit]
                        + [admit[-1][1][5]] * pad)
            tables = np.stack([pl["table"] for _, _, pl in admit]
                              + [admit[-1][2]["table"]] * pad)
            seed_lens = [m] * len(ins_slots)
            try:
                if self._st is None:
                    self._st = self.cengine.init_slots()

                def run_insert(st=self._st):
                    return self.cengine.insert_many(
                        st, ins_slots, pstate, ins_rows, firsts,
                        ins_aids, tables=tables, seed_lens=seed_lens)

                async with self.gpu_lock:
                    # ONE dispatch for the whole group's scatters (the
                    # admission-side sibling of the group prefill)
                    self._st = await loop.run_in_executor(
                        None, run_insert)
            except Exception as e:  # noqa: BLE001
                self._free.extend(slots)
                for _, it, pl in admit:
                    self._drop_plan(pl)
                    self._fail(it[3], it[4], e)
                # insert donates self._st: a failure that fired AFTER
                # dispatch leaves the old buffers consumed, and keeping
                # them would crash the NEXT decode step with a
                # confusing deleted-buffer error. A failure BEFORE
                # dispatch (bad shapes, host-side raise) leaves them
                # intact — then only this group dies. Distinguish the
                # two instead of guessing.
                if self._st is not None and any(
                        leaf.is_deleted() for leaf in
                        jax.tree.leaves(self._st)
                        if hasattr(leaf, "is_deleted")):
                    self._fail_all(RuntimeError(
                        f"slot state lost to donated insert: {e}"))
                continue
            for slot, (row, (tokens, max_new, sampling, fut, queue,
                             aid, _, meta), plan) in zip(slots, admit):
                self.requests += 1
                rec = _Slot(fut, max_new, queue,
                            stop=tuple(tuple(s) for s in
                                       sampling.get("stop", ())))
                rec.meta = meta
                rec.sampling = sampling
                rec.aid = aid
                resumed = meta.resume is not None
                if resumed:
                    # preemption replay: restore the already-emitted
                    # tokens and the ORIGINAL budget (item max_new was
                    # only the remainder, for block planning)
                    rec.out = list(meta.resume["out"])
                    rec.lps = list(meta.resume["lps"])
                    rec.max_new = meta.resume["max_new"]
                    meta.resume = None
                if self._ledger is not None:
                    rec.block_charge = len(plan["fresh"])
                    self._ledger.note_slot_taken(meta.tenant,
                                                 rec.block_charge)
                rec.kv_toks = list(plan["full"])
                rec.node_refs = list(plan["chain"])
                cut = len(plan["chain"])
                rec.owned = {cut + i: blk
                             for i, blk in enumerate(plan["fresh"])}
                if plan["extra"] is not None:
                    # the partial block was only a read-only seed
                    # source; its content now lives in this row's own
                    # fresh block (the copy half of copy-on-write)
                    self._radix.unref([plan["extra"]])
                self._active[slot] = rec
                # make this prompt's blocks reusable immediately, not
                # just at retirement (in-flight prefix sharing)
                self._index_inflight(rec)
                computed, reused = len(plan["suffix"]), plan["m"]
                self.tokens_prefilled += computed
                self.tokens_reused += reused
                if reused > 0:
                    self.prefix_hits += 1
                else:
                    self.prefix_misses += 1
                if self.on_prefix is not None:
                    try:
                        self.on_prefix(computed, reused, reused > 0,
                                       meta.tenant,
                                       restored=meta.restored)
                    except Exception:  # noqa: BLE001 — metrics hook
                        pass           # must never kill the worker
                if resumed:
                    # zero-duration marker: the replay's cost already
                    # lives in admit/prefill; the marker's COUNT is
                    # what reconciles against timeline `resume` events
                    self.profiler.record("resume", 0.0)
                if meta.timeline is not None:
                    meta.timeline.event(
                        "resume" if resumed else "admit", slot=slot,
                        prefill_computed=computed,
                        prefill_reused=reused)
                if not resumed and self.on_queue_wait is not None:
                    try:
                        self.on_queue_wait(
                            self._clock() - meta.t_enqueue)
                    except Exception:  # noqa: BLE001 — metrics hook
                        pass
                ec = self.engine.ec
                self._temp[slot] = sampling.get(
                    "temperature", ec.temperature)
                self._topk[slot] = sampling.get("top_k", ec.top_k)
                self._topp[slot] = sampling.get("top_p", ec.top_p)
                self._sp_dirty = True
                if self.cengine.draft is not None and self.spec_enabled:
                    # seed the draft cache row BEFORE the first token
                    # is appended: the draft row must hold exactly the
                    # prompt's KV, aligned with the target cursor
                    await self._draft_seed(loop, slot, rec)
                self._emit(slot, rec, int(firsts[row]),
                           float(flps[row]), decode=False)

    async def _admit_chunked(self, loop, items: list) -> list:
        """Chunked-prefill admission: reserve each request's blocks
        (same planner as the monolithic path — radix seeding, CoW and
        tenancy quotas identical), point a FROZEN slot at them, and
        queue the suffix for budget-slice feeding by the worker loop.
        Returns the items this path does not handle (registered-prefix
        requests), for the monolithic admission to pick up."""
        rest = [it for it in items if it[6]]
        mine = [it for it in items if not it[6]]
        if not mine:
            return rest
        deferred = []
        for item in mine:
            if item[3].done():
                continue
            try:
                await self._restore_spilled(item)
            except Exception:  # noqa: BLE001 — restore is best-effort
                pass           # plain prefill covers whatever's missing
            plan = self._plan_blocks(item)
            if plan is None:
                deferred.append(item)
                if item[7].priority == "interactive":
                    self._interactive_blocked = True
                continue
            try:
                await self._adopt_one(loop, item, plan)
            except Exception as e:  # noqa: BLE001
                self._drop_plan(plan)
                self._fail(item[3], item[4], e)
                # adopt donates self._st: distinguish pre- from
                # post-dispatch failure exactly like insert does
                if self._st is not None and any(
                        leaf.is_deleted() for leaf in
                        jax.tree.leaves(self._st)
                        if hasattr(leaf, "is_deleted")):
                    self._fail_all(RuntimeError(
                        f"slot state lost to donated adopt: {e}"))
                    return []
        for item in reversed(deferred):
            self._pending.appendleft(item)
        return rest

    async def _adopt_one(self, loop, item, plan) -> None:
        """Device + bookkeeping half of one chunked admission: install
        the planned block table on a free slot (frozen, cursor at the
        cached-seed length), copy the partial CoW seed block if any,
        and register the host record with its pending suffix."""
        tokens, max_new, sampling, fut, queue, aid, _pfx, meta = item
        slot = self._free.pop()
        full, m = plan["full"], plan["m"]
        bs = self.cengine.block_size
        try:
            if self._st is None:
                self._st = self.cengine.init_slots()

            def run_adopt(st=self._st):
                st = self.cengine.adopt_slot(
                    st, slot, plan["table"], m, full[m], aid)
                if plan["extra"] is not None:
                    # cells [cut*bs, m) seed from the partially-matched
                    # shared block into this row's first fresh block —
                    # the copy half of copy-on-write
                    st = self.cengine.copy_cells(
                        st, plan["extra"].block, plan["fresh"][0],
                        m % bs)
                return st

            async with self.gpu_lock:
                self._st = await loop.run_in_executor(None, run_adopt)
        except Exception:
            self._free.append(slot)
            raise
        self.requests += 1
        rec = _Slot(fut, max_new, queue,
                    stop=tuple(tuple(s) for s in
                               sampling.get("stop", ())))
        rec.meta = meta
        rec.sampling = sampling
        rec.aid = aid
        resumed = meta.resume is not None
        if resumed:
            rec.out = list(meta.resume["out"])
            rec.lps = list(meta.resume["lps"])
            rec.max_new = meta.resume["max_new"]
            meta.resume = None
        if self._ledger is not None:
            rec.block_charge = len(plan["fresh"])
            self._ledger.note_slot_taken(meta.tenant, rec.block_charge)
        rec.kv_toks = list(full)
        rec.node_refs = list(plan["chain"])
        cut = len(plan["chain"])
        rec.owned = {cut + i: blk
                     for i, blk in enumerate(plan["fresh"])}
        if plan["extra"] is not None:
            # read-only seed consumed (the copy is dispatched and
            # ordered before any later write by the donation chain)
            self._radix.unref([plan["extra"]])
        rec.prefilling = {"suffix": list(plan["suffix"]), "fed": 0}
        self._active[slot] = rec
        self._prefill_q.append(slot)
        self.tokens_reused += m
        if m > 0:
            self.prefix_hits += 1
        else:
            self.prefix_misses += 1
        ec = self.engine.ec
        self._temp[slot] = sampling.get("temperature", ec.temperature)
        self._topk[slot] = sampling.get("top_k", ec.top_k)
        self._topp[slot] = sampling.get("top_p", ec.top_p)
        self._sp_dirty = True
        if resumed:
            self.profiler.record("resume", 0.0)
        if meta.timeline is not None:
            meta.timeline.event(
                "resume" if resumed else "admit", slot=slot,
                prefill_computed=len(plan["suffix"]),
                prefill_reused=m)
        if not resumed and self.on_queue_wait is not None:
            try:
                self.on_queue_wait(self._clock() - meta.t_enqueue)
            except Exception:  # noqa: BLE001 — metrics hook
                pass

    async def _advance_prefills(self, loop) -> None:
        """Feed ONE budget-size slice of an unfinished chunked prefill
        through the fused append path. One slice per worker iteration
        bounds the decode stall at exactly the token budget; among
        waiting slots the slice goes to the SHORTEST REMAINING suffix
        (FIFO on ties), so a short interactive prompt that arrived
        behind a long bulk prefill finishes ahead of it instead of
        paying the whole bulk prompt in TTFT. Starvation is bounded:
        a long prefill competes only with already-admitted slots
        (at most max_slots - 1 of them), not the unbounded queue.
        The finishing slice samples the request's first token, unrefs
        the frozen flag, and indexes the now-complete prompt blocks in
        the radix tree (the same in-flight indexing the monolithic
        path does at admission)."""
        best = None
        for cand in list(self._prefill_q):
            crec = self._active.get(cand)
            if crec is None or crec.prefilling is None:
                self._prefill_q.remove(cand)  # retired underneath us
                continue
            if crec.fut.done():               # cancelled mid-prefill
                self._prefill_q.remove(cand)
                self._finish(cand, crec)
                continue
            left = (len(crec.prefilling["suffix"])
                    - crec.prefilling["fed"])
            if best is None or left < best[0]:
                best = (left, cand, crec)
        if best is None:
            return
        _, slot, rec = best
        pf = rec.prefilling
        s = self.prefill_chunk_tokens
        fed, suffix = pf["fed"], pf["suffix"]
        n = min(s, len(suffix) - fed)
        finish = fed + n == len(suffix)
        toks = np.zeros((1, s), np.int32)
        toks[0, :n] = suffix[fed:fed + n]
        sp = self._sp()

        def run_append(st=self._st, toks=toks, n=n, finish=finish,
                       slot=slot, sp=sp):
            st, nxt, lp, rng = self.cengine.append_rows(
                st, [slot], toks, [n], [finish], sp, self._rng)
            if finish:  # host-sync only the slice that samples
                return (st, int(np.asarray(nxt)[0]),
                        float(np.asarray(lp)[0]), rng)
            return st, None, None, rng

        with self.profiler.phase("prefill_chunk", tokens=n):
            async with self.gpu_lock:
                st, first, flp, rng = await loop.run_in_executor(
                    None, run_append)
                self._st = st
                self._rng = rng
        pf["fed"] = fed + n
        self.tokens_prefilled += n
        if not finish:
            return
        self._prefill_q.remove(slot)
        rec.prefilling = None
        self._index_inflight(rec)
        reused = len(rec.kv_toks) - len(suffix)
        if self.on_prefix is not None:
            try:
                self.on_prefix(
                    len(suffix), reused, reused > 0,
                    rec.meta.tenant if rec.meta is not None else "",
                    restored=(rec.meta.restored
                              if rec.meta is not None else 0))
            except Exception:  # noqa: BLE001 — metrics hook
                pass           # must never kill the worker
        if self.cengine.draft is not None and self.spec_enabled:
            with self.profiler.phase("draft"):
                await self._draft_seed(loop, slot, rec)
        self._emit(slot, rec, first, flp, decode=False)

    async def _draft_seed(self, loop, slot: int, rec: _Slot) -> None:
        """Seed the draft model's cache row for a freshly-admitted
        slot. Called BEFORE the first token is emitted, so the row
        holds exactly the prompt's KV and the draft cursor equals the
        target cursor — the alignment every speculative round
        preserves."""
        toks = list(rec.kv_toks)

        def run(dst=self._dst):
            if dst is None:
                dst = self.cengine.init_draft_slots()
            return self.cengine.draft_prefill(dst, slot, toks,
                                              self._rng)

        async with self.gpu_lock:
            dst, rng = await loop.run_in_executor(None, run)
            self._dst = dst
            self._rng = rng

    async def _spec_round(self, loop) -> None:
        """One speculative round for every live (non-frozen) slot:
        gamma draft proposals, one fused paged verify, then k+1 tokens
        emitted per row. Synchronous (no dispatch-ahead): acceptance
        counts gate retirement, so the host must observe each round
        before planning the next."""
        sp = self._sp()
        gamma = self.spec_gamma
        # cancelled (fut.done) rows stay IN the snapshot — the
        # detokenize loop below is where they get finished, exactly
        # like _process_chunk; only frozen rows are excluded
        snap = {s: r for s, r in self._active.items()
                if r.prefilling is None}
        if not snap:
            return

        def run_draft(st=self._st, dst=self._dst):
            return self.cengine.spec_draft(st, dst, sp, self._rng,
                                           gamma)

        with self.profiler.phase("draft", tokens=gamma * len(snap)):
            async with self.gpu_lock:
                dst, drafted, qs, rng = await loop.run_in_executor(
                    None, run_draft)
                self._dst = dst
                self._rng = rng

        def run_verify(st=self._st, dst=self._dst, drafted=drafted,
                       qs=qs):
            st, dst, emit, lps, k, rng = self.cengine.spec_verify(
                st, dst, drafted, qs, sp, self._rng, gamma)
            # host sync inside the executor, like every other dispatch
            return (st, dst, np.asarray(emit), np.asarray(lps),
                    np.asarray(k), rng)

        with self.profiler.phase("verify"):
            async with self.gpu_lock:
                st, dst, emit, lps, k, rng = \
                    await loop.run_in_executor(None, run_verify)
                self._st = st
                self._dst = dst
                self._rng = rng
        self.calls += 1
        round_proposed = gamma * len(snap)
        round_accepted = 0
        self.spec_proposed += round_proposed
        emitted0 = self.tokens_emitted
        with self.profiler.phase("detokenize"):
            for slot, srec in list(self._active.items()):
                if snap.get(slot) is not srec:
                    continue
                if srec.fut.done():  # cancelled mid-round
                    self._finish(slot, srec)
                    continue
                acc = int(k[slot])
                self.spec_accepted += acc
                round_accepted += acc
                for j in range(acc + 1):
                    self._emit(slot, srec, int(emit[slot, j]),
                               float(lps[slot, j]))
                    if slot not in self._active:
                        break  # retired mid-window; tail is dropped
        self.profiler.add_tokens("verify",
                                 self.tokens_emitted - emitted0)
        if self.on_spec_round is not None and round_proposed:
            try:
                self.on_spec_round(round_proposed, round_accepted)
            except Exception:
                pass  # hooks must never kill the worker

    def _plan_steps(self, inflight) -> int:
        """Next chunk size: bounded by the longest remaining budget NOT
        already covered by in-flight chunks (per slot — a slot admitted
        after a dispatch isn't covered by it). 0 = nothing useful to
        dispatch ahead."""
        if not self._active:
            return 0
        best = 0
        for slot, rec in self._active.items():
            if rec.prefilling is not None:
                continue  # frozen row: no decode budget yet
            cover = sum(r["steps"] for r in inflight
                        if r["snap"].get(slot) is rec)
            best = max(best, rec.max_new - len(rec.out) - cover)
        return min(self.chunk, best) if best > 0 else 0

    async def _dispatch_chunk(self, loop, steps: int) -> dict:
        """Dispatch one decode chunk WITHOUT host sync: device arrays
        come back as futures, the device starts computing, and the
        host keeps working. The snapshot maps slot -> the _Slot RECORD
        active at dispatch: chunk tokens are valid only for that exact
        request. Identity (not slot id) matters — a slot freed by a
        retirement and re-admitted while this chunk is in flight
        carries a NEW request whose tokens start with the next
        dispatch; emitting this chunk's row into it would corrupt its
        stream (caught by test_stop_sequences_retire_slots_early)."""
        sp = self._sp()
        # frozen (mid-chunked-prefill) rows are excluded at DISPATCH
        # time: the device masks them, so their chunk rows are garbage
        # even if they unfreeze while this chunk is in flight
        snap = {s: r for s, r in self._active.items()
                if r.prefilling is None}

        def run_step(st=self._st, sp=sp, steps=steps):
            # The rng chains THROUGH the compiled step (it splits
            # internally and returns the next key) — no host-side
            # jax.random.split dispatch per chunk.
            return self.cengine.step(st, sp, self._rng, steps)

        if self.tracer is not None:
            # Tracer.wrap propagates the current context into the
            # executor thread, so the span nests under the request's
            # root when one is active.
            run_step = self.tracer.wrap(
                run_step, "decode.attention",
                impl=self.cengine.attention_impl, steps=steps)
        # `decode` phase = dispatch + any blocking inside run_step.
        # Tokens are attributed where they're OBSERVED (_process_chunk)
        # so over-decoded garbage rows never inflate the count.
        with self.profiler.phase("decode"):
            async with self.gpu_lock:
                st, toks, lps, rng = await loop.run_in_executor(
                    None, run_step)
                self._st = st
                self._rng = rng
        self.calls += steps
        return {"toks": toks, "lps": lps, "steps": steps, "snap": snap}

    @staticmethod
    async def _sync_chunk(loop, rec: dict) -> None:
        """Force a chunk's results to host (in the executor: jax
        dispatch is async and syncing on the loop thread would block
        the whole HTTP server for the device time)."""
        rec["toks"], rec["lps"] = await loop.run_in_executor(
            None, lambda: (np.asarray(rec["toks"]),
                           np.asarray(rec["lps"])))

    def _process_chunk(self, rec: dict) -> None:
        # `sample` = host materialization of the device's sampled
        # tokens; `detokenize` = per-token emit bookkeeping. Decode
        # TOKENS are booked here (each emitted token exactly once, so
        # preempt/resume replay — which RESTORES rec.out rather than
        # re-emitting — cannot double count).
        with self.profiler.phase("sample"):
            toks = np.asarray(rec["toks"])
            lps = np.asarray(rec["lps"])
        emitted0 = self.tokens_emitted
        with self.profiler.phase("detokenize"):
            for slot, srec in list(self._active.items()):
                if rec["snap"].get(slot) is not srec:
                    continue  # admitted after dispatch: not its tokens
                if srec.fut.done():  # caller cancelled mid-decode
                    self._finish(slot, srec)
                    continue
                for j in range(rec["steps"]):
                    self._emit(slot, srec, int(toks[slot, j]),
                               float(lps[slot, j]))
                    if slot not in self._active:
                        break  # retired mid-chunk; tail is trimmed
        self.profiler.add_tokens("decode",
                                 self.tokens_emitted - emitted0)

    async def _run(self) -> None:
        loop = asyncio.get_event_loop()
        # Chunks in flight on device, oldest first. Depth > 1 keeps the
        # chip busy while the host emits/retires the previous chunk.
        inflight: collections.deque = collections.deque()
        while True:
            if not self._active and not self._pending and not inflight:
                self._wake.clear()
                # `idle` (no work) is its own phase, excluded from the
                # goodput denominator — an empty batcher parked on its
                # wake event is not a bubble
                with self.profiler.phase("idle"):
                    await self._wake.wait()
            if self._halt:
                # migration export wants the batcher quiescent: park at
                # the loop boundary (active/pending intact, no local
                # buffers in flight) and let export_sequences serialize
                return
            # One profiled iteration: every explicit phase below claims
            # its wall time; end_iteration books the residual as
            # host_gap, so phase sums reconcile against loop wall time
            self.profiler.begin_iteration()
            # Preemption runs BEFORE the dirty-slot reset so an evicted
            # slot's table is trash-reset in this same iteration —
            # admission below may hand its freed blocks to the
            # interactive request that triggered the eviction.
            if self._ledger is not None and self._pending:
                self._maybe_preempt()
            # Reset retired slots' block tables to trash BEFORE any
            # admission can hand their freed blocks to a new request:
            # the reset rides the state-donation chain, so it lands
            # after the retiree's last in-flight garbage writes and
            # before the new owner's insert. (Slots re-admitted in the
            # same iteration are safe either way — insert overwrites
            # the table — but an idle freed slot must stop writing.)
            if self._dirty and self._st is not None:
                dirty = sorted(set(self._dirty))
                try:
                    # slot recycling is part of the admission path's
                    # block management — attribute it there, not to the
                    # host_gap residual (its first call is also the
                    # reset program's compile)
                    with self.profiler.phase("admit"):
                        async with self.gpu_lock:
                            self._st = await loop.run_in_executor(
                                None, self.cengine.reset_slots,
                                self._st, dirty)
                except Exception as e:  # noqa: BLE001
                    self._fail_all(e)
                    inflight.clear()
                    continue
                self._dirty.clear()
            elif self._dirty:
                self._dirty.clear()  # no state left to reset
            # admit up to the free-slot count; dead futures are skipped
            if self._free and self._pending:
                take: list = []
                while self._pending and len(take) < len(self._free):
                    item = self._pending.popleft()
                    if item is None:
                        # fair-share queue: requests are waiting but
                        # every queued tenant is token-paced
                        break
                    if not item[3].done():
                        take.append(item)
                if take:
                    await self._admit_group(take)
                elif not self._active and not inflight and self._pending:
                    # nothing to decode and nothing admittable (all
                    # queued tenants paced): nap for the shortest
                    # refill instead of spinning the loop hot
                    delay = 0.05
                    if self._ledger is not None:
                        delay = min(max(
                            self._pending.pacing_delay(), 0.001), 0.05)
                    await asyncio.sleep(delay)
            if self._prefill_q and self._st is not None:
                # one prompt slice per iteration: the decode stall a
                # monolithic prefill would impose is chopped into
                # budget-size pieces interleaved with decode chunks
                try:
                    await self._advance_prefills(loop)
                except Exception as e:  # noqa: BLE001
                    self._fail_all(e)
                    inflight.clear()
                    continue
            try:
                # drain whatever already finished, without blocking.
                # INSIDE the try: an async-dispatched chunk that failed
                # on device reports ready and raises at materialization
                # — that must reach _fail_all like every other failure,
                # not kill the worker and hang every future.
                while inflight and inflight[0]["toks"].is_ready():
                    self._process_chunk(inflight.popleft())
                if self.cengine.draft is not None and self.spec_enabled:
                    # speculative rounds replace plain decode chunks;
                    # synchronous (acceptance gates retirement), so the
                    # inflight pipeline stays empty in spec mode
                    await self._spec_round(loop)
                    steps = 0
                else:
                    steps = self._plan_steps(inflight)
                if steps and len(inflight) < self.pipeline_depth:
                    inflight.append(
                        await self._dispatch_chunk(loop, steps))
                elif inflight:
                    # nothing useful to dispatch ahead: block on the
                    # oldest chunk and process it (the blocking wait IS
                    # device decode time: attribute it to `decode`)
                    head = inflight.popleft()
                    with self.profiler.phase("decode"):
                        await self._sync_chunk(loop, head)
                    self._process_chunk(head)
            except Exception as e:  # noqa: BLE001 — fail active requests
                self._fail_all(e)  # donated buffers may be mid-flight
                inflight.clear()
                continue
            self.profiler.note_pool(self.cengine.pool.in_use,
                                    self.cengine.pool.capacity)
            self.profiler.note_occupancy(
                len(self._active), len(self._active) + len(self._free))
            self.profiler.end_iteration()
            # let submissions/cancellations interleave between steps
            await asyncio.sleep(0)

    # -- migration / failover ---------------------------------------------

    def checkpoints(self) -> list[dict]:
        """Lightweight resume records (tokens only, no KV) for every
        admitted request — the crash-failover feed each fleet
        heartbeat carries to the router. `tokens` is the full replay
        prompt (original prompt incl. any registered-prefix expansion,
        plus every emitted token); a healthy peer resumes by
        re-prefilling `tokens` with budget `max_new - len(out)` —
        token-identical under greedy sampling, the same replay
        contract preemption relies on."""
        out: list[dict] = []
        for rec in self._active.values():
            if rec.fut.done() or rec.meta is None:
                continue
            # the replay tokens already embed any registered prefix —
            # re-expanding it on the peer would double-prefix
            samp = {k: v for k, v in (rec.sampling or {}).items()
                    if k != "prefix"}
            out.append({
                "request_id": rec.meta.request_id,
                "tenant": rec.meta.tenant,
                "tokens": list(rec.kv_toks),
                "out": list(rec.out),
                "max_new": rec.max_new,
                "sampling": samp,
            })
        pending = (self._pending.items() if self._ledger is not None
                   else list(self._pending))
        for item in pending:
            tokens, max_new, sampling, fut, _q, _aid, _pfx, meta = item
            if fut.done() or meta is None:
                continue
            emitted: list[int] = []
            samp = dict(sampling)
            if meta.resume is not None:
                # preempted-and-parked: tokens is already the replay
                # prompt (incl. emitted), budget is the original
                emitted = list(meta.resume["out"])
                max_new = meta.resume["max_new"]
                samp.pop("prefix", None)
            out.append({
                "request_id": meta.request_id,
                "tenant": meta.tenant,
                "tokens": list(tokens),
                "out": emitted,
                "max_new": max_new,
                "sampling": samp,
            })
        return out

    async def export_sequences(self) -> list[dict]:
        """Instant drain: stop admission, park the worker at a loop
        boundary, and serialize EVERY admitted request — active slots
        with their guaranteed-written full KV blocks, pending items
        tokens-only — into versioned migration wire records
        (serving.migration). Each exported future fails with
        `MigratedAway` (the router absorbs it and resumes on the
        peer); all blocks are released, so the replica can exit
        immediately instead of waiting out its longest generation."""
        self._draining = True
        w = self._worker
        if w is not None and not w.done():
            self._halt = True
            self._wake.set()
            try:
                await w
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            finally:
                self._halt = False
        loop = asyncio.get_event_loop()
        ceng = self.cengine
        bs = ceng.block_size
        geometry = migration.pool_geometry(ceng)
        # Only full blocks strictly below len(kv_toks) - 1 are
        # guaranteed written (the final token's KV may still be in
        # flight from a discarded chunk) — the same line _cache_blocks
        # trusts. The tail re-prefills on the destination.
        exports = []
        tables = (np.asarray(self._st.block_table)
                  if self._st is not None else None)
        for slot, rec in list(self._active.items()):
            if rec.fut.done():
                self._release(slot)
                continue
            if rec.prefilling is not None:
                # mid-chunked-prefill: blocks past the fed frontier are
                # unwritten — export tokens-only, the peer re-prefills
                n_full = 0
            else:
                n_full = ((len(rec.kv_toks) - 1) // bs
                          if rec.kv_toks else 0)
            phys = ([int(b) for b in tables[slot][:n_full]]
                    if tables is not None and n_full > 0 else [])
            exports.append((slot, rec, phys))
        all_ids = [b for _, _, phys in exports for b in phys]
        k_host = v_host = None
        if all_ids:
            async with self.gpu_lock:
                k_host, v_host = await loop.run_in_executor(
                    None, ceng.export_blocks, self._st, all_ids)
        records: list[dict] = []
        off = 0
        for slot, rec, phys in exports:
            n = len(phys)
            kv = ((k_host[:, off:off + n], v_host[:, off:off + n])
                  if n else None)
            off += n
            meta = rec.meta
            rid = meta.request_id if meta is not None else ""
            samp = {k: v for k, v in (rec.sampling or {}).items()
                    if k != "prefix"}  # tokens already embed it
            records.append(migration.pack_record(
                request_id=rid,
                tenant=meta.tenant if meta is not None else "",
                ns=meta.ns if meta is not None else "",
                tokens=list(rec.kv_toks), out=list(rec.out),
                lps=list(rec.lps), max_new=rec.max_new,
                sampling=samp, geometry=geometry, kv=kv))
            if meta is not None and meta.timeline is not None:
                meta.timeline.event("migrate_out",
                                    emitted=len(rec.out), blocks=n)
            self._release(slot, cause="migration")
            self._fail(rec.fut, rec.queue, MigratedAway(rid))
        if self._ledger is not None:
            leftovers = self._pending.drain_all()
        else:
            leftovers = list(self._pending)
            self._pending.clear()
        for item in leftovers:
            tokens, max_new, sampling, fut, queue, _aid, _p, meta = item
            if fut.done():
                continue
            out_toks: list[int] = []
            lps: list[float] = []
            samp = dict(sampling)
            if meta is not None and meta.resume is not None:
                out_toks = list(meta.resume["out"])
                lps = list(meta.resume["lps"])
                max_new = meta.resume["max_new"]
                samp.pop("prefix", None)
            rid = meta.request_id if meta is not None else ""
            records.append(migration.pack_record(
                request_id=rid,
                tenant=meta.tenant if meta is not None else "",
                ns=meta.ns if meta is not None else "",
                tokens=list(tokens), out=out_toks, lps=lps,
                max_new=max_new, sampling=samp, geometry=geometry,
                kv=None))
            if meta is not None and meta.timeline is not None:
                meta.timeline.event("migrate_out",
                                    emitted=len(out_toks), blocks=0)
            self._fail(fut, queue, MigratedAway(rid))
        return records

    async def import_sequence(self, record: dict, *,
                              wedge: bool = False) -> int:
        """Import one migrated sequence's KV blocks into the local
        pool and index them in the radix cache under the record's
        namespace — cache-WARM, not an orphan decode: the router
        re-issues the generation (`tokens`, remaining budget), which
        radix-hits the imported prefix and prefills only the tail.
        Returns the number of blocks the cache adopted (0 for
        tokens-only records or already-cached prefixes). Raises
        ValueError on wire/geometry mismatch. On ANY failure —
        including a wedged transfer (`wedge=True`, the chaos harness's
        mid-transfer fault) — every allocated block is freed back: a
        failed import must leak nothing."""
        rec = migration.unpack_record(record)
        migration.validate_geometry(rec["geometry"], self.cengine)
        if rec["kv"] is None:
            return 0
        k, v = migration.decode_kv(rec["kv"])
        n_full = int(k.shape[1])
        bs = self.cengine.block_size
        if n_full * bs > len(rec["tokens"]):
            raise ValueError(
                f"migration record claims {n_full} full blocks "
                f"({n_full * bs} cells) but carries only "
                f"{len(rec['tokens'])} tokens")
        pool = self.cengine.pool
        fresh = pool.alloc(n_full)
        if fresh is None:
            self._radix.evict(n_full - pool.num_free)
            fresh = pool.alloc(n_full)
            if fresh is None:
                raise RuntimeError(
                    f"migration import needs {n_full} blocks, pool "
                    f"has {pool.num_free} free")
        loop = asyncio.get_event_loop()
        done = False
        dup: list[int] = []
        try:
            if wedge:
                raise RuntimeError(
                    "migration transfer wedged (fault injection)")
            if self._st is None:
                self._st = self.cengine.init_slots()

            def run_import():
                # read self._st INSIDE the lock: import_blocks donates
                # the slot-state buffers, so a reference captured before
                # acquisition (another import, a decode step) would be
                # deleted by whoever held the lock first
                return self.cengine.import_blocks(
                    self._st, fresh, k, v)

            async with self.gpu_lock:
                self._st = await loop.run_in_executor(None, run_import)
            # index LAST: once the tree adopts a block it owns it, and
            # the rollback below must never free tree-owned blocks
            blocks = {i: b for i, b in enumerate(fresh)}
            adopted, _ = self._radix.insert(
                rec["tokens"][:n_full * bs], blocks, ns=rec["ns"])
            dup = [b for i, b in blocks.items() if i not in adopted]
            done = True
        finally:
            if not done:
                pool.free(fresh, cause="migration")
                if self._st is not None and any(
                        leaf.is_deleted() for leaf in
                        jax.tree.leaves(self._st)
                        if hasattr(leaf, "is_deleted")):
                    self._fail_all(RuntimeError(
                        "slot state lost to donated migration import"))
        if dup:
            # this prefix (or part of it) was already cached locally:
            # the tree kept its own blocks, ours are duplicates
            pool.free(dup, cause="divergence")
        return n_full - len(dup)

    async def export_prefix(self, tokens: list[int], *, ns: str = "",
                            request_id: str = "") -> dict | None:
        """Disaggregated prefill handoff (ISSUE 12): pack the cached
        full-block KV prefix of `tokens` into a migration wire record
        with `out=[]` — the prefill half of a prefill->decode handoff.
        The caller (the server's `:prefill` endpoint) pushes it to a
        decode peer's `/v1/migrate/in`; the peer's `import_sequence`
        indexes the blocks in its radix cache, so the re-issued
        generation radix-hits the prefix and only the partial tail
        block prefills there. Token-parity holds because radix reuse
        is bit-exact and the blocks travel in canonical form.

        Returns None when nothing is exportable (no cached full block
        for this prompt, or no device state yet) — the caller treats
        that as "skip the handoff", never as an error. Matched nodes
        are ref-pinned for the duration of the device->host copy so
        concurrent admission cannot evict them mid-export."""
        ceng = self.cengine
        bs = ceng.block_size
        if self._st is None or len(tokens) < bs:
            return None
        nodes, _partial, _plen = self._radix.match(tokens, ns=ns)
        if not nodes:
            return None
        self._radix.ref(nodes)
        try:
            phys = [n.block for n in nodes]
            loop = asyncio.get_event_loop()
            async with self.gpu_lock:
                k_host, v_host = await loop.run_in_executor(
                    None, ceng.export_blocks, self._st, phys)
        finally:
            self._radix.unref(nodes)
        n_full = len(phys)
        return migration.pack_record(
            request_id=request_id, tenant="", ns=ns,
            tokens=[int(t) for t in tokens[:n_full * bs]],
            out=[], lps=[], max_new=0, sampling={},
            geometry=migration.pool_geometry(ceng),
            kv=(k_host, v_host))

    def in_flight(self) -> int:
        """Admitted-but-unfinished requests (pending, mid-prefill in
        the worker's local pipeline, or active in a slot). Zero means
        `close()` has nothing to abandon."""
        return self._admitted

    def begin_drain(self) -> None:
        """Stop admission (new `_enqueue` calls raise) while in-flight
        requests keep decoding to completion. Sticky until close() or
        end_drain()."""
        self._draining = True

    def end_drain(self) -> None:
        """Re-open admission after a completed drain. The reload path
        (`POST /v1/reload`) drains to zero, swaps weights, then calls
        this — a drain is only terminal when close() follows it."""
        self._draining = False

    def flush_cache(self) -> None:
        """Invalidate the radix prefix cache: after a weight swap every
        cached KV block describes activations of a model that no longer
        exists. Only safe at in_flight() == 0 — active sequences hold
        refs the clear would strand."""
        self._radix.clear(cause="refdrop")

    async def drain(self, timeout: float | None = None) -> bool:
        """Stop admission and wait for in-flight work to finish.
        Returns True when everything completed, False on timeout (or a
        dead worker with work still admitted) — the caller decides
        whether to close() anyway. Safe to call multiple times."""
        self._draining = True
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._admitted > 0:
            if self._worker is None or self._worker.done():
                return False  # nobody left to finish the work
            if deadline is not None and time.monotonic() > deadline:
                return False
            await asyncio.sleep(0.01)
        return True

    async def close(self) -> None:
        self._closed = True
        if self._worker is not None:
            self._worker.cancel()
            try:
                await self._worker
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        for slot, rec in list(self._active.items()):
            self._active.pop(slot, None)
            self._release_blocks(rec)
            if rec.queue is not None and not rec.fut.done():
                rec.queue.put_nowait(None)
            if not rec.fut.done():
                rec.fut.set_exception(RuntimeError("server shutting down"))
        if self._ledger is not None:
            leftovers = self._pending.drain_all()
        else:
            leftovers = list(self._pending)
            self._pending.clear()
        for item in leftovers:
            fut, queue = item[3], item[4]
            if queue is not None and not fut.done():
                queue.put_nowait(None)
            if not fut.done():
                fut.set_exception(RuntimeError("server shutting down"))
