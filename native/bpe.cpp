// Native BPE word encoder: the hot inner loop of tokenization.
//
// Same greedy rank-ordered merge semantics as the pure-Python
// implementation in kubeflow_tpu/data/bpe.py::_encode_word_cached —
// bit-identical outputs are a tested contract (tests/test_bpe.py), the
// same native/fallback discipline as dataloader.cpp. The reference has
// no tokenizer at all (no compute, SURVEY.md §2b); this is part of the
// TPU framework's native runtime alongside the data loader.
//
// C ABI (ctypes-consumed, no C++ types across the boundary):
//   kt_bpe_new(merges, n)  merges = int32[n*2] (left,right) by rank
//   kt_bpe_encode_word     utf-8 bytes in, int32 piece ids out
//   kt_bpe_free
//
// Complexity: the scan-for-best-pair loop is O(pieces^2) per word like
// the Python twin (words are capped at _MAX_WORD_CHARS upstream so the
// quadratic is bounded); the win here is the constant factor.

#include <cstddef>
#include <cstdint>
#include <unordered_map>
#include <vector>

using std::size_t;

namespace {

struct Encoder {
  // (left<<32 | right) -> rank
  std::unordered_map<uint64_t, int32_t> ranks;
};

inline uint64_t pair_key(int32_t a, int32_t b) {
  return (static_cast<uint64_t>(static_cast<uint32_t>(a)) << 32) |
         static_cast<uint32_t>(b);
}

}  // namespace

extern "C" {

void* kt_bpe_new(const int32_t* merges, int64_t n_merges) {
  auto* enc = new Encoder();
  enc->ranks.reserve(static_cast<size_t>(n_merges) * 2);
  for (int64_t i = 0; i < n_merges; ++i) {
    // operator[] (last-wins) — Python builds _ranks as {pair: i} in a
    // comprehension where a duplicate pair keeps the LAST rank; emplace
    // (first-wins) would silently break the bit-identical contract on
    // tokenizers loaded from JSON that carries duplicates.
    enc->ranks[pair_key(merges[2 * i], merges[2 * i + 1])] =
        static_cast<int32_t>(i);
  }
  return enc;
}

void kt_bpe_free(void* handle) { delete static_cast<Encoder*>(handle); }

// Encode one word. `out` must hold at least n ids. Returns the piece
// count (<= n). n == 0 returns 0.
int64_t kt_bpe_encode_word(void* handle, const uint8_t* bytes, int64_t n,
                           int32_t* out) {
  const auto* enc = static_cast<Encoder*>(handle);
  std::vector<int32_t> pieces;
  pieces.reserve(static_cast<size_t>(n));
  for (int64_t i = 0; i < n; ++i) pieces.push_back(bytes[i]);

  while (pieces.size() > 1) {
    int32_t best_rank = -1;
    size_t best_i = 0;
    for (size_t i = 0; i + 1 < pieces.size(); ++i) {
      auto it = enc->ranks.find(pair_key(pieces[i], pieces[i + 1]));
      if (it != enc->ranks.end() &&
          (best_rank < 0 || it->second < best_rank)) {
        best_rank = it->second;
        best_i = i;
      }
    }
    if (best_rank < 0) break;
    pieces[best_i] = 256 + best_rank;
    pieces.erase(pieces.begin() + static_cast<int64_t>(best_i) + 1);
  }

  for (size_t i = 0; i < pieces.size(); ++i) out[i] = pieces[i];
  return static_cast<int64_t>(pieces.size());
}

}  // extern "C"
