// kubeflow-tpu native data loader: mmap'd token shards, prefetch threads.
//
// The reference has no native code anywhere (SURVEY.md §2a: "no C++, Rust,
// or CUDA in the reference"); its data path is container images pulling
// datasets inside notebook pods. A TPU training framework lives or dies on
// host-side input throughput — the device steps in microseconds and the
// Python GIL cannot fill a v5e host's batch pipe. This loader keeps the
// hot path native:
//   - shards are mmap'd (zero-copy reads, page cache shared across procs);
//   - a thread pool assembles fixed-shape [batch, seq+1] int32 windows
//     into a bounded ring of buffers (prefetch overlaps host->device);
//   - window order is a deterministic per-epoch Fisher-Yates driven by an
//     LCG, bit-identical to the Python fallback in
//     kubeflow_tpu/data/loader.py — swap implementations, same batches.
//
// Shard format ("KTSH"): magic u32 | version u32 | n_tokens u64 | i32[].
// C ABI (ctypes-consumed, no pybind11 per environment constraints):
//   kt_loader_open(paths, n_paths, batch, seq, seed, host, n_hosts,
//                  prefetch, threads, start_ticket) -> handle (0 on error)
//   kt_loader_next(handle, out) -> 0 ok / -1 bad handle
//   kt_loader_n_windows(handle) -> total windows visible to this host
//   kt_loader_close(handle)
//   kt_last_error() -> const char* (thread-local message)
//
// start_ticket is the resume cursor: batches are pure functions of a
// dense ticket (epoch = ticket / batches_per_epoch, order from the
// seeded per-epoch shuffle), so a loader opened at ticket k emits
// exactly the stream a fresh loader emits after k next() calls —
// checkpoint/resume restores the data position without replaying.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

thread_local std::string g_last_error;

constexpr uint32_t kMagic = 0x4853544b;  // "KTSH" little-endian
constexpr uint32_t kVersion = 1;

struct Header {
  uint32_t magic;
  uint32_t version;
  uint64_t n_tokens;
};

struct Shard {
  const int32_t* tokens = nullptr;  // into the mmap
  uint64_t n_tokens = 0;
  void* map = nullptr;
  size_t map_len = 0;
};

// Deterministic 64-bit LCG (same constants in the Python fallback).
struct Lcg {
  uint64_t state;
  explicit Lcg(uint64_t seed) : state(seed) {}
  uint64_t next() {
    state = state * 6364136223846793005ULL + 1442695040888963407ULL;
    return state >> 33;
  }
};

class Loader {
 public:
  Loader(std::vector<Shard> shards, int batch, int seq, uint64_t seed,
         int host, int n_hosts, int prefetch, int threads,
         uint64_t start_ticket)
      : shards_(std::move(shards)),
        batch_(batch),
        seq_(seq),
        seed_(seed),
        host_(host),
        n_hosts_(n_hosts),
        prefetch_(prefetch < 1 ? 1 : prefetch),
        next_ticket_(start_ticket),
        next_emit_(start_ticket) {
    // Windows never cross shard boundaries; global index = shard-major.
    uint64_t cum = 0;
    for (auto& s : shards_) {
      uint64_t w = s.n_tokens > (uint64_t)seq_ ? (s.n_tokens - 1) / seq_ : 0;
      window_base_.push_back(cum);
      windows_per_shard_.push_back(w);
      cum += w;
    }
    total_windows_ = cum;
    // Host partition: windows at positions host, host+n_hosts, ... of the
    // shuffled order. Per-host batch count floors so hosts stay in step.
    host_windows_ = total_windows_ / n_hosts_;
    batches_per_epoch_ = host_windows_ / batch_;
    if (batches_per_epoch_ > 0)  // else open() rejects; no workers to race
      for (int i = 0; i < (threads < 1 ? 1 : threads); ++i)
        workers_.emplace_back([this] { WorkerLoop(); });
  }

  uint64_t batches_per_epoch() const { return batches_per_epoch_; }

  ~Loader() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_not_full_.notify_all();
    cv_not_empty_.notify_all();
    for (auto& t : workers_) t.join();
    for (auto& s : shards_)
      if (s.map) munmap(s.map, s.map_len);
  }

  uint64_t total_windows() const { return host_windows_; }

  int Next(int32_t* out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_not_empty_.wait(lk, [this] { return !ready_.empty() || stop_; });
    if (stop_ && ready_.empty()) return -1;
    std::vector<int32_t> buf = std::move(ready_.front());
    ready_.pop_front();
    lk.unlock();
    // notify_all, not notify_one: several workers can wait on cv_not_full_
    // with distinct tickets, and only the next_emit_ holder's predicate is
    // true. notify_one may wake a non-holder, which re-sleeps and consumes
    // the wakeup — the holder would then never run (lost-wakeup deadlock).
    cv_not_full_.notify_all();
    std::memcpy(out, buf.data(), buf.size() * sizeof(int32_t));
    return 0;
  }

 private:
  void CopyWindow(uint64_t global_w, int32_t* dst) const {
    // Locate the shard (linear scan: shard counts are small).
    size_t si = 0;
    while (si + 1 < window_base_.size() &&
           window_base_[si + 1] <= global_w)
      ++si;
    uint64_t local = global_w - window_base_[si];
    const int32_t* src = shards_[si].tokens + local * (uint64_t)seq_;
    std::memcpy(dst, src, (seq_ + 1) * sizeof(int32_t));
  }

  // One epoch's shuffled order, restricted to this host's slots.
  std::vector<uint64_t> EpochOrder(uint64_t epoch) const {
    std::vector<uint64_t> perm(total_windows_);
    for (uint64_t i = 0; i < total_windows_; ++i) perm[i] = i;
    Lcg rng(seed_ ^ (epoch * 0x9E3779B97F4A7C15ULL));
    for (uint64_t i = total_windows_; i > 1; --i) {
      uint64_t j = rng.next() % i;
      std::swap(perm[i - 1], perm[j]);
    }
    std::vector<uint64_t> mine;
    mine.reserve(host_windows_);
    for (uint64_t i = (uint64_t)host_; i < total_windows_;
         i += (uint64_t)n_hosts_)
      mine.push_back(perm[i]);
    return mine;
  }

  void WorkerLoop() {
    while (true) {
      uint64_t ticket;
      {
        std::lock_guard<std::mutex> lk(mu_);
        if (stop_) return;
        ticket = next_ticket_++;
      }
      uint64_t epoch = ticket / batches_per_epoch_;
      uint64_t b = ticket % batches_per_epoch_;
      // Epoch order memoized per worker would still recompute across
      // epochs; cache the latest per-thread (sequential access pattern).
      thread_local uint64_t cached_epoch = UINT64_MAX;
      thread_local std::vector<uint64_t> order;
      if (cached_epoch != epoch) {
        order = EpochOrder(epoch);
        cached_epoch = epoch;
      }
      std::vector<int32_t> buf((size_t)batch_ * (seq_ + 1));
      for (int i = 0; i < batch_; ++i)
        CopyWindow(order[b * batch_ + i], buf.data() + (size_t)i * (seq_ + 1));
      std::unique_lock<std::mutex> lk(mu_);
      // Emit strictly in ticket order into a bounded queue. Each worker
      // holds exactly one dense ticket, so the next_emit_ holder always
      // becomes runnable once the consumer drains a slot: no deadlock.
      cv_not_full_.wait(lk, [this, ticket] {
        return ((int)ready_.size() < prefetch_ && next_emit_ == ticket)
               || stop_;
      });
      if (stop_) return;
      ready_.push_back(std::move(buf));
      ++next_emit_;
      lk.unlock();
      cv_not_empty_.notify_all();
      cv_not_full_.notify_all();
    }
  }

  std::vector<Shard> shards_;
  std::vector<uint64_t> window_base_, windows_per_shard_;
  uint64_t total_windows_ = 0, host_windows_ = 0, batches_per_epoch_ = 0;
  int batch_, seq_;
  uint64_t seed_;
  int host_, n_hosts_, prefetch_;
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_not_empty_, cv_not_full_;
  std::deque<std::vector<int32_t>> ready_;
  uint64_t next_ticket_ = 0, next_emit_ = 0;
  bool stop_ = false;
};

bool MapShard(const char* path, Shard* out) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) {
    g_last_error = std::string("open failed: ") + path;
    return false;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || (size_t)st.st_size < sizeof(Header)) {
    g_last_error = std::string("stat failed or too small: ") + path;
    close(fd);
    return false;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (map == MAP_FAILED) {
    g_last_error = std::string("mmap failed: ") + path;
    return false;
  }
  const Header* h = static_cast<const Header*>(map);
  if (h->magic != kMagic || h->version != kVersion) {
    g_last_error = std::string("bad magic/version: ") + path;
    munmap(map, st.st_size);
    return false;
  }
  // Divide instead of multiply: n_tokens near 2^62 would wrap the product
  // past the file size and slip through, then read far out of the mmap.
  if (h->n_tokens >
      ((uint64_t)st.st_size - sizeof(Header)) / sizeof(int32_t)) {
    g_last_error = std::string("truncated shard: ") + path;
    munmap(map, st.st_size);
    return false;
  }
  out->map = map;
  out->map_len = st.st_size;
  out->n_tokens = h->n_tokens;
  out->tokens = reinterpret_cast<const int32_t*>(
      static_cast<const char*>(map) + sizeof(Header));
  return true;
}

}  // namespace

extern "C" {

void* kt_loader_open(const char** paths, int n_paths, int batch, int seq,
                     uint64_t seed, int host, int n_hosts, int prefetch,
                     int threads, uint64_t start_ticket) {
  if (n_paths < 1 || batch < 1 || seq < 1 || n_hosts < 1 || host < 0 ||
      host >= n_hosts) {
    g_last_error = "invalid arguments";
    return nullptr;
  }
  std::vector<Shard> shards(n_paths);
  for (int i = 0; i < n_paths; ++i) {
    if (!MapShard(paths[i], &shards[i])) {
      for (int j = 0; j < i; ++j) munmap(shards[j].map, shards[j].map_len);
      return nullptr;
    }
  }
  auto* loader = new Loader(std::move(shards), batch, seq, seed, host,
                            n_hosts, prefetch, threads, start_ticket);
  if (loader->batches_per_epoch() == 0) {
    g_last_error = "not enough windows for one batch";
    delete loader;
    return nullptr;
  }
  return loader;
}

int kt_loader_next(void* handle, int32_t* out) {
  if (!handle) {
    g_last_error = "null handle";
    return -1;
  }
  return static_cast<Loader*>(handle)->Next(out);
}

uint64_t kt_loader_n_windows(void* handle) {
  if (!handle) return 0;
  return static_cast<Loader*>(handle)->total_windows();
}

void kt_loader_close(void* handle) {
  delete static_cast<Loader*>(handle);
}

const char* kt_last_error() { return g_last_error.c_str(); }

// Bump on ANY C-ABI change (kt_loader_open gained start_ticket at 2).
// The Python side refuses to load a .so whose version disagrees —
// loading a stale prebuilt binary would silently misread arguments.
uint64_t kt_abi_version() { return 2; }

}  // extern "C"
