"""Boot-what-you-ship smoke tier: stand the platform up FROM the
rendered overlay artifacts and run the full e2e suite against it.

The reference proves its manifests by booting KinD + Istio and
`kustomize build | kubectl apply`-ing every component in CI
(`/root/reference/.github/workflows/nb_controller_kind_test.yaml:1-30`,
`components/testing/gh-actions/install_kind.sh`). This is the same tier
without a cluster, in the fake-kubelet spirit the repo's tests use
everywhere (SURVEY.md §4): act as the kubelet for the platform
Deployment in `deploy/overlays/<name>/` —

  1. parse the COMMITTED manifests (not the emitter — drift between
     emitter and committed output is tests/test_deploy.py's job; this
     tier runs what an operator would `kubectl apply`);
  2. materialize every ConfigMap the pod mounts into a temp dir and
     remap the mount paths in the container's command;
  3. exec the container's exact command with the manifest's env
     (a free port substituted for the in-cluster one);
  4. run `e2e/run_e2e.py --base-url` against it.

Exit 0 iff the platform came up from the shipped artifacts and every
e2e phase passed. Run: `python deploy/smoke.py [standalone|gke]`.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
READY_BUDGET_S = 90.0


def _load_yaml_docs(path: str) -> list[dict]:
    import yaml

    with open(path) as f:
        return [d for d in yaml.safe_load_all(f) if d]


def load_overlay(name: str) -> list[dict]:
    """All objects from the overlay's committed kustomization."""
    d = os.path.join(REPO, "deploy", "overlays", name)
    kust = _load_yaml_docs(os.path.join(d, "kustomization.yaml"))[0]
    docs: list[dict] = []
    for res in kust["resources"]:
        docs.extend(_load_yaml_docs(os.path.join(d, res)))
    return docs


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def boot_platform(docs: list[dict], workdir: str):
    """Fake-kubelet for the platform Deployment: returns (proc, base_url,
    log_path)."""
    deployments = [d for d in docs if d.get("kind") == "Deployment"]
    assert len(deployments) == 1, [d.get("kind") for d in docs]
    pod = deployments[0]["spec"]["template"]["spec"]
    (container,) = pod["containers"]
    configmaps = {d["metadata"]["name"]: d for d in docs
                  if d.get("kind") == "ConfigMap"}

    # Materialize ConfigMap volumes; mount-path -> local-dir remap.
    remap: dict[str, str] = {}
    for vol in pod.get("volumes", []):
        cm_name = vol.get("configMap", {}).get("name")
        if cm_name is None:
            continue
        cm = configmaps[cm_name]  # dangling ref = broken overlay: raise
        mount = next(m for m in container["volumeMounts"]
                     if m["name"] == vol["name"])
        local = os.path.join(workdir, vol["name"])
        os.makedirs(local, exist_ok=True)
        for fname, text in cm.get("data", {}).items():
            with open(os.path.join(local, fname), "w") as f:
                f.write(text)
        remap[mount["mountPath"]] = local

    port = _free_port()
    command = []
    for arg in container["command"]:
        for mount_path, local in remap.items():
            if arg.startswith(mount_path):
                arg = local + arg[len(mount_path):]
        command.append(arg)
    # The in-cluster port becomes a free local one (Service targetPort).
    for i, arg in enumerate(command):
        if arg == "--port":
            command[i + 1] = str(port)

    env = dict(os.environ)
    for e in container.get("env", []):
        env[e["name"]] = e.get("value", "")

    log_path = os.path.join(workdir, "platform.log")
    with open(log_path, "w") as log:
        # Popen dups the descriptor; closing our handle right away
        # means the tail read on failure sees everything the child
        # flushed, with no second writer racing it.
        proc = subprocess.Popen(command, cwd=REPO, env=env, stdout=log,
                                stderr=subprocess.STDOUT, text=True)
    return proc, f"http://127.0.0.1:{port}", log_path


def wait_ready(base: str, proc: subprocess.Popen) -> None:
    """Poll the manifest's readiness path (the kubelet's job)."""
    deadline = time.monotonic() + READY_BUDGET_S
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"platform exited rc={proc.returncode} before ready")
        try:
            with urllib.request.urlopen(f"{base}/readyz", timeout=2) as r:
                if r.status == 200:
                    return
        except (urllib.error.URLError, OSError):
            pass
        time.sleep(0.5)
    raise RuntimeError(f"platform not ready within {READY_BUDGET_S}s")


def main() -> int:
    overlay = sys.argv[1] if len(sys.argv) > 1 else "standalone"
    docs = load_overlay(overlay)
    kinds = sorted({d["kind"] for d in docs})
    print(f"[smoke] overlay {overlay}: {len(docs)} objects ({kinds})")

    with tempfile.TemporaryDirectory(prefix="kftpu-smoke-") as workdir:
        proc, base, log_path = boot_platform(docs, workdir)

        def log_tail() -> None:
            with open(log_path) as f:
                print("---- platform log tail ----")
                print("\n".join(f.read().splitlines()[-40:]))

        try:
            wait_ready(base, proc)
            print(f"[smoke] platform up at {base} "
                  f"(command from the {overlay} overlay)")
            e2e = subprocess.run(
                [sys.executable, os.path.join(REPO, "e2e", "run_e2e.py"),
                 "--base-url", base], cwd=REPO)
            if e2e.returncode != 0:
                # In --base-url mode run_e2e cannot tail the server log
                # (it never spawned one) — surface it here or a CI
                # failure ships only the client-side assertion.
                log_tail()
            return e2e.returncode
        except Exception as e:  # noqa: BLE001 — report, then log tail
            print(f"[smoke] FAILED: {e}")
            log_tail()
            return 1
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()


if __name__ == "__main__":
    sys.exit(main())
