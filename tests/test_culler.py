"""Culler semantics with fake clock + fake probe (the reference's culler
tests also never touch HTTP — SURVEY.md §4 tier 1)."""

from kubeflow_tpu.api.crds import (
    CULLING_DISABLED_ANNOTATION,
    LAST_ACTIVITY_ANNOTATION,
    Notebook,
    STOP_ANNOTATION,
)
from kubeflow_tpu.controlplane.controllers.culler import Culler, KernelStatus
from kubeflow_tpu.controlplane.store import Store


class FakeProbe:
    def __init__(self):
        self.result = [KernelStatus("idle", 0.0)]

    def kernels(self, namespace, name):
        return self.result


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def mk(store, name="nb"):
    nb = Notebook()
    nb.metadata.name = name
    nb.metadata.namespace = "u"
    return store.create(nb)


def setup():
    store = Store()
    probe = FakeProbe()
    clock = FakeClock()
    culler = Culler(probe, idle_time=600.0, check_period=60.0, clock=clock)
    return store, probe, clock, culler


def test_idle_past_threshold_culls():
    store, probe, clock, culler = setup()
    mk(store)
    culler.reconcile(store, "u", "nb")       # records activity at t=1000
    clock.t += 601
    culler.reconcile(store, "u", "nb")
    nb = store.get("Notebook", "u", "nb")
    assert STOP_ANNOTATION in nb.metadata.annotations
    assert any(e.reason == "Culled" for e in store.events_for("Notebook", "u", "nb"))


def test_busy_kernel_never_culled():
    """A 3-day pretrain keeps the kernel busy ⇒ no cull (SURVEY.md §7d)."""
    store, probe, clock, culler = setup()
    mk(store)
    probe.result = [KernelStatus("busy", 0.0)]
    culler.reconcile(store, "u", "nb")
    for _ in range(10):
        clock.t += 590
        culler.reconcile(store, "u", "nb")
    nb = store.get("Notebook", "u", "nb")
    assert STOP_ANNOTATION not in nb.metadata.annotations


def test_kernel_activity_advances_timestamp():
    store, probe, clock, culler = setup()
    mk(store)
    culler.reconcile(store, "u", "nb")
    clock.t += 500
    probe.result = [KernelStatus("idle", clock.t - 10)]  # recent activity
    culler.reconcile(store, "u", "nb")
    clock.t += 500
    culler.reconcile(store, "u", "nb")   # idle 510s < 600 ⇒ not culled
    nb = store.get("Notebook", "u", "nb")
    assert STOP_ANNOTATION not in nb.metadata.annotations
    last = float(nb.metadata.annotations[LAST_ACTIVITY_ANNOTATION])
    assert last == clock.t - 510


def test_disabled_annotation_skips():
    store, probe, clock, culler = setup()
    nb = Notebook()
    nb.metadata.name = "nb"
    nb.metadata.namespace = "u"
    nb.metadata.annotations[CULLING_DISABLED_ANNOTATION] = "true"
    store.create(nb)
    clock.t += 10000
    culler.reconcile(store, "u", "nb")
    assert STOP_ANNOTATION not in store.get(
        "Notebook", "u", "nb").metadata.annotations


def test_unreachable_probe_does_not_cull_fresh_notebook():
    store, probe, clock, culler = setup()
    mk(store)
    probe.result = None
    culler.reconcile(store, "u", "nb")
    clock.t += 10000
    culler.reconcile(store, "u", "nb")
    assert STOP_ANNOTATION not in store.get(
        "Notebook", "u", "nb").metadata.annotations


def test_culling_end_to_end_scales_down():
    """Integration: culler + notebook controller through the Cluster —
    idle notebook ends at replicas 0 (the full reference loop §3.2)."""
    import time

    from kubeflow_tpu.api.core import Container, PodTemplateSpec
    from kubeflow_tpu.controlplane.cluster import Cluster, ClusterConfig

    probe = FakeProbe()
    cfg = ClusterConfig(
        enable_culling=True, activity_probe=probe,
        cull_idle_time=0.3, cull_check_period=0.05,
    )
    with Cluster(cfg) as c:
        nb = Notebook()
        nb.metadata.name = "idle-nb"
        nb.metadata.namespace = "u"
        nb.spec.template = PodTemplateSpec()
        nb.spec.template.spec.containers.append(Container(name="idle-nb"))
        c.store.create(nb)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            sts = c.store.try_get("StatefulSet", "u", "idle-nb")
            cur = c.store.get("Notebook", "u", "idle-nb")
            if (sts is not None and sts.spec.replicas == 0
                    and STOP_ANNOTATION in cur.metadata.annotations
                    and c.store.list("Pod", "u") == []):
                break
            time.sleep(0.05)
        sts = c.store.get("StatefulSet", "u", "idle-nb")
        assert sts.spec.replicas == 0
        assert c.store.list("Pod", "u") == []


def test_http_probe_dev_mode_routes_through_local_proxy(monkeypatch):
    """Out-of-cluster operation (VERDICT r3 missing #4; ref
    culler.go:160-164): DEV mode swaps in-cluster svc DNS for the
    kubectl-proxy service-proxy path, toggled by env or constructor."""
    from kubeflow_tpu.controlplane.controllers.culler import (
        HTTPActivityProbe,
    )

    prod = HTTPActivityProbe(dev_mode=False)
    assert prod.url("user1", "nb", "kernels") == (
        "http://nb.user1.svc.cluster.local/notebook/user1/nb/api/kernels")

    dev = HTTPActivityProbe(dev_mode=True)
    assert dev.url("user1", "nb", "kernels") == (
        "http://localhost:8001/api/v1/namespaces/user1/services/nb"
        "/proxy/notebook/user1/nb/api/kernels")

    monkeypatch.setenv("KFTPU_CULLER_DEV", "true")
    monkeypatch.setenv("KFTPU_DEV_PROXY_BASE", "http://127.0.0.1:9001")
    from_env = HTTPActivityProbe()
    assert from_env.dev_mode
    assert from_env.url("a", "b", "terminals").startswith(
        "http://127.0.0.1:9001/api/v1/namespaces/a/services/b/proxy/")


def test_terminal_activity_holds_notebook_alive():
    """ref updateTimestampFromTerminalsActivity (culler.go:357-382): an
    active terminal advances last-activity even with idle kernels, so a
    shell-run job is not culled; probes without terminal support keep
    the kernel-only behavior."""
    from kubeflow_tpu.api.crds import (
        LAST_ACTIVITY_ANNOTATION,
        STOP_ANNOTATION,
    )
    from kubeflow_tpu.controlplane.controllers.culler import (
        Culler,
        KernelStatus,
    )
    from kubeflow_tpu.controlplane.store import Store

    clock = [1000.0]

    class TermProbe:
        term_stamp = 0.0

        def kernels(self, ns, name):
            return [KernelStatus("idle", 0.0)]

        def terminals(self, ns, name):
            return [self.term_stamp]

    store = Store()
    mk(store)
    probe = TermProbe()
    culler = Culler(probe, idle_time=100.0, check_period=5.0,
                clock=lambda: clock[0])

    culler.reconcile(store, "u", "nb")  # initializes the clock
    # terminal keeps touching the notebook as time passes
    clock[0] = 1090.0
    probe.term_stamp = 1085.0
    culler.reconcile(store, "u", "nb")
    got = store.get("Notebook", "u", "nb")
    assert got.metadata.annotations[LAST_ACTIVITY_ANNOTATION] == "1085.0"
    clock[0] = 1180.0  # 95s after the terminal stamp: still alive
    culler.reconcile(store, "u", "nb")
    assert STOP_ANNOTATION not in store.get(
        "Notebook", "u", "nb").metadata.annotations
    # terminal goes quiet -> idle window elapses -> culled
    clock[0] = 1190.0
    culler.reconcile(store, "u", "nb")
    assert STOP_ANNOTATION in store.get(
        "Notebook", "u", "nb").metadata.annotations


def test_busy_notebook_does_not_hot_loop_writes():
    """Review finding: the busy path's last_activity=now write emits a
    MODIFIED event that re-enqueues the culler — without the probe gate
    that is a write loop at probe latency. Re-reconciles inside one
    check_period must not probe or write."""
    from kubeflow_tpu.api.crds import LAST_ACTIVITY_ANNOTATION

    calls = []

    class CountingProbe:
        def kernels(self, ns, name):
            calls.append(1)
            return [KernelStatus("busy", 0.0)]

    store = Store()
    mk(store)
    clock = FakeClock(1000.0)
    culler = Culler(CountingProbe(), idle_time=100.0, check_period=60.0,
                    clock=clock)
    culler.reconcile(store, "u", "nb")      # init stamp (no probe yet)
    clock.t += 61.0
    culler.reconcile(store, "u", "nb")      # first real probe + write
    rv = store.get("Notebook", "u", "nb").metadata.resource_version
    for _ in range(10):                     # watch-event storm simulated
        culler.reconcile(store, "u", "nb")
    assert len(calls) == 1, f"{len(calls)} probes inside one period"
    assert store.get("Notebook", "u", "nb").metadata.resource_version == rv

    clock.t += 61.0                         # next period: probes again
    culler.reconcile(store, "u", "nb")
    assert len(calls) == 2
    got = store.get("Notebook", "u", "nb")
    assert got.metadata.annotations[LAST_ACTIVITY_ANNOTATION] == "1122.0"
