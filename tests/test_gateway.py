"""Gateway layer (odh-notebook-controller equivalent): auth-proxy
injection, Routes, NetworkPolicies, reconciliation lock.

Mirrors the reference's envtest suite shape (odh-notebook-controller/
controllers/notebook_controller_test.go:40-719: reconcile-when-modified,
recreate-when-deleted, lock-removal patterns)."""

import pytest

from kubeflow_tpu.api.core import ConfigMap, Container, PodTemplateSpec
from kubeflow_tpu.api.crds import Notebook, STOP_ANNOTATION
from kubeflow_tpu.controlplane.cluster import Cluster, ClusterConfig
from kubeflow_tpu.controlplane.controllers import gateway as gw


def mk_notebook(name="nb1", ns="user1", auth=False, topology=""):
    nb = Notebook()
    nb.metadata.name = name
    nb.metadata.namespace = ns
    if auth:
        nb.metadata.annotations[gw.INJECT_AUTH_PROXY_ANNOTATION] = "true"
    nb.spec.template = PodTemplateSpec()
    nb.spec.template.spec.containers.append(
        Container(name=name, image="kubeflow-tpu/jupyter-jax:latest")
    )
    nb.spec.tpu.topology = topology
    return nb


@pytest.fixture()
def cluster():
    cfg = ClusterConfig(tpu_slices={"v5e-16": 1, "v5e-1": 4},
                        enable_gateway=True)
    with Cluster(cfg) as c:
        yield c


def test_lock_injected_then_removed(cluster):
    """Create → lock holds STS at 0; gateway unlocks → pods start
    (ref InjectReconciliationLock + RemoveReconciliationLock)."""
    cluster.store.create(mk_notebook())
    assert cluster.wait_idle()
    nb = cluster.store.get("Notebook", "user1", "nb1")
    assert STOP_ANNOTATION not in nb.metadata.annotations
    sts = cluster.store.get("StatefulSet", "user1", "nb1")
    assert sts.spec.replicas == 1
    pod = cluster.store.get("Pod", "user1", "nb1-0")
    assert pod.phase == "Running"


def test_auth_proxy_sidecar_injected(cluster):
    cluster.store.create(mk_notebook("secure", auth=True))
    assert cluster.wait_idle()
    nb = cluster.store.get("Notebook", "user1", "secure")
    names = [c.name for c in nb.spec.template.spec.containers]
    assert names == ["secure", gw.AUTH_PROXY_CONTAINER]
    sidecar = nb.spec.template.spec.containers[1]
    assert sidecar.ports == [gw.AUTH_PROXY_PORT]
    assert sidecar.resources.requests == {"cpu": "100m", "memory": "64Mi"}
    assert sidecar.resources.limits == sidecar.resources.requests
    assert sidecar.liveness_probe.initial_delay_seconds == 30
    assert sidecar.readiness_probe.initial_delay_seconds == 5
    assert any("--sar=" in a and '"resourceName":"secure"' in a
               for a in sidecar.args)
    vols = {v.name: v for v in nb.spec.template.spec.volumes}
    assert vols["auth-config"].secret == "secure-auth-config"
    assert vols["tls-certificates"].secret == "secure-tls"
    # dedicated SA, never default (ref notebook_webhook.go:221-222)
    assert nb.spec.template.spec.service_account == "secure"


def test_auth_children_reconciled(cluster):
    cluster.store.create(mk_notebook("secure", auth=True))
    assert cluster.wait_idle()
    sa = cluster.store.get("ServiceAccount", "user1", "secure")
    assert sa.image_pull_secrets  # platform stamped the pull secret
    svc = cluster.store.get("Service", "user1", "secure-tls")
    assert svc.spec.ports[0].port == gw.AUTH_SERVICE_PORT
    assert svc.spec.ports[0].target_port == gw.AUTH_PROXY_PORT
    sec = cluster.store.get("Secret", "user1", "secure-auth-config")
    assert sec.data["cookie_secret"]
    route = cluster.store.get("Route", "user1", "secure")
    assert route.to_service == "secure-tls"
    assert route.tls_termination == "reencrypt"
    # cookie secret is generated once, stable across reconciles
    nb = cluster.store.get("Notebook", "user1", "secure")
    nb.metadata.labels["touch"] = "1"
    cluster.store.update(nb)
    assert cluster.wait_idle()
    assert cluster.store.get(
        "Secret", "user1", "secure-auth-config"
    ).data["cookie_secret"] == sec.data["cookie_secret"]


def test_plain_route_without_auth(cluster):
    cluster.store.create(mk_notebook())
    assert cluster.wait_idle()
    route = cluster.store.get("Route", "user1", "nb1")
    assert route.to_service == "nb1"
    assert route.target_port == "http"
    assert route.tls_termination == "edge"
    assert route.host == "nb1-user1.apps.example.com"


def test_network_policies(cluster):
    cluster.store.create(mk_notebook("secure", auth=True))
    assert cluster.wait_idle()
    np = cluster.store.get("NetworkPolicy", "user1", "secure-ctrl-np")
    assert np.allow_ports == [8888]
    assert np.allow_from_namespaces == [gw.SYSTEM_NAMESPACE]
    np2 = cluster.store.get("NetworkPolicy", "user1", "secure-auth-np")
    assert np2.allow_ports == [gw.AUTH_PROXY_PORT]
    assert np2.allow_from_namespaces == []  # any


def test_route_recreated_when_deleted(cluster):
    """Delete-owned-object → reconcile recreates (ref odh
    notebook_controller_test.go recreate-when-deleted specs)."""
    cluster.store.create(mk_notebook())
    assert cluster.wait_idle()
    cluster.store.delete("Route", "user1", "nb1")
    assert cluster.wait_idle()
    assert cluster.store.get("Route", "user1", "nb1")


def test_route_drift_reverted_host_kept(cluster):
    cluster.store.create(mk_notebook())
    assert cluster.wait_idle()
    route = cluster.store.get("Route", "user1", "nb1")
    route.host = "custom.host.example"     # platform-assigned: preserved
    route.target_port = "wrong"            # owned field: reverted
    cluster.store.update(route)
    assert cluster.wait_idle()
    route = cluster.store.get("Route", "user1", "nb1")
    assert route.host == "custom.host.example"
    assert route.target_port == "http"


def test_cluster_proxy_env_injection(cluster):
    cm = ConfigMap(data={"http_proxy": "http://proxy:3128",
                         "https_proxy": "http://proxy:3128",
                         "no_proxy": ".svc,.cluster.local"})
    cm.metadata.name = gw.CLUSTER_PROXY_CONFIGMAP
    cm.metadata.namespace = gw.SYSTEM_NAMESPACE
    cluster.store.create(cm)
    ca = ConfigMap(data={"ca-bundle.crt": "FAKE-CA"})
    ca.metadata.name = gw.TRUSTED_CA_CONFIGMAP
    ca.metadata.namespace = gw.SYSTEM_NAMESPACE
    cluster.store.create(ca)

    cluster.store.create(mk_notebook("proxied"))
    assert cluster.wait_idle()
    nb = cluster.store.get("Notebook", "user1", "proxied")
    env = {e.name: e.value for e in nb.spec.template.spec.containers[0].env}
    assert env["HTTP_PROXY"] == "http://proxy:3128"
    assert env["NO_PROXY"] == ".svc,.cluster.local"
    # trusted CA mirrored into the user namespace
    mirrored = cluster.store.get("ConfigMap", "user1", gw.TRUSTED_CA_CONFIGMAP)
    assert mirrored.data["ca-bundle.crt"] == "FAKE-CA"


def test_trusted_ca_recreated_when_deleted(cluster):
    ca = ConfigMap(data={"ca-bundle.crt": "FAKE-CA"})
    ca.metadata.name = gw.TRUSTED_CA_CONFIGMAP
    ca.metadata.namespace = gw.SYSTEM_NAMESPACE
    cluster.store.create(ca)
    cluster.store.create(mk_notebook())
    assert cluster.wait_idle()
    assert cluster.store.get("ConfigMap", "user1", gw.TRUSTED_CA_CONFIGMAP)
    cluster.store.delete("ConfigMap", "user1", gw.TRUSTED_CA_CONFIGMAP)
    assert cluster.wait_idle()
    # WATCHES=("ConfigMap",) re-enqueues the notebook: mirror comes back
    assert cluster.store.get("ConfigMap", "user1", gw.TRUSTED_CA_CONFIGMAP)


def test_lock_wait_budget_expires_then_force_unlocks():
    """Without the pull-secret webhook, the gate waits out its budget then
    unlocks anyway (ref swallows the wait error and removes the lock)."""
    from kubeflow_tpu.controlplane.controllers.gateway import (
        GatewayNotebookController,
        NotebookGatewayWebhook,
    )
    from kubeflow_tpu.controlplane.store import Store

    store = Store()
    store.register_mutating_webhook("Notebook", NotebookGatewayWebhook(store))
    t = [0.0]
    ctrl = GatewayNotebookController(lock_wait_budget=10.0, clock=lambda: t[0])
    nb = mk_notebook("slow", auth=True)
    store.create(nb)
    res = ctrl.reconcile(store, "user1", "slow")
    # SA exists but has no pull secret (no platform webhook): still locked
    assert res.requeue_after is not None
    assert STOP_ANNOTATION in store.get(
        "Notebook", "user1", "slow").metadata.annotations
    t[0] = 11.0
    ctrl.reconcile(store, "user1", "slow")
    assert STOP_ANNOTATION not in store.get(
        "Notebook", "user1", "slow").metadata.annotations


def test_gang_notebook_gated_by_lock(cluster):
    """TPU twist: the lock gates the WHOLE gang — no partial slice starts
    before the control plane unlocks."""
    cluster.store.create(mk_notebook("big", topology="v5e-16"))
    assert cluster.wait_idle()
    sts = cluster.store.get("StatefulSet", "user1", "big")
    assert sts.spec.replicas == 4
    pods = cluster.store.list("Pod", "user1",
                              label_selector={"notebook-name": "big"})
    assert len(pods) == 4


def test_ca_rotation_in_system_namespace_propagates(cluster):
    """Updating the SOURCE bundle (system namespace) must refresh every
    user-namespace mirror — cluster-wide fan-out, not namespace-scoped."""
    ca = ConfigMap(data={"ca-bundle.crt": "CA-V1"})
    ca.metadata.name = gw.TRUSTED_CA_CONFIGMAP
    ca.metadata.namespace = gw.SYSTEM_NAMESPACE
    cluster.store.create(ca)
    cluster.store.create(mk_notebook())
    assert cluster.wait_idle()
    assert cluster.store.get(
        "ConfigMap", "user1", gw.TRUSTED_CA_CONFIGMAP
    ).data["ca-bundle.crt"] == "CA-V1"

    src = cluster.store.get("ConfigMap", gw.SYSTEM_NAMESPACE,
                            gw.TRUSTED_CA_CONFIGMAP)
    src.data = {"ca-bundle.crt": "CA-V2-ROTATED"}
    cluster.store.update(src)
    assert cluster.wait_idle()
    assert cluster.store.get(
        "ConfigMap", "user1", gw.TRUSTED_CA_CONFIGMAP
    ).data["ca-bundle.crt"] == "CA-V2-ROTATED"


def test_recreated_notebook_gets_fresh_lock_wait():
    """Delete + recreate same-name notebook: the new one must not inherit
    the old (expired) lock-wait deadline and unlock instantly."""
    from kubeflow_tpu.controlplane.controllers.gateway import (
        GatewayNotebookController,
        NotebookGatewayWebhook,
    )
    from kubeflow_tpu.controlplane.store import Store

    store = Store()
    store.register_mutating_webhook("Notebook", NotebookGatewayWebhook(store))
    t = [0.0]
    ctrl = GatewayNotebookController(lock_wait_budget=10.0, clock=lambda: t[0])
    store.create(mk_notebook("nb", auth=True))
    ctrl.reconcile(store, "user1", "nb")          # starts the wait at t=0
    t[0] = 50.0                                    # way past the budget
    store.delete("Notebook", "user1", "nb")
    ctrl.reconcile(store, "user1", "nb")          # delete-event reconcile

    store.create(mk_notebook("nb", auth=True))    # recreated, re-locked
    res = ctrl.reconcile(store, "user1", "nb")
    # Fresh wait: still locked, requeued — NOT force-unlocked.
    assert res.requeue_after is not None
    assert STOP_ANNOTATION in store.get(
        "Notebook", "user1", "nb").metadata.annotations
    t[0] = 61.0                                    # budget elapses again
    ctrl.reconcile(store, "user1", "nb")
    assert STOP_ANNOTATION not in store.get(
        "Notebook", "user1", "nb").metadata.annotations


def test_coalesced_delete_recreate_still_fresh_wait():
    """Delete+recreate that coalesces into ONE reconcile (dedup workqueue)
    must still start a fresh lock wait — the deadline is uid-pinned."""
    from kubeflow_tpu.controlplane.controllers.gateway import (
        GatewayNotebookController,
        NotebookGatewayWebhook,
    )
    from kubeflow_tpu.controlplane.store import Store

    store = Store()
    store.register_mutating_webhook("Notebook", NotebookGatewayWebhook(store))
    t = [0.0]
    ctrl = GatewayNotebookController(lock_wait_budget=10.0, clock=lambda: t[0])
    store.create(mk_notebook("nb", auth=True))
    ctrl.reconcile(store, "user1", "nb")          # deadline pinned to uid A
    t[0] = 50.0
    store.delete("Notebook", "user1", "nb")
    store.create(mk_notebook("nb", auth=True))    # uid B; no reconcile between
    res = ctrl.reconcile(store, "user1", "nb")    # the single coalesced run
    assert res.requeue_after is not None
    assert STOP_ANNOTATION in store.get(
        "Notebook", "user1", "nb").metadata.annotations
