"""Profile controller + KFAM + authz integration."""

import pytest

from kubeflow_tpu.api.crds import Profile
from kubeflow_tpu.controlplane.auth import (
    Forbidden,
    Unauthenticated,
    User,
    authenticate,
    check_csrf,
    ensure_authorized,
    namespaces_for,
    new_csrf_token,
)
from kubeflow_tpu.controlplane.controllers.profile import (
    OWNER_ANNOTATION,
    ProfileController,
    WorkloadIdentityPlugin,
)
from kubeflow_tpu.controlplane.kfam import Binding, Kfam, KfamError, PermissionDenied
from kubeflow_tpu.controlplane.runtime import Manager
from kubeflow_tpu.controlplane.store import NotFound, Store


def mk_profile(name="alice", owner="alice@example.com", quota=None):
    p = Profile()
    p.metadata.name = name
    p.spec.owner = owner
    if quota:
        p.spec.resource_quota = quota
    return p


@pytest.fixture()
def env():
    store = Store()
    mgr = Manager(store)
    mgr.register(ProfileController(
        default_namespace_labels={"istio-injection": "enabled"},
        plugins=[WorkloadIdentityPlugin()],
    ))
    mgr.start()
    yield store, mgr
    mgr.stop()


def test_profile_materializes_tenancy(env):
    store, mgr = env
    store.create(mk_profile(quota={"cpu": "32", "tpu/v5e-chips": "16"}))
    assert mgr.wait_idle()
    ns = store.get("Namespace", "", "alice")
    assert ns.metadata.annotations[OWNER_ANNOTATION] == "alice@example.com"
    assert ns.metadata.labels["istio-injection"] == "enabled"
    assert store.get("ServiceAccount", "alice", "default-editor")
    assert store.get("ServiceAccount", "alice", "default-viewer")
    rb = store.get("RoleBinding", "alice", "namespace-admin")
    assert rb.subjects == ["alice@example.com"]
    ap = store.get("AuthorizationPolicy", "alice", "ns-owner-access")
    assert "alice@example.com" in ap.allow_users
    rq = store.get("ResourceQuota", "alice", "kf-resource-quota")
    assert rq.hard["tpu/v5e-chips"] == "16"
    # workload identity plugin annotated the editor SA
    sa = store.get("ServiceAccount", "alice", "default-editor")
    assert sa.metadata.annotations[WorkloadIdentityPlugin.SA_ANNOTATION] == (
        "alice@project.iam.gserviceaccount.com")
    assert store.get("Profile", "", "alice").status.phase == "Ready"


def test_profile_delete_cleans_namespace(env):
    store, mgr = env
    store.create(mk_profile())
    assert mgr.wait_idle()
    store.delete("Profile", "", "alice")
    assert mgr.wait_idle()
    assert store.try_get("Profile", "", "alice") is None
    assert store.try_get("Namespace", "", "alice") is None
    assert store.try_get("ServiceAccount", "alice", "default-editor") is None


def test_foreign_namespace_not_adopted(env):
    store, mgr = env
    from kubeflow_tpu.api.core import Namespace

    ns = Namespace()
    ns.metadata.name = "taken"
    ns.metadata.annotations[OWNER_ANNOTATION] = "mallory@example.com"
    store.create(ns)
    store.create(mk_profile("taken", owner="alice@example.com"))
    assert mgr.wait_idle()
    p = store.get("Profile", "", "taken")
    assert p.status.phase == "Failed"
    assert "not owned" in p.status.message


def test_kfam_contributor_flow(env):
    store, mgr = env
    store.create(mk_profile())
    assert mgr.wait_idle()
    kfam = Kfam(store)
    owner = User("alice@example.com")
    bob = User("bob@example.com")

    # owner adds bob as editor
    kfam.create_binding(owner, Binding("bob@example.com", "alice", "edit"))
    listed = kfam.list_bindings(owner, "alice")
    assert Binding("bob@example.com", "alice", "edit") in listed
    ap = store.get("AuthorizationPolicy", "alice", "ns-owner-access")
    assert "bob@example.com" in ap.allow_users

    # bob (not owner/admin) cannot add carol
    with pytest.raises(PermissionDenied):
        kfam.create_binding(bob, Binding("carol@example.com", "alice", "view"))

    # bob can edit resources in alice's namespace now
    ensure_authorized(store, bob, "create", "Notebook", "alice")
    with pytest.raises(Forbidden):
        ensure_authorized(store, User("carol@example.com"), "get",
                          "Notebook", "alice")

    # remove bob: authz falls back to forbidden
    kfam.delete_binding(owner, Binding("bob@example.com", "alice", "edit"))
    with pytest.raises(Forbidden):
        ensure_authorized(store, bob, "create", "Notebook", "alice")
    ap = store.get("AuthorizationPolicy", "alice", "ns-owner-access")
    assert "bob@example.com" not in ap.allow_users


def test_kfam_validation(env):
    store, mgr = env
    store.create(mk_profile())
    assert mgr.wait_idle()
    kfam = Kfam(store)
    owner = User("alice@example.com")
    with pytest.raises(KfamError, match="unknown role"):
        kfam.create_binding(owner, Binding("bob@example.com", "alice", "root"))
    with pytest.raises(KfamError, match="invalid user"):
        kfam.create_binding(owner, Binding("not an email", "alice", "edit"))


def test_kfam_cluster_admin(env):
    store, mgr = env
    store.create(mk_profile())
    assert mgr.wait_idle()
    kfam = Kfam(store, cluster_admins={"root@example.com"})
    root = User("root@example.com")
    assert kfam.is_cluster_admin(root)
    assert not kfam.is_cluster_admin(User("alice@example.com"))
    # admin can create profiles for others and manage any namespace
    kfam.create_profile(root, "bobspace", owner="bob@example.com")
    assert mgr.wait_idle()
    kfam.create_binding(root, Binding("carol@example.com", "alice", "view"))


def test_viewer_cannot_write(env):
    store, mgr = env
    store.create(mk_profile())
    assert mgr.wait_idle()
    kfam = Kfam(store)
    owner = User("alice@example.com")
    kfam.create_binding(owner, Binding("carol@example.com", "alice", "view"))
    carol = User("carol@example.com")
    ensure_authorized(store, carol, "list", "Notebook", "alice")
    with pytest.raises(Forbidden):
        ensure_authorized(store, carol, "delete", "Notebook", "alice")


def test_namespaces_for_and_authn(env):
    store, mgr = env
    store.create(mk_profile())
    store.create(mk_profile("bob", owner="bob@example.com"))
    assert mgr.wait_idle()
    kfam = Kfam(store)
    kfam.create_binding(User("alice@example.com"),
                        Binding("bob@example.com", "alice", "edit"))
    assert namespaces_for(store, User("bob@example.com")) == ["alice", "bob"]
    assert namespaces_for(
        store, User("root@x.com"), cluster_admins={"root@x.com"}
    ) == ["alice", "bob"]

    u = authenticate({"kubeflow-userid": "x@y.z"})
    assert u.name == "x@y.z"
    with pytest.raises(Unauthenticated):
        authenticate({})


def test_csrf():
    t = new_csrf_token()
    assert check_csrf(t, t)
    assert not check_csrf(t, new_csrf_token())
    assert not check_csrf(None, t)
    assert not check_csrf(t, None)


def test_reserved_namespace_rejected(env):
    """Privilege-escalation guard: self-serve profile cannot claim system
    namespaces (owning kubeflow-tpu-system would mint cluster admins)."""
    store, mgr = env
    kfam = Kfam(store)
    attacker = User("mallory@example.com")
    for name in ("kubeflow-tpu-system", "kube-system", "default",
                 "kubeflow-tpu-anything"):
        with pytest.raises(PermissionDenied, match="reserved"):
            kfam.create_profile(attacker, name)
    # direct CR creation (bypassing kfam) is also neutralized
    store.create(mk_profile("kubeflow-tpu-system", owner="mallory@example.com"))
    assert mgr.wait_idle()
    p = store.get("Profile", "", "kubeflow-tpu-system")
    assert p.status.phase == "Failed"
    assert store.try_get("RoleBinding", "kubeflow-tpu-system",
                         "namespace-admin") is None
    from kubeflow_tpu.controlplane.auth import is_cluster_admin
    assert not is_cluster_admin(store, attacker)
