"""Memory-fit planner (tools/memplan.py): the BASELINE north-star
config must plan green; impossible configs must plan red — all via
eval_shape, no device allocation."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import memplan  # noqa: E402


def test_llama3_8b_fsdp16_fits_v5e():
    """The BASELINE north star: Llama-3-8B FSDP over a v5e-16 slice."""
    r = memplan.plan("llama3-8b", {"data": 1, "fsdp": 16, "tensor": 1},
                     batch=16, seq=2048, generation="v5e")
    assert r["fits"], r
    assert 7.9e9 < r["params"] < 8.2e9  # it really is the 8B
    # fp32 master params 32 GB over 16 chips = 2 GB/chip
    assert abs(r["per_chip_gb"]["params"] - 2.0) < 0.1


def test_llama3_8b_single_chip_does_not_fit():
    r = memplan.plan("llama3-8b", {"data": 1, "fsdp": 1, "tensor": 1},
                     batch=1, seq=128, generation="v5e")
    assert not r["fits"], r  # 32 GB of fp32 params alone > 16 GB HBM


def test_tp_shards_the_right_tensors():
    """tensor-axis sharding reduces per-chip bytes for heads/mlp/vocab
    tensors: an fsdp16 plan and an fsdp8xtp2 plan land close, both far
    below fsdp8 alone."""
    fsdp16 = memplan.plan("llama3-8b",
                          {"data": 1, "fsdp": 16, "tensor": 1},
                          batch=16, seq=2048, generation="v5e")
    mixed = memplan.plan("llama3-8b",
                         {"data": 1, "fsdp": 8, "tensor": 2},
                         batch=16, seq=2048, generation="v5e")
    fsdp8 = memplan.plan("llama3-8b",
                         {"data": 2, "fsdp": 8, "tensor": 1},
                         batch=16, seq=2048, generation="v5e")
    assert fsdp16["per_chip_gb"]["params"] < fsdp8["per_chip_gb"]["params"]
    assert mixed["per_chip_gb"]["params"] < fsdp8["per_chip_gb"]["params"]


def test_cli_contract():
    """One JSON line on stdout, human table on stderr, rc reflects fit."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    ok = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "memplan.py"),
         "--model", "llama3-1b", "--topology", "v5e-4"],
        capture_output=True, text=True, env=env, timeout=120)
    assert ok.returncode == 0, ok.stderr
    out = json.loads(ok.stdout.strip().splitlines()[-1])
    assert out["fits"] is True
    assert "fits" in ok.stderr

    bad = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "memplan.py"),
         "--model", "llama3-8b", "--topology", "v5e-1"],
        capture_output=True, text=True, env=env, timeout=120)
    assert bad.returncode == 1, bad.stderr
    assert json.loads(bad.stdout.strip().splitlines()[-1])["fits"] is False

    mismatch = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "memplan.py"),
         "--topology", "v5e-16", "--mesh", "data=2,fsdp=2,tensor=2"],
        capture_output=True, text=True, env=env, timeout=120)
    assert mismatch.returncode == 2  # argparse error: 8 devices != 16


def test_eval_ppl_tool(tmp_path, capsys):
    """tools/eval_ppl: token-weighted NLL over KTSH shards; a random
    model on uniform-random tokens lands near ln(vocab) (it can't be
    much better than uniform, and random confident preferences make it
    somewhat worse)."""
    import json
    import math

    import numpy as np

    from kubeflow_tpu.data import loader as dl
    from kubeflow_tpu.models import llama
    import eval_ppl

    shard = str(tmp_path / "val.ktsh")
    dl.write_shard(shard, np.random.default_rng(0).integers(
        0, llama.LLAMA_TINY.vocab_size, 6000).astype(np.int32))
    rc = eval_ppl.main(["--shards", shard, "--model", "llama-tiny",
                        "--random", "--batch", "2", "--seq", "64",
                        "--max-batches", "3"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["tokens"] == 2 * 64 * 3
    uniform = math.log(llama.LLAMA_TINY.vocab_size)
    assert uniform * 0.9 < out["loss"] < uniform * 1.5, out


def test_serving_planner_modes():
    """Serving fit: 8B bf16 cannot fit one v5e chip, int8 can, and
    TP sharding divides both weights and (kv-head-sharded) cache."""
    from memplan import plan_serving

    one = {"data": 1, "fsdp": 1, "tensor": 1}
    bf16 = plan_serving("llama3-8b", one, 8, 4096, "v5e", "")
    assert not bf16["fits"]
    int8 = plan_serving("llama3-8b", one, 8, 4096, "v5e", "int8")
    assert int8["fits"]
    assert int8["per_chip_gb"]["weights"] == pytest.approx(
        bf16["per_chip_gb"]["weights"] / 2, rel=0.01)
    tp4 = plan_serving("llama3-8b", {"data": 1, "fsdp": 1, "tensor": 4},
                       16, 8192, "v5e", "")
    assert tp4["fits"]
    # 2x slots x 2x max_len / 4-way kv-head sharding = the same per-chip
    # cache bytes as the single-chip 8x4096 plan
    assert tp4["per_chip_gb"]["kv_cache"] == pytest.approx(
        bf16["per_chip_gb"]["kv_cache"], rel=0.01)
    assert tp4["max_slots_that_fit"] >= 16


def test_prepare_data_tool(tmp_path, capsys):
    """prepare_data: corpus -> tokenizer + shards that the loader and
    tokenizer round-trip; --tokenizer reuse keeps one vocabulary."""
    import json

    import numpy as np

    import prepare_data
    from kubeflow_tpu.data import bpe
    from kubeflow_tpu.data import loader as dl

    for i in range(2):
        (tmp_path / f"doc{i}.txt").write_text(
            ("the quick brown fox jumps over the lazy dog " * 30)
            + f"document {i} ")
    out = tmp_path / "out"
    rc = prepare_data.main([
        "--input", str(tmp_path / "*.txt"), "--out", str(out),
        "--vocab-size", "300", "--shard-tokens", "150"])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip())
    assert summary["shards"] >= 2, summary  # shard-tokens forced a split
    assert (out / "tokenizer.json").exists()

    shards = sorted(str(s) for s in out.glob("shard-*.ktsh"))
    with dl.open_loader(shards, batch=2, seq=32, seed=0) as ld:
        batch = ld.next_batch()
        assert batch.shape == (2, 33)
        tok = bpe.Tokenizer.load(str(out / "tokenizer.json"))
        assert batch.max() < tok.vocab_size
        text = tok.decode([int(t) for t in batch[0] if t >= 0])
        assert "fox" in text or "dog" in text or "document" in text

    # val shards reuse the train vocabulary
    val = tmp_path / "val"
    rc = prepare_data.main([
        "--input", str(tmp_path / "doc0.txt"), "--out", str(val),
        "--tokenizer", str(out / "tokenizer.json")])
    assert rc == 0
    summary2 = json.loads(capsys.readouterr().out.strip())
    assert summary2["vocab_size"] == summary["vocab_size"]
    assert not (val / "tokenizer.json").exists()  # reused, not retrained
