"""Fleet layer: registry health states, rendezvous routing, router
retry/hedging, autoscale math, drain (batcher, server, controller)."""

import asyncio
import json
import socket
import time

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

pytest_plugins = ("aiohttp.pytest_plugin",)

from kubeflow_tpu.fleet import autoscale as autoscale_mod
from kubeflow_tpu.fleet import router as router_mod
from kubeflow_tpu.fleet.registry import (
    DEAD,
    DEGRADED,
    DRAINING,
    READY,
    ReplicaRegistry,
    rendezvous,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# -- registry ---------------------------------------------------------------


def test_registry_heartbeat_state_machine():
    clk = FakeClock()
    reg = ReplicaRegistry(degraded_after_s=5, dead_after_s=15, clock=clk)
    rep = reg.register("http://a:1", replica_id="a", max_slots=4)
    assert rep.state == READY and reg.counts()[READY] == 1

    clk.t = 6.0
    reg.sweep()
    assert reg.get("a").state == DEGRADED
    clk.t = 16.0
    reg.sweep()
    assert reg.get("a").state == DEAD
    # a fresh heartbeat resurrects (the process came back)
    assert reg.heartbeat("a", queue_depth=2)
    assert reg.get("a").state == READY
    assert reg.get("a").queue_depth == 2
    # unknown id tells the replica to re-register
    assert not reg.heartbeat("ghost")
    # draining is sticky: neither heartbeat nor sweep unsticks it
    reg.drain("a")
    assert reg.heartbeat("a")
    assert reg.get("a").state == DRAINING
    clk.t = 100.0
    reg.sweep()
    assert reg.get("a").state == DRAINING
    assert reg.deregister("a") and reg.get("a") is None


def test_registry_heartbeat_reports_draining():
    reg = ReplicaRegistry(clock=FakeClock())
    reg.register("http://a:1", replica_id="a")
    assert reg.heartbeat("a", draining=True)
    assert reg.get("a").state == DRAINING


def test_registry_failure_path_degrades_then_kills():
    reg = ReplicaRegistry(dead_failures=3, clock=FakeClock())
    reg.register("http://a:1", replica_id="a")
    reg.note_failure("a")
    assert reg.get("a").state == DEGRADED
    reg.note_success("a")          # recovery resets the streak
    assert reg.get("a").failures == 0
    for _ in range(3):
        reg.note_failure("a")
    assert reg.get("a").state == DEAD


def test_registry_stats_reject_garbage():
    reg = ReplicaRegistry(clock=FakeClock())
    reg.register("http://a:1", replica_id="a", max_slots=8)
    reg.heartbeat("a", queue_depth=-5, max_slots=True, active_slots="x")
    rep = reg.get("a")
    assert rep.queue_depth == 0 and rep.max_slots == 8
    assert rep.active_slots == 0


def test_rendezvous_stability_under_add_remove():
    ids = ["r0", "r1", "r2"]
    keys = [f"prefix-{i}".encode() for i in range(200)]
    before = {k: rendezvous(k, ids) for k in keys}
    # removing r2 moves ONLY r2's keys
    after_rm = {k: rendezvous(k, ["r0", "r1"]) for k in keys}
    for k in keys:
        if before[k] != "r2":
            assert after_rm[k] == before[k]
    # adding r3 steals only the keys r3 now wins — nothing else moves
    after_add = {k: rendezvous(k, ids + ["r3"]) for k in keys}
    moved = 0
    for k in keys:
        if after_add[k] != before[k]:
            assert after_add[k] == "r3"
            moved += 1
    assert 0 < moved < len(keys)


def _prompt_mapped_to(reg, want_id, block_size=4):
    """First token list whose affinity key rendezvous-maps to want_id."""
    ids = [r.id for r in reg.replicas()]
    for s in range(3, 2000):
        toks = [s, 1, 2, 3]
        key = router_mod.affinity_key({"tokens": [toks]}, block_size)
        if rendezvous(key, ids) == want_id:
            return toks
    raise AssertionError(f"no prompt maps to {want_id}")


def test_pick_affinity_vs_fallback():
    clk = FakeClock()
    reg = ReplicaRegistry(overload_depth=4, clock=clk)
    reg.register("http://a:1", replica_id="a")
    reg.register("http://b:1", replica_id="b")
    toks = _prompt_mapped_to(reg, "a")
    key = router_mod.affinity_key({"tokens": [toks]}, 4)

    rep, reason = reg.pick(key)
    assert (rep.id, reason) == ("a", "affinity")
    # overloaded affinity target: least-loaded fallback takes over
    reg.heartbeat("a", queue_depth=10)
    rep, reason = reg.pick(key)
    assert (rep.id, reason) == ("b", "fallback")
    reg.heartbeat("a", queue_depth=0)
    # draining target is not routable at all
    reg.drain("a")
    rep, reason = reg.pick(key)
    assert rep.id == "b"
    # no affinity key: least (load, id)
    rep, reason = reg.pick(b"")
    assert reason == "fallback"
    # everything unroutable -> none (degraded would still be tried)
    reg.drain("b")
    rep, reason = reg.pick(key)
    assert rep is None


def test_affinity_key_mirrors_server_byte_encode():
    """The router hashes text bodies WITHOUT importing the jax-loaded
    server module; this pins the two tokenizations together."""
    from kubeflow_tpu.serving.server import byte_encode

    text = "hello fleet"
    want = " ".join(str(t) for t in byte_encode(text)[:64]).encode()
    assert router_mod.affinity_key({"text": text}, 64) == want
    # token bodies hash the first block only
    assert router_mod.affinity_key({"tokens": [[5, 6, 7, 8]]}, 2) == b"5 6"
    # malformed bodies -> no affinity, never a crash
    assert router_mod.affinity_key({"tokens": "nope"}, 4) == b""
    assert router_mod.affinity_key({}, 4) == b""


# -- autoscale --------------------------------------------------------------


def test_autoscale_recommendation_math():
    rec = autoscale_mod.recommend_replicas([], min_replicas=2)
    assert rec.desired == 2 and "no live" in rec.reason

    def rep(**kw):
        base = {"state": READY, "queue_depth": 0, "active_slots": 0,
                "max_slots": 8, "kv_blocks_free": 100,
                "kv_blocks_total": 100}
        base.update(kw)
        return base

    # demand 20 over 8 slots/replica -> 3
    rec = autoscale_mod.recommend_replicas(
        [rep(active_slots=8, queue_depth=12)], max_replicas=8)
    assert rec.desired == 3
    # clamped by max_replicas
    rec = autoscale_mod.recommend_replicas(
        [rep(active_slots=8, queue_depth=120)], max_replicas=4)
    assert rec.desired == 4
    # KV pressure forces scale-out even with idle slots
    rec = autoscale_mod.recommend_replicas(
        [rep(kv_blocks_free=5), rep(kv_blocks_free=90)], max_replicas=8)
    assert rec.desired == 3 and "kv pressure" in rec.reason
    # scale-down hysteresis: demand 6 fits 1 replica's 8 slots but not
    # with 0.7 headroom (6 > 5.6) -> hold at 2
    rec = autoscale_mod.recommend_replicas(
        [rep(active_slots=3), rep(active_slots=3)], max_replicas=8)
    assert rec.desired == 2 and "hold" in rec.reason
    # demand 4 leaves headroom (4 <= 5.6) -> shrink to 1
    rec = autoscale_mod.recommend_replicas(
        [rep(active_slots=2), rep(active_slots=2)], max_replicas=8)
    assert rec.desired == 1
    # draining/dead replicas are not capacity
    rec = autoscale_mod.recommend_replicas(
        [rep(active_slots=8, queue_depth=12), rep(state=DRAINING),
         rep(state=DEAD)], max_replicas=8)
    assert rec.signals["live"] == 1 and rec.desired == 3
    with pytest.raises(ValueError):
        autoscale_mod.recommend_replicas([], min_replicas=3,
                                         max_replicas=2)


# -- router (HTTP, stub replicas) ------------------------------------------


def _stub_app(replica_name, delay=0.0, status=200):
    """Minimal generate-only replica: echoes max_new sevens."""
    async def gen(request):
        body = await request.json()
        if delay:
            await asyncio.sleep(delay)
        if status != 200:
            return web.json_response({"error": "boom"}, status=status)
        return web.json_response(
            {"tokens": [[7] * body.get("max_new", 4)],
             "served_by": replica_name})

    app = web.Application()
    app.router.add_post("/v1/models/{name}:generate", gen)
    return app


async def _start_stub(name, **kw):
    server = TestServer(_stub_app(name, **kw))
    await server.start_server()
    return server, f"http://127.0.0.1:{server.port}"


async def test_router_registration_endpoints(aiohttp_client):
    reg = ReplicaRegistry()
    client = await aiohttp_client(router_mod.create_router_app(reg))
    r = await client.post("/fleet/register",
                          json={"id": "r0", "url": "http://x:1",
                                "models": ["tiny"], "max_slots": 8})
    assert r.status == 200 and (await r.json())["id"] == "r0"
    r = await client.post("/fleet/heartbeat",
                          json={"id": "r0", "queue_depth": 3})
    assert r.status == 200
    r = await client.get("/fleet/replicas")
    snap = (await r.json())["replicas"][0]
    assert snap["queue_depth"] == 3 and snap["state"] == READY
    assert snap["last_heartbeat_age_s"] is not None
    # unknown heartbeat -> 404 (the replica's cue to re-register)
    r = await client.post("/fleet/heartbeat", json={"id": "ghost"})
    assert r.status == 404
    r = await client.get("/fleet/autoscale?min=1&max=4")
    assert (await r.json())["desired"] == 1
    r = await client.get("/healthz")
    assert (await r.json())["routable"] == 1
    r = await client.post("/fleet/deregister", json={"id": "r0"})
    assert (await r.json())["removed"] is True
    # bad registrations are 400s, not crashes
    r = await client.post("/fleet/register", json={"url": 7})
    assert r.status == 400


async def test_router_routes_by_affinity_and_retries_dead_replica(
        aiohttp_client):
    good_server, good_url = await _start_stub("good")
    # a registered-but-dead replica: nothing listens on this port
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        dead_url = f"http://127.0.0.1:{s.getsockname()[1]}"
    reg = ReplicaRegistry()
    reg.register(good_url, replica_id="good")
    reg.register(dead_url, replica_id="dead")
    client = await aiohttp_client(router_mod.create_router_app(
        reg, block_size=4, hedge_after_s=0, backoff_s=0.001))
    try:
        toks = _prompt_mapped_to(reg, "dead")
        r = await client.post("/v1/models/tiny:generate",
                              json={"tokens": [toks], "max_new": 3})
        assert r.status == 200
        body = await r.json()
        assert body["served_by"] == "good"
        assert r.headers["X-Fleet-Replica"] == "good"
        assert "X-Trace-Id" in r.headers
        assert reg.get("dead").state == DEGRADED  # first observed failure
        stats = await (await client.get("/fleet/stats")).json()
        assert stats["route_total"]["retry"] >= 1
        # an affinity-routable prompt routes (and labels) as affinity
        toks = _prompt_mapped_to(reg, "good")
        r = await client.post("/v1/models/tiny:generate",
                              json={"tokens": [toks], "max_new": 3})
        assert (await r.json())["served_by"] == "good"
        stats = await (await client.get("/fleet/stats")).json()
        assert stats["route_total"]["affinity"] >= 1
        # metrics expose the same counters + the replica-state gauge
        text = await (await client.get("/metrics")).text()
        assert "fleet_route_total" in text
        # fleet_replicas carries (state, pool) since disaggregated
        # pools landed; both replicas here are role-less -> mixed
        assert 'fleet_replicas{pool="mixed",state="ready"} 1' in text
    finally:
        await good_server.close()


async def test_router_hedges_slow_replica(aiohttp_client):
    slow_server, slow_url = await _start_stub("slow", delay=1.5)
    fast_server, fast_url = await _start_stub("fast")
    reg = ReplicaRegistry()
    reg.register(slow_url, replica_id="slow")
    reg.register(fast_url, replica_id="fast")
    client = await aiohttp_client(router_mod.create_router_app(
        reg, block_size=4, hedge_after_s=0.05))
    try:
        toks = _prompt_mapped_to(reg, "slow")
        t0 = time.monotonic()
        r = await client.post("/v1/models/tiny:generate",
                              json={"tokens": [toks], "max_new": 3})
        assert r.status == 200
        assert (await r.json())["served_by"] == "fast"  # hedge won
        assert time.monotonic() - t0 < 1.4  # did not wait out the slow
        stats = await (await client.get("/fleet/stats")).json()
        assert stats["route_total"]["hedge"] == 1
        assert stats["hedge_wins"] == 1
    finally:
        await slow_server.close()
        await fast_server.close()


async def test_router_503_when_no_replicas(aiohttp_client):
    client = await aiohttp_client(router_mod.create_router_app())
    r = await client.post("/v1/models/tiny:generate",
                          json={"tokens": [[1, 2]], "max_new": 2})
    assert r.status == 503
    assert "Retry-After" in r.headers
    r = await client.post("/v1/models/tiny:generate", data=b"not json")
    assert r.status == 400


async def test_router_drain_endpoint_stops_routing(aiohttp_client):
    a_server, a_url = await _start_stub("a")
    b_server, b_url = await _start_stub("b")
    reg = ReplicaRegistry()
    reg.register(a_url, replica_id="a")
    reg.register(b_url, replica_id="b")
    client = await aiohttp_client(router_mod.create_router_app(
        reg, block_size=4))
    try:
        r = await client.post("/fleet/drain", json={"id": "a"})
        assert (await r.json())["state"] == "draining"
        assert reg.get("a").state == DRAINING
        toks = _prompt_mapped_to(reg, "a")
        r = await client.post("/v1/models/tiny:generate",
                              json={"tokens": [toks], "max_new": 2})
        assert (await r.json())["served_by"] == "b"
        r = await client.post("/fleet/drain", json={"id": "ghost"})
        assert r.status == 404
    finally:
        await a_server.close()
        await b_server.close()


# -- circuit breaker / retry budget / failover / chaos ----------------------


def test_circuit_breaker_trips_cooldown_and_half_open():
    clk = FakeClock()
    reg = ReplicaRegistry(circuit_failures=2, circuit_cooldown_s=2.0,
                          dead_failures=5, clock=clk)
    reg.register("http://a:1", replica_id="a")
    reg.register("http://b:1", replica_id="b")
    reg.note_failure("a")
    assert not reg.circuit_open("a")    # one failure never trips
    reg.note_failure("a")
    assert reg.circuit_open("a")
    reg.note_failure("b")               # b degraded, circuit closed
    # no ready replicas left: the degraded pool is circuit-filtered
    assert [r.id for r in reg.routable()] == ["b"]
    clk.t = 2.5                         # cooldown over: half-open
    assert not reg.circuit_open("a")
    assert {r.id for r in reg.routable()} == {"a", "b"}
    reg.note_failure("a")               # the probe failed: re-trips
    assert reg.circuit_open("a")
    reg.note_success("a")               # probe passed: closes
    assert not reg.circuit_open("a")
    # a live heartbeat clears the circuit too (recovery path)
    reg.note_failure("b")
    assert reg.circuit_open("b")
    reg.heartbeat("b")
    assert not reg.circuit_open("b")
    # every circuit open -> still routable: a long-shot retry beats a
    # certain client 503 (and the attempt doubles as the probe)
    solo = ReplicaRegistry(circuit_failures=1, clock=FakeClock())
    solo.register("http://x:1", replica_id="x")
    solo.note_failure("x")
    assert solo.circuit_open("x")
    assert [r.id for r in solo.routable()] == ["x"]


async def test_circuit_gauge_and_placements_endpoint(aiohttp_client):
    reg = ReplicaRegistry(circuit_failures=2)
    reg.register("http://a:1", replica_id="r0")
    reg.register("http://b:1", replica_id="r1")
    client = await aiohttp_client(router_mod.create_router_app(reg))
    reg.note_failure("r0")
    reg.note_failure("r0")
    text = await (await client.get("/metrics")).text()
    assert 'fleet_circuit_open{replica="r0"} 1' in text
    assert 'fleet_circuit_open{replica="r1"} 0' in text
    assert "fleet_failover_total 0" in text
    # placements: healthy migration targets, least-loaded first
    r = await client.get("/fleet/placements")
    assert (await r.json())["ids"] == ["r1"]     # r0 is degraded
    r = await client.get("/fleet/placements?exclude=r1")
    body = await r.json()
    assert body["ids"] == ["r0"] and body["peers"] == ["http://a:1"]


async def test_retry_budget_caps_total_dispatches(aiohttp_client):
    """max_attempts bounds TOTAL upstream dispatches per request — a
    dead fleet must not amplify one client request into retries
    against every replica."""
    reg = ReplicaRegistry()
    for i in range(4):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            reg.register(f"http://127.0.0.1:{s.getsockname()[1]}",
                         replica_id=f"d{i}")
    client = await aiohttp_client(router_mod.create_router_app(
        reg, retries=6, max_attempts=2, backoff_s=0.001,
        hedge_after_s=0))
    r = await client.post("/v1/models/tiny:generate",
                          json={"tokens": [[1, 2, 3]], "max_new": 2})
    assert r.status == 503
    # exactly two dispatches spent: the budget, not the retry count
    assert sum(rep.failures for rep in reg.replicas()) == 2


async def test_transient_fault_on_last_replica_gets_fresh_sweep(
        aiohttp_client):
    """A chaos drop on the ONLY routable replica must not strand the
    request: once every candidate is in the per-request tried set, the
    router clears it and sweeps again while attempt budget remains —
    transient faults recover, persistent corpses are the circuit
    breaker's job. Regression: a lone survivor's dropped dispatch
    once 503'd with budget left."""
    from kubeflow_tpu.fleet.chaos import ChaosInjector

    server, url = await _start_stub("solo")
    reg = ReplicaRegistry()
    reg.register(url, replica_id="solo")
    # seed 1: first draw 0.134 < 0.2 -> the first dispatch drops
    chaos = ChaosInjector(1, drop_rate=0.2)
    client = await aiohttp_client(router_mod.create_router_app(
        reg, retries=3, backoff_s=0.001, hedge_after_s=0, chaos=chaos))
    try:
        r = await client.post("/v1/models/tiny:generate",
                              json={"tokens": [[1, 2, 3]], "max_new": 2})
        assert r.status == 200            # second sweep, same replica
        assert chaos.injected["drop"] == 1
        stats = await (await client.get("/fleet/stats")).json()
        assert stats["route_total"]["retry"] >= 1
    finally:
        await server.close()


async def test_fleet_wide_blip_waits_for_heartbeat_resurrection(
        aiohttp_client):
    """When EVERY replica is momentarily unroutable — the lone
    survivor just tripped its breaker to DEAD with the heartbeat that
    would resurrect it still in flight — the router must burn retries
    waiting (the sleep yields the event loop so the heartbeat can
    land) instead of 503ing with attempt budget left. Regression: a
    chaos run under CPU contention turned this sub-second blip into
    18 client-visible 503s."""
    server, url = await _start_stub("solo")
    reg = ReplicaRegistry()
    reg.register(url, replica_id="solo")
    for _ in range(3):                    # dead_failures -> DEAD
        reg.note_failure("solo")
    assert reg.routable() == []

    async def late_heartbeat():
        await asyncio.sleep(0.05)
        assert reg.heartbeat("solo")      # READY again

    client = await aiohttp_client(router_mod.create_router_app(
        reg, retries=6, backoff_s=0.02, hedge_after_s=0))
    task = asyncio.ensure_future(late_heartbeat())
    try:
        r = await client.post("/v1/models/tiny:generate",
                              json={"tokens": [[1, 2, 3]], "max_new": 2})
        assert r.status == 200
    finally:
        await task
        await server.close()


def _sse_stub(name, toks, *, die=False, seen=None):
    """Streaming replica stub: emits one SSE token event per entry of
    `toks`, then either a terminal done frame or (die=True) an abrupt
    connection cut with no terminal frame — a mid-stream crash."""
    async def gen(request):
        body = await request.json()
        if seen is not None:
            seen.append(body)
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream"})
        await resp.prepare(request)
        for t in toks:
            await resp.write(
                b"data: " + json.dumps({"tokens": [[t]]}).encode()
                + b"\n\n")
        if die:
            request.transport.close()
        else:
            await resp.write(b"data: " + json.dumps(
                {"done": True, "total": len(toks)}).encode() + b"\n\n")
            await resp.write_eof()
        return resp

    app = web.Application()
    app.router.add_post("/v1/models/{name}:generate", gen)
    return app


async def test_stream_failover_splices_without_dup_or_gap(
        aiohttp_client):
    """A replica dies two tokens into an SSE stream: the router must
    resume on a peer and splice the halves into ONE stream with no
    duplicate and no missing tokens, terminal frame included."""
    seen: list = []
    dying = TestServer(_sse_stub("dying", [1, 2], die=True))
    healer = TestServer(_sse_stub("healer", [3, 4, 5], seen=seen))
    await dying.start_server()
    await healer.start_server()
    reg = ReplicaRegistry()
    reg.register(f"http://127.0.0.1:{dying.port}", replica_id="dying")
    reg.register(f"http://127.0.0.1:{healer.port}", replica_id="healer")
    client = await aiohttp_client(router_mod.create_router_app(
        reg, block_size=4, backoff_s=0.001, hedge_after_s=0))
    try:
        toks = _prompt_mapped_to(reg, "dying")
        r = await client.post(
            "/v1/models/tiny:generate",
            json={"tokens": [toks], "max_new": 5, "stream": True})
        assert r.status == 200
        assert r.headers["X-Fleet-Replica"] == "dying"  # first owner
        events = [json.loads(f.split(b"data:", 1)[1])
                  for f in (await r.read()).split(b"\n\n") if f.strip()]
        stream = [e["tokens"][0][0] for e in events if "tokens" in e]
        assert stream == [1, 2, 3, 4, 5]        # no dup, no gap
        assert events[-1]["done"] is True and events[-1]["total"] == 5
        # checkpoint-less resume: the healer got the client's prompt
        # spliced with the 2 delivered tokens, budget = remainder only
        assert seen[0]["tokens"] == [[*toks, 1, 2]]
        assert seen[0]["max_new"] == 3
        stats = await (await client.get("/fleet/stats")).json()
        assert stats["failover"] == 1
    finally:
        await dying.close()
        await healer.close()


async def test_oneshot_failover_resumes_from_checkpoint(aiohttp_client):
    """Crash failover for a one-shot generate: the dead replica's last
    heartbeat carried a sequence checkpoint; the retry re-prefills the
    CHECKPOINT prompt (not the original body) with only the remaining
    budget, and the response splices into one complete row."""
    seen: list = []

    async def gen(request):
        body = await request.json()
        seen.append(body)
        return web.json_response(
            {"tokens": [[8] * body["max_new"]], "served_by": "healer"})

    app = web.Application()
    app.router.add_post("/v1/models/{name}:generate", gen)
    healer = TestServer(app)
    await healer.start_server()
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        dead_url = f"http://127.0.0.1:{s.getsockname()[1]}"
    reg = ReplicaRegistry()
    reg.register(dead_url, replica_id="dead")
    reg.register(f"http://127.0.0.1:{healer.port}", replica_id="healer")
    client = await aiohttp_client(router_mod.create_router_app(
        reg, block_size=4, backoff_s=0.001, hedge_after_s=0))
    try:
        r = await client.post("/fleet/heartbeat", json={
            "id": "dead", "checkpoints": [{
                "request_id": "req-ck", "tokens": [9, 8, 1, 2],
                "out": [1, 2], "max_new": 5, "sampling": {}}]})
        assert r.status == 200
        toks = _prompt_mapped_to(reg, "dead")
        r = await client.post(
            "/v1/models/tiny:generate",
            json={"tokens": [toks], "max_new": 5},
            headers={"X-Request-Id": "req-ck"})
        assert r.status == 200
        body = await r.json()
        # spliced: checkpointed [1, 2] + the healer's 3-token tail
        assert body["tokens"] == [[1, 2, 8, 8, 8]]
        assert r.headers["X-Request-Id"] == "req-ck"
        assert seen[0]["tokens"] == [[9, 8, 1, 2]]
        assert seen[0]["max_new"] == 3
        stats = await (await client.get("/fleet/stats")).json()
        assert stats["failover"] >= 1 and stats["checkpoints"] == 1
    finally:
        await healer.close()


async def test_router_drain_forwards_migrate_peers(aiohttp_client):
    """`/fleet/drain` forwards `{"migrate": true, "peers": [...]}` to
    the replica when healthy peers exist; a lone replica gets the
    legacy bodiless wait-out drain (nowhere to migrate to)."""
    bodies: dict = {}

    def drainable(name):
        app = _stub_app(name)

        async def drain_h(request):
            bodies[name] = await request.text()
            return web.json_response({"draining": True, "in_flight": 0,
                                      "migrated": 1, "failed": 0})

        app.router.add_post("/drain", drain_h)
        return app

    a = TestServer(drainable("a"))
    b = TestServer(drainable("b"))
    await a.start_server()
    await b.start_server()
    b_url = f"http://127.0.0.1:{b.port}"
    reg = ReplicaRegistry()
    reg.register(f"http://127.0.0.1:{a.port}", replica_id="a")
    reg.register(b_url, replica_id="b")
    client = await aiohttp_client(router_mod.create_router_app(reg))
    try:
        r = await client.post("/fleet/drain", json={"id": "a"})
        body = await r.json()
        assert body["state"] == "draining"
        assert body["replica"]["migrated"] == 1
        sent = json.loads(bodies["a"])
        assert sent["migrate"] is True and sent["peers"] == [b_url]
        # b is now the lone healthy replica: legacy drain, no body
        r = await client.post("/fleet/drain", json={"id": "b"})
        assert (await r.json())["state"] == "draining"
        assert bodies["b"] == ""
    finally:
        await a.close()
        await b.close()


async def test_chaos_injector_is_seed_deterministic():
    from kubeflow_tpu.fleet.chaos import ChaosInjector

    a = ChaosInjector(7, drop_rate=0.3, delay_rate=0.0,
                      duplicate_rate=0.2)
    b = ChaosInjector(7, drop_rate=0.3, delay_rate=0.0,
                      duplicate_rate=0.2)
    sa = [await a.before_dispatch("r") for _ in range(60)]
    sb = [await b.before_dispatch("r") for _ in range(60)]
    assert sa == sb                      # same seed, same fault plan
    assert a.injected == b.injected
    assert a.injected["drop"] > 0 and a.injected["duplicate"] > 0
    with pytest.raises(ValueError):
        ChaosInjector(1, drop_rate=1.5)
    # blackhole arms, decrements, and ledgers
    a.blackhole("x", 2)
    assert a.heartbeat_blackholed("x")
    assert a.heartbeat_blackholed("x")
    assert not a.heartbeat_blackholed("x")
    assert a.injected["blackhole"] == 2


async def test_chaos_drop_absorbed_and_heartbeat_blackhole(
        aiohttp_client):
    from kubeflow_tpu.fleet.chaos import ChaosInjector

    g1, g1_url = await _start_stub("g1")
    g2, g2_url = await _start_stub("g2")
    reg = ReplicaRegistry()
    reg.register(g1_url, replica_id="g1")
    reg.register(g2_url, replica_id="g2")
    # seed 1: first draw 0.134 < 0.2 -> the FIRST dispatch drops;
    # the second call's draws all miss. Deterministic by contract.
    chaos = ChaosInjector(1, drop_rate=0.2)
    client = await aiohttp_client(router_mod.create_router_app(
        reg, block_size=4, retries=3, backoff_s=0.001,
        hedge_after_s=0, chaos=chaos))
    try:
        r = await client.post("/v1/models/tiny:generate",
                              json={"tokens": [[1, 2, 3]], "max_new": 2})
        assert r.status == 200           # the retry absorbed the drop
        assert chaos.injected["drop"] == 1
        stats = await (await client.get("/fleet/stats")).json()
        assert stats["route_total"]["retry"] >= 1
        # heartbeat blackhole: the beat is swallowed (stats untouched,
        # replica believes it landed), then the window closes
        chaos.blackhole("g1", 1)
        r = await client.post("/fleet/heartbeat",
                              json={"id": "g1", "queue_depth": 9})
        assert (await r.json())["ok"] is True
        assert reg.get("g1").queue_depth == 0
        await client.post("/fleet/heartbeat",
                          json={"id": "g1", "queue_depth": 9})
        assert reg.get("g1").queue_depth == 9
    finally:
        await g1.close()
        await g2.close()


async def test_chaos_drop_on_stream_retries_not_500(aiohttp_client):
    """A chaos drop fires BEFORE the streaming dispatch: the router
    must treat it like any upstream failure (retry on a peer), not let
    it escape the handler as a client-visible 500. Regression: the
    stream path's except clause once missed `_UpstreamError`."""
    from kubeflow_tpu.fleet.chaos import ChaosInjector

    a = TestServer(_sse_stub("a", [1, 2, 3]))
    b = TestServer(_sse_stub("b", [1, 2, 3]))
    await a.start_server()
    await b.start_server()
    reg = ReplicaRegistry()
    reg.register(f"http://127.0.0.1:{a.port}", replica_id="a")
    reg.register(f"http://127.0.0.1:{b.port}", replica_id="b")
    # seed 1: first draw 0.134 < 0.2 -> the first dispatch drops
    chaos = ChaosInjector(1, drop_rate=0.2)
    client = await aiohttp_client(router_mod.create_router_app(
        reg, block_size=4, retries=3, backoff_s=0.001,
        hedge_after_s=0, chaos=chaos))
    try:
        r = await client.post(
            "/v1/models/tiny:generate",
            json={"tokens": [[1, 2, 3]], "max_new": 3, "stream": True})
        assert r.status == 200
        events = [json.loads(f.split(b"data:", 1)[1])
                  for f in (await r.read()).split(b"\n\n") if f.strip()]
        stream = [e["tokens"][0][0] for e in events if "tokens" in e]
        assert stream == [1, 2, 3]
        assert events[-1]["done"] is True
        assert chaos.injected["drop"] == 1
        stats = await (await client.get("/fleet/stats")).json()
        assert stats["route_total"]["retry"] >= 1
        assert stats["chaos"]["drop"] == 1     # ledger on /fleet/stats
    finally:
        await a.close()
        await b.close()


def test_create_router_app_validates():
    with pytest.raises(ValueError):
        router_mod.create_router_app(policy="random")
    with pytest.raises(ValueError):
        router_mod.create_router_app(block_size=0)


# -- serving: healthz / drain / shutdown-drain ------------------------------


@pytest.fixture(scope="module")
def tiny_engine():
    import jax

    from kubeflow_tpu.models import llama
    from kubeflow_tpu.serving import (
        EngineConfig,
        InferenceEngine,
        LLAMA_FAMILY,
    )

    cfg = llama.LLAMA_TINY
    params = llama.init(jax.random.key(0), cfg)
    return InferenceEngine(params, cfg, LLAMA_FAMILY,
                           EngineConfig(max_len=64))


async def test_healthz_reports_and_drain_stops_admission(
        tiny_engine, aiohttp_client):
    from kubeflow_tpu.serving import server as server_lib

    app = server_lib.create_serving_app({"tiny": tiny_engine},
                                        continuous=True, max_batch=2)
    client = await aiohttp_client(app)
    r = await client.get("/healthz")
    assert r.status == 200
    body = await r.json()
    assert body["models"]["tiny"]["kv_blocks_total"] > 0
    stats = server_lib.fleet_stats(app)
    assert stats["max_slots"] == 2 and not stats["draining"]

    r = await client.post("/drain")
    body = await r.json()
    assert body["draining"] is True and body["in_flight"] == 0
    r = await client.get("/healthz")
    assert r.status == 503
    assert (await r.json())["status"] == "draining"
    # liveness stays green — the pod is healthy, just not admitting
    assert (await client.get("/readyz")).status == 200
    r = await client.post("/v1/models/tiny:generate",
                          json={"tokens": [[1, 2, 3]], "max_new": 2})
    assert r.status == 503
    r = await client.post("/v1/models/tiny:score",
                          json={"tokens": [[1, 2, 3]]})
    assert r.status == 503
    assert server_lib.fleet_stats(app)["draining"] is True


async def test_continuous_drain_completes_in_flight(tiny_engine):
    from kubeflow_tpu.serving.continuous import ContinuousBatcher

    b = ContinuousBatcher(tiny_engine, asyncio.Lock(), max_slots=2,
                          kv_block_size=8)
    task = asyncio.ensure_future(b.submit([1, 2, 3], 4, ()))
    await asyncio.sleep(0)  # let the submission enqueue
    assert await b.drain(timeout=120)
    out = await task        # completed, NOT failed by shutdown
    assert len(out) == 4
    with pytest.raises(RuntimeError, match="draining"):
        b._enqueue([1, 2, 3], 2, {}, queue=None)
    assert b.in_flight() == 0
    await b.close()


async def test_shutdown_drains_in_flight_requests(
        tiny_engine, aiohttp_client):
    """ISSUE 3 bugfix: app cleanup used to fail in-flight generations
    with 'server shutting down'; now it drains them to completion."""
    from kubeflow_tpu.serving import server as server_lib

    app = server_lib.create_serving_app({"tiny": tiny_engine},
                                        continuous=True, max_batch=2,
                                        drain_grace_s=120)
    client = await aiohttp_client(app)
    batcher = app[server_lib.BATCHERS_KEY]["tiny"]
    task = asyncio.ensure_future(batcher.submit([1, 2, 3], 4, ()))
    await asyncio.sleep(0.05)  # admitted (or at least enqueued)
    await client.close()       # runs on_cleanup: drain THEN close
    out = await task
    assert len(out) == 4


async def test_window_batcher_drain(tiny_engine, aiohttp_client):
    from kubeflow_tpu.serving import server as server_lib

    app = server_lib.create_serving_app({"tiny": tiny_engine},
                                        batch_window_ms=1.0)
    client = await aiohttp_client(app)
    r = await client.post("/v1/models/tiny:generate",
                          json={"tokens": [[1, 2, 3]], "max_new": 2})
    assert r.status == 200
    await client.post("/drain")
    b = app[server_lib.BATCHERS_KEY]["tiny"]
    with pytest.raises(RuntimeError, match="draining"):
        await b.submit([1, 2, 3], 2, ())
    assert await b.drain(timeout=10)


async def test_fleet_registration_handshake(tiny_engine, aiohttp_client):
    """Replica registers on startup, heartbeats stats, deregisters on
    cleanup — and the router routes a real generate to it."""
    from kubeflow_tpu.serving import server as server_lib

    reg = ReplicaRegistry()
    router_server = TestServer(router_mod.create_router_app(
        reg, block_size=8))
    await router_server.start_server()
    router_url = f"http://127.0.0.1:{router_server.port}"

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        rep_port = s.getsockname()[1]
    app = server_lib.create_serving_app({"tiny": tiny_engine},
                                        continuous=True, max_batch=2)
    server_lib.enable_fleet_registration(
        app, router_url, f"http://127.0.0.1:{rep_port}",
        replica_id="r0", period_s=0.05)
    rep_server = TestServer(app, port=rep_port)
    await rep_server.start_server()
    router_client = TestClient(router_server)
    try:
        rep = reg.get("r0")
        assert rep is not None and rep.state == READY
        assert rep.models == ["tiny"] and rep.max_slots == 2
        hb0 = rep.last_heartbeat
        await asyncio.sleep(0.2)
        assert reg.get("r0").last_heartbeat > hb0
        r = await router_client.post(
            "/v1/models/tiny:generate",
            json={"tokens": [[1, 2, 3]], "max_new": 2})
        assert r.status == 200
        assert len((await r.json())["tokens"][0]) == 2
        assert r.headers["X-Fleet-Replica"] == "r0"
    finally:
        await rep_server.close()   # cleanup deregisters
        assert reg.get("r0") is None
        await router_client.close()
        await router_server.close()


# -- controller: scale-down drains before delete ----------------------------


def _mk_ms(name="srv1", ns="user1", **spec):
    from kubeflow_tpu.api.crds import ModelServer

    ms = ModelServer()
    ms.metadata.name = name
    ms.metadata.namespace = ns
    for k, v in spec.items():
        setattr(ms.spec, k, v)
    return ms


@pytest.fixture()
def cluster():
    from kubeflow_tpu.controlplane.cluster import Cluster, ClusterConfig

    with Cluster(ClusterConfig()) as c:
        yield c


def test_modelserver_scale_down_drains_before_delete(cluster, monkeypatch):
    from kubeflow_tpu.controlplane.controllers import modelserver as msc

    monkeypatch.setattr(msc, "DRAIN_GRACE_S", 0.3)
    ms = _mk_ms("srv-fleet", replicas=1, max_replicas=4)
    ms.metadata.annotations[msc.DESIRED_REPLICAS_ANNOTATION] = "3"
    cluster.store.create(ms)
    assert cluster.wait_idle()
    dep = cluster.store.get("Deployment", "user1", "srv-fleet")
    assert dep.spec.replicas == 3
    pods = cluster.store.list("Pod", "user1",
                              owner_uid=dep.metadata.uid)
    assert len(pods) == 3

    fresh = cluster.store.get("ModelServer", "user1", "srv-fleet")
    fresh.metadata.annotations[msc.DESIRED_REPLICAS_ANNOTATION] = "1"
    cluster.store.update(fresh)
    assert cluster.wait_idle()
    # drain window open: Deployment HELD at 3, excess pods annotated
    dep = cluster.store.get("Deployment", "user1", "srv-fleet")
    assert dep.spec.replicas == 3
    pods = cluster.store.list("Pod", "user1",
                              owner_uid=dep.metadata.uid)
    draining = [p for p in pods
                if msc.DRAIN_ANNOTATION in p.metadata.annotations]
    assert len(pods) == 3 and len(draining) == 2
    events = cluster.store.events_for("ModelServer", "user1",
                                      "srv-fleet")
    assert any(e.reason == "DrainingReplica" for e in events)

    time.sleep(0.4)  # past the (shrunken) grace window
    fresh = cluster.store.get("ModelServer", "user1", "srv-fleet")
    fresh.metadata.labels["nudge"] = "1"  # wait_idle skips delayed
    cluster.store.update(fresh)           # requeues; re-trigger now
    assert cluster.wait_idle()
    dep = cluster.store.get("Deployment", "user1", "srv-fleet")
    assert dep.spec.replicas == 1
    pods = cluster.store.list("Pod", "user1",
                              owner_uid=dep.metadata.uid)
    assert len(pods) == 1
    assert msc.DRAIN_ANNOTATION not in pods[0].metadata.annotations
    events = cluster.store.events_for("ModelServer", "user1",
                                      "srv-fleet")
    assert any(e.reason == "ScaledDown" for e in events)


def test_modelserver_annotation_clamped_and_validated(cluster):
    from kubeflow_tpu.controlplane.controllers import modelserver as msc

    # clamp to max_replicas
    ms = _mk_ms("srv-clamp", replicas=2, max_replicas=3)
    ms.metadata.annotations[msc.DESIRED_REPLICAS_ANNOTATION] = "99"
    cluster.store.create(ms)
    # annotation without max_replicas: autoscale off, spec wins
    ms2 = _mk_ms("srv-off", replicas=1)
    ms2.metadata.annotations[msc.DESIRED_REPLICAS_ANNOTATION] = "7"
    cluster.store.create(ms2)
    # garbage annotation: event, fall back to spec
    ms3 = _mk_ms("srv-bad", replicas=2, max_replicas=4)
    ms3.metadata.annotations[msc.DESIRED_REPLICAS_ANNOTATION] = "lots"
    cluster.store.create(ms3)
    # invalid replica bounds: validation event, nothing rendered
    cluster.store.create(_mk_ms("srv-inv", replicas=3, max_replicas=2))
    assert cluster.wait_idle()

    assert cluster.store.get("Deployment", "user1",
                             "srv-clamp").spec.replicas == 3
    assert cluster.store.get("Deployment", "user1",
                             "srv-off").spec.replicas == 1
    assert cluster.store.get("Deployment", "user1",
                             "srv-bad").spec.replicas == 2
    assert any(e.reason == "InvalidDesiredReplicas" for e in
               cluster.store.events_for("ModelServer", "user1",
                                        "srv-bad"))
    assert cluster.store.try_get("Deployment", "user1",
                                 "srv-inv") is None
    assert any(e.reason == "InvalidReplicas" for e in
               cluster.store.events_for("ModelServer", "user1",
                                        "srv-inv"))


# -- cross-process trace merge (ISSUE 6) ------------------------------------


async def test_router_merges_replica_trace_segments(
        tiny_engine, aiohttp_client):
    """One generate through the router lands on a real serving replica;
    `/debug/traces?trace_id=` on the router then reassembles BOTH
    processes' segments into one Chrome trace: same trace id
    everywhere, replica root parented on the router's upstream span,
    per-process tracks. Two replicas, round-robin, so the merge is
    exercised against a fleet, not a single backend."""
    from kubeflow_tpu.serving import server as server_lib

    reg = ReplicaRegistry()
    reps = []
    for i in range(2):
        app = server_lib.create_serving_app({"tiny": tiny_engine},
                                            continuous=True, max_batch=2)
        srv = TestServer(app)
        await srv.start_server()
        reg.register(f"http://127.0.0.1:{srv.port}",
                     replica_id=f"rep-{i}")
        reps.append(srv)
    # hedging off: a hedge during the first compile-heavy generate
    # would advance the round-robin cursor mid-request
    client = await aiohttp_client(
        router_mod.create_router_app(reg, policy="roundrobin",
                                     hedge_after_s=0))
    try:
        by_replica: dict[str, str] = {}
        for i in range(4):
            r = await client.post(
                "/v1/models/tiny:generate",
                json={"tokens": [[1 + i, 2, 3]], "max_new": 2})
            assert r.status == 200
            by_replica.setdefault(r.headers["X-Fleet-Replica"],
                                  r.headers["X-Trace-Id"])
        assert set(by_replica) == {"rep-0", "rep-1"}  # both exercised

        for rep_id, tid in sorted(by_replica.items()):
            r = await client.get(f"/debug/traces?trace_id={tid}")
            doc = await r.json()
            meta = {e["args"]["name"]: e["pid"]
                    for e in doc["traceEvents"] if e["ph"] == "M"}
            assert "router" in meta and rep_id in meta
            spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
            assert spans and all(
                e["args"]["trace_id"] == tid for e in spans)
            rep_roots = [e for e in spans
                         if e["pid"] == meta[rep_id]
                         and e["name"] == "http.request"]
            assert rep_roots, "replica segment missing from the merge"
            # the replica's root span is parented on a ROUTER span —
            # the joinable edge X-Parent-Span propagated
            router_span_ids = {e["args"]["span_id"] for e in spans
                               if e["pid"] == meta["router"]}
            assert rep_roots[0]["args"]["parent_id"] in router_span_ids
    finally:
        for srv in reps:
            await srv.close()
