"""Chunked prefill: token parity and composition with every
continuous-path feature.

`prefill_chunk_tokens=N` changes WHEN prompt tokens are fed (budget
slices interleaved with decode chunks, through the fused append path)
but must never change WHAT any request receives: every test here pins
bit-exact parity against the monolithic batcher / solo-generate
oracle — across budgets (1 token per iteration up to >= the whole
prompt in one slice), model families, radix prefix reuse, tenancy
preemption, and mid-flight migration export.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest_plugins = ("aiohttp.pytest_plugin",)

from kubeflow_tpu.models import gemma, llama
from kubeflow_tpu.serving import (
    EngineConfig,
    GEMMA_FAMILY,
    InferenceEngine,
    LLAMA_FAMILY,
)
from kubeflow_tpu.serving.continuous import ContinuousBatcher, MigratedAway
from kubeflow_tpu.tenancy import config_from_dict

BS = 8


def _build_engine(family="llama", max_len=96):
    if family == "llama":
        cfg = llama.LLAMA_TINY
        params = dict(llama.init(jax.random.key(0), cfg))
        params["lm_head"] = params["lm_head"] * 50.0  # argmax can't flip
        return InferenceEngine(params, cfg, LLAMA_FAMILY,
                               EngineConfig(max_len=max_len)), cfg
    cfg = gemma.GEMMA_TINY
    params = dict(gemma.init(jax.random.key(1), cfg))
    if "lm_head" in params:  # gemma ties its embeddings
        params["lm_head"] = params["lm_head"] * 50.0
    return InferenceEngine(params, cfg, GEMMA_FAMILY,
                           EngineConfig(max_len=max_len)), cfg


@pytest.fixture(scope="module")
def llama_engine():
    return _build_engine("llama")


def _solo(engine, prompt, max_new):
    return np.asarray(engine.generate(
        jnp.asarray([prompt], jnp.int32), max_new=max_new))[0].tolist()


def _batcher(engine, budget=None, **kw):
    return ContinuousBatcher(engine, asyncio.Lock(), max_slots=4,
                             kv_block_size=BS,
                             prefill_chunk_tokens=budget, **kw)


async def _run_all(batcher, prompts, max_new):
    try:
        out = await asyncio.gather(
            *(batcher.submit(p, max_new, ()) for p in prompts))
        return [list(o) for o in out]
    finally:
        await batcher.close()


async def test_chunked_parity_across_budgets_llama(llama_engine):
    """Budget 1 (one token per worker iteration — the most interleaved
    schedule possible), a mid-size budget that straddles block
    boundaries, and a budget >= every prompt (one slice, the chunked
    path's degenerate monolithic case) all emit the oracle's exact
    tokens."""
    engine, cfg = llama_engine
    gen = np.random.default_rng(4)
    prompts = [gen.integers(0, cfg.vocab_size, n).tolist()
               for n in (4, 7, 12, 20)]
    want = [_solo(engine, p, 5) for p in prompts]
    for budget in (1, 3, 64):
        got = await _run_all(_batcher(engine, budget), prompts, 5)
        assert got == want, f"budget={budget}"


@pytest.mark.slow
async def test_chunked_parity_gemma():
    """The other family: GQA 4:1, different norm/rope plumbing — the
    fused append path must track it through the same config."""
    engine, cfg = _build_engine("gemma")
    gen = np.random.default_rng(9)
    prompts = [gen.integers(0, cfg.vocab_size, n).tolist()
               for n in (7, 11, 17)]
    want = [_solo(engine, p, 5) for p in prompts]
    for budget in (1, 4, 32):
        got = await _run_all(_batcher(engine, budget), prompts, 5)
        assert got == want, f"budget={budget}"


async def test_chunked_radix_reuse(llama_engine):
    """A chunk-admitted request seeds from the radix cache like a
    monolithic one: the second identical prompt re-prefills only the
    uncached tail, token-identically."""
    engine, cfg = llama_engine
    prompt = list(range(2, 2 + 21))
    want = _solo(engine, prompt, 5)
    b = _batcher(engine, budget=4)
    try:
        assert await b.submit(prompt, 5, ()) == want
        fed_first = b.tokens_prefilled
        assert await b.submit(prompt, 5, ()) == want
        assert b.prefix_hits == 1
        # blocks donated at retirement cover the prompt's full blocks;
        # the rerun computes at most the partial tail + 1
        assert b.tokens_reused >= (len(prompt) // BS) * BS
        assert b.tokens_prefilled - fed_first < fed_first
    finally:
        await b.close()


async def test_chunked_interleaves_decode_with_prefill(llama_engine):
    """The throughput mechanism itself: while a LONG prompt trickles
    in at budget 1, a short already-running request keeps emitting —
    its stream finishes well before the long prompt's first token.
    (Monolithic admission would stall the short request for the whole
    prefill.)"""
    engine, cfg = llama_engine
    gen = np.random.default_rng(11)
    short = gen.integers(0, cfg.vocab_size, 4).tolist()
    long = gen.integers(0, cfg.vocab_size, 60).tolist()
    want_s, want_l = _solo(engine, short, 8), _solo(engine, long, 4)
    b = _batcher(engine, budget=1)
    try:
        fut_s, q = b.open_stream(short, 8, ())
        # wait until the short request is admitted and decoding
        first = await asyncio.wait_for(q.get(), 30)
        assert first is not None
        fut_l = asyncio.ensure_future(b.submit(long, 4, ()))
        # the short request's remaining tokens arrive while the long
        # prompt is still mid-prefill (60 iterations at budget 1)
        got_s = [first]
        while True:
            tok = await asyncio.wait_for(q.get(), 30)
            if tok is None:
                break
            got_s.append(tok)
        assert got_s == want_s
        assert any(r.prefilling is not None
                   for r in b._active.values()), \
            "long prompt should still be mid-prefill"
        assert await fut_l == want_l
        await fut_s
    finally:
        await b.close()


async def test_chunked_preemption_replay(llama_engine):
    """Tenancy preemption composes: bulk requests admitted through the
    chunked path preempt and replay token-identically."""
    engine, _ = llama_engine
    qos = {"tenants": {"live": {"priority": "interactive"},
                       "bulk": {"priority": "batch"}}}
    p1, p2, p3 = [3, 5, 7, 11], [4, 6, 8, 10], [9, 2, 4, 8]
    want1, want2 = _solo(engine, p1, 80), _solo(engine, p2, 80)
    want3 = _solo(engine, p3, 8)
    b = ContinuousBatcher(engine, asyncio.Lock(), max_slots=2,
                          kv_block_size=BS, prefill_chunk_tokens=2,
                          tenancy=config_from_dict(qos))
    try:
        # long budgets keep both bulks busy well past the live
        # arrival — the preemption window cannot close underneath the
        # test (a victim mid-chunked-prefill is skipped; one that has
        # finished prefilling is fair game)
        f1 = asyncio.ensure_future(
            b.submit(p1, 80, (("tenant", "bulk"),)))
        f2 = asyncio.ensure_future(
            b.submit(p2, 80, (("tenant", "bulk"),)))
        for _ in range(400):
            if len(b._active) == 2 and all(
                    r.prefilling is None for r in b._active.values()):
                break
            await asyncio.sleep(0.02)
        assert len(b._active) == 2
        got3 = await b.submit(p3, 8, (("tenant", "live"),))
        assert b.preemptions >= 1
        assert await f1 == want1
        assert await f2 == want2
        assert got3 == want3
    finally:
        await b.close()


async def test_chunked_migration_export_mid_prefill(llama_engine):
    """Export while a request is STILL mid-chunked-prefill: its blocks
    past the fed frontier are unwritten, so the record must go out
    tokens-only and replay from scratch on the peer, token-exactly."""
    engine, cfg = llama_engine
    gen = np.random.default_rng(13)
    prompt = gen.integers(0, cfg.vocab_size, 40).tolist()
    want = _solo(engine, prompt, 6)
    a = _batcher(engine, budget=1)
    fut = asyncio.ensure_future(a.submit(prompt, 6, ()))
    try:
        for _ in range(400):  # wait for mid-prefill adoption
            if any(r.prefilling is not None
                   for r in a._active.values()):
                break
            await asyncio.sleep(0.01)
        records = await a.export_sequences()
        with pytest.raises(MigratedAway):
            await fut
    finally:
        await a.close()
    assert len(records) == 1
    rec = records[0]
    assert rec["kv"] is None and rec["out"] == []
    bb = _batcher(engine, budget=4)
    try:
        await bb.import_sequence(rec)
        got = await bb.submit(rec["tokens"], rec["max_new"], ())
        assert got == want
    finally:
        await bb.close()


async def test_chunked_migration_mid_generation(llama_engine):
    """The standard migrate point — mid-generation, past a block
    boundary — with chunked admission on BOTH replicas."""
    engine, _ = llama_engine
    prompt = [3, 5, 7, 11, 13, 17]
    want = _solo(engine, prompt, 24)
    a = _batcher(engine, budget=3)
    fut, q = a.open_stream(prompt, 24, ())
    try:
        for _ in range(11):
            tok = await asyncio.wait_for(q.get(), 30)
            assert tok is not None
        records = await a.export_sequences()
        with pytest.raises(MigratedAway):
            await fut
    finally:
        await a.close()
    (rec,) = records
    assert rec["kv"] is not None and rec["kv"]["n_full"] >= 2
    bb = _batcher(engine, budget=3)
    try:
        assert await bb.import_sequence(rec) == rec["kv"]["n_full"]
        out = await bb.submit(rec["tokens"],
                              rec["max_new"] - len(rec["out"]), ())
        assert rec["out"] + out == want
        assert bb.prefix_hits >= 1  # the resume seeded from the import
    finally:
        await bb.close()


def test_knob_validation(llama_engine):
    engine, _ = llama_engine
    with pytest.raises(ValueError, match="prefill_chunk_tokens"):
        ContinuousBatcher(engine, asyncio.Lock(), max_slots=2,
                          prefill_chunk_tokens=0)
    from kubeflow_tpu.serving.server import create_serving_app
    with pytest.raises(ValueError, match="require continuous"):
        create_serving_app({"m": engine}, prefill_chunk_tokens=4)
