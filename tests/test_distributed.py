"""Distributed bootstrap: webhook env → real multi-process JAX group.

Spawns TWO actual Python processes on the CPU backend wearing exactly
the env the admission webhook injects (controlplane/webhook.py
_inject_tpu_env), and asserts the group forms, the global mesh spans
both processes, and a cross-process reduction returns the right value —
the envtest-style proof SURVEY.md §5 asks for ("Distributed
communication backend": jax.distributed.initialize replaces NCCL
rendezvous).
"""

import os
import socket
import subprocess
import sys

import pytest

from kubeflow_tpu import distributed

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import os
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from kubeflow_tpu import distributed

assert distributed.initialize_from_env(timeout_secs=120)
assert jax.process_count() == 2, jax.process_count()
devs = jax.devices()
assert len(devs) == 4, devs  # 2 virtual CPU devices per process
mesh = Mesh(np.array(devs), ("data",))
local = np.full((2,), float(jax.process_index() + 1), np.float32)
garr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")), local)
total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(garr)
# process 0 contributes 2x1.0, process 1 contributes 2x2.0
assert float(total) == 6.0, float(total)
print("CHILD-OK", jax.process_index(), flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _gang_env(worker_id: int, port: int) -> dict[str, str]:
    env = dict(os.environ)
    env.update({
        # Exactly the names _inject_tpu_env sets (DNS replaced by
        # loopback — no kube DNS in a unit test).
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "KFTPU_NUM_PROCESSES": "2",
        "TPU_WORKER_ID": str(worker_id),
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO,
    })
    return env


def test_two_process_gang_forms_global_mesh():
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", CHILD],
            env=_gang_env(i, port),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"CHILD-OK {i}" in out


def test_single_process_env_is_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("KFTPU_NUM_PROCESSES", raising=False)
    assert distributed.initialize_from_env() is False
    # size-1 gang: env present but nothing to rendezvous
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:1")
    monkeypatch.setenv("KFTPU_NUM_PROCESSES", "1")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    assert distributed.initialize_from_env() is False


def test_half_injected_env_fails_loudly(monkeypatch):
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:1")
    monkeypatch.delenv("KFTPU_NUM_PROCESSES", raising=False)
    with pytest.raises(ValueError, match="half-injected"):
        distributed.initialize_from_env()
    monkeypatch.setenv("KFTPU_NUM_PROCESSES", "two")
    with pytest.raises(ValueError, match="non-integer"):
        distributed.initialize_from_env()


def test_multislice_requires_global_process_id(monkeypatch):
    """TPU_WORKER_ID repeats across slices (it is per-slice for libtpu),
    so a multi-slice gang missing KFTPU_PROCESS_ID must fail loudly
    instead of registering duplicate process ids at the coordinator."""
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:1")
    monkeypatch.setenv("KFTPU_NUM_PROCESSES", "8")
    monkeypatch.setenv("KFTPU_NUM_SLICES", "2")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    monkeypatch.delenv("KFTPU_PROCESS_ID", raising=False)
    with pytest.raises(ValueError, match="KFTPU_PROCESS_ID"):
        distributed.initialize_from_env()
