"""Distributed bootstrap: webhook env → real multi-process JAX group.

Spawns TWO actual Python processes on the CPU backend wearing exactly
the env the admission webhook injects (controlplane/webhook.py
_inject_tpu_env), and asserts the group forms, the global mesh spans
both processes, and a cross-process reduction returns the right value —
the envtest-style proof SURVEY.md §5 asks for ("Distributed
communication backend": jax.distributed.initialize replaces NCCL
rendezvous).
"""

import os
import socket
import subprocess
import sys

import pytest

from kubeflow_tpu import distributed

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import os
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from kubeflow_tpu import distributed

assert distributed.initialize_from_env(timeout_secs=120)
assert jax.process_count() == 2, jax.process_count()
devs = jax.devices()
assert len(devs) == 4, devs  # 2 virtual CPU devices per process
mesh = Mesh(np.array(devs), ("data",))
local = np.full((2,), float(jax.process_index() + 1), np.float32)
garr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")), local)
total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(garr)
# process 0 contributes 2x1.0, process 1 contributes 2x2.0
assert float(total) == 6.0, float(total)
print("CHILD-OK", jax.process_index(), flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _gang_env(worker_id: int, port: int) -> dict[str, str]:
    env = dict(os.environ)
    env.update({
        # Exactly the names _inject_tpu_env sets (DNS replaced by
        # loopback — no kube DNS in a unit test).
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "KFTPU_NUM_PROCESSES": "2",
        "TPU_WORKER_ID": str(worker_id),
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO,
    })
    return env


def test_two_process_gang_forms_global_mesh():
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", CHILD],
            env=_gang_env(i, port),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"CHILD-OK {i}" in out


def test_single_process_env_is_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("KFTPU_NUM_PROCESSES", raising=False)
    assert distributed.initialize_from_env() is False
    # size-1 gang: env present but nothing to rendezvous
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:1")
    monkeypatch.setenv("KFTPU_NUM_PROCESSES", "1")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    assert distributed.initialize_from_env() is False


def test_half_injected_env_fails_loudly(monkeypatch):
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:1")
    monkeypatch.delenv("KFTPU_NUM_PROCESSES", raising=False)
    with pytest.raises(ValueError, match="half-injected"):
        distributed.initialize_from_env()
    monkeypatch.setenv("KFTPU_NUM_PROCESSES", "two")
    with pytest.raises(ValueError, match="non-integer"):
        distributed.initialize_from_env()


def test_multislice_requires_global_process_id(monkeypatch):
    """TPU_WORKER_ID repeats across slices (it is per-slice for libtpu),
    so a multi-slice gang missing KFTPU_PROCESS_ID must fail loudly
    instead of registering duplicate process ids at the coordinator."""
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:1")
    monkeypatch.setenv("KFTPU_NUM_PROCESSES", "8")
    monkeypatch.setenv("KFTPU_NUM_SLICES", "2")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    monkeypatch.delenv("KFTPU_PROCESS_ID", raising=False)
    with pytest.raises(ValueError, match="KFTPU_PROCESS_ID"):
        distributed.initialize_from_env()


HYBRID_CHILD = r"""
import os
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from kubeflow_tpu import distributed
from kubeflow_tpu.parallel import mesh_from_env

assert distributed.initialize_from_env(timeout_secs=180)
assert jax.process_count() == 4, jax.process_count()
devs = jax.devices()
assert len(devs) == 8, devs  # 2 slices x 2 processes x 2 devices

# mesh_from_env reads the SAME env the webhook injects and must build
# the hybrid dcn x ici mesh: dcn spans the slices, KFTPU_MESH lays out
# one slice.
mesh = mesh_from_env()
assert mesh.axis_names == ("dcn", "data", "fsdp", "tensor"), mesh
assert dict(mesh.shape) == {"dcn": 2, "data": 1, "fsdp": 2,
                            "tensor": 2}, dict(mesh.shape)

# Cross-slice reduction over the dcn axis: row s carries (s+1); the
# sum must cross DCN (here: gRPC between the slice process groups).
gl = np.asarray([1.0, 2.0], np.float32)
arr = jax.make_array_from_callback(
    (2,), NamedSharding(mesh, P("dcn")), lambda idx: gl[idx])
total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(arr)
assert float(total) == 3.0, float(total)

# One REAL Trainer step over the hybrid mesh, inputs built shard-wise
# from a deterministic global batch (every process can materialize any
# addressable shard).
from kubeflow_tpu.models import llama
from kubeflow_tpu.train import Trainer, TrainConfig

cfg = llama.LLAMA_TINY
trainer = Trainer(
    mesh=mesh,
    apply_fn=lambda p, t: llama.apply(p, cfg, t),
    init_fn=lambda k: llama.init(k, cfg),
    logical_axes=llama.param_logical_axes(cfg),
    train_config=TrainConfig(warmup_steps=1, total_steps=10),
)
state = trainer.init(jax.random.key(0))
gtoks = np.random.default_rng(0).integers(
    0, cfg.vocab_size, (8, 16)).astype(np.int32)
gtarg = np.roll(gtoks, -1, axis=1)
toks = jax.make_array_from_callback(
    gtoks.shape, trainer.batch_sharding, lambda idx: gtoks[idx])
targ = jax.make_array_from_callback(
    gtarg.shape, trainer.batch_sharding, lambda idx: gtarg[idx])
state, loss = trainer.step(state, toks, targ)
loss = float(loss)
assert np.isfinite(loss), loss
print("HYBRID-OK", jax.process_index(), round(loss, 4), flush=True)
"""


def _hybrid_env(slice_id: int, worker_id: int, port: int) -> dict[str, str]:
    """Exactly the multi-slice env _inject_tpu_env sets for a
    2-slice x 2-host gang (webhook.py:230-238), DNS -> loopback."""
    env = dict(os.environ)
    env.update({
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "KFTPU_NUM_PROCESSES": "4",
        "TPU_WORKER_ID": str(worker_id),          # per-slice (libtpu)
        "KFTPU_PROCESS_ID": str(slice_id * 2 + worker_id),  # global
        "KFTPU_NUM_SLICES": "2",
        "MEGASCALE_NUM_SLICES": "2",
        "MEGASCALE_SLICE_ID": str(slice_id),
        "MEGASCALE_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "KFTPU_MESH": "data=1,fsdp=2,tensor=2",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO,
    })
    return env


@pytest.mark.slow
def test_two_slice_gang_forms_hybrid_mesh_and_trains():
    """VERDICT r04 task 7: a 2-slice x 2-process gang wearing the FULL
    webhook env (MEGASCALE_*, KFTPU_NUM_SLICES=2) forms the hybrid
    dcn x ici mesh via mesh_from_env, proves a cross-slice reduction,
    and runs one real Trainer step — the multi-PROCESS proof of what
    the dryrun exercises single-process."""
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", HYBRID_CHILD],
            env=_hybrid_env(s, w, port),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for s in range(2) for w in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    losses = set()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"gang member {i} failed:\n{out}"
        assert f"HYBRID-OK {i}" in out, out
        losses.add(out.strip().splitlines()[-1].split()[-1])
    # every process observed the SAME loss — the reduction crossed DCN
    assert len(losses) == 1, losses
