"""GPipe-style pipeline parallelism on the fake-TPU backend."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from kubeflow_tpu.parallel import pipeline as pp

# Whole module is compile-heavy (multi-device grads/scan compiles, >15s/test
# on the dev box): slow tier (pyproject addopts deselect; CI runs it on main).
pytestmark = pytest.mark.slow


def mk_mesh(n_stages=4):
    return Mesh(np.asarray(jax.devices()[:n_stages]), ("stage",))


def stage_fn(params, x):
    """Homogeneous residual MLP stage: [mb, d] -> [mb, d]."""
    h = jax.nn.gelu(x @ params["w1"] + params["b1"])
    return x + h @ params["w2"]


def mk_params(n_stages=4, d=16, h=32, seed=0):
    rng = np.random.default_rng(seed)
    per_stage = [
        {
            "w1": jnp.asarray(rng.normal(size=(d, h)) * 0.1, jnp.float32),
            "b1": jnp.zeros((h,), jnp.float32),
            "w2": jnp.asarray(rng.normal(size=(h, d)) * 0.1, jnp.float32),
        }
        for _ in range(n_stages)
    ]
    return per_stage, pp.stack_stage_params(per_stage)


def test_pipeline_matches_sequential():
    per_stage, stacked = mk_params()
    x = jnp.asarray(np.random.default_rng(1).normal(size=(8, 16)), jnp.float32)
    y_ref = pp.reference_forward(stage_fn, per_stage, x)
    y = pp.pipeline_sharded(stage_fn, stacked, x, mk_mesh(),
                            stage_axis="stage", num_microbatches=4)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_single_microbatch_and_many():
    per_stage, stacked = mk_params()
    x = jnp.asarray(np.random.default_rng(2).normal(size=(8, 16)), jnp.float32)
    y_ref = pp.reference_forward(stage_fn, per_stage, x)
    for m in (1, 2, 8):
        y = pp.pipeline_sharded(stage_fn, stacked, x, mk_mesh(),
                                stage_axis="stage", num_microbatches=m)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   rtol=1e-5, atol=1e-5)


def test_pipeline_grads_match_sequential():
    per_stage, stacked = mk_params()
    x = jnp.asarray(np.random.default_rng(3).normal(size=(4, 16)), jnp.float32)
    tgt = jnp.asarray(np.random.default_rng(4).normal(size=(4, 16)), jnp.float32)
    mesh = mk_mesh()

    def loss_pp(stacked_p):
        y = pp.pipeline_sharded(stage_fn, stacked_p, x, mesh,
                                stage_axis="stage", num_microbatches=2)
        return jnp.mean((y - tgt) ** 2)

    def loss_seq(stacked_p):
        per = [jax.tree.map(lambda l: l[i], stacked_p) for i in range(4)]
        return jnp.mean((pp.reference_forward(stage_fn, per, x) - tgt) ** 2)

    g_pp = jax.grad(loss_pp)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        g_pp, g_seq,
    )


def test_pipeline_validation_errors():
    _, stacked = mk_params()
    x = jnp.ones((8, 16), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        pp.pipeline_sharded(stage_fn, stacked, x, mk_mesh(),
                            stage_axis="stage", num_microbatches=3)
    _, stacked_wrong = mk_params(n_stages=2)
    with pytest.raises(ValueError, match="leading dim"):
        pp.pipeline_sharded(stage_fn, stacked_wrong, x, mk_mesh(),
                            stage_axis="stage", num_microbatches=4)
