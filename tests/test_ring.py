"""Ring attention / Ulysses sequence parallelism vs full attention.

Numerical equivalence on the hermetic 8-device CPU mesh (conftest.py):
sequence-sharded blockwise online-softmax must match the dense XLA
attention path bit-for-bit up to fp32 accumulation noise.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from kubeflow_tpu.ops.attention import dot_product_attention
from kubeflow_tpu.parallel.ring import (
    ring_attention_sharded,
    ulysses_attention_sharded,
)

# Whole module is compile-heavy (multi-device grads/scan compiles, >15s/test
# on the dev box): slow tier (pyproject addopts deselect; CI runs it on main).
pytestmark = pytest.mark.slow


def _make_qkv(b=2, s=64, n_q=8, n_kv=4, hd=16, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((b, s, n_q, hd)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, n_kv, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, n_kv, hd)), jnp.float32)
    return q, k, v


def _reference(q, k, v, causal):
    b, s = q.shape[:2]
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    return dot_product_attention(q, k, v, pos, pos, causal=causal, impl="xla")


def _seq_mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]).reshape(1, n, 1),
                ("data", "fsdp", "tensor"))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("ring_size", [2, 4, 8])
def test_ring_matches_full(causal, ring_size):
    mesh = _seq_mesh(ring_size)
    q, k, v = _make_qkv()
    got = ring_attention_sharded(q, k, v, mesh, causal=causal)
    want = _reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_mha_no_gqa():
    mesh = _seq_mesh(4)
    q, k, v = _make_qkv(n_q=4, n_kv=4)
    got = ring_attention_sharded(q, k, v, mesh, causal=True)
    want = _reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_rejects_indivisible_seq():
    mesh = _seq_mesh(8)
    q, k, v = _make_qkv(s=60)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention_sharded(q, k, v, mesh)


def test_ring_under_jit_and_grad():
    """Ring attention must trace under jit and be differentiable."""
    mesh = _seq_mesh(4)
    q, k, v = _make_qkv(s=32)

    @jax.jit
    def loss(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh) ** 2)

    g = jax.grad(loss)(q, k, v)
    assert g.shape == q.shape
    assert bool(jnp.all(jnp.isfinite(g)))

    # Gradients must match the dense path too.
    def ref_loss(q, k, v):
        return jnp.sum(_reference(q, k, v, True) ** 2)

    g_ref = jax.grad(ref_loss)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("ring_size", [2, 4])
def test_ring_flash_matches_full(causal, ring_size):
    """The Pallas-block ring (interpret mode on CPU) must match dense
    attention: fused per-block kernels + online merge + future-block
    skip change the schedule, not the math."""
    from kubeflow_tpu.parallel.ring import ring_flash_attention_sharded

    mesh = _seq_mesh(ring_size)
    q, k, v = _make_qkv(s=32, n_q=4, n_kv=2, hd=16)
    got = ring_flash_attention_sharded(q, k, v, mesh, causal=causal)
    want = _reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_ring_flash_grads_match_dense():
    """The ring-flash custom VJP (per-block kernel bwd with GLOBAL
    lse/delta residuals, accumulators rotated home) must reproduce the
    dense path's gradients for q, k, AND v."""
    from kubeflow_tpu.parallel.ring import ring_flash_attention_sharded

    mesh = _seq_mesh(4)
    q, k, v = _make_qkv(s=32, n_q=4, n_kv=2, hd=16)

    @jax.jit
    def loss(q, k, v):
        return jnp.sum(ring_flash_attention_sharded(q, k, v, mesh) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(_reference(q, k, v, True) ** 2)

    got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, "qkv"):
        assert bool(jnp.all(jnp.isfinite(g))), name
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=2e-3, atol=2e-3,
            err_msg=name)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_full(causal):
    mesh = _seq_mesh(4)
    q, k, v = _make_qkv(n_q=8, n_kv=4)
    got = ulysses_attention_sharded(q, k, v, mesh, causal=causal)
    want = _reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_flash_matches_xla(causal):
    """impl="flash" routes the post-a2a local attention through the
    Pallas kernel; values and grads must match the XLA path."""
    from kubeflow_tpu.parallel.ring import ulysses_attention_sharded as ua

    mesh = _seq_mesh(4)
    q, k, v = _make_qkv(s=32, n_q=8, n_kv=4, hd=16)
    got = ua(q, k, v, mesh, causal=causal, impl="flash")
    want = ua(q, k, v, mesh, causal=causal, impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)
    # grads for q, k, AND v, in BOTH masking modes — a causal-only,
    # q-only check would miss mask-dependent bwd-kernel regressions
    g_f = jax.grad(lambda q, k, v: jnp.sum(
        ua(q, k, v, mesh, causal=causal, impl="flash") ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_x = jax.grad(lambda q, k, v: jnp.sum(
        ua(q, k, v, mesh, causal=causal, impl="xla") ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for f, x, name in zip(g_f, g_x, "qkv"):
        np.testing.assert_allclose(np.asarray(f), np.asarray(x),
                                   rtol=2e-3, atol=2e-3, err_msg=name)


def test_ulysses_rejects_indivisible_heads():
    mesh = _seq_mesh(8)
    q, k, v = _make_qkv(n_q=8, n_kv=4)  # n_kv=4 < 8-way axis
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention_sharded(q, k, v, mesh)
