"""Inference engine + serving REST app + export."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

pytest_plugins = ("aiohttp.pytest_plugin",)

from kubeflow_tpu.models import gemma, llama
from kubeflow_tpu.serving import (
    EngineConfig, GEMMA_FAMILY, InferenceEngine, LLAMA_FAMILY,
)
from kubeflow_tpu.serving import export as export_lib
from kubeflow_tpu.serving import server as server_lib


@pytest.fixture(scope="module")
def llama_engine():
    cfg = llama.LLAMA_TINY
    params = llama.init(jax.random.key(0), cfg)
    return InferenceEngine(params, cfg, LLAMA_FAMILY,
                           EngineConfig(max_len=64)), cfg, params


def _naive_greedy(module, params, cfg, prompt, max_new):
    """Oracle: full-prefix recompute argmax decode."""
    toks = prompt
    out = []
    for _ in range(max_new):
        logits = module.apply(params, cfg, toks)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        out.append(nxt)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


@pytest.mark.slow
def test_cached_decode_matches_full_recompute(llama_engine):
    engine, cfg, params = llama_engine
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 8)),
        jnp.int32)
    got = engine.generate(prompt, max_new=6)
    want = _naive_greedy(llama, params, cfg, prompt, 6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_gemma_cached_decode_matches():
    cfg = gemma.GEMMA_TINY
    params = gemma.init(jax.random.key(1), cfg)
    engine = InferenceEngine(params, cfg, GEMMA_FAMILY,
                             EngineConfig(max_len=32))
    prompt = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (1, 5)),
        jnp.int32)
    got = engine.generate(prompt, max_new=4)
    want = _naive_greedy(gemma, params, cfg, prompt, 4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_filter_logits_top_k():
    from kubeflow_tpu.serving import filter_logits
    logits = jnp.asarray([[3.0, 1.0, 4.0, 1.5, 5.0]])
    out = filter_logits(logits, jnp.asarray(2), jnp.asarray(1.0))
    finite = np.isfinite(np.asarray(out))[0]
    assert list(finite) == [False, False, True, False, True]  # 4.0, 5.0
    # 0 disables
    out = filter_logits(logits, jnp.asarray(0), jnp.asarray(1.0))
    assert np.isfinite(np.asarray(out)).all()


def test_filter_logits_top_p():
    from kubeflow_tpu.serving import filter_logits
    # probs ~ [0.643, 0.237, 0.087, 0.032] for logits [3, 2, 1, 0]
    logits = jnp.log(jnp.asarray([[0.643, 0.237, 0.087, 0.032]]))
    for p, want in [(0.5, [True, False, False, False]),   # first alone
                    (0.7, [True, True, False, False]),
                    (0.9, [True, True, True, False]),
                    (1.0, [True, True, True, True])]:
        out = filter_logits(logits, jnp.asarray(0), jnp.asarray(p))
        assert list(np.isfinite(np.asarray(out))[0]) == want, p


def test_filter_logits_top_p_renormalizes_after_top_k():
    """HF sequential semantics: k filters, RENORMALIZE, then nucleus.
    probs [0.4, 0.3, 0.3] with top_k=2 renormalize to [0.571, 0.429];
    top_p=0.5 must keep only the first token (raw-mass semantics would
    wrongly keep both: 0.4 < 0.5)."""
    from kubeflow_tpu.serving import filter_logits
    logits = jnp.log(jnp.asarray([[0.4, 0.3, 0.3]]))
    out = filter_logits(logits, jnp.asarray(2), jnp.asarray(0.5))
    assert list(np.isfinite(np.asarray(out))[0]) == [True, False, False]


def test_sampling_params_are_dynamic_and_respected(llama_engine):
    """top_k=1 / tiny top_p must reproduce greedy exactly, sampled runs
    stay inside the allowed set, and sweeping the knobs must NOT
    recompile the decode scan (they are traced values, not statics)."""
    engine, cfg, params = llama_engine
    prompt = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 8)),
        jnp.int32)
    greedy = np.asarray(engine.generate(prompt, max_new=6))
    compiles_before = engine._generate_jit._cache_size()

    k1 = engine.generate(prompt, max_new=6, temperature=1.0, top_k=1,
                         rng=jax.random.key(7))
    np.testing.assert_array_equal(np.asarray(k1), greedy)
    p_tiny = engine.generate(prompt, max_new=6, temperature=2.5,
                             top_p=1e-6, rng=jax.random.key(8))
    np.testing.assert_array_equal(np.asarray(p_tiny), greedy)
    drawn = np.asarray(engine.generate(
        prompt, max_new=6, temperature=0.7, top_k=5, top_p=0.9,
        rng=jax.random.key(9)))
    assert engine._generate_jit._cache_size() == compiles_before
    # Every sampled token must come from that step's top-5 logits
    # (replay the emitted prefix through the dense forward as oracle).
    seq = np.concatenate([np.asarray(prompt), drawn], axis=1)
    for step in range(drawn.shape[1]):
        logits = np.asarray(llama.apply(
            params, cfg, jnp.asarray(seq[:, :prompt.shape[1] + step])))
        top5 = np.argsort(-logits[:, -1], axis=-1)[:, :5]
        for b in range(seq.shape[0]):
            assert drawn[b, step] in top5[b], (step, b)

    with pytest.raises(ValueError):
        engine.generate(prompt, max_new=6, top_p=0.0)
    with pytest.raises(ValueError):
        engine.generate(prompt, max_new=6, top_k=-1)


def test_generate_length_validation(llama_engine):
    engine, cfg, _ = llama_engine
    prompt = jnp.zeros((1, 60), jnp.int32)
    with pytest.raises(ValueError, match="exceeds cache bucket"):
        engine.generate(prompt, max_new=10)


def test_export_stablehlo_roundtrip(tmp_path, llama_engine):
    engine, cfg, params = llama_engine
    toks = jnp.zeros((1, 8), jnp.int32)
    fn = lambda t: llama.apply(params, cfg, t)
    path = str(tmp_path / "llama_tiny.shlo")
    size = export_lib.export_stablehlo(fn, (toks,), path)
    assert size > 0
    loaded = export_lib.load_stablehlo(path)
    got = loaded.call(toks)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(fn(toks)), rtol=1e-5, atol=1e-5)


def test_saved_model_export_degrades_clearly(tmp_path, llama_engine):
    engine, cfg, params = llama_engine
    try:
        import tensorflow  # noqa: F401
        pytest.skip("tensorflow present; degradation path not applicable")
    except ImportError:
        pass
    with pytest.raises(RuntimeError, match="stablehlo"):
        export_lib.export_saved_model(
            lambda t: llama.apply(params, cfg, t),
            (jnp.zeros((1, 8), jnp.int32),), str(tmp_path / "sm"))


def test_saved_model_export_roundtrip(tmp_path, llama_engine):
    """When TF is present, the reference's serving format (SavedModel via
    jax2tf — ref docs_dev/tf_serving.md) round-trips numerically."""
    tf = pytest.importorskip("tensorflow")
    engine, cfg, params = llama_engine
    toks = jnp.zeros((1, 8), jnp.int32)
    fn = lambda t: llama.apply(params, cfg, t)
    path = str(tmp_path / "sm")
    export_lib.export_saved_model(fn, (toks,), path)
    loaded = tf.saved_model.load(path)
    got = np.asarray(loaded.f(tf.constant(np.asarray(toks))))
    np.testing.assert_allclose(got, np.asarray(fn(toks)),
                               rtol=1e-4, atol=1e-4)


def test_eos_masking():
    """After EOS appears, the rest of the generation is EOS."""
    cfg = llama.LLAMA_TINY
    params = llama.init(jax.random.key(0), cfg)
    plain = InferenceEngine(params, cfg, LLAMA_FAMILY,
                            EngineConfig(max_len=64))
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (1, 8)),
        jnp.int32)
    ref = np.asarray(plain.generate(prompt, max_new=8))[0]
    # Pick the greedy second token as the "EOS" so masking must trigger.
    eos = int(ref[1])
    eng = InferenceEngine(params, cfg, LLAMA_FAMILY,
                          EngineConfig(max_len=64, eos_token=eos))
    got = np.asarray(eng.generate(prompt, max_new=8))[0]
    first_eos = int(np.argmax(got == eos))
    assert np.all(got[first_eos:] == eos)


def test_byte_tokenizer_roundtrip():
    s = "hello TPU ✓"
    assert server_lib.byte_decode(server_lib.byte_encode(s)) == s


async def test_serving_rest_api(llama_engine):
    engine, cfg, _ = llama_engine
    app = server_lib.create_serving_app({"llama-tiny": engine})
    client = TestClient(TestServer(app))
    await client.start_server()

    r = await client.get("/healthz")
    assert r.status == 200

    r = await client.get("/v1/models")
    models = (await r.json())["models"]
    assert models[0]["name"] == "llama-tiny"
    assert models[0]["family"] == "llama"

    r = await client.post("/v1/models/llama-tiny:generate",
                          json={"tokens": [[1, 2, 3, 4]], "max_new": 4})
    assert r.status == 200
    toks = (await r.json())["tokens"]
    assert len(toks) == 1 and len(toks[0]) == 4

    # validation surface
    r = await client.post("/v1/models/llama-tiny:generate",
                          json={"tokens": [[1, 2], [1, 2, 3]]})
    assert r.status == 400
    r = await client.post("/v1/models/llama-tiny:generate",
                          json={"tokens": [[99999]]})
    assert r.status == 400
    r = await client.post("/v1/models/nope:generate",
                          json={"tokens": [[1]]})
    assert r.status == 404
    r = await client.post("/v1/models/llama-tiny:generate",
                          json={"tokens": [[1] * 60], "max_new": 30})
    assert r.status == 400
    # malformed types must be 400, not 500
    r = await client.post("/v1/models/llama-tiny:generate",
                          json={"tokens": [[1, "a"]]})
    assert r.status == 400
    r = await client.post("/v1/models/llama-tiny:generate",
                          json={"text": 123})
    assert r.status == 400
    r = await client.post("/v1/models/llama-tiny:generate",
                          json={"tokens": [[1]], "max_new": "x"})
    assert r.status == 400

    # per-request sampling params: accepted and validated
    r = await client.post(
        "/v1/models/llama-tiny:generate",
        json={"tokens": [[1, 2, 3, 4]], "max_new": 4,
              "temperature": 0.8, "top_k": 5, "top_p": 0.9})
    assert r.status == 200, await r.text()
    assert len((await r.json())["tokens"][0]) == 4
    for bad in ({"temperature": -1}, {"temperature": "hot"},
                {"top_k": -2}, {"top_k": 1.5}, {"top_p": 0},
                {"top_p": 1.2}):
        r = await client.post(
            "/v1/models/llama-tiny:generate",
            json={"tokens": [[1]], "max_new": 2, **bad})
        assert r.status == 400, bad
    await client.close()


@pytest.mark.slow
def test_left_padded_prompts_decode_like_unpadded():
    """A left-padded row must generate exactly what its unpadded prompt
    would: pads are masked out of attention and rope sees logical
    positions. Sharpened head -> stable argmax despite shape-dependent
    reduction order."""
    import dataclasses as _dc
    params = dict(llama.init(jax.random.key(0), llama.LLAMA_TINY))
    params["lm_head"] = params["lm_head"] * 50.0
    cfg = llama.LLAMA_TINY
    eng = InferenceEngine(params, cfg, LLAMA_FAMILY, EngineConfig(max_len=64))

    rng = np.random.default_rng(5)
    short = rng.integers(0, cfg.vocab_size, 5)
    long = rng.integers(0, cfg.vocab_size, 9)
    want_short = np.asarray(eng.generate(
        jnp.asarray([short], jnp.int32), max_new=6))
    want_long = np.asarray(eng.generate(
        jnp.asarray([long], jnp.int32), max_new=6))

    arr = np.zeros((2, 9), np.int32)
    mask = np.zeros((2, 9), bool)
    arr[0, 4:] = short; mask[0, 4:] = True
    arr[1, :] = long;   mask[1, :] = True
    got = np.asarray(eng.generate(
        jnp.asarray(arr), max_new=6, prompt_mask=jnp.asarray(mask)))
    np.testing.assert_array_equal(got[0], want_short[0])
    np.testing.assert_array_equal(got[1], want_long[0])

    # malformed masks are rejected
    bad = mask.copy(); bad[0] = [True] * 4 + [False] + [True] * 4
    with pytest.raises(ValueError, match="LEFT-aligned"):
        eng.generate(jnp.asarray(arr), max_new=2,
                     prompt_mask=jnp.asarray(bad))
    with pytest.raises(ValueError, match="shape"):
        eng.generate(jnp.asarray(arr), max_new=2,
                     prompt_mask=jnp.ones((2, 4), bool))


@pytest.mark.slow
async def test_dynamic_batcher_coalesces_concurrent_requests():
    """N concurrent single-prompt requests with different lengths must
    run as ONE padded engine call and return what each request would
    get alone. Sharpened head: batch-1 vs batch-4 reduction order must
    not flip near-tied argmaxes (same hazard as the left-padding test)."""
    import asyncio as aio

    cfg = llama.LLAMA_TINY
    params = dict(llama.init(jax.random.key(0), cfg))
    params["lm_head"] = params["lm_head"] * 50.0
    engine = InferenceEngine(params, cfg, LLAMA_FAMILY,
                             EngineConfig(max_len=64))
    app = server_lib.create_serving_app(
        {"m": engine}, batch_window_ms=80.0)
    client = TestClient(TestServer(app))
    await client.start_server()

    rng = np.random.default_rng(2)
    prompts = [rng.integers(0, cfg.vocab_size, n).tolist()
               for n in (4, 7, 7, 10)]
    want = [np.asarray(engine.generate(
        jnp.asarray([p], jnp.int32), max_new=5))[0].tolist()
        for p in prompts]

    async def one(p):
        r = await client.post("/v1/models/m:generate",
                              json={"tokens": [p], "max_new": 5})
        assert r.status == 200, await r.text()
        return (await r.json())["tokens"][0]

    batcher = app[server_lib.BATCHERS_KEY]["m"]
    got = await aio.gather(*(one(p) for p in prompts))
    assert batcher.calls == 1, batcher.calls  # coalesced, not serialized
    assert batcher.requests == len(prompts)  # success-counted: the
    # mean-effective-batch evidence /v1/models exposes
    for g, w in zip(got, want):
        assert g == w
    await client.close()


@pytest.mark.slow
async def test_batcher_mixes_sampling_params_in_one_call():
    """Per-row SamplingParams: requests with DIFFERENT knobs (greedy,
    sampled, top_k=1-forced-greedy) coalesce into a single engine call,
    and the deterministic rows still get exactly their solo outputs."""
    import asyncio as aio

    cfg = llama.LLAMA_TINY
    params = dict(llama.init(jax.random.key(0), cfg))
    params["lm_head"] = params["lm_head"] * 50.0
    engine = InferenceEngine(params, cfg, LLAMA_FAMILY,
                             EngineConfig(max_len=64))
    app = server_lib.create_serving_app(
        {"m": engine}, batch_window_ms=80.0)
    client = TestClient(TestServer(app))
    await client.start_server()

    rng = np.random.default_rng(4)
    prompts = [rng.integers(0, cfg.vocab_size, n).tolist()
               for n in (4, 6, 8)]
    greedy_refs = [np.asarray(engine.generate(
        jnp.asarray([p], jnp.int32), max_new=5))[0].tolist()
        for p in prompts]
    bodies = [
        {"tokens": [prompts[0]], "max_new": 5},                 # greedy
        {"tokens": [prompts[1]], "max_new": 5,
         "temperature": 0.9, "top_p": 0.8},                     # sampled
        {"tokens": [prompts[2]], "max_new": 5,
         "temperature": 1.0, "top_k": 1},                       # =greedy
    ]

    async def one(body):
        r = await client.post("/v1/models/m:generate", json=body)
        assert r.status == 200, await r.text()
        return (await r.json())["tokens"][0]

    batcher = app[server_lib.BATCHERS_KEY]["m"]
    before = batcher.calls
    got = await aio.gather(*(one(b) for b in bodies))
    assert batcher.calls == before + 1, "mixed knobs must coalesce"
    assert got[0] == greedy_refs[0]
    assert got[2] == greedy_refs[2]           # top_k=1 is argmax
    assert all(0 <= t < cfg.vocab_size for t in got[1])
    await client.close()


async def test_speculative_decoding_over_rest():
    """A model registered with a draft serves "speculative": true —
    greedy output identical to the plain path, acceptance stats in the
    response, validation on batch/gamma/missing-draft."""
    cfg = llama.LLAMA_TINY
    params = dict(llama.init(jax.random.key(0), cfg))
    params["lm_head"] = params["lm_head"] * 50.0
    engine = InferenceEngine(params, cfg, LLAMA_FAMILY,
                             EngineConfig(max_len=64))
    app = server_lib.create_serving_app(
        {"m": engine}, drafts={"m": engine})   # self-draft: accepts all
    client = TestClient(TestServer(app))
    await client.start_server()

    prompt = np.random.default_rng(6).integers(
        0, cfg.vocab_size, 8).tolist()
    want = np.asarray(engine.generate(
        jnp.asarray([prompt], jnp.int32), max_new=10))[0].tolist()
    r = await client.post("/v1/models/m:generate",
                          json={"tokens": [prompt], "max_new": 10,
                                "speculative": True, "gamma": 3})
    assert r.status == 200, await r.text()
    out = await r.json()
    assert out["tokens"][0] == want
    assert out["speculative"]["acceptance_rate"] == 1.0
    assert out["speculative"]["proposed"] > 0

    # client-swept gamma buckets to powers of two <= 8: a second value
    # in the same bucket must not add a compile
    spec_eng = app[server_lib.SPEC_KEY]["m"]
    before = spec_eng._jit._cache_size()
    r = await client.post("/v1/models/m:generate",
                          json={"tokens": [prompt], "max_new": 10,
                                "speculative": True, "gamma": 2})
    assert r.status == 200
    # first request's gamma=3 bucketed to 2; same bucket -> cached
    assert spec_eng._jit._cache_size() == before

    r = await client.post("/v1/models/m:generate",
                          json={"tokens": [prompt, prompt],
                                "max_new": 4, "speculative": True})
    assert r.status == 400  # batch-1 only
    r = await client.post("/v1/models/m:generate",
                          json={"tokens": [prompt], "max_new": 4,
                                "speculative": True, "gamma": 0})
    assert r.status == 400
    r = await client.post("/v1/models/m:generate",
                          json={"tokens": [prompt], "max_new": 50,
                                "speculative": True, "gamma": 8})
    assert r.status == 400  # gamma overflows the cache bucket

    app2 = server_lib.create_serving_app({"m": engine})
    client2 = TestClient(TestServer(app2))
    await client2.start_server()
    r = await client2.post("/v1/models/m:generate",
                           json={"tokens": [prompt], "max_new": 4,
                                 "speculative": True})
    assert r.status == 400  # no draft registered
    await client2.close()
    await client.close()


def test_byte_decode_drops_out_of_range_ids():
    # vocab-tail ids (>= 256+offset) and specials must not crash decode
    assert server_lib.byte_decode(
        [1, 300, ord("h") + 3, ord("i") + 3, 2, 500]) == "hi"


async def test_out_of_int32_token_ids_are_400(llama_engine):
    engine, _, _ = llama_engine
    app = server_lib.create_serving_app({"m": engine})
    client = TestClient(TestServer(app))
    await client.start_server()
    r = await client.post("/v1/models/m:generate",
                          json={"tokens": [[2**40]], "max_new": 1})
    assert r.status == 400
    await client.close()


@pytest.mark.slow
def test_sharded_gemma_scale_vocab_decode_matches_unsharded():
    """VERDICT r2 weak #7: serving embed at Gemma vocab scale under a
    sharded mesh. The engine's embed (ops.embedding.embed_lookup) must
    switch to the one-hot MXU contraction when vocab/embed are sharded
    (a gather would force the SPMD partitioner to replicate the 256k
    table every step) and produce IDENTICAL greedy tokens."""
    import dataclasses

    from kubeflow_tpu.parallel import (
        LLAMA_RULES, MeshSpec, create_mesh, set_mesh, shard_pytree_specs)

    # Gemma-2B's 256k vocabulary on otherwise-tiny dims (the sharding
    # semantics depend on the table's vocab axis, not the block sizes).
    cfg = dataclasses.replace(
        llama.LLAMA_TINY, vocab_size=262144, tie_embeddings=True)
    params = jax.jit(lambda k: llama.init(k, cfg))(jax.random.key(1))
    prompt = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 6)),
        jnp.int32)

    ref_engine = InferenceEngine(params, cfg, LLAMA_FAMILY,
                                 EngineConfig(max_len=32))
    want = ref_engine.generate(prompt, max_new=4)

    mesh = create_mesh(MeshSpec(data=1, fsdp=2, tensor=4))
    shardings = shard_pytree_specs(
        LLAMA_RULES, llama.param_logical_axes(cfg), mesh)
    sharded_params = jax.device_put(params, shardings)
    # vocab axis genuinely sharded over tensor
    assert sharded_params["embed"].sharding.spec[0] == "tensor"
    engine = InferenceEngine(sharded_params, cfg, LLAMA_FAMILY,
                             EngineConfig(max_len=32))
    with set_mesh(mesh):
        got = engine.generate(prompt, max_new=4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow
async def test_direct_path_buckets_max_new_but_trims_response(llama_engine):
    """max_new is jit-static on the direct (client-batch) path: the
    server buckets it (ADVICE r3: a sweep must not mint one compile per
    value) yet the response carries exactly the requested count."""
    engine, cfg, _ = llama_engine
    app = server_lib.create_serving_app({"llama-tiny": engine})
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        for ask in (3, 5, 7):  # same power-of-two bucket (16)
            r = await client.post(
                "/v1/models/llama-tiny:generate",
                json={"tokens": [[1, 2, 3], [4, 5, 6]], "max_new": ask})
            assert r.status == 200, await r.text()
            toks = (await r.json())["tokens"]
            assert [len(t) for t in toks] == [ask, ask]
    finally:
        await client.close()


def test_top_k_overflow_rejected_in_library_api(llama_engine):
    """ADVICE r3: top_k >= 2**31 wrapped negative through the int32
    cast for direct library callers; must ValueError like the server."""
    engine, cfg, _ = llama_engine
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    with pytest.raises(ValueError, match="2\\*\\*31"):
        engine.generate(prompt, max_new=2, temperature=1.0, top_k=2**31)


@pytest.mark.slow
def test_generate_stream_equals_oneshot(llama_engine):
    """Streamed chunks concatenate to exactly generate()'s output under
    the same rng — both entry points scan the SAME step body — and the
    stream stops early once every row hits EOS."""
    engine, cfg, _ = llama_engine
    prompt = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
    for kwargs in ({}, {"rng": jax.random.key(7), "temperature": 0.8,
                        "top_k": 5}):
        full = np.asarray(engine.generate(prompt, max_new=13, **kwargs))
        parts = list(engine.generate_stream(
            prompt, max_new=13, chunk=4, **kwargs))
        assert [p.shape[0] for p in parts] == [2] * len(parts)
        got = np.concatenate(parts, axis=1)
        assert got.shape[1] <= 13
        assert (got == full[:, :got.shape[1]]).all()
        # anything generate() produced past an early stream stop is
        # post-EOS padding by construction
        if got.shape[1] < 13 and engine.ec.eos_token is not None:
            assert (full[:, got.shape[1]:] == engine.ec.eos_token).all()


@pytest.mark.slow
async def test_sse_streaming_over_rest(llama_engine):
    """POST {"stream": true} returns text/event-stream whose chunk
    events concatenate to the non-streaming response's tokens."""
    engine, cfg, _ = llama_engine
    app = server_lib.create_serving_app({"llama-tiny": engine})
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        body = {"tokens": [[1, 2, 3, 4]], "max_new": 11}
        r = await client.post("/v1/models/llama-tiny:generate", json=body)
        assert r.status == 200
        oneshot = (await r.json())["tokens"]

        r = await client.post("/v1/models/llama-tiny:generate",
                              json={**body, "stream": True})
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/event-stream")
        events = []
        async for line in r.content:
            line = line.strip()
            if line.startswith(b"data: "):
                import json as _json
                events.append(_json.loads(line[len(b"data: "):]))
        assert events and events[-1]["done"] is True
        streamed = [t for e in events[:-1] for t in e["tokens"][0]]
        assert events[-1]["total"] == len(streamed)
        assert streamed == oneshot[0][:len(streamed)]

        # stream + speculative is a 400, not a silent fallback
        r = await client.post(
            "/v1/models/llama-tiny:generate",
            json={**body, "stream": True, "speculative": True})
        assert r.status == 400
    finally:
        await client.close()


def test_generate_stream_validates_eagerly(llama_engine):
    """Review finding: bad arguments must raise at CALL time, not at
    first next() (a server would have already sent SSE headers)."""
    engine, cfg, _ = llama_engine
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    with pytest.raises(ValueError, match="exceeds cache bucket"):
        engine.generate_stream(prompt, max_new=10**6)
    with pytest.raises(ValueError, match="chunk"):
        engine.generate_stream(prompt, max_new=4, chunk=0)


def test_moe_cached_decode_matches_full_recompute():
    """MoE serving: the engine's injected-FFN family (dropless routing)
    must match a full-prefix recompute through llama_moe.apply with the
    same dropless capacity (training's capacity_factor drops tokens by
    design; serving never may — both sides pinned dropless here so any
    mismatch is a cache/routing bug, not a drop)."""
    import dataclasses

    from kubeflow_tpu.models import llama_moe
    from kubeflow_tpu.serving import MOE_LLAMA_FAMILY

    cfg = dataclasses.replace(
        llama_moe.MIXTRAL_TINY,
        capacity_factor=(llama_moe.MIXTRAL_TINY.num_experts
                         / llama_moe.MIXTRAL_TINY.top_k))
    params = dict(llama_moe.init(jax.random.key(2), cfg))
    params["lm_head"] = params["lm_head"] * 50.0
    engine = InferenceEngine(params, cfg, MOE_LLAMA_FAMILY,
                             EngineConfig(max_len=32))
    prompt = jnp.asarray(
        np.random.default_rng(3).integers(0, cfg.vocab_size, (2, 6)),
        jnp.int32)
    got = engine.generate(prompt, max_new=4)

    toks = prompt
    want = []
    for _ in range(4):
        logits, _aux = llama_moe.apply(params, cfg, toks)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        want.append(nxt)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(
        np.asarray(got), np.asarray(jnp.stack(want, axis=1)))


@pytest.mark.slow
async def test_moe_serves_through_continuous_batcher():
    """Composition: the MoE engine rides the continuous batcher (slot
    KV scatter + injected-FFN step) unchanged."""
    import asyncio as aio
    import dataclasses

    from kubeflow_tpu.models import llama_moe
    from kubeflow_tpu.serving import MOE_LLAMA_FAMILY
    from kubeflow_tpu.serving.continuous import ContinuousBatcher

    cfg = dataclasses.replace(
        llama_moe.MIXTRAL_TINY,
        capacity_factor=(llama_moe.MIXTRAL_TINY.num_experts
                         / llama_moe.MIXTRAL_TINY.top_k))
    params = dict(llama_moe.init(jax.random.key(2), cfg))
    params["lm_head"] = params["lm_head"] * 50.0
    engine = InferenceEngine(params, cfg, MOE_LLAMA_FAMILY,
                             EngineConfig(max_len=64))
    batcher = ContinuousBatcher(engine, aio.Lock(), max_slots=2)
    gen = np.random.default_rng(4)
    prompts = [gen.integers(0, cfg.vocab_size, n).tolist()
               for n in (5, 9)]
    want = [np.asarray(engine.generate(
        jnp.asarray([p], jnp.int32), max_new=5))[0].tolist()
        for p in prompts]
    got = await aio.gather(
        *(batcher.submit(p, 5, ()) for p in prompts))
    assert list(got) == want
    await batcher.close()


def test_continuous_only_knobs_rejected_without_continuous(llama_engine):
    engine, _, _ = llama_engine
    with pytest.raises(ValueError, match="require continuous"):
        server_lib.create_serving_app({"m": engine}, warmup=True)
    with pytest.raises(ValueError, match="require continuous"):
        server_lib.create_serving_app({"m": engine},
                                      prefixes={"sys": [1, 2]})


@pytest.mark.slow
async def test_score_endpoint_matches_full_forward(llama_engine):
    """Teacher-forced scoring: engine.score and the :score door match
    a direct log-softmax over llama.apply logits, and total/count give
    perplexity directly."""
    import math

    engine, cfg, params = llama_engine
    seq = np.random.default_rng(50).integers(
        0, cfg.vocab_size, (2, 9)).tolist()
    lps = np.asarray(engine.score(jnp.asarray(seq, jnp.int32)))
    logits = llama.apply(params, cfg, jnp.asarray(seq, jnp.int32))
    want = np.asarray(
        jnp.take_along_axis(
            jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32),
                               axis=-1),
            jnp.asarray(seq, jnp.int32)[:, 1:, None], axis=-1)[:, :, 0])
    np.testing.assert_allclose(lps, want, atol=1e-4)

    app = server_lib.create_serving_app({"m": engine})
    client = TestClient(TestServer(app))
    await client.start_server()
    r = await client.post("/v1/models/m:score", json={"tokens": seq})
    assert r.status == 200, await r.text()
    body = await r.json()
    assert body["count"] == 8
    assert len(body["logprobs"][0]) == 8
    for row, tot in zip(body["logprobs"], body["total"]):
        assert tot == pytest.approx(sum(row), abs=1e-3)
        assert all(lp <= 0.0 and math.isfinite(lp) for lp in row)
    r = await client.post("/v1/models/m:score", json={"tokens": [[5]]})
    assert r.status == 400
    await client.close()


async def test_score_text_mode_short_input_is_400(llama_engine):
    engine, _, _ = llama_engine
    app = server_lib.create_serving_app({"m": engine})
    client = TestClient(TestServer(app))
    await client.start_server()
    r = await client.post("/v1/models/m:score", json={"text": ""})
    assert r.status == 400
    assert "at least 2" in (await r.json())["error"]
    await client.close()
