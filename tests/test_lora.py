"""LoRA fine-tuning: zero-init identity, frozen base, tiny opt state,
loss falls under a sharded Trainer, merge-then-serve."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import llama
from kubeflow_tpu.parallel import MeshSpec, create_mesh
from kubeflow_tpu.train import (
    LoraConfig,
    TrainConfig,
    Trainer,
    cross_entropy_loss,
    init_lora,
    lora_freeze_labels,
    lora_logical_axes,
    lora_loss_fn,
    lora_train_tree,
    merge_lora,
)

# Whole module is compile-heavy (multi-device grads/scan compiles, >15s/test
# on the dev box): slow tier (pyproject addopts deselect; CI runs it on main).
pytestmark = pytest.mark.slow

CFG = llama.LLAMA_TINY
LC = LoraConfig(rank=4, alpha=8.0)


def test_lora_config_validation():
    with pytest.raises(ValueError, match="unknown LoRA targets"):
        LoraConfig(targets=("wq", "nope"))
    with pytest.raises(ValueError, match="rank"):
        LoraConfig(rank=0)


def test_zero_init_merge_is_identity():
    """B = 0 at init: the merged model IS the base model, bitwise."""
    base = llama.init(jax.random.key(0), CFG)
    adapters = init_lora(jax.random.key(1), CFG, LC)
    merged = merge_lora(base, adapters, LC)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(base),
            jax.tree_util.tree_leaves_with_path(merged)):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_merge_applies_scaled_delta():
    base = llama.init(jax.random.key(0), CFG)
    adapters = init_lora(jax.random.key(1), CFG, LC)
    adapters["blocks"]["wq"]["B"] = jnp.ones_like(
        adapters["blocks"]["wq"]["B"])
    merged = merge_lora(base, adapters, LC)
    want = np.asarray(base["blocks"]["wq"], np.float32) + LC.scaling * (
        np.asarray(adapters["blocks"]["wq"]["A"], np.float32)
        @ np.ones((CFG.num_layers, LC.rank, CFG.q_dim), np.float32))
    np.testing.assert_allclose(
        np.asarray(merged["blocks"]["wq"], np.float32), want,
        rtol=2e-5, atol=2e-5)
    # non-adapted weights untouched
    np.testing.assert_array_equal(
        np.asarray(merged["blocks"]["attn_norm"]),
        np.asarray(base["blocks"]["attn_norm"]))


def _lora_trainer(mesh):
    base_axes = llama.param_logical_axes(CFG)
    axes = {"base": base_axes, "lora": lora_logical_axes(base_axes, LC)}

    def init_fn(rng):
        k1, k2 = jax.random.split(rng)
        return lora_train_tree(llama.init(k1, CFG),
                               init_lora(k2, CFG, LC))

    shapes = jax.eval_shape(init_fn, jax.ShapeDtypeStruct((2,), jnp.uint32))
    return Trainer(
        mesh=mesh,
        apply_fn=lambda tree, toks: llama.apply(
            merge_lora(tree["base"], tree["lora"], LC), CFG, toks),
        init_fn=init_fn,
        logical_axes=axes,
        train_config=TrainConfig(warmup_steps=2, total_steps=100,
                                 learning_rate=3e-3),
        loss_fn=lora_loss_fn(
            lambda p, t, tg, m: cross_entropy_loss(
                llama.apply(p, CFG, t), tg, m), LC),
        freeze_labels=lora_freeze_labels(shapes),
    )


def test_lora_trains_adapters_only_under_sharded_mesh():
    mesh = create_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    trainer = _lora_trainer(mesh)
    state = trainer.init(jax.random.key(0))

    base_before = jax.tree.map(np.asarray, state.params["base"])
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, CFG.vocab_size, (8, 32)), jnp.int32)
    tgts = jnp.roll(toks, -1, 1)
    losses = []
    for _ in range(8):
        state, loss = trainer.step(state, toks, tgts)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses

    # The base never moved — bitwise.
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(base_before),
            jax.tree_util.tree_leaves_with_path(state.params["base"])):
        np.testing.assert_array_equal(a, np.asarray(b), err_msg=str(pa))
    # Adapters moved.
    assert any(
        np.abs(np.asarray(leaf)).max() > 0
        for name in LC.targets
        for leaf in [state.params["lora"]["blocks"][name]["B"]])

    # Frozen base has EMPTY optimizer state: moment leaves exist only
    # for adapters (~the LoRA memory win).
    n_lora = len(jax.tree.leaves(state.params["lora"]))
    n_base = len(jax.tree.leaves(state.params["base"]))
    moment_like = [
        leaf for leaf in jax.tree.leaves(state.opt_state)
        if hasattr(leaf, "ndim") and leaf.ndim >= 2]
    assert len(moment_like) == 2 * n_lora  # mu+nu per adapter, none for base
    moment_params = sum(leaf.size for leaf in moment_like)
    base_params = sum(
        leaf.size for leaf in jax.tree.leaves(state.params["base"]))
    assert moment_params < 0.2 * base_params  # full Adam would be 2x


def test_lora_state_checkpoints_and_resumes(tmp_path):
    """The {"base", "lora"} train tree plus the multi_transform opt
    state round-trips through Orbax: restore is bit-identical and the
    resumed run continues exactly like the uninterrupted one."""
    from kubeflow_tpu.train.checkpoint import CheckpointConfig, Checkpointer

    mesh = create_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    trainer = _lora_trainer(mesh)
    ckpt = Checkpointer(
        CheckpointConfig(str(tmp_path / "lora"), save_interval_steps=1,
                         enable_async=False),
        trainer)
    state = trainer.init(jax.random.key(3))
    rng = np.random.default_rng(3)
    toks = jnp.asarray(rng.integers(0, CFG.vocab_size, (8, 16)), jnp.int32)
    tgts = jnp.roll(toks, -1, 1)
    state, _ = trainer.step(state, toks, tgts)
    assert ckpt.save(state)
    ckpt.wait()
    # the next step DONATES state's buffers — snapshot for comparison
    saved_params = jax.tree.map(
        lambda a: np.asarray(jax.device_get(a)), state.params)

    cont, _ = trainer.step(state, toks, tgts)  # uninterrupted path
    restored = ckpt.restore()
    saved_leaves = jax.tree_util.tree_leaves_with_path(saved_params)
    restored_leaves = jax.tree_util.tree_leaves_with_path(restored.params)
    assert len(saved_leaves) == len(restored_leaves)
    for (pa, a), (pb, b) in zip(saved_leaves, restored_leaves):
        assert pa == pb
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b)),
            err_msg=str(pa))
    resumed, _ = trainer.step(restored, toks, tgts)
    for a, b in zip(jax.tree.leaves(cont.params["lora"]),
                    jax.tree.leaves(resumed.params["lora"])):
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b)))


def test_warm_start_and_merge_then_serve():
    """init_from_params warm-starts from an existing base; after a few
    steps the merged params serve through the engine."""
    from kubeflow_tpu.serving import (EngineConfig, InferenceEngine,
                                      LLAMA_FAMILY)

    mesh = create_mesh(MeshSpec(data=1, fsdp=-1, tensor=1))
    trainer = _lora_trainer(mesh)
    base = llama.init(jax.random.key(7), CFG)
    tree = lora_train_tree(base, init_lora(jax.random.key(8), CFG, LC))
    state = trainer.init_from_params(tree)
    assert int(state.step) == 0

    toks = jnp.asarray(
        np.random.default_rng(1).integers(0, CFG.vocab_size, (8, 16)),
        jnp.int32)
    for _ in range(3):
        state, _ = trainer.step(state, toks, jnp.roll(toks, -1, 1))

    merged = jax.jit(merge_lora, static_argnums=2)(
        state.params["base"], state.params["lora"], LC)
    eng = InferenceEngine(merged, CFG, LLAMA_FAMILY,
                          EngineConfig(max_len=48))
    out = eng.generate(toks[:1], max_new=4)
    assert out.shape == (1, 4)
