"""PP-Llama: flagship blocks as pipeline stages (VERDICT r1 item 5).

Numerics vs the plain full-depth forward, and training: a few SGD steps
on the 8-device mesh with the loss decreasing and matching the non-PP
loss on identical data.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import llama, llama_pp
from kubeflow_tpu.train import trainer as trainer_lib

# Whole module is compile-heavy (multi-device grads/scan compiles, >15s/test
# on the dev box): slow tier (pyproject addopts deselect; CI runs it on main).
pytestmark = pytest.mark.slow


CFG = llama.LLAMA_TINY  # 2 layers
# 4 layers: deep enough that 2 stages x 2 layers runs the stage-INTERNAL
# layer scan with >1 layer (VERDICT r2 weak #4 — previously every PP test
# used 1 layer/stage, so that scan never really scanned).
CFG4 = dataclasses.replace(llama.LLAMA_TINY, num_layers=4)


@pytest.fixture(scope="module")
def mesh4():
    devs = np.array(jax.devices()[:2])
    return jax.sharding.Mesh(devs, ("stage",))


def _data(b=4, s=16, seed=0):
    rng = np.random.default_rng(seed)
    toks = jnp.asarray(rng.integers(0, CFG.vocab_size, (b, s)), jnp.int32)
    return toks, jnp.roll(toks, -1, axis=1)


def test_split_merge_roundtrip():
    params = llama.init(jax.random.key(0), CFG)
    staged = llama_pp.split_stages(params, CFG, 2)
    for leaf in jax.tree.leaves(staged):
        assert leaf.shape[0] == 2 and leaf.shape[1] == 1
    merged = llama_pp.merge_stages(staged)
    for a, b in zip(jax.tree.leaves(merged),
                    jax.tree.leaves(params["blocks"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_indivisible_layers_rejected():
    params = llama.init(jax.random.key(0), CFG)
    with pytest.raises(ValueError, match="not divisible"):
        llama_pp.split_stages(params, CFG, 3)


def test_pp_logits_match_dense(mesh4):
    params = llama.init(jax.random.key(0), CFG)
    toks, _ = _data()
    ref = llama.apply(params, CFG, toks)
    out = llama_pp.apply_pipelined(params, CFG, toks, mesh4,
                                   num_microbatches=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_pp_loss_matches_dense_and_trains(mesh4):
    params = llama.init(jax.random.key(1), CFG)
    toks, tgts = _data(seed=1)

    def dense_loss(p):
        logits = llama.apply(p, CFG, toks)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return jnp.mean(-jnp.take_along_axis(logp, tgts[..., None], axis=-1))

    pp_loss = llama_pp.loss_pipelined(params, CFG, toks, tgts, mesh4,
                                      num_microbatches=2)
    np.testing.assert_allclose(float(pp_loss), float(dense_loss(params)),
                               rtol=1e-4)

    step = llama_pp.make_train_step(CFG, mesh4, learning_rate=5e-2,
                                    num_microbatches=2)
    momentum = jax.tree.map(jnp.zeros_like, params)
    losses = []
    for _ in range(6):
        params, momentum, loss = step(params, momentum, toks, tgts)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()


@pytest.mark.parametrize("n_stages", [2, 4])
def test_pp_multilayer_stages_match_dense(n_stages):
    """4-layer model over 2 stages x 2 layers AND 4 stages x 1 layer:
    the 2x2 split exercises the stage-internal multi-layer scan."""
    params = llama.init(jax.random.key(3), CFG4)
    rng = np.random.default_rng(3)
    toks = jnp.asarray(rng.integers(0, CFG4.vocab_size, (4, 16)), jnp.int32)
    staged = llama_pp.split_stages(params, CFG4, n_stages)
    for leaf in jax.tree.leaves(staged):
        assert leaf.shape[:2] == (n_stages, 4 // n_stages)

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:n_stages]), ("stage",))
    ref = llama.apply(params, CFG4, toks)
    out = llama_pp.apply_pipelined(params, CFG4, toks, mesh,
                                   num_microbatches=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_trainer_matches_dense_trainer():
    """PipelineTrainer (stage=2 x data=2 mesh, 2 layers/stage, real AdamW
    chain) must produce the same loss and the same updated params as the
    dense Trainer on identical data — PP composed with the actual
    training stack, not bespoke SGD."""
    from kubeflow_tpu.parallel import MeshSpec, create_mesh

    tc = trainer_lib.TrainConfig(warmup_steps=2, total_steps=10)
    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    pp_mesh = jax.sharding.Mesh(devs, ("stage", "data"))
    ptrainer = llama_pp.PipelineTrainer(
        CFG4, pp_mesh, num_microbatches=4, train_config=tc
    )

    dense_mesh = create_mesh(
        MeshSpec(data=1, fsdp=2, tensor=1), devices=jax.devices()[:2]
    )
    dtrainer = trainer_lib.Trainer(
        mesh=dense_mesh,
        apply_fn=lambda p, t: llama.apply(p, CFG4, t),
        init_fn=lambda k: llama.init(k, CFG4),
        logical_axes=llama.param_logical_axes(CFG4),
        train_config=tc,
    )

    rng = np.random.default_rng(4)
    toks = jnp.asarray(rng.integers(0, CFG4.vocab_size, (8, 16)), jnp.int32)
    tgts = jnp.roll(toks, -1, axis=1)

    pstate = ptrainer.init(jax.random.key(4))
    dstate = dtrainer.init(jax.random.key(4))
    # Block params genuinely live sharded over the stage axis.
    wq_shard = pstate.params["blocks"]["wq"].sharding
    assert wq_shard.spec[0] == "stage", wq_shard

    losses = []
    for _ in range(4):
        pstate, ploss = ptrainer.step(pstate, toks, tgts)
        dstate, dloss = dtrainer.step(dstate, toks, tgts)
        np.testing.assert_allclose(float(ploss), float(dloss), rtol=2e-4)
        losses.append(float(ploss))
    assert losses[-1] < losses[0], losses
    for (kp, pv), (kd, dv) in zip(
        jax.tree_util.tree_leaves_with_path(pstate.params),
        jax.tree_util.tree_leaves_with_path(dstate.params),
    ):
        assert jax.tree_util.keystr(kp) == jax.tree_util.keystr(kd)
        np.testing.assert_allclose(
            np.asarray(pv), np.asarray(dv), rtol=5e-3, atol=5e-4,
            err_msg=jax.tree_util.keystr(kp),
        )


def test_pp_grads_match_dense(mesh4):
    """Gradients THROUGH the pipeline (scan + ppermute VJPs) must equal
    the dense path's — per-stage grads live on their stage but the
    values agree."""
    params = llama.init(jax.random.key(2), CFG)
    toks, tgts = _data(seed=2)

    def dense_loss(p):
        logits = llama.apply(p, CFG, toks)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return jnp.mean(-jnp.take_along_axis(logp, tgts[..., None], axis=-1))

    g_dense = jax.grad(dense_loss)(params)
    g_pp = jax.grad(
        lambda p: llama_pp.loss_pipelined(p, CFG, toks, tgts, mesh4,
                                          num_microbatches=2)
    )(params)
    dense_leaves = {
        jax.tree_util.keystr(k): v
        for k, v in jax.tree_util.tree_leaves_with_path(g_dense)
    }
    pp_leaves = {
        jax.tree_util.keystr(k): v
        for k, v in jax.tree_util.tree_leaves_with_path(g_pp)
    }
    assert dense_leaves.keys() == pp_leaves.keys()
    for key in dense_leaves:
        np.testing.assert_allclose(
            np.asarray(pp_leaves[key]), np.asarray(dense_leaves[key]),
            rtol=5e-3, atol=5e-4, err_msg=key,
        )
