"""PP-Llama: flagship blocks as pipeline stages (VERDICT r1 item 5).

Numerics vs the plain full-depth forward, and training: a few SGD steps
on the 8-device mesh with the loss decreasing and matching the non-PP
loss on identical data.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import llama, llama_pp


CFG = llama.LLAMA_TINY  # 2 layers


@pytest.fixture(scope="module")
def mesh4():
    devs = np.array(jax.devices()[:2])
    return jax.sharding.Mesh(devs, ("stage",))


def _data(b=4, s=16, seed=0):
    rng = np.random.default_rng(seed)
    toks = jnp.asarray(rng.integers(0, CFG.vocab_size, (b, s)), jnp.int32)
    return toks, jnp.roll(toks, -1, axis=1)


def test_split_merge_roundtrip():
    params = llama.init(jax.random.key(0), CFG)
    staged = llama_pp.split_stages(params, CFG, 2)
    for leaf in jax.tree.leaves(staged):
        assert leaf.shape[0] == 2 and leaf.shape[1] == 1
    merged = llama_pp.merge_stages(staged)
    for a, b in zip(jax.tree.leaves(merged),
                    jax.tree.leaves(params["blocks"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_indivisible_layers_rejected():
    params = llama.init(jax.random.key(0), CFG)
    with pytest.raises(ValueError, match="not divisible"):
        llama_pp.split_stages(params, CFG, 3)


def test_pp_logits_match_dense(mesh4):
    params = llama.init(jax.random.key(0), CFG)
    toks, _ = _data()
    ref = llama.apply(params, CFG, toks)
    out = llama_pp.apply_pipelined(params, CFG, toks, mesh4,
                                   num_microbatches=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_pp_loss_matches_dense_and_trains(mesh4):
    params = llama.init(jax.random.key(1), CFG)
    toks, tgts = _data(seed=1)

    def dense_loss(p):
        logits = llama.apply(p, CFG, toks)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return jnp.mean(-jnp.take_along_axis(logp, tgts[..., None], axis=-1))

    pp_loss = llama_pp.loss_pipelined(params, CFG, toks, tgts, mesh4,
                                      num_microbatches=2)
    np.testing.assert_allclose(float(pp_loss), float(dense_loss(params)),
                               rtol=1e-4)

    step = llama_pp.make_train_step(CFG, mesh4, learning_rate=5e-2,
                                    num_microbatches=2)
    momentum = jax.tree.map(jnp.zeros_like, params)
    losses = []
    for _ in range(6):
        params, momentum, loss = step(params, momentum, toks, tgts)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()


def test_pp_grads_match_dense(mesh4):
    """Gradients THROUGH the pipeline (scan + ppermute VJPs) must equal
    the dense path's — per-stage grads live on their stage but the
    values agree."""
    params = llama.init(jax.random.key(2), CFG)
    toks, tgts = _data(seed=2)

    def dense_loss(p):
        logits = llama.apply(p, CFG, toks)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return jnp.mean(-jnp.take_along_axis(logp, tgts[..., None], axis=-1))

    g_dense = jax.grad(dense_loss)(params)
    g_pp = jax.grad(
        lambda p: llama_pp.loss_pipelined(p, CFG, toks, tgts, mesh4,
                                          num_microbatches=2)
    )(params)
    dense_leaves = {
        jax.tree_util.keystr(k): v
        for k, v in jax.tree_util.tree_leaves_with_path(g_dense)
    }
    pp_leaves = {
        jax.tree_util.keystr(k): v
        for k, v in jax.tree_util.tree_leaves_with_path(g_pp)
    }
    assert dense_leaves.keys() == pp_leaves.keys()
    for key in dense_leaves:
        np.testing.assert_allclose(
            np.asarray(pp_leaves[key]), np.asarray(dense_leaves[key]),
            rtol=5e-3, atol=5e-4, err_msg=key,
        )
