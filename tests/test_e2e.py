"""Runs the out-of-process e2e suite (e2e/run_e2e.py) under pytest so
`pytest tests/` exercises the real server binary too — the hermetic
analog of the reference wiring `make e2e-test` into CI (odh
Makefile:172). The suite spawns its own server subprocess; this wrapper
only asserts the phase report."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_e2e_suite_passes():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "e2e", "run_e2e.py")],
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["ok"] is True
    phases = {p["phase"]: p["status"] for p in report["phases"]}
    # the three reference phases (creation/update/deletion) plus ours
    for must in ("profile-creation", "notebook-creation",
                 "gang-env-injection", "notebook-stop-restart",
                 "notebook-deletion", "profile-deletion"):
        assert phases.get(must) == "pass", phases
