"""Mixtral-style MoE transformer: shapes, causality, routed training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import llama_moe
from kubeflow_tpu.parallel import MeshSpec, create_mesh
from kubeflow_tpu.parallel.sharding import LLAMA_RULES, shard_pytree_specs
from kubeflow_tpu.train import Trainer, TrainConfig

CFG = llama_moe.MIXTRAL_TINY


@pytest.fixture(scope="module")
def params():
    return llama_moe.init(jax.random.key(0), CFG)


def test_forward_shapes_and_aux(params):
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, CFG.vocab_size, (2, 16)),
        jnp.int32)
    logits, aux = llama_moe.apply(params, CFG, toks)
    assert logits.shape == (2, 16, CFG.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(logits)))
    # Switch aux loss: E * sum(frac * mean_prob) ~ 1 at uniform routing
    assert 0.0 < float(aux) < 10.0


def test_causality_with_headroom_and_documented_capacity_leak():
    """With capacity that never overflows, routing is strictly causal.
    Under capacity PRESSURE the rank-major Switch slot assignment lets
    a later token evict an earlier token's secondary route — the
    documented train-time approximation; pin that it actually happens
    so a silent semantic change to _route gets noticed either way."""
    import dataclasses

    rng = np.random.default_rng(1)
    t1 = rng.integers(0, CFG.vocab_size, (1, 12)).astype(np.int32)
    t2 = t1.copy()
    t2[0, -1] = (t2[0, -1] + 5) % CFG.vocab_size

    roomy = dataclasses.replace(CFG, capacity_factor=4.0)
    params = llama_moe.init(jax.random.key(0), roomy)
    l1, _ = llama_moe.apply(params, roomy, jnp.asarray(t1))
    l2, _ = llama_moe.apply(params, roomy, jnp.asarray(t2))
    np.testing.assert_allclose(np.asarray(l1[:, :-1]),
                               np.asarray(l2[:, :-1]),
                               rtol=2e-4, atol=2e-4)

    tight = dataclasses.replace(CFG, capacity_factor=0.5)
    l1, _ = llama_moe.apply(params, tight, jnp.asarray(t1))
    l2, _ = llama_moe.apply(params, tight, jnp.asarray(t2))
    assert np.abs(np.asarray(l1[:, :-1])
                  - np.asarray(l2[:, :-1])).max() > 0


def test_logical_axes_cover_params_and_resolve(params):
    axes = llama_moe.param_logical_axes(CFG)
    assert (jax.tree_util.tree_structure(
        jax.tree.map(lambda _: 0, params))
        == jax.tree_util.tree_structure(
            jax.tree.map(lambda _: 0, axes,
                         is_leaf=lambda x: isinstance(x, tuple))))
    mesh = create_mesh(MeshSpec(data=1, fsdp=4, tensor=2))
    shardings = shard_pytree_specs(LLAMA_RULES, axes, mesh)
    for leaf, sh in zip(jax.tree.leaves(params),
                        jax.tree.leaves(shardings, is_leaf=lambda x: hasattr(x, "spec"))):
        assert len(sh.spec) <= leaf.ndim


@pytest.mark.slow
def test_moe_trains_under_sharded_mesh():
    """CE + aux loss falls under a (data, fsdp, tensor) mesh and the
    ROUTER learns (its weights move) — the full Mixtral train recipe on
    the fake-TPU backend."""
    mesh = create_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    trainer = Trainer(
        mesh=mesh,
        apply_fn=lambda p, t: llama_moe.apply(p, CFG, t)[0],
        init_fn=lambda k: llama_moe.init(k, CFG),
        logical_axes=llama_moe.param_logical_axes(CFG),
        train_config=TrainConfig(warmup_steps=2, total_steps=100,
                                 learning_rate=3e-3),
        loss_fn=llama_moe.loss_fn(CFG),
    )
    state = trainer.init(jax.random.key(0))
    router_before = np.asarray(state.params["blocks"]["router"])
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, CFG.vocab_size, (8, 32)), jnp.int32)
    tgts = jnp.roll(toks, -1, 1)
    losses = []
    for _ in range(8):
        state, loss = trainer.step(state, toks, tgts)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    router_after = np.asarray(state.params["blocks"]["router"])
    assert np.abs(router_after - router_before).max() > 0
