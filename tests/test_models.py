"""ViT / Gemma / MNIST model families on the fake-TPU backend."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import gemma, mnist, vit
from kubeflow_tpu.parallel import MeshSpec, create_mesh
from kubeflow_tpu.train import Trainer, TrainConfig


def test_gemma_forward_shapes_and_tied_head():
    cfg = gemma.GEMMA_TINY
    params = gemma.init(jax.random.key(0), cfg)
    assert "lm_head" not in params  # always tied
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 12)),
        jnp.int32)
    logits = gemma.apply(params, cfg, toks)
    assert logits.shape == (2, 12, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert np.all(np.isfinite(np.asarray(logits)))


def test_gemma_causality():
    """Changing a future token must not affect earlier logits."""
    cfg = gemma.GEMMA_TINY
    params = gemma.init(jax.random.key(0), cfg)
    rng = np.random.default_rng(1)
    t1 = rng.integers(0, cfg.vocab_size, (1, 8)).astype(np.int32)
    t2 = t1.copy()
    t2[0, -1] = (t2[0, -1] + 7) % cfg.vocab_size
    l1 = gemma.apply(params, cfg, jnp.asarray(t1))
    l2 = gemma.apply(params, cfg, jnp.asarray(t2))
    np.testing.assert_allclose(np.asarray(l1[:, :-1]), np.asarray(l2[:, :-1]),
                               rtol=1e-5, atol=1e-5)


def test_gemma_sliding_window_locality():
    """GemmaConfig.sliding_window: a token beyond the window cannot
    influence the last position (1-layer receptive field == window)."""
    import dataclasses

    cfg = dataclasses.replace(gemma.GEMMA_TINY, num_layers=1,
                              sliding_window=3)
    params = gemma.init(jax.random.key(0), cfg)
    rng = np.random.default_rng(2)
    t1 = rng.integers(0, cfg.vocab_size, (1, 12)).astype(np.int32)
    far = t1.copy(); far[0, 4] = (far[0, 4] + 1) % cfg.vocab_size
    l1 = np.asarray(gemma.apply(params, cfg, jnp.asarray(t1))[:, -1])
    l2 = np.asarray(gemma.apply(params, cfg, jnp.asarray(far))[:, -1])
    np.testing.assert_array_equal(l1, l2)
    near = t1.copy(); near[0, 10] = (near[0, 10] + 1) % cfg.vocab_size
    l3 = np.asarray(gemma.apply(params, cfg, jnp.asarray(near))[:, -1])
    assert np.abs(l3 - l1).max() > 0


@pytest.mark.slow
def test_gemma_trains_sharded():
    """Gemma composes with the FSDP/TP Trainer unchanged."""
    cfg = gemma.GEMMA_TINY
    mesh = create_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    trainer = Trainer(
        mesh=mesh,
        apply_fn=lambda p, t: gemma.apply(p, cfg, t),
        init_fn=lambda k: gemma.init(k, cfg),
        logical_axes=gemma.param_logical_axes(cfg),
        train_config=TrainConfig(warmup_steps=1, total_steps=10),
    )
    state = trainer.init(jax.random.key(0))
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 16)),
        jnp.int32)
    losses = []
    for _ in range(3):
        state, loss = trainer.step(state, toks, jnp.roll(toks, -1, 1))
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_vit_forward_and_patchify():
    cfg = vit.VIT_TINY
    params = vit.init(jax.random.key(0), cfg)
    imgs = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 32, 32, 3)), jnp.float32)
    logits = vit.apply(params, cfg, imgs)
    assert logits.shape == (2, 10)
    # Zero-init head ⇒ zero logits at init (fine-tune convention).
    np.testing.assert_allclose(np.asarray(logits), 0.0, atol=1e-6)
    # Patchify is a pure rearrangement: pixel sums preserved.
    patches = vit.patchify(cfg, imgs)
    assert patches.shape == (2, cfg.num_patches, cfg.patch_dim)
    np.testing.assert_allclose(
        float(jnp.sum(patches)), float(jnp.sum(imgs)), rtol=1e-5)


@pytest.mark.slow
def test_vit_finetune_learns():
    """Few steps of full fine-tune separate two synthetic classes."""
    cfg = vit.VIT_TINY
    params = vit.init(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)
    # Class 0: bright top half; class 1: bright bottom half.
    n = 32
    y = rng.integers(0, 2, n).astype(np.int32)
    x = rng.normal(scale=0.1, size=(n, 32, 32, 3)).astype(np.float32)
    x[y == 0, :16] += 1.0
    x[y == 1, 16:] += 1.0
    xb, yb = jnp.asarray(x), jnp.asarray(y)

    import optax
    opt = optax.adam(3e-3)
    ost = opt.init(params)

    @jax.jit
    def step(params, ost):
        def loss_fn(p):
            logits = vit.apply(p, cfg, xb)
            onehot = jax.nn.one_hot(yb, cfg.num_classes)
            return -jnp.mean(
                jnp.sum(onehot * jax.nn.log_softmax(logits), -1))
        loss, g = jax.value_and_grad(loss_fn)(params)
        u, ost = opt.update(g, ost)
        return optax.apply_updates(params, u), ost, loss

    losses = []
    for _ in range(30):
        params, ost, loss = step(params, ost)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses
    acc = float(jnp.mean(
        (jnp.argmax(vit.apply(params, cfg, xb), -1) == yb)))
    assert acc >= 0.9, acc


@pytest.mark.slow
def test_vit_trainer_sharded_smoke():
    """ViT under the sharded Trainer: one FSDP/TP step compiles + runs.
    (Trainer's loss is next-token CE over [b,s,vocab]; ViT emits [b,c] —
    wrap apply to add a seq dim so the same Trainer drives both.)"""
    cfg = vit.VIT_TINY
    mesh = create_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    trainer = Trainer(
        mesh=mesh,
        apply_fn=lambda p, imgs: vit.apply(p, cfg, imgs)[:, None, :],
        init_fn=lambda k: vit.init(k, cfg),
        logical_axes=vit.param_logical_axes(cfg),
        train_config=TrainConfig(warmup_steps=1, total_steps=10),
    )
    state = trainer.init(jax.random.key(0))
    imgs = jnp.asarray(
        np.random.default_rng(0).normal(size=(8, 32, 32, 3)), jnp.float32)
    y = jnp.asarray(np.random.default_rng(1).integers(0, 10, (8, 1)), jnp.int32)
    state, loss = trainer.step(state, imgs, y, jnp.ones((8, 1), jnp.float32))
    assert np.isfinite(float(loss))


@pytest.mark.slow
def test_mnist_smoke_learns():
    metrics = mnist.train_smoke(steps=60)
    assert metrics["test_accuracy"] > 0.8, metrics
    assert metrics["final_train_loss"] < 1.0, metrics
