"""Fused prefill/append kernel vs the XLA scatter+gather oracle.

The kernel (ops/pallas/prefill_append.py) merges each row's s new
tokens into the paged KV pool THROUGH the block table in-kernel
(input_output_aliases) and attends them in the same pass;
`ops.paged_prefill_attention(impl="xla")` scatters the new cells with
`.at[].set` and gathers the full window. The two must agree — on the
attention output to fp32 tolerance AND on the pool contents
bit-for-bit — across GQA ratios, ragged cursors and lengths, sliding
windows, and radix-shared tables; and the continuous engine must emit
IDENTICAL tokens with either impl under chunked prefill.

Write disjointness is a precondition, not a tested behavior: each
row's write range [q_start, q_start + q_lens) must lie in blocks no
OTHER row's table references. The serving engine satisfies it by
construction (writes land in exclusively-owned fresh blocks; shared
radix blocks sit strictly below every sharer's cursor) — see
serving/paged.py.

All kernel runs here are interpret mode (CPU backend — see conftest).
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import llama
from kubeflow_tpu.ops.attention import (
    impl_counts,
    paged_prefill_attention,
    resolve_paged_prefill_impl,
)
from kubeflow_tpu.serving import EngineConfig, InferenceEngine, LLAMA_FAMILY
from kubeflow_tpu.serving.continuous import ContinuousBatcher, ContinuousEngine

TOL = dict(atol=1e-5, rtol=1e-5)


def _mk(seed, b=3, s=5, n_q=8, n_kv=2, hd=32, bs=8, nb=6,
        num_blocks=64, starts=None, lens=None):
    """Random pool + per-row table/cursor in the engine's layout:
    ragged cursors, chains of EXCLUSIVE blocks per row covering
    [0, start + s) (write-disjoint by construction), table tails
    trash-padded (block 0)."""
    rng = np.random.default_rng(seed)
    width = nb * bs
    q = jnp.asarray(rng.normal(size=(b, s, n_q, hd)), jnp.float32)
    kn = jnp.asarray(rng.normal(size=(b, s, n_kv, hd)), jnp.float32)
    vn = jnp.asarray(rng.normal(size=(b, s, n_kv, hd)), jnp.float32)
    kp = np.asarray(rng.normal(size=(num_blocks, bs, n_kv, hd)),
                    np.float32)
    vp = np.asarray(rng.normal(size=(num_blocks, bs, n_kv, hd)),
                    np.float32)
    kp[0] = vp[0] = 0.0  # the trash block holds no real tokens
    if starts is None:
        starts = rng.integers(0, width - s + 1, size=(b,))
    starts = np.asarray(starts, np.int32)
    if lens is None:
        lens = np.full((b,), s, np.int32)
    lens = np.asarray(lens, np.int32)
    table = np.zeros((b, nb), np.int32)
    used = {0}
    for i in range(b):
        need = -(-int(starts[i] + s) // bs) if starts[i] + s else 1
        for j in range(max(need, 1)):
            blk = int(rng.choice([x for x in range(1, num_blocks)
                                  if x not in used]))
            used.add(blk)
            table[i, j] = blk
    return (q, kn, vn, jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(table), jnp.asarray(starts), jnp.asarray(lens))


def _run(args, impl, window=None, mask=None):
    q, kn, vn, kp, vp, table, starts, lens = args
    return paged_prefill_attention(
        q, kn, vn, kp, vp, table, starts, lens, kv_mask=mask,
        window=window, impl=impl, interpret=True)


def _check(args, window=None, mask=None):
    """Output parity on valid rows/tokens + pool parity on every
    non-trash block (the kernel rewrites each visited block fully, so
    untouched cells must round-trip bit-identically)."""
    wo, wk, wv = _run(args, "xla", window=window, mask=mask)
    go, gk, gv = _run(args, "pallas", window=window, mask=mask)
    lens = np.asarray(args[7])
    for i, n in enumerate(lens):
        np.testing.assert_allclose(np.asarray(go)[i, :n],
                                   np.asarray(wo)[i, :n], **TOL)
    # block 0 is the garbage sink: both impls route invalid tokens
    # there, in impl-specific order — everything else must agree
    np.testing.assert_array_equal(np.asarray(gk)[1:],
                                  np.asarray(wk)[1:])
    np.testing.assert_array_equal(np.asarray(gv)[1:],
                                  np.asarray(wv)[1:])


@pytest.mark.parametrize("n_q,n_kv", [(8, 2), (4, 4), (8, 1)])
def test_kernel_matches_oracle_across_gqa_ratios(n_q, n_kv):
    for seed in (0, 1):
        _check(_mk(seed, n_q=n_q, n_kv=n_kv))


def test_kernel_matches_oracle_ragged_cursors():
    # cursors pinned to the raggedest corners: empty pool, block
    # boundaries either side, chunk straddling a boundary, window end
    _check(_mk(2, b=5, s=5, starts=[0, 7, 8, 30, 43]))


def test_kernel_matches_oracle_ragged_lens():
    # group padding: q_lens rags from full to ZERO new tokens (a row
    # admitted in a bigger group's dispatch with nothing to feed)
    _check(_mk(3, b=4, s=6, lens=[6, 3, 1, 0]))


@pytest.mark.parametrize("window", [1, 4, 13, 100])
def test_kernel_matches_oracle_sliding_window(window):
    _check(_mk(4), window=window)


def test_kernel_matches_oracle_masked_holes():
    q, kn, vn, kp, vp, table, starts, lens = _mk(5, b=2, nb=6, bs=8)
    mask = np.ones((2, 48), bool)
    mask[:, 3] = False  # a left-pad hole, same for every row
    _check((q, kn, vn, kp, vp, table, starts, lens),
           mask=jnp.asarray(mask))


def test_kernel_shared_prefix_blocks_are_read_only():
    """Radix sharing: two rows' tables reference the SAME physical
    block strictly below both cursors. Reads must not cross-talk, and
    the shared block's content must survive both rows' visits
    bit-identically (the kernel's rewrite of a read-only block is the
    content it read)."""
    rng = np.random.default_rng(11)
    bs, n_kv, hd = 8, 2, 16
    q = jnp.asarray(rng.normal(size=(2, 4, 4, hd)), jnp.float32)
    kn = jnp.asarray(rng.normal(size=(2, 4, n_kv, hd)), jnp.float32)
    vn = jnp.asarray(rng.normal(size=(2, 4, n_kv, hd)), jnp.float32)
    kp = np.asarray(rng.normal(size=(8, bs, n_kv, hd)), np.float32)
    vp = np.asarray(rng.normal(size=(8, bs, n_kv, hd)), np.float32)
    kp[0] = vp[0] = 0.0
    # both rows share block 3 (cells 0..7); writes land in exclusive
    # blocks 5 and 6 — the serving invariant exactly
    table = jnp.asarray([[3, 5, 0], [3, 6, 0]], jnp.int32)
    starts = jnp.asarray([8, 10], jnp.int32)
    args = (q, kn, vn, jnp.asarray(kp), jnp.asarray(vp), table,
            starts, jnp.asarray([4, 4], jnp.int32))
    _check(args)
    _, gk, gv = _run(args, "pallas")
    np.testing.assert_array_equal(np.asarray(gk)[3], kp[3])
    np.testing.assert_array_equal(np.asarray(gv)[3], vp[3])


def test_kernel_preserves_unvisited_blocks():
    """Blocks past each row's last visited block (and blocks owned by
    nobody) must come back byte-identical — the pool is shared state;
    a stray DMA would corrupt OTHER requests' KV."""
    args = _mk(6, b=2, s=4, starts=[0, 5])
    _, kp0, vp0 = args[3], args[3], args[4]
    kp_before = np.asarray(args[3]).copy()
    _, gk, gv = _run(args, "pallas")
    table = np.asarray(args[5])
    starts, s = np.asarray(args[6]), 4
    visited = {0}
    for i in range(2):
        last = (int(starts[i]) + s - 1) // 8
        visited.update(int(b) for b in table[i, :last + 1])
    for blk in range(kp_before.shape[0]):
        if blk not in visited:
            np.testing.assert_array_equal(np.asarray(gk)[blk],
                                          kp_before[blk])


# -- dispatcher doors -------------------------------------------------------


def test_prefill_impl_dispatch_and_counters():
    args = _mk(7)
    base = impl_counts()
    _run(args, "pallas")
    _run(args, "xla")
    now = impl_counts()
    assert now["paged_prefill"] == base["paged_prefill"] + 2
    assert now["paged_prefill_pallas"] == base["paged_prefill_pallas"] + 1
    assert now["paged_prefill_xla"] == base["paged_prefill_xla"] + 1


def test_resolve_prefill_impl():
    assert resolve_paged_prefill_impl("xla") == "xla"
    assert resolve_paged_prefill_impl("pallas") == "pallas"
    # conftest pins the CPU backend, so auto must scatter+gather
    assert resolve_paged_prefill_impl("auto") == "xla"
    with pytest.raises(ValueError, match="impl"):
        resolve_paged_prefill_impl("cuda")


def test_dispatcher_validation_doors():
    q, kn, vn, kp, vp, table, starts, lens = _mk(8)
    with pytest.raises(ValueError, match="disagree"):
        paged_prefill_attention(q, kn, vn, kp, vp[:-1], table, starts)
    with pytest.raises(ValueError, match="block_table"):
        paged_prefill_attention(q, kn, vn, kp, vp, table[0], starts)
    with pytest.raises(ValueError, match="kv_mask"):
        paged_prefill_attention(
            q, kn, vn, kp, vp, table, starts,
            kv_mask=jnp.ones((3, 40), bool))


# -- continuous engine end-to-end token parity ------------------------------


def _engine(max_len=64):
    cfg = llama.LLAMA_TINY
    params = dict(llama.init(jax.random.key(0), cfg))
    params["lm_head"] = params["lm_head"] * 50.0  # argmax can't flip
    return InferenceEngine(params, cfg, LLAMA_FAMILY,
                           EngineConfig(max_len=max_len)), cfg


def test_engine_resolves_prefill_impl():
    engine, _ = _engine()
    ce = ContinuousEngine(engine, max_slots=2,
                          paged_attention_impl="auto")
    assert ce.prefill_impl == "xla"  # CPU auto-resolution
    ce = ContinuousEngine(engine, max_slots=2,
                          paged_attention_impl="pallas")
    assert ce.prefill_impl == "pallas"


@pytest.mark.slow
def test_chunked_prefill_token_parity_across_impls():
    """The serving-level A/B: chunked prefill emits IDENTICAL tokens
    whether the append runs through the fused kernel (interpret) or
    the XLA scatter+gather — the same contract the decode kernel
    pins."""
    engine, cfg = _engine()
    gen = np.random.default_rng(5)
    prompts = [gen.integers(0, cfg.vocab_size, n).tolist()
               for n in (9, 17)]

    def run(impl):
        async def go():
            b = ContinuousBatcher(engine, asyncio.Lock(), max_slots=2,
                                  kv_block_size=8,
                                  prefill_chunk_tokens=4,
                                  paged_attention_impl=impl)
            assert b.cengine.prefill_impl == impl
            out = await asyncio.gather(
                *(b.submit(p, 5, ()) for p in prompts))
            await b.close()
            return [list(o) for o in out]

        return asyncio.get_event_loop().run_until_complete(go())

    assert run("xla") == run("pallas")
