"""KV-cache observatory (ISSUE 13): block lifecycle ledger
conservation, reuse-distance math, decayed prefix heat, heartbeat
digest round-trip, and the router's counterfactual fleet-hit counter.

The structural invariant under test everywhere: every block death is
booked to a cause from a closed set and the causes SUM to total frees
(`unattributed` stays zero), the same discipline as PR 8's
phase-sums == wall. The fleet half pins that replica heat digests and
the router's routing key hash the same canonical prefix form, so the
fleet heat map joins on real keys."""

import asyncio
import socket

import pytest
from aiohttp import web  # noqa: F401  (pytest plugin needs aiohttp)
from aiohttp.test_utils import TestClient, TestServer

pytest_plugins = ("aiohttp.pytest_plugin",)

from kubeflow_tpu.fleet import router as router_mod
from kubeflow_tpu.fleet.registry import ReplicaRegistry, rendezvous
from kubeflow_tpu.obs.cachestats import (
    DEFER_CAUSES,
    EVICTION_CAUSES,
    UNATTRIBUTED,
    CacheLedger,
    canonical_prefix,
    prefix_hash,
)
from kubeflow_tpu.obs.cardinality import LabelGuard
from kubeflow_tpu.serving.paged import BlockPool, RadixPrefixCache

BS = 8  # kv block size for the engine-level tests


# -- prefix hashing ---------------------------------------------------------


def test_prefix_hash_matches_hashed_label_guard():
    """The ONE join key: a replica's hashed LabelGuard over the
    canonical prefix string must equal the router's prefix_hash of the
    same token slice, or /fleet/cache merges garbage."""
    guard = LabelGuard(hashed=True)
    toks = [3, 5, 7, 11, 13, 17, 19, 23]
    h = prefix_hash(toks)
    assert h == guard.admit(canonical_prefix(toks))
    assert len(h) == 16 and all(c in "0123456789abcdef" for c in h)
    # tenant namespace salts the hash: same tokens, different name
    assert prefix_hash(toks, ns="acme") != h
    assert prefix_hash(toks, ns="acme") == guard.admit(
        canonical_prefix(toks, ns="acme"))
    # canonical form is the router's space-joined-decimal affinity form
    assert canonical_prefix([1, 2, 3]) == "1 2 3"


def test_hashed_guard_modes_are_exclusive():
    with pytest.raises(ValueError):
        LabelGuard(hashed=True, closed=True, seed=("a", "b"))
    # hashed mode never overflows: unbounded values, bounded output
    guard = LabelGuard(max_values=2, hashed=True)
    outs = {guard.admit(f"v{i}") for i in range(50)}
    assert len(outs) == 50 and all(len(o) == 16 for o in outs)


# -- ledger: scripted trace -------------------------------------------------


def test_ledger_scripted_trace_conservation_and_reuse_math():
    led = CacheLedger(wall=lambda: 42.0)
    led.note_alloc([1, 2, 3])           # born at tick 0
    led.note_admission()                # tick 1
    led.note_admission()                # tick 2
    led.note_reuse([1, 2])              # d = 2 - 0 = 2, twice
    led.note_admission()                # tick 3
    led.note_reuse([1])                 # d = 3 - 2 = 1
    led.note_reuse([99])                # untracked block: ignored
    led.note_free([2], "lru")           # age 3
    led.note_free([3], "pressure")      # age 3
    led.note_free([], "lru")            # empty free books nothing

    snap = led.snapshot()
    assert snap["admissions"] == 3 and snap["births"] == 3
    assert snap["frees"]["lru"] == 1
    assert snap["frees"]["pressure"] == 1
    assert snap["frees"][UNATTRIBUTED] == 0
    assert snap["frees_total"] == 2 and snap["live_blocks"] == 1
    assert snap["conserved"] is True
    assert snap["reuse_distance"]["count"] == 3
    assert snap["reuse_distance"]["p50"] == 2      # sorted [1, 2, 2]
    assert snap["reuse_distance"]["p95"] == 2
    assert snap["block_age"] == {"count": 2, "p50": 3, "p95": 3}

    # defers: unknown causes collapse into pool_exhausted, never a new
    # label
    led.note_defer("kv_quota")
    led.note_defer("???")
    assert led.snapshot()["defers"] == {"kv_quota": 1,
                                        "pool_exhausted": 1}

    # a free that forgot its cause breaks conservation VISIBLY
    led.note_free([1], None)
    snap = led.snapshot()
    assert snap["frees"][UNATTRIBUTED] == 1
    assert snap["conserved"] is False

    # chrome counter track: all-zero seed point first, then one point
    # per non-empty free, names prefixed per model
    evs = led.counter_events(prefix="tiny")
    assert [e["name"] for e in evs] == ["tiny.kv_evictions"] * 4
    assert evs[0]["args"] == {c: 0 for c in EVICTION_CAUSES}
    assert evs[1]["args"]["lru"] == 1
    assert evs[-1]["ts"] == 42.0 * 1e6


def test_ledger_hooks_fire_and_swallow_exceptions():
    led = CacheLedger()
    seen = {"free": [], "reuse": [], "age": [], "defer": []}
    led.on_free = lambda c, n: seen["free"].append((c, n))
    led.on_reuse = seen["reuse"].append
    led.on_age = seen["age"].append
    led.on_defer = seen["defer"].append
    led.note_alloc([1, 2])
    led.note_admission()
    led.note_reuse([1])
    led.note_free([1, 2], "refdrop")
    led.note_defer("kv_quota")
    assert seen == {"free": [("refdrop", 2)], "reuse": [1],
                    "age": [1, 1], "defer": ["kv_quota"]}

    # a hook that raises must never reach the batcher worker
    led2 = CacheLedger()
    led2.on_free = led2.on_age = lambda *a: 1 / 0
    led2.note_alloc([5])
    led2.note_free([5], "lru")
    assert led2.snapshot()["frees"]["lru"] == 1


# -- pool + radix integration ----------------------------------------------


def test_pool_ledger_attach_guard_and_cause_plumbing():
    pool = BlockPool(num_blocks=6, block_size=4)
    got = pool.alloc(2)
    # attaching after blocks are live would desync births vs in_use
    with pytest.raises(ValueError, match="already live"):
        pool.attach_ledger(CacheLedger())
    pool.free(got)

    led = CacheLedger()
    pool.attach_ledger(led)
    got = pool.alloc(3)
    assert led.snapshot()["births"] == 3
    pool.free(got[:1], cause="migration")
    pool.free(got[1:])  # cause-less free: booked, but unattributed
    snap = led.snapshot()
    assert snap["frees"]["migration"] == 1
    assert snap["frees"][UNATTRIBUTED] == 2
    assert snap["live_blocks"] == pool.in_use == 0


def test_radix_eviction_books_lru_and_clear_books_refdrop():
    pool = BlockPool(num_blocks=10, block_size=2)
    led = CacheLedger()
    pool.attach_ledger(led)
    cache = RadixPrefixCache(pool)
    (a,) = pool.alloc(1)
    (b,) = pool.alloc(1)
    cache.insert([1, 2], {0: a})
    cache.insert([3, 4], {0: b})
    cache.match([1, 2])          # touch a: b becomes the LRU victim
    assert cache.evict(1) == 1
    assert led.snapshot()["frees"]["lru"] == 1
    cache.clear()
    snap = led.snapshot()
    assert snap["frees"]["refdrop"] == 1
    assert snap["conserved"] and snap["live_blocks"] == 0


# -- decayed prefix heat ----------------------------------------------------


def test_heat_decay_ranking_and_digest_hashes():
    pool = BlockPool(num_blocks=10, block_size=2)
    cache = RadixPrefixCache(pool, heat_half_life=2)
    (a,) = pool.alloc(1)
    (b,) = pool.alloc(1)
    cache.insert([1, 2], {0: a})
    cache.insert([3, 4], {0: b})
    for _ in range(3):
        cache.match([1, 2])
    dg = cache.heat_digest(16)
    assert [e["prefix"] for e in dg] == [prefix_hash([1, 2]),
                                         prefix_hash([3, 4])]
    assert dg[0]["score"] > dg[1]["score"] > 0

    # heat is RECENCY-weighted: hammer the other prefix and the old
    # leader's score halves every 2 clock ticks until it's overtaken
    for _ in range(10):
        cache.match([3, 4])
    dg = cache.heat_digest(16)
    assert dg[0]["prefix"] == prefix_hash([3, 4])
    # k caps the digest; every score survives JSON round-trip as-is
    assert len(cache.heat_digest(1)) == 1
    assert all(isinstance(e["score"], float) for e in dg)


def test_heat_table_is_pruned_to_bound():
    pool = BlockPool(num_blocks=40, block_size=2)
    cache = RadixPrefixCache(pool, heat_max_entries=4)
    hot = [1, 2]
    (h,) = pool.alloc(1)
    cache.insert(hot, {0: h})
    for _ in range(8):
        cache.match(hot)
    for i in range(10):
        (blk,) = pool.alloc(1)
        cache.insert([100 + i, 200 + i], {0: blk})
        assert len(cache._heat) <= 4
    # the genuinely hot prefix survived every prune
    assert any(e["prefix"] == prefix_hash(hot)
               for e in cache.heat_digest(16))


# -- registry: digest round-trip -------------------------------------------


def test_registry_heartbeat_digest_roundtrip_and_sanitation():
    reg = ReplicaRegistry()
    good = {"prefix": prefix_hash([1, 2, 3]), "score": 2.5}
    reg.register("http://a:1", replica_id="a", cache_digest=[good])
    assert reg.get("a").cache_digest == [good]
    assert reg.get("a").snapshot()["cache_digest"] == [good]

    # heartbeats replace the digest wholesale (it's a point-in-time
    # top-K, not a delta) and scrub anything that isn't a 16-hex
    # prefix with a finite non-negative score
    reg.heartbeat("a", cache_digest=[
        good,
        {"prefix": "not-hex!", "score": 1.0},
        {"prefix": "ab", "score": 1.0},            # wrong length
        {"prefix": prefix_hash([9]), "score": -1}, # negative
        {"prefix": prefix_hash([8]), "score": True},  # bool
        "garbage",
        {"score": 3.0},
    ])
    assert reg.get("a").cache_digest == [good]
    # a digest longer than the cap is truncated, not rejected
    reg.heartbeat("a", cache_digest=[
        {"prefix": prefix_hash([i]), "score": 1.0} for i in range(100)])
    assert len(reg.get("a").cache_digest) == 64
    # non-list payloads leave the previous digest untouched
    reg.heartbeat("a", cache_digest="nope")
    assert len(reg.get("a").cache_digest) == 64


# -- router: /fleet/cache merge --------------------------------------------


async def test_fleet_cache_endpoint_merges_digests(aiohttp_client):
    reg = ReplicaRegistry()
    shared = prefix_hash([1, 2, 3, 4])
    only_b = prefix_hash([5, 6, 7, 8])
    reg.register("http://a:1", replica_id="a", cache_digest=[
        {"prefix": shared, "score": 2.0}])
    reg.register("http://b:1", replica_id="b", cache_digest=[
        {"prefix": shared, "score": 1.5},
        {"prefix": only_b, "score": 9.0}])
    client = await aiohttp_client(router_mod.create_router_app(reg))
    body = await (await client.get("/fleet/cache")).json()
    assert set(body["replicas"]) == {"a", "b"}
    assert body["replicas"]["a"]["digest"] == [
        {"prefix": shared, "score": 2.0}]
    heat = {e["prefix"]: e for e in body["heat"]}
    assert heat[shared]["score"] == 3.5
    assert heat[shared]["replicas"] == ["a", "b"]
    assert heat[only_b]["replicas"] == ["b"]
    # sorted hottest-first; one prefix is hot on both replicas
    assert body["heat"][0]["prefix"] == only_b
    assert body["shared_prefixes"] == 1
    assert body["remote_hits_total"] == 0
    # the counter is zero-seeded in /metrics even before any routing
    text = await (await client.get("/metrics")).text()
    assert "fleet_prefix_remote_hits_total 0" in text


# -- engine-level conservation ---------------------------------------------


@pytest.fixture(scope="module")
def tiny_engine():
    import jax

    from kubeflow_tpu.models import llama
    from kubeflow_tpu.serving import (
        EngineConfig,
        InferenceEngine,
        LLAMA_FAMILY,
    )

    cfg = llama.LLAMA_TINY
    params = llama.init(jax.random.key(0), cfg)
    return InferenceEngine(params, cfg, LLAMA_FAMILY,
                           EngineConfig(max_len=64))


def _batcher(engine, **kw):
    from kubeflow_tpu.serving.continuous import ContinuousBatcher

    kw.setdefault("max_slots", 2)
    kw.setdefault("kv_block_size", BS)
    return ContinuousBatcher(engine, asyncio.Lock(), **kw)


def _assert_conserved(b):
    snap = b.cache_ledger.snapshot()
    assert snap["conserved"], snap
    assert snap["frees"][UNATTRIBUTED] == 0
    assert snap["live_blocks"] == b.cengine.pool.in_use
    return snap


async def test_ledger_conserves_through_reuse_and_close(tiny_engine):
    """Two identical requests: the second radix-hits, records a reuse
    distance, and every block the server ever allocated is accounted
    dead (refdrop) after close — births - frees == 0 live."""
    b = _batcher(tiny_engine)
    prompt = [3, 5, 7, 11, 13, 17, 19, 23]  # exactly one full block
    try:
        await b.submit(prompt, 4, ())
        _assert_conserved(b)
        await b.submit(prompt, 4, ())
        assert b.prefix_hits >= 1
        snap = _assert_conserved(b)
        assert snap["admissions"] == 2
        assert snap["reuse_distance"]["count"] >= 1
        assert snap["reuse_distance"]["p50"] >= 1
        # the reused prefix is the hottest entry, named by the same
        # hash the router would compute for this prompt
        anat = b.cache_anatomy()
        assert anat["heat"][0]["prefix"] == prefix_hash(prompt[:BS])
    finally:
        await b.close()
    # close() keeps the radix warm (cached blocks stay live); clearing
    # it retires the remainder as refdrop and the books close to zero
    b._radix.clear()
    snap = b.cache_ledger.snapshot()
    assert snap["conserved"] and snap["live_blocks"] == 0
    assert snap["frees"]["refdrop"] > 0
    assert snap["births"] == snap["frees_total"]


async def test_ledger_books_divergence_on_duplicate_import(tiny_engine):
    """CoW-style duplicate: importing a migrated prefix the target
    already cached frees the duplicate blocks under `divergence`, and
    both replicas' ledgers stay conserved."""
    from kubeflow_tpu.serving.continuous import MigratedAway

    prompt = [3, 5, 7, 11, 13, 17, 19, 23, 2, 4]
    src = _batcher(tiny_engine)
    try:
        fut, q = src.open_stream(prompt, 12, ())
        for _ in range(3):
            assert (await q.get()) is not None
        records = await src.export_sequences()
        with pytest.raises(MigratedAway):
            await fut
        assert len(records) == 1 and records[0]["kv"] is not None
        snap = _assert_conserved(src)
        assert snap["frees"]["migration"] >= records[0]["kv"]["n_full"]
    finally:
        await src.close()

    dst = _batcher(tiny_engine)
    try:
        n_full = records[0]["kv"]["n_full"]
        assert await dst.import_sequence(records[0]) == n_full
        _assert_conserved(dst)
        # second import of the same record: radix keeps its blocks,
        # the fresh copies die as divergence
        assert await dst.import_sequence(records[0]) == 0
        snap = _assert_conserved(dst)
        assert snap["frees"]["divergence"] >= n_full
    finally:
        await dst.close()
    assert dst.cache_ledger.snapshot()["conserved"]


@pytest.mark.slow
async def test_ledger_books_pressure_on_preemption(tiny_engine):
    """Tenancy preemption: the victim's blocks die as `pressure`, and
    the ledger stays conserved through preempt + replay + close."""
    from kubeflow_tpu.tenancy import config_from_dict

    qos = {"tenants": {"live": {"priority": "interactive"},
                       "bulk": {"priority": "batch"}}}
    b = _batcher(tiny_engine, tenancy=config_from_dict(qos))
    try:
        f1 = asyncio.ensure_future(
            b.submit([3, 5, 7, 11], 24, (("tenant", "bulk"),)))
        f2 = asyncio.ensure_future(
            b.submit([4, 6, 8, 10], 24, (("tenant", "bulk"),)))
        for _ in range(400):
            if len(b._active) == 2:
                break
            await asyncio.sleep(0.02)
        assert len(b._active) == 2
        got = await b.submit([9, 2, 4, 8], 8, (("tenant", "live"),))
        await f1
        await f2
        assert len(got) == 8 and b.preemptions >= 1
        snap = _assert_conserved(b)
        assert snap["frees"]["pressure"] >= 1
    finally:
        await b.close()
    assert b.cache_ledger.snapshot()["conserved"]


# -- router: counterfactual remote hits, two real replicas ------------------


async def _start_replica(engine):
    from kubeflow_tpu.serving import server as server_lib

    app = server_lib.create_serving_app(
        {"tiny": engine}, continuous=True, max_batch=2,
        kv_block_size=BS)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    server = TestServer(app, port=port)
    await server.start_server()
    return app, server, f"http://127.0.0.1:{port}"


@pytest.mark.slow
async def test_router_counterfactual_remote_hits_two_replicas(
        tiny_engine):
    """The headline fleet measurement: a prompt routed (by affinity)
    to replica X that missed, while peer Y's heartbeat digest shows
    the same prefix hot, increments fleet_prefix_remote_hits_total —
    the hit a cross-replica cache tier would have converted."""
    from kubeflow_tpu.serving import server as server_lib

    app_a, srv_a, url_a = await _start_replica(tiny_engine)
    app_b, srv_b, url_b = await _start_replica(tiny_engine)
    reg = ReplicaRegistry()
    reg.register(url_a, replica_id="ra", models=["tiny"])
    reg.register(url_b, replica_id="rb", models=["tiny"])
    router_server = TestServer(router_mod.create_router_app(
        reg, block_size=BS))
    await router_server.start_server()
    rc = TestClient(router_server)
    try:
        # a prompt whose affinity key pins replica "ra"
        ids = ["ra", "rb"]
        prompt = None
        for s in range(3, 2000):
            toks = [s, 1, 2, 3, 5, 7, 11, 13]
            key = router_mod.affinity_key({"tokens": [toks]}, BS)
            if rendezvous(key, ids) == "ra":
                prompt = toks
                break
        assert prompt is not None

        # warm the OTHER replica ("rb") with this prompt, out of band
        peers = {"ra": (app_a, srv_a), "rb": (app_b, srv_b)}
        pc = TestClient(peers["rb"][1])
        r = await pc.post("/v1/models/tiny:generate",
                          json={"tokens": [prompt], "max_new": 2})
        assert r.status == 200
        await pc.close()
        dg = server_lib.fleet_stats(app_b)["cache_digest"]
        assert any(e["prefix"] == prefix_hash(prompt[:BS])
                   for e in dg), dg
        reg.heartbeat("rb", cache_digest=dg)
        reg.heartbeat("ra", cache_digest=[])

        # routed request lands cold on "ra" while "rb" is hot -> one
        # counterfactual remote hit, visible on /fleet/cache
        r = await rc.post("/v1/models/tiny:generate",
                          json={"tokens": [prompt], "max_new": 2})
        assert r.status == 200
        assert r.headers["X-Fleet-Replica"] == "ra"
        body = await (await rc.get("/fleet/cache")).json()
        assert body["remote_hits_total"] == 1
        assert any(e["prefix"] == prefix_hash(prompt[:BS])
                   and e["replicas"] == ["rb"] for e in body["heat"])

        # once "ra" itself reports the prefix hot, the same request is
        # a LOCAL hit and the counterfactual counter stays put
        dg_a = server_lib.fleet_stats(app_a)["cache_digest"]
        assert any(e["prefix"] == prefix_hash(prompt[:BS])
                   for e in dg_a), dg_a
        reg.heartbeat("ra", cache_digest=dg_a)
        r = await rc.post("/v1/models/tiny:generate",
                          json={"tokens": [prompt], "max_new": 2})
        assert r.status == 200
        body = await (await rc.get("/fleet/cache")).json()
        assert body["remote_hits_total"] == 1
        text = await (await rc.get("/metrics")).text()
        assert "fleet_prefix_remote_hits_total 1" in text
    finally:
        await rc.close()
        await router_server.close()
        await srv_a.close()
        await srv_b.close()
