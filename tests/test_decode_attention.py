"""decode_attention kernel vs the XLA oracle (interpret mode on CPU).

Same tier as test_flash.py: the kernel must match
ops.attention._xla_attention bit-for-meaning on the decode shape class
— per-row cursors, left-pad holes, GQA grouping, sliding windows, and
the block-skip path (cursors far below max_len)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.ops.attention import _xla_attention
from kubeflow_tpu.ops.pallas.decode_attention import decode_attention


def _mk(b, max_len, n_q, n_kv, hd, seed=0):
    gen = np.random.default_rng(seed)
    q = jnp.asarray(gen.normal(size=(b, 1, n_q, hd)), jnp.float32)
    k = jnp.asarray(gen.normal(size=(b, max_len, n_kv, hd)), jnp.float32)
    v = jnp.asarray(gen.normal(size=(b, max_len, n_kv, hd)), jnp.float32)
    return gen, q, k, v


def _oracle(q, k, v, pos, kv_mask, window=None):
    b, max_len = k.shape[0], k.shape[1]
    q_positions = pos[:, None].astype(jnp.int32)
    kv_positions = jnp.broadcast_to(
        jnp.arange(max_len, dtype=jnp.int32)[None], (b, max_len))
    return _xla_attention(q, k, v, q_positions, kv_positions,
                          causal=True, kv_mask=kv_mask, window=window)


@pytest.mark.parametrize("n_q,n_kv", [(4, 4), (8, 2)])
def test_matches_oracle_ragged_cursors(n_q, n_kv):
    b, max_len, hd = 4, 256, 32
    gen, q, k, v = _mk(b, max_len, n_q, n_kv, hd)
    pos = jnp.asarray([3, 77, 128, 255], jnp.int32)
    mask = jnp.ones((b, max_len), bool)
    got = decode_attention(q, k, v, pos, mask, block_k=64)
    want = _oracle(q, k, v, pos, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_matches_oracle_with_pad_holes():
    """Left-pad holes (the engines' bucket padding) inside the visible
    prefix must be excluded exactly like the oracle's kv_mask."""
    b, max_len, n_q, n_kv, hd = 3, 128, 4, 2, 32
    gen, q, k, v = _mk(b, max_len, n_q, n_kv, hd, seed=1)
    pos = jnp.asarray([40, 90, 127], jnp.int32)
    mask_np = np.ones((b, max_len), bool)
    mask_np[0, :5] = False     # 5 pad cells at the head
    mask_np[1, 10:20] = False  # a hole mid-prefix
    mask = jnp.asarray(mask_np)
    got = decode_attention(q, k, v, pos, mask, block_k=32)
    want = _oracle(q, k, v, pos, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_matches_oracle_sliding_window():
    b, max_len, n_q, n_kv, hd = 2, 128, 4, 4, 32
    gen, q, k, v = _mk(b, max_len, n_q, n_kv, hd, seed=2)
    pos = jnp.asarray([100, 127], jnp.int32)
    mask = jnp.ones((b, max_len), bool)
    got = decode_attention(q, k, v, pos, mask, window=16, block_k=32)
    want = _oracle(q, k, v, pos, mask, window=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_fresh_row_cursor_zero():
    """pos=0: only the just-written cell is visible — the degenerate
    single-cell softmax must return exactly that cell's value."""
    b, max_len, n_q, n_kv, hd = 1, 64, 2, 2, 16
    gen, q, k, v = _mk(b, max_len, n_q, n_kv, hd, seed=3)
    pos = jnp.asarray([0], jnp.int32)
    mask = jnp.ones((b, max_len), bool)
    got = decode_attention(q, k, v, pos, mask, block_k=16)
    want = _oracle(q, k, v, pos, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
    # and it literally equals v[:, 0] repeated over the q group
    np.testing.assert_allclose(
        np.asarray(got)[0, 0], np.asarray(v)[0, 0], atol=1e-6)


def test_rejects_multi_token_queries():
    gen, q, k, v = _mk(1, 64, 2, 2, 16)
    q2 = jnp.concatenate([q, q], axis=1)
    with pytest.raises(ValueError, match="s=1 only"):
        decode_attention(q2, k, v, jnp.asarray([0], jnp.int32))


def test_dispatcher_impl_decode_matches_xla():
    """dot_product_attention(impl='decode') must agree with the XLA
    path on the exact call shape the engines make."""
    from kubeflow_tpu.ops.attention import dot_product_attention

    b, max_len, n_q, n_kv, hd = 3, 256, 8, 2, 32
    gen, q, k, v = _mk(b, max_len, n_q, n_kv, hd, seed=7)
    pos = jnp.asarray([12, 200, 255], jnp.int32)
    q_positions = pos[:, None]
    kv_positions = jnp.broadcast_to(
        jnp.arange(max_len, dtype=jnp.int32)[None], (b, max_len))
    mask_np = np.ones((b, max_len), bool)
    mask_np[1, :7] = False
    mask = jnp.asarray(mask_np)
    got = dot_product_attention(
        q, k, v, q_positions, kv_positions, causal=True, kv_mask=mask,
        impl="decode", contiguous_positions=True)
    want = dot_product_attention(
        q, k, v, q_positions, kv_positions, causal=True, kv_mask=mask,
        impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_dispatcher_decode_door_is_causal_only():
    from kubeflow_tpu.ops.attention import dot_product_attention

    gen, q, k, v = _mk(1, 256, 2, 2, 16, seed=8)
    q_positions = jnp.asarray([[5]], jnp.int32)
    kv_positions = jnp.arange(256, dtype=jnp.int32)[None]
    with pytest.raises(ValueError, match="causal-only"):
        dot_product_attention(q, k, v, q_positions, kv_positions,
                              causal=False, impl="decode",
                              contiguous_positions=True)


def test_dispatcher_decode_door_requires_cell_index_contract():
    from kubeflow_tpu.ops.attention import dot_product_attention

    gen, q, k, v = _mk(1, 256, 2, 2, 16, seed=9)
    q_positions = jnp.asarray([[5]], jnp.int32)
    kv_positions = jnp.arange(256, dtype=jnp.int32)[None]
    with pytest.raises(ValueError, match="cell index"):
        dot_product_attention(q, k, v, q_positions, kv_positions,
                              causal=True, impl="decode")
